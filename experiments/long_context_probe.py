"""Long-context training on the real chip (SURVEY §5.7 made concrete).

The CPU-mesh tests prove ring/Ulysses sequence parallelism and remat
compose; this probe measures what one v5e actually sustains as the
context grows: the 136M LM (fused Pallas flash attention, bf16 compute)
trained at T = 1024 -> 16384 with tokens/step held at 8192 (batch
scaled down), with and without per-block rematerialization at the long
end. Timing is fetch-synced with the tunnel round trip subtracted and
executed-work checked (block_until_ready can no-op through the tunnel
— see bench.py / googlenet_layout_probe.py).

Writes results/long_context.json. Run: python experiments/long_context_probe.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS = os.path.join(HERE, "results")

TOKENS_PER_STEP = 8192
STEPS = 8


def _latency() -> float:
    ts = []
    for i in range(5):
        t0 = time.perf_counter()
        float(jnp.sum(jnp.ones(()) * i))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def run_config(T: int, remat: bool, trials: int = 3,
               steps: int = STEPS) -> dict:
    from theanompi_tpu.models.lm import TransformerLM_136M
    from theanompi_tpu.train import init_train_state, make_multi_step, make_train_step

    batch = max(1, TOKENS_PER_STEP // T)
    recipe = TransformerLM_136M.default_recipe().replace(
        batch_size=batch, input_shape=(T,), remat=remat
    )
    model = TransformerLM_136M(recipe)
    # no compiler flags: at T >= 8192 the flash backward dispatches to
    # the 2-D-grid kernels (block-resident both sides — see
    # ops/pallas_attention.py "long-context operation" note)
    runner = jax.jit(
        make_multi_step(make_train_step(model), steps), donate_argnums=(0,)
    )
    state = init_train_state(model, jax.random.PRNGKey(0))
    r = np.random.RandomState(0)
    toks = jnp.asarray(
        r.randint(0, recipe.num_classes, (batch, T)), jnp.int32
    )
    lat = _latency()
    start = int(np.asarray(state.step))
    state, m = runner(state, toks, toks, jax.random.PRNGKey(1))  # compile
    np.asarray(m["loss"])
    times = []
    for t in range(trials):
        t0 = time.perf_counter()
        state, m = runner(state, toks, toks, jax.random.PRNGKey(100 + t))
        np.asarray(m["loss"])
        times.append(time.perf_counter() - t0 - lat)
    got = int(np.asarray(state.step))
    assert got == start + steps * (trials + 1), (got, start)
    med = float(np.median(times))
    assert med > 4 * lat, f"window {med*1e3:.0f} ms too small vs latency"
    return {
        "seq_len": T,
        "batch": batch,
        "remat": remat,
        "tokens_per_sec": round(steps * batch * T / med, 1),
        "step_ms": round(1000 * med / steps, 2),
    }


def main() -> int:
    os.makedirs(RESULTS, exist_ok=True)
    path = os.path.join(RESULTS, "long_context.json")
    out = {"device": jax.devices()[0].device_kind,
           "model": "transformer_lm_136m (bf16, flash attention)",
           "tokens_per_step": TOKENS_PER_STEP, "rows": []}
    for T, remat in ((1024, False), (2048, False), (4096, False),
                     (8192, False), (8192, True), (16384, False),
                     (16384, True)):
        try:
            # short-T steps raised so the timed window clears the
            # 4x-round-trip guard (a 1024-token step is ~60 ms)
            row = run_config(T, remat, steps=24 if T <= 2048 else STEPS)
        except Exception as e:  # OOM at some T IS the measured boundary
            row = {"seq_len": T, "remat": remat,
                   "error": type(e).__name__,
                   "detail": str(e).splitlines()[0][:120]}
        out["rows"].append(row)
        print("row:", row, flush=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    print(json.dumps({"name": "long_context", "done": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
