"""ResNet-50 BN-bottleneck probe (round-3 verdict item 2).

tools/op_profile.py's committed case study shows the batch-256 ResNet-50
step spends ~half its time in BN-statistic reduce fusions + the
normalize sweeps (each BN re-reads the conv output from HBM: the step is
bandwidth-bound, not MXU-bound). This probe measures candidate fixes on
the real chip, one variable at a time:

  baseline       BatchNorm as shipped (fp32 upcast sweeps)
  dtype_reduce   stats via dtype=f32 reduction args on the bf16 x
                 (no materialized fp32 copy; XLA fuses convert into the
                 reduce pass)
  bf16_norm      + the normalize sweep computed in bf16 (per-channel
                 inv/bias still derived in fp32; halves the bytes of the
                 scale-shift pass)
  batch512       baseline at global batch 512 (amortizes fixed costs,
                 bigger reduce tiles)
  combo512       dtype_reduce + bf16_norm at batch 512

Writes experiments/results/resnet_bn_probe.json; the winner (with the
measured table) graduates into nn/layers.py like the LRN matmul did.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu import nn
from theanompi_tpu.models.model_zoo.resnet50 import ResNet50
from theanompi_tpu.train import init_train_state, make_multi_step, make_train_step
from theanompi_tpu.utils.flops import compiled_flops, peak_flops

STEPS = 8


def patched_apply(fast_stats: bool, bf16_norm: bool, variadic: bool = False):
    """Build a BatchNorm.apply variant; closure over the flags."""

    def apply(self, params, state, x, *, train=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            if variadic:
                # ONE pass for both moments: the profiler shows 104
                # convert_reduce fusions/step = 2 separate reduces per
                # BN, each re-reading the activation from HBM; a
                # variadic lax.reduce computes (sum x, sum x^2) in a
                # single sweep
                xf = x.astype(jnp.float32)
                n = 1
                for a in reduce_axes:
                    n *= x.shape[a]
                s, s2 = lax.reduce(
                    (xf, xf * xf), (jnp.float32(0), jnp.float32(0)),
                    lambda a, b: (a[0] + b[0], a[1] + b[1]), reduce_axes
                )
                mean, mean_sq = s / n, s2 / n
            elif fast_stats:
                mean = jnp.mean(x, axis=reduce_axes, dtype=jnp.float32)
                mean_sq = jnp.mean(
                    jnp.square(x.astype(jnp.float32)), axis=reduce_axes
                )
            else:
                xf = x.astype(jnp.float32)
                mean = jnp.mean(xf, axis=reduce_axes)
                mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean_sq = lax.pmean(mean_sq, self.axis_name)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean,
                "var": m * state["var"] + (1 - m) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps) * params["scale"]
        if bf16_norm and x.dtype == jnp.bfloat16:
            y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + params[
                "bias"
            ].astype(x.dtype)
            return y, new_state
        y = (x.astype(jnp.float32) - mean) * inv + params["bias"]
        return y.astype(x.dtype), new_state

    return apply


def measure(batch: int, fast_stats: bool, bf16_norm: bool,
            variadic: bool = False) -> dict:
    orig = nn.BatchNorm.apply
    nn.BatchNorm.apply = patched_apply(fast_stats, bf16_norm, variadic)
    try:
        model = ResNet50(ResNet50.default_recipe().replace(batch_size=batch))
        single = jax.jit(make_train_step(model))
        runner = jax.jit(make_multi_step(make_train_step(model), STEPS))
        state = init_train_state(model, jax.random.PRNGKey(0))
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(batch, 224, 224, 3), jnp.float32)
        y = jnp.asarray(r.randint(0, 1000, batch), jnp.int32)
        args = (state, x, y, jax.random.PRNGKey(1))
        flops = compiled_flops(single, *args)
        out = runner(*args)  # warmup
        assert int(np.asarray(out[0].step)) == STEPS, "executed-work check"
        best = None
        for t in range(3):
            t0 = time.perf_counter()
            out = runner(state, x, y, jax.random.PRNGKey(2 + t))
            float(np.asarray(out[1]["loss"])[-1])  # hard sync via fetch
            best = min(best or 1e9, time.perf_counter() - t0)
        assert int(np.asarray(out[0].step)) == STEPS
        img_s = STEPS * batch / best
        peak = peak_flops()
        mfu = (flops * STEPS / best / peak) if (flops and peak) else None
        return {
            "batch": batch, "fast_stats": fast_stats, "bf16_norm": bf16_norm,
            "variadic": variadic,
            "img_s": round(img_s, 1), "step_ms": round(1000 * best / STEPS, 2),
            "mfu": round(mfu, 4) if mfu else None,
        }
    finally:
        nn.BatchNorm.apply = orig


def main():
    dev = jax.devices()[0]
    rows = {}
    for name, (batch, fast, bnorm, var) in {
        "baseline": (256, False, False, False),
        "dtype_reduce": (256, True, False, False),
        "bf16_norm": (256, True, True, False),
        "batch512": (512, False, False, False),
        "combo512": (512, True, True, False),
        "variadic": (256, False, False, True),
        "variadic_bf16norm": (256, False, True, True),
    }.items():
        rows[name] = measure(batch, fast, bnorm, var)
        print(json.dumps({name: rows[name]}), flush=True)
    out = {
        "device": dev.device_kind, "steps": STEPS, "variants": rows,
        "date": "2026-07-30",
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "results", "resnet_bn_probe.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"wrote": path}))


if __name__ == "__main__":
    main()
