"""Pipeline-schedule bubble measurement: analytic law vs executed ticks.

Round-3 verdict called pipeline parallelism "correct but unmeasured as
a performance feature". This probe measures it in the only way that is
meaningful without an n-chip pod: the schedule's TICK COUNT is the
wall-clock model (every tick is one chunk of compute plus one ppermute
hop, gang-scheduled), so we count executed ticks for GPipe vs the
interleaved schedule across microbatch counts and check the measured
step time on the 8-way virtual CPU mesh tracks the tick ratio.

Two claims, both falsifiable here:

1. **Tick law (exact):** GPipe runs ``M + n - 1`` ticks, interleaved
   runs ``M*v + n - 1`` ticks of ``1/v`` the work — the probe asserts
   the analytic report against the jaxpr's scan trip counts.
2. **Time follows work+bubble (measured):** per-step wall-clock on the
   CPU mesh, normalized by microbatch count, falls as M grows and the
   fill/drain bubble amortizes, approaching the no-bubble asymptote;
   interleave=v reaches the same bubble fraction at ~v x fewer
   microbatches.

Writes results/pp_bubble.json. Run:  python experiments/pp_bubble_probe.py
"""

from __future__ import annotations

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS = os.path.join(HERE, "results")
sys.path.insert(0, os.path.dirname(HERE))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

N_PIPE = 4
VOCAB, D, LAYERS, T, B = 64, 64, 8, 32, 2


def _scan_lengths(jaxpr):
    """All scan trip counts in a (closed) jaxpr, recursively."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(int(eqn.params["length"]))
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else [v]:
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    out.extend(_scan_lengths(inner))
    return out


def main():
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.parallel import make_mesh
    from theanompi_tpu.parallel.pipeline import (
        PIPE_AXIS,
        make_pp_train_step,
        pipeline_schedule_report,
        stack_pipeline_params,
    )

    model = TransformerLM(
        vocab=VOCAB, d_model=D, n_heads=4, n_layers=LAYERS, d_ff=2 * D, max_len=T
    )
    params = model.init(jax.random.PRNGKey(0))
    mesh = make_mesh(N_PIPE, axis_names=(PIPE_AXIS,))
    rows = []
    r = np.random.RandomState(0)

    for v in (1, 2):
        stacked = stack_pipeline_params(params, n_stages=N_PIPE, interleave=v)
        for M in (4, 8, 16, 32):
            step = make_pp_train_step(model, mesh, lr=0.01, interleave=v)
            toks = jnp.asarray(r.randint(0, VOCAB, (M, B, T)), jnp.int32)
            report = pipeline_schedule_report(N_PIPE, M, v)

            # claim 1: the compiled program executes EXACTLY the
            # schedule's tick count (fwd scan; AD adds the reverse scan)
            jaxpr = jax.make_jaxpr(lambda p, t: step(p, t))(stacked, toks)
            lengths = _scan_lengths(jaxpr.jaxpr)
            assert report["ticks"] in lengths, (v, M, report["ticks"], lengths)

            out = step(stacked, toks)  # compile + warm
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                out = step(stacked, toks)
                jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / reps
            rows.append(
                {
                    "interleave": v,
                    "microbatches": M,
                    "ticks": report["ticks"],
                    "bubble_fraction": report["bubble_fraction"],
                    "step_seconds": dt,
                    "seconds_per_microbatch": dt / M,
                }
            )
            print(
                f"v={v} M={M:3d} ticks={report['ticks']:4d} "
                f"bubble={report['bubble_fraction']:.3f} "
                f"step={dt * 1e3:8.1f}ms  per-ub={dt / M * 1e3:6.1f}ms"
            )

    # claim 2 (measured AND asserted): per-microbatch time at M=32 must
    # undercut M=4 for GPipe (bubble 3/35 vs 3/7) — if the mesh timing
    # ever stops showing the amortization, the probe fails instead of
    # committing a result that contradicts the claim.
    by = {(row["interleave"], row["microbatches"]): row for row in rows}
    amort = by[(1, 4)]["seconds_per_microbatch"] / by[(1, 32)]["seconds_per_microbatch"]
    assert amort > 1.0, f"GPipe bubble amortization not observed: {amort:.2f}x"
    out = {
        "note": (
            "8-way virtual CPU mesh, 4-stage pipeline over a "
            f"{LAYERS}-layer {D}-d LM; tick counts asserted against the "
            "compiled scan trip counts (exact), times are wall-clock "
            "(CPU-mesh proxy: shows amortization trend, not TPU ratios)"
        ),
        "n_stages": N_PIPE,
        "amortization_gpipe_M4_over_M32": amort,
        "rows": rows,
    }
    os.makedirs(RESULTS, exist_ok=True)
    with open(os.path.join(RESULTS, "pp_bubble.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(f"amortization M4/M32 (GPipe): {amort:.2f}x  -> results/pp_bubble.json")


if __name__ == "__main__":
    main()
