"""Committed convergence experiments (SURVEY.md §4: "correctness is
validated by convergence curves"; §7 hard-part 1: the synchronous
EASGD/GoSGD redesigns need empirical convergence parity vs BSP).

Two experiments, both run on the virtual 8-device CPU mesh so anyone
can reproduce them without hardware:

1. ``rules``  — BSP vs EASGD vs GoSGD, same model, same step budget, on
   the seeded synthetic task. The async rules use per-worker batches
   (reference semantics), so their images/step is 8x BSP's per-batch —
   the comparison is at a fixed STEP budget, matching how the reference
   compared rules (iterations of local SGD + exchange).
2. ``digits`` — BSP on REAL data (sklearn's bundled handwritten digits;
   the only real image dataset available offline — stands in for
   BASELINE config #1 until cifar-10-batches-py is on disk; the same
   command with ``--dataset cifar10`` runs the real config #1).

Writes recorder JSONL per run + results/summary.json. Run:

    python experiments/run_convergence.py [rules|digits|all]
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RESULTS = os.path.join(HERE, "results")

_CHILD = """
import os, json, sys
import jax
spec = json.loads(sys.argv[1])
if spec.get("platform", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
from theanompi_tpu.launch.worker import run_training
from theanompi_tpu.launch.session import resolve_model

model_cls = resolve_model(spec.get("modelfile", "cifar10"),
                          spec.get("modelclass", "Cifar10_model"))
summary = run_training(model_cls=model_cls, **spec["kwargs"])
print("RESULT " + json.dumps({
    "name": spec["name"],
    "val": summary.get("val"),
    "steps": summary["steps"],
    "resumed_from_step": summary.get("resumed_from_step"),
}))
"""


def _run(name: str, kwargs: dict, n_devices: int = 8,
         modelfile: str = "cifar10", modelclass: str = "Cifar10_model",
         platform: str = "cpu") -> dict:
    # fresh per-run dir, replaced only on SUCCESS: the Recorder APPENDS
    # to existing JSONL (a naive rerun would accumulate runs in one
    # artifact), and deleting up front would destroy the committed
    # evidence if the child fails
    run_dir = os.path.join(RESULTS, name)
    tmp_dir = run_dir + ".new"
    shutil.rmtree(tmp_dir, ignore_errors=True)
    kwargs = dict(kwargs, save_dir=tmp_dir)
    env = dict(os.environ)
    if platform == "cpu":
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
        env["JAX_PLATFORMS"] = "cpu"
    spec = {"name": name, "kwargs": kwargs, "platform": platform,
            "modelfile": modelfile, "modelclass": modelclass}
    p = subprocess.run(
        [sys.executable, "-c", _CHILD, json.dumps(spec)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=3600,
    )
    if p.returncode != 0:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        sys.stderr.write(p.stdout[-1000:] + "\n" + p.stderr[-3000:])
        raise RuntimeError(f"experiment {name} failed")
    shutil.rmtree(run_dir, ignore_errors=True)
    os.rename(tmp_dir, run_dir)
    line = [l for l in p.stdout.splitlines() if l.startswith("RESULT ")][-1]
    out = json.loads(line[len("RESULT "):])
    print(json.dumps(out))
    return out


def exp_rules() -> list[dict]:
    """BSP vs EASGD vs GoSGD at n=8, fixed 320-step budget, synthetic.

    Per-worker batch 16 for the async rules (global 128/step); BSP uses
    global batch 128 — identical images/step across rules.
    """
    os.makedirs(RESULTS, exist_ok=True)
    common = dict(
        devices=8,
        n_epochs=100,  # truncated by max_steps
        max_steps=320,
        dataset="synthetic",
        dataset_kwargs={"n_train": 2048, "n_val": 512,
                        "image_shape": [16, 16, 3]},
        recipe_overrides={
            "input_shape": (16, 16, 3),
            "n_epochs": 100,
            "sched_kwargs": {"lr": 0.05, "boundaries": [10**9]},
        },
        seed=7,
        print_freq=0,
        save_dir=RESULTS,
    )
    runs = []
    runs.append(_run("bsp", dict(
        common, rule="bsp",
        recipe_overrides={**common["recipe_overrides"], "batch_size": 128},
    )))
    # Async rules: per-worker batch 16 local SGD needs a cooler LR than
    # the 128-batch lockstep run (the reference likewise tuned per rule)
    async_over = {
        **common["recipe_overrides"], "batch_size": 16,
        "sched_kwargs": {"lr": 0.02, "boundaries": [10**9]},
    }
    runs.append(_run("easgd", dict(
        common, rule="easgd", avg_freq=8,
        recipe_overrides=async_over,
    )))
    runs.append(_run("gosgd", dict(
        common, rule="gosgd", p_push=0.25,
        recipe_overrides=async_over,
    )))
    return runs


def exp_digits() -> list[dict]:
    """BSP on real data (digits), 15 epochs — the model must exceed 90%
    val accuracy for the experiment to count as converged."""
    os.makedirs(RESULTS, exist_ok=True)
    out = _run("digits_bsp", dict(
        rule="bsp",
        devices=8,
        n_epochs=15,
        dataset="digits",
        dataset_kwargs={"size": 16},
        recipe_overrides={
            "batch_size": 128,
            "input_shape": (16, 16, 3),
            "n_epochs": 15,
            "sched_kwargs": {"lr": 0.05, "boundaries": [10, 13],
                             "factor": 0.1},
        },
        seed=3,
        print_freq=0,
        save_dir=RESULTS,
    ))
    return [out]


def exp_wrn() -> list[dict]:
    """The FULL model-zoo recipe path on real data (round-3 verdict item
    6): WRN-16-4 on digits with the WRN recipe's augmentation (random
    crop from reflect pad + mirror), step-decay LR schedule, 10-crop
    multi-view validation, and a checkpointed MID-RUN resume — phase 1
    stops at step 44 of 110, phase 2 resumes from its checkpoint and
    completes. Converged = final 10-crop val error <= 8%."""
    os.makedirs(RESULTS, exist_ok=True)
    ck = os.path.join(RESULTS, "wrn_digits_ckpt")
    shutil.rmtree(ck, ignore_errors=True)
    common = dict(
        rule="bsp",
        devices=8,
        dataset="digits",
        dataset_kwargs={"size": 16, "augment_crop": True,
                        "ten_crop_val": True},
        recipe_overrides={
            "batch_size": 128,
            "input_shape": (16, 16, 3),
            "n_epochs": 10,
            # the WRN recipe's step-decay shape, compressed to 10 epochs
            "sched_kwargs": {"lr": 0.05, "boundaries": [6, 8],
                             "factor": 0.2},
        },
        seed=3,
        print_freq=0,
        run_name="wrn_digits",
        ckpt_dir=ck,
        ckpt_every_epochs=2,
        async_checkpoint=False,
    )
    # phase 1: stop mid-experiment (11 steps/epoch x 10 = 110 total)
    _run("wrn_digits_phase1", dict(common, max_steps=44),
         modelfile="wrn", modelclass="WRN_16_4")
    # phase 2: resume from the phase-1 checkpoint, run to completion
    out = _run("wrn_digits", dict(common, resume=True),
               modelfile="wrn", modelclass="WRN_16_4")
    shutil.rmtree(ck, ignore_errors=True)
    assert out["val"]["error"] <= 0.08, (
        f"WRN full-recipe run did not converge: {out['val']}"
    )
    assert out["resumed_from_step"] == 44, out
    return [out]


def _train_rows(run_dir: str, run_name: str) -> dict[int, dict]:
    rows = {}
    with open(os.path.join(RESULTS, run_dir, run_name + ".jsonl")) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == "train":
                rows[int(r["step"])] = r
    return rows


def exp_wrn_tpu() -> list[dict]:
    """The WRN recipe ON THE REAL TPU with the production hot path active
    (round-4 verdict item 1): bf16 compute, fused 4-step dispatch,
    augmentation, 10-crop val, and a checkpointed mid-run resume — the
    exact code path the throughput claims (ZOO_BENCH/BENCH_rNN) measure,
    carried to an accuracy number instead of a perf sample. A same-seed
    single-device CPU run in f32 per-step dispatch is the trusted-math
    reference curve; results/wrn_tpu_vs_cpu.json quantifies divergence
    (bf16 + platform + fusion, jointly — each alone is below the run-to-
    run noise of the task). Converged = final 10-crop val error <= 8%
    on BOTH paths (the SURVEY §4 convergence-curve validation applied to
    the TPU hot path)."""
    os.makedirs(RESULTS, exist_ok=True)
    ck = os.path.join(RESULTS, "wrn_digits_tpu_ckpt")
    shutil.rmtree(ck, ignore_errors=True)
    common = dict(
        rule="bsp",
        devices=1,
        dataset="digits",
        dataset_kwargs={"size": 16, "augment_crop": True,
                        "ten_crop_val": True},
        recipe_overrides={
            "batch_size": 128,
            "input_shape": (16, 16, 3),
            "n_epochs": 10,
            "sched_kwargs": {"lr": 0.05, "boundaries": [6, 8],
                             "factor": 0.2},
        },
        seed=3,
        print_freq=0,
    )
    tpu = dict(
        common,
        recipe_overrides={**common["recipe_overrides"],
                          "compute_dtype": "bfloat16"},
        steps_per_dispatch=4,
        ckpt_dir=ck,
        ckpt_every_epochs=2,
        async_checkpoint=False,
    )
    # phase 1: stop mid-experiment (11 steps/epoch x 10 = 110 total)
    _run("wrn_digits_tpu_phase1",
         dict(tpu, max_steps=44, run_name="wrn_digits_tpu"),
         modelfile="wrn", modelclass="WRN_16_4", platform="tpu")
    out = _run("wrn_digits_tpu",
               dict(tpu, resume=True, run_name="wrn_digits_tpu"),
               modelfile="wrn", modelclass="WRN_16_4", platform="tpu")
    shutil.rmtree(ck, ignore_errors=True)
    # trusted-math reference: same seed/config, single device (so BN
    # moments see the same 128-row batch — the 8-device committed
    # wrn_digits run normalizes per 16-row shard), f32, per-step
    ref = _run("wrn_digits_cpu1",
               dict(common, run_name="wrn_digits_cpu1"),
               n_devices=1, modelfile="wrn", modelclass="WRN_16_4")
    assert out["resumed_from_step"] == 44, out
    for r in (out, ref):
        assert r["val"]["error"] <= 0.08, (
            f"run did not converge: {r['name']}: {r['val']}"
        )
    # side-by-side divergence numbers for the committed numerics note
    tpu_rows = {**_train_rows("wrn_digits_tpu_phase1", "wrn_digits_tpu"),
                **_train_rows("wrn_digits_tpu", "wrn_digits_tpu")}
    cpu_rows = _train_rows("wrn_digits_cpu1", "wrn_digits_cpu1")
    steps = sorted(set(tpu_rows) & set(cpu_rows))
    dloss = [abs(tpu_rows[s]["loss"] - cpu_rows[s]["loss"]) for s in steps]
    rel = [
        d / max(abs(cpu_rows[s]["loss"]), 1e-9)
        for d, s in zip(dloss, steps)
    ]
    cmp_out = {
        "tpu": {"path": "bf16 compute + fused 4-step dispatch, 1x v5e",
                "val": out["val"], "resumed_from_step": 44},
        "cpu": {"path": "f32 per-step dispatch, 1-device CPU mesh",
                "val": ref["val"]},
        "steps_compared": len(steps),
        "mean_abs_dloss": sum(dloss) / len(dloss),
        "max_abs_dloss": max(dloss),
        "max_rel_dloss": max(rel),
        "final_val_error_gap": abs(out["val"]["error"] - ref["val"]["error"]),
    }
    with open(os.path.join(RESULTS, "wrn_tpu_vs_cpu.json"), "w") as f:
        json.dump(cmp_out, f, indent=1)
    print(json.dumps({"name": "wrn_tpu_vs_cpu", **{
        k: cmp_out[k] for k in ("mean_abs_dloss", "max_abs_dloss",
                                "final_val_error_gap")}}))
    return [out, ref]


def exp_rules_scale() -> list[dict]:
    """Async-rule convergence at n=32 and n=64 workers (round-3 verdict
    item 7): the gang-scheduled EASGD/GoSGD redesigns' documented law
    divergence is most at risk at high worker counts (BASELINE config #5
    is 64 workers). Same synthetic task, per-worker batch 16, lr, and
    320-step budget as the committed n=8 curves (exp_rules), so the
    trend vs n is directly comparable;
    BSP at the same global images/step is the reference point."""
    os.makedirs(RESULTS, exist_ok=True)
    runs = []
    for n in (16, 32, 64):
        common = dict(
            devices=n,
            n_epochs=1000,
            max_steps=320,
            dataset="synthetic",
            dataset_kwargs={"n_train": 4096, "n_val": 512,
                            "image_shape": [16, 16, 3]},
            recipe_overrides={
                "input_shape": (16, 16, 3),
                "n_epochs": 1000,
                # global batch reaches 16x64=1024 > n_val: pin the val
                # batch so validation never silently empties
                "val_batch_size": 256,
                "sched_kwargs": {"lr": 0.02, "boundaries": [10**9]},
            },
            seed=7,
            print_freq=0,
        )
        async_over = {**common["recipe_overrides"], "batch_size": 16}
        runs.append(_run(f"bsp_n{n}", dict(
            common, rule="bsp", run_name=f"bsp_n{n}",
            recipe_overrides={**common["recipe_overrides"],
                              "batch_size": 16 * n,
                              "sched_kwargs": {"lr": 0.05,
                                               "boundaries": [10**9]}},
        ), n_devices=n))
        runs.append(_run(f"easgd_n{n}", dict(
            common, rule="easgd", avg_freq=8, run_name=f"easgd_n{n}",
            recipe_overrides=async_over,
        ), n_devices=n))
        if n > 16:
            # symmetric EASGD's elastic coupling is alpha = beta/n
            # (paper default beta=0.9): at n>=32 the per-worker pull
            # weakens 1/n and the center lags at a fixed step budget.
            # More frequent exchange compensates (same wire/step as
            # n=8 @ avg_freq=8 per worker) — committed as the tuning
            # note for beyond-config-#4 worker counts.
            runs.append(_run(f"easgd_n{n}_freq2", dict(
                common, rule="easgd", avg_freq=2,
                run_name=f"easgd_n{n}_freq2",
                recipe_overrides=async_over,
            ), n_devices=n))
        runs.append(_run(f"gosgd_n{n}", dict(
            common, rule="gosgd", p_push=0.25, run_name=f"gosgd_n{n}",
            recipe_overrides=async_over,
        ), n_devices=n))
    return runs


def exp_easgd_law() -> list[dict]:
    """EASGD worker-count compensation law (round-4 verdict item 3).

    Symmetric EASGD couples each worker to the center with elastic rate
    ``alpha = beta/n`` (beta=0.9 paper default), so the per-step worker
    <-> center coupling is ``alpha/avg_freq ~ beta/(n*avg_freq)``: at a
    fixed step budget, consolidation stalls as n grows unless
    ``n * avg_freq`` is held constant. The committed n=8 baseline ran
    avg_freq=8 (n*avg_freq = 64), and the round-4 sweep already
    CONFIRMS the law at n=32: avg_freq=2 (n*avg_freq=64) recovered
    0% val error where avg_freq=8 (256) sat at 91%. This experiment
    completes the panel at the law's prescription — n=16 -> avg_freq=4,
    n=64 -> avg_freq=1 — and emits a steps-to-accuracy table
    (results/time_to_accuracy.json) across every committed scale run so
    the BASELINE.md "EASGD vs BSP: competitive time-to-accuracy" row has
    direct evidence (config #4 is 1 center + 16 workers)."""
    os.makedirs(RESULTS, exist_ok=True)
    runs = []
    for n, freq in ((16, 4), (64, 1)):
        common = dict(
            devices=n,
            n_epochs=1000,
            max_steps=320,
            dataset="synthetic",
            dataset_kwargs={"n_train": 4096, "n_val": 512,
                            "image_shape": [16, 16, 3]},
            recipe_overrides={
                "input_shape": (16, 16, 3),
                "n_epochs": 1000,
                "val_batch_size": 256,
                "batch_size": 16,
                "sched_kwargs": {"lr": 0.02, "boundaries": [10**9]},
            },
            seed=7,
            print_freq=0,
        )
        runs.append(_run(f"easgd_n{n}_freq{freq}", dict(
            common, rule="easgd", avg_freq=freq,
            run_name=f"easgd_n{n}_freq{freq}",
        ), n_devices=n))
    _write_time_to_accuracy()
    return runs


def _write_time_to_accuracy(threshold: float = 0.05) -> None:
    """Steps-to-accuracy panel over every committed scale run: the first
    step whose epoch-val error is <= ``threshold`` (and the final val
    error), per rule and worker count — the reference's own framing for
    comparing sync rules (BASELINE.md 'EASGD vs BSP')."""
    import glob as _glob

    panel = {}
    for d in sorted(os.listdir(RESULTS)):
        run_dir = os.path.join(RESULTS, d)
        if d.split("_")[0] not in ("bsp", "easgd", "gosgd"):
            continue
        # the run's single recorder JSONL, whatever its run_name (the
        # n=8 baselines predate run_name and carry cifar10_<rule>.jsonl)
        files = _glob.glob(os.path.join(run_dir, "*.jsonl"))
        if len(files) != 1:
            continue
        jsonl = files[0]
        vals, last_step = [], 0
        with open(jsonl) as f:
            for line in f:
                r = json.loads(line)
                if r.get("kind") == "train":
                    last_step = max(last_step, int(r["step"]))
                elif r.get("kind") == "val":
                    vals.append((last_step, r.get("error")))
        if not vals or vals[-1][1] is None:
            continue
        reached = next((s for s, e in vals if e <= threshold), None)
        panel[d] = {
            "steps_to_{:.0%}_err".format(threshold): reached,
            "final_val_error": vals[-1][1],
            "val_points": len(vals),
        }
    out = {"threshold": threshold, "runs": panel,
           "note": ("steps are optimization steps; async rules process "
                    "n_workers x 16 images/step, BSP the same global "
                    "batch — identical images/step at equal worker count")}
    with open(os.path.join(RESULTS, "time_to_accuracy.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"name": "time_to_accuracy",
                      "runs": {k: v["final_val_error"]
                               for k, v in panel.items()}}))


def main(argv=None) -> int:
    which = (argv or sys.argv[1:] or ["all"])[0]
    results = []
    if which in ("rules", "all"):
        results += exp_rules()
    if which in ("digits", "all"):
        results += exp_digits()
    if which in ("wrn", "all"):
        results += exp_wrn()
    if which in ("wrn_tpu",):
        # not part of "all": needs the real chip (the default tiers stay
        # reproducible on any host); run explicitly on TPU hardware
        results += exp_wrn_tpu()
    if which in ("rules_scale", "all"):
        results += exp_rules_scale()
    if which in ("easgd_law", "all"):
        results += exp_easgd_law()
    os.makedirs(RESULTS, exist_ok=True)
    # merge by name so a partial run ("rules" / "digits") does not drop
    # the other experiments' entries from the summary
    path = os.path.join(RESULTS, "summary.json")
    merged = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                merged = {r["name"]: r for r in json.load(f)}
        except (json.JSONDecodeError, KeyError, TypeError):
            pass  # a truncated/garbled summary must not sink fresh results
    merged.update({r["name"]: r for r in results})
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(list(merged.values()), f, indent=1)
    os.replace(tmp, path)  # atomic: no torn summary on interrupt
    return 0


if __name__ == "__main__":
    sys.exit(main())
