"""Committed convergence experiments (SURVEY.md §4: "correctness is
validated by convergence curves"; §7 hard-part 1: the synchronous
EASGD/GoSGD redesigns need empirical convergence parity vs BSP).

Two experiments, both run on the virtual 8-device CPU mesh so anyone
can reproduce them without hardware:

1. ``rules``  — BSP vs EASGD vs GoSGD, same model, same step budget, on
   the seeded synthetic task. The async rules use per-worker batches
   (reference semantics), so their images/step is 8x BSP's per-batch —
   the comparison is at a fixed STEP budget, matching how the reference
   compared rules (iterations of local SGD + exchange).
2. ``digits`` — BSP on REAL data (sklearn's bundled handwritten digits;
   the only real image dataset available offline — stands in for
   BASELINE config #1 until cifar-10-batches-py is on disk; the same
   command with ``--dataset cifar10`` runs the real config #1).

Writes recorder JSONL per run + results/summary.json. Run:

    python experiments/run_convergence.py [rules|digits|all]
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RESULTS = os.path.join(HERE, "results")

_CHILD = """
import os, json, sys
import jax
jax.config.update("jax_platforms", "cpu")
from theanompi_tpu.launch.worker import run_training
from theanompi_tpu.models.cifar10 import Cifar10_model

spec = json.loads(sys.argv[1])
summary = run_training(model_cls=Cifar10_model, **spec["kwargs"])
print("RESULT " + json.dumps({
    "name": spec["name"],
    "val": summary.get("val"),
    "steps": summary["steps"],
}))
"""


def _run(name: str, kwargs: dict, n_devices: int = 8) -> dict:
    # fresh per-run dir, replaced only on SUCCESS: the Recorder APPENDS
    # to existing JSONL (a naive rerun would accumulate runs in one
    # artifact), and deleting up front would destroy the committed
    # evidence if the child fails
    run_dir = os.path.join(RESULTS, name)
    tmp_dir = run_dir + ".new"
    shutil.rmtree(tmp_dir, ignore_errors=True)
    kwargs = dict(kwargs, save_dir=tmp_dir)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    spec = {"name": name, "kwargs": kwargs}
    p = subprocess.run(
        [sys.executable, "-c", _CHILD, json.dumps(spec)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=3600,
    )
    if p.returncode != 0:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        sys.stderr.write(p.stdout[-1000:] + "\n" + p.stderr[-3000:])
        raise RuntimeError(f"experiment {name} failed")
    shutil.rmtree(run_dir, ignore_errors=True)
    os.rename(tmp_dir, run_dir)
    line = [l for l in p.stdout.splitlines() if l.startswith("RESULT ")][-1]
    out = json.loads(line[len("RESULT "):])
    print(json.dumps(out))
    return out


def exp_rules() -> list[dict]:
    """BSP vs EASGD vs GoSGD at n=8, fixed 320-step budget, synthetic.

    Per-worker batch 16 for the async rules (global 128/step); BSP uses
    global batch 128 — identical images/step across rules.
    """
    os.makedirs(RESULTS, exist_ok=True)
    common = dict(
        devices=8,
        n_epochs=100,  # truncated by max_steps
        max_steps=320,
        dataset="synthetic",
        dataset_kwargs={"n_train": 2048, "n_val": 512,
                        "image_shape": [16, 16, 3]},
        recipe_overrides={
            "input_shape": (16, 16, 3),
            "n_epochs": 100,
            "sched_kwargs": {"lr": 0.05, "boundaries": [10**9]},
        },
        seed=7,
        print_freq=0,
        save_dir=RESULTS,
    )
    runs = []
    runs.append(_run("bsp", dict(
        common, rule="bsp",
        recipe_overrides={**common["recipe_overrides"], "batch_size": 128},
    )))
    # Async rules: per-worker batch 16 local SGD needs a cooler LR than
    # the 128-batch lockstep run (the reference likewise tuned per rule)
    async_over = {
        **common["recipe_overrides"], "batch_size": 16,
        "sched_kwargs": {"lr": 0.02, "boundaries": [10**9]},
    }
    runs.append(_run("easgd", dict(
        common, rule="easgd", avg_freq=8,
        recipe_overrides=async_over,
    )))
    runs.append(_run("gosgd", dict(
        common, rule="gosgd", p_push=0.25,
        recipe_overrides=async_over,
    )))
    return runs


def exp_digits() -> list[dict]:
    """BSP on real data (digits), 15 epochs — the model must exceed 90%
    val accuracy for the experiment to count as converged."""
    os.makedirs(RESULTS, exist_ok=True)
    out = _run("digits_bsp", dict(
        rule="bsp",
        devices=8,
        n_epochs=15,
        dataset="digits",
        dataset_kwargs={"size": 16},
        recipe_overrides={
            "batch_size": 128,
            "input_shape": (16, 16, 3),
            "n_epochs": 15,
            "sched_kwargs": {"lr": 0.05, "boundaries": [10, 13],
                             "factor": 0.1},
        },
        seed=3,
        print_freq=0,
        save_dir=RESULTS,
    ))
    return [out]


def main(argv=None) -> int:
    which = (argv or sys.argv[1:] or ["all"])[0]
    results = []
    if which in ("rules", "all"):
        results += exp_rules()
    if which in ("digits", "all"):
        results += exp_digits()
    os.makedirs(RESULTS, exist_ok=True)
    # merge by name so a partial run ("rules" / "digits") does not drop
    # the other experiments' entries from the summary
    path = os.path.join(RESULTS, "summary.json")
    merged = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                merged = {r["name"]: r for r in json.load(f)}
        except (json.JSONDecodeError, KeyError, TypeError):
            pass  # a truncated/garbled summary must not sink fresh results
    merged.update({r["name"]: r for r in results})
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(list(merged.values()), f, indent=1)
    os.replace(tmp, path)  # atomic: no torn summary on interrupt
    return 0


if __name__ == "__main__":
    sys.exit(main())
