"""GoogLeNet layout experiments (round-4 verdict item 6).

The round-5 op profile (tools/op_profile.py, v5e, batch 1024, step ~202
ms) localizes the MFU floor: pooling is ~35% of the step
(select-and-scatter backward 17.9% + reduce_window-max forward fusions
~14% + pad_maximum ~3%), generic conv/elementwise fusions 46%, LRN 3.7%
— and concatenate is INVISIBLE (copy/slice ops ~1.5% total), so the
"concat-free inception output" hypothesis is rejected by measurement
before any rewrite: there is no concat time to recover.

This probe measures the two remaining verdict hypotheses:

1. **batch 2048 (and 512)** — full-model fused-step throughput vs the
   committed batch-1024 row (pool/BN-style sweeps scale with batch, but
   bigger batches can fill the MXU better on the small-channel convs);
2. **channels-major trunk** — the dominant stride-1 3x3 max pool and a
   full inception module (convs + pool + concat), forward+backward, in
   NHWC vs NCHW, with and without entry/exit transposes. If C-major
   wins at the module level, the trunk rewrite is justified; if it
   loses, this probe is the committed measured-and-rejected evidence.

Writes results/googlenet_layout.json. Run on the real chip:

    python experiments/googlenet_layout_probe.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS = os.path.join(HERE, "results")


_LAT = None


def _latency() -> float:
    """Median host<->device round trip (~115 ms through the tunnel) —
    subtracted from every fetch-synced measurement below."""
    global _LAT
    if _LAT is None:
        ts = []
        for i in range(5):
            t0 = time.perf_counter()
            float(jnp.sum(jnp.ones(()) * i))
            ts.append(time.perf_counter() - t0)
        _LAT = float(np.median(ts))
    return _LAT


def _median_time(fn, *args, trials=3):
    """Fetch-synced wall clock: ``fn`` must return a SCALAR; syncing is
    an actual host fetch of it (on the tunneled chip block_until_ready
    can return without blocking — bench.py documents the fault — so a
    dispatch-timed 'measurement' reads ~100x too fast; the first probe
    revision measured a 192 MB pool fwd+bwd at 0.09 ms, beyond the HBM
    read bound, exactly that failure). The separately measured round
    trip is subtracted."""
    lat = _latency()
    val = float(np.asarray(fn(*args)).sum())
    assert np.isfinite(val), "probe program produced non-finite output"
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(np.asarray(fn(*args)).sum())
        ts.append(time.perf_counter() - t0 - lat)
    med = float(np.median(ts))
    if med < 4 * lat:
        # the work window must dominate latency jitter or the number is
        # noise — callers loop the op inside the program to get there
        raise RuntimeError(
            f"probe window {med*1e3:.1f} ms < 4x round-trip "
            f"{lat*1e3:.1f} ms: raise the in-program repeat count"
        )
    return med


def _measure_scaled(build, k0: int = 256):
    """Per-op time via an in-program ``lax.scan`` of ``k`` repetitions
    (input varied per iteration to defeat CSE); ``k`` escalates until
    the window dominates the tunnel round trip."""
    k = k0
    while True:
        try:
            return _median_time(build(k)) / k
        except RuntimeError:
            if k >= 8192:
                raise
            k *= 4


def full_model(batch: int, steps: int = 8) -> dict:
    """Fused-step throughput for the whole GoogLeNet at ``batch`` —
    same construction as bench.py compute mode (single chip); synced by
    fetching the stacked losses (8 steps x ~200 ms dominates the
    round trip), executed-work-checked via the device step counter."""
    from theanompi_tpu.models.googlenet import GoogLeNet
    from theanompi_tpu.train import init_train_state, make_multi_step, make_train_step

    model = GoogLeNet(GoogLeNet.default_recipe().replace(batch_size=batch))
    runner = jax.jit(make_multi_step(make_train_step(model), steps))
    state = init_train_state(model, jax.random.PRNGKey(0))
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(batch, 224, 224, 3), jnp.float32)
    y = jnp.asarray(r.randint(0, 1000, batch), jnp.int32)
    t = _median_time(
        lambda: runner(state, x, y, jax.random.PRNGKey(1))[1]["loss"]
    )
    got = int(np.asarray(
        runner(state, x, y, jax.random.PRNGKey(1))[0].step
    ))
    assert got == steps, f"executed {got} != {steps}"
    return {"batch": batch, "img_s": round(steps * batch / t, 1),
            "step_ms": round(1000 * t / steps, 2)}


def _pool_fwd_bwd(layout: str, B=256, H=28, W=28, C=480):
    """Stride-1 3x3 SAME max pool fwd+bwd — the op family carrying ~35%
    of the GoogLeNet step — in NHWC vs NCHW."""
    r = np.random.RandomState(0)
    if layout == "NHWC":
        x = jnp.asarray(r.randn(B, H, W, C), jnp.bfloat16)
        dims, strides = (1, 3, 3, 1), (1, 1, 1, 1)
        pad = ((0, 0), (1, 1), (1, 1), (0, 0))
    else:
        x = jnp.asarray(r.randn(B, C, H, W), jnp.bfloat16)
        dims, strides = (1, 1, 3, 3), (1, 1, 1, 1)
        pad = ((0, 0), (0, 0), (1, 1), (1, 1))

    def loss(x):
        y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def build(k):
        @jax.jit
        def run():
            def body(acc, i):
                g = jax.grad(loss)(x + i.astype(x.dtype))
                return acc + jnp.sum(g.astype(jnp.float32)), None

            acc, _ = lax.scan(body, jnp.float32(0.0),
                              jnp.arange(k, dtype=jnp.int32))
            return acc

        return run

    return _measure_scaled(build)


def _inception_fwd_bwd(layout: str, B=256, H=28, W=28, Cin=480,
                       transpose_io: bool = False):
    """One inception-4a-shaped module (1x1 / 1x1-3x3 / 1x1-5x5 /
    pool-1x1, concat) fwd+bwd in NHWC vs NCHW. ``transpose_io`` adds
    the entry/exit transposes a C-major TRUNK would amortize away —
    both numbers are reported so the trunk-level decision is honest."""
    c1, c3r, c3, c5r, c5, cp = 192, 96, 208, 16, 48, 64
    r = np.random.RandomState(0)
    nhwc = layout == "NHWC"
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")

    def mk(shape):
        return jnp.asarray(0.05 * r.randn(*shape), jnp.bfloat16)

    if nhwc:
        ws = {
            "w1": mk((1, 1, Cin, c1)), "w3r": mk((1, 1, Cin, c3r)),
            "w3": mk((3, 3, c3r, c3)), "w5r": mk((1, 1, Cin, c5r)),
            "w5": mk((5, 5, c5r, c5)), "wp": mk((1, 1, Cin, cp)),
        }
    else:
        ws = {
            "w1": mk((c1, Cin, 1, 1)), "w3r": mk((c3r, Cin, 1, 1)),
            "w3": mk((c3, c3r, 3, 3)), "w5r": mk((c5r, Cin, 1, 1)),
            "w5": mk((c5, c5r, 5, 5)), "wp": mk((cp, Cin, 1, 1)),
        }
    x = jnp.asarray(
        r.randn(*(B, H, W, Cin) if nhwc or transpose_io else (B, Cin, H, W)),
        jnp.bfloat16,
    )
    caxis = 3 if nhwc else 1
    if nhwc:
        dims, strides = (1, 3, 3, 1), (1, 1, 1, 1)
        pad = ((0, 0), (1, 1), (1, 1), (0, 0))
    else:
        dims, strides = (1, 1, 3, 3), (1, 1, 1, 1)
        pad = ((0, 0), (0, 0), (1, 1), (1, 1))

    def conv(h, w):
        return jax.nn.relu(
            lax.conv_general_dilated(h, w, (1, 1), "SAME",
                                     dimension_numbers=dn)
        )

    def loss(ws, x):
        if not nhwc and transpose_io:
            x = jnp.transpose(x, (0, 3, 1, 2))  # entry transpose
        y1 = conv(x, ws["w1"])
        y3 = conv(conv(x, ws["w3r"]), ws["w3"])
        y5 = conv(conv(x, ws["w5r"]), ws["w5"])
        yp = conv(
            lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad),
            ws["wp"],
        )
        out = jnp.concatenate([y1, y3, y5, yp], axis=caxis)
        if not nhwc and transpose_io:
            out = jnp.transpose(out, (0, 2, 3, 1))  # exit transpose
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def build(k):
        @jax.jit
        def run():
            def body(acc, i):
                g = jax.grad(loss)(ws, x + i.astype(x.dtype))
                return acc + sum(
                    jnp.sum(l.astype(jnp.float32))
                    for l in jax.tree_util.tree_leaves(g)
                ), None

            acc, _ = lax.scan(body, jnp.float32(0.0),
                              jnp.arange(k, dtype=jnp.int32))
            return acc

        return run

    return _measure_scaled(build)


def main() -> int:
    os.makedirs(RESULTS, exist_ok=True)
    path = os.path.join(RESULTS, "googlenet_layout.json")
    out = {"device": jax.devices()[0].device_kind}

    def flush():
        with open(path, "w") as f:
            json.dump(out, f, indent=1)

    out["pool_3x3s1_ms"] = {
        "NHWC": round(1000 * _pool_fwd_bwd("NHWC"), 2),
        "NCHW": round(1000 * _pool_fwd_bwd("NCHW"), 2),
        "shape": "[256, 28, 28, 480] bf16, fwd+bwd",
    }
    print("pool:", out["pool_3x3s1_ms"], flush=True)
    flush()

    out["inception_4ash_ms"] = {
        "NHWC": round(1000 * _inception_fwd_bwd("NHWC"), 2),
        "NCHW_resident": round(1000 * _inception_fwd_bwd("NCHW"), 2),
        "NCHW_transposed_io": round(
            1000 * _inception_fwd_bwd("NCHW", transpose_io=True), 2
        ),
        "shape": "[256, 28, 28, 480] bf16 in, 512 out, fwd+bwd",
    }
    print("inception:", out["inception_4ash_ms"], flush=True)
    flush()

    out["full_model"] = []
    for batch in (512, 1024, 2048):
        try:
            out["full_model"].append(full_model(batch))
        except Exception as e:  # OOM at 2048 IS a measured result
            out["full_model"].append(
                {"batch": batch, "error": type(e).__name__,
                 "detail": str(e).splitlines()[0][:120]}
            )
        print("full model:", out["full_model"][-1], flush=True)
        flush()

    print(json.dumps({"name": "googlenet_layout", "done": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
