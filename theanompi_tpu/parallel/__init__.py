"""Parallelism layer: mesh runtime, exchanger strategies, sync rules.

TPU-native replacement for the reference's process/communication stack
(SURVEY.md §1 L1-L2): ``lib/base.py`` (``MPI_GPU_Process``, MPI world +
NCCL clique), ``lib/exchanger.py`` (``BSP_Exchanger`` / ``EASGD_Exchanger``
/ ``GOSGD_Exchanger``) and ``lib/exchanger_strategy.py`` (the pluggable
allreduce implementations). One SPMD program over a named
``jax.sharding.Mesh`` replaces process-per-GPU + mpirun; collectives
compiled into the step replace between-step MPI calls.
"""

from theanompi_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    make_mesh,
    host_local_batch_slice,
)
from theanompi_tpu.parallel.strategies import get_strategy  # noqa: F401
from theanompi_tpu.parallel.bsp import make_bsp_train_step, make_bsp_eval_step  # noqa: F401
from theanompi_tpu.parallel.pipeline import (  # noqa: F401
    PIPE_AXIS,
    make_pp_train_step,
    stack_pipeline_params,
    unstack_pipeline_params,
)
from theanompi_tpu.parallel.zero import make_zero1_train_step  # noqa: F401
