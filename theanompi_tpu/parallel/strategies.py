"""Pluggable gradient-exchange strategies.

TPU-native rebuild of the reference's exchanger strategy layer
(reference: ``lib/exchanger_strategy.py`` — ``Exch_allreduce`` (host
MPI), ``Exch_copper``/``Exch_cudaaware`` (GPU-direct MPI), ``Exch_asa32``
/ ``Exch_asa16`` (hand-rolled alternating-segmented ring allreduce, fp32
and fp16-compressed), ``Exch_nccl32``/``Exch_nccl16`` (NCCL); SURVEY.md
§2.1, §5.8).

A strategy is a function ``grads -> synced_grads`` executed INSIDE the
compiled SPMD step (under ``shard_map``), where the reference ran Python
MPI calls between Theano calls. All strategies produce the **mean**
gradient across the data axis.

Like the reference's ``BSP_Exchanger``, gradients are packed into one
contiguous buffer before the collective (the paper's "big fused buffer"
optimization) — for ``psum`` XLA would fuse anyway, but for the explicit
ring variants the single buffer is what makes segmentation work.

Strategy names keep the reference's config vocabulary as aliases:
``ar``/``cudaaware``/``nccl32`` -> psum, ``asa32`` -> ring,
``asa16``/``nccl16`` -> ring_bf16 / psum_bf16.

check_vma pin & migration plan
------------------------------
Every shard_map in this framework passes ``check_vma=False``, because
the whole strategy abstraction assumes classic pmap AD semantics: the
transpose of a forward psum is a psum, so each device's backward yields
its LOCAL gradient contribution and the strategy's explicit collective
completes the global mean. Under ``check_vma=True`` (the modern
default) the cotangent of a replicated parameter arrives ALREADY
globally summed — running any strategy here on top of that would
multiply by the axis size. Both behaviors are pinned by a canary
(tests/test_check_vma_canary.py, measured on jax 0.9.0) that fails
loudly if a JAX upgrade changes either side.

Migration (executed when the canary trips, or deliberately): in checked
mode the exchanger degenerates to ``g / axis_size`` with NO collective
for the psum family — a working checked-mode BSP step lives in the
canary file as the prototype. The explicit ring/compressed strategies
do not survive the migration as gradient SYNCS (AD already summed), but
remain useful as weight-exchange collectives (EASGD/GoSGD param
averaging) and would move there. The migration must flip all shard_maps
at once — grep ``check_vma=False``; a mixed tree double-counts.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

PyTree = Any
Strategy = Callable[[PyTree], PyTree]


def _packed(fn):
    """Wrap a flat-buffer collective into a pytree strategy: pack all
    gradient leaves into one contiguous fp32 vector, run the collective,
    unpack (reference: ``BSP_Exchanger`` pre-concatenated per-param GPU
    buffers into one big comm buffer)."""

    def strategy(grads: PyTree) -> PyTree:
        flat, unravel = ravel_pytree(grads)
        out = fn(flat.astype(jnp.float32))
        return unravel(out.astype(flat.dtype))

    return strategy


# --------------------------------------------------------------------------
# psum family — XLA-native allreduce (≙ Exch_nccl32 / Exch_allreduce /
# Exch_cudaaware: on TPU, one ICI collective replaces all three tiers)
# --------------------------------------------------------------------------


def psum_mean(axis_name: str) -> Strategy:
    def strategy(grads):
        return lax.pmean(grads, axis_name)

    return strategy


def psum_bf16(axis_name: str) -> Strategy:
    """Compressed allreduce: bf16 operands into a single pmean
    (≙ ``Exch_nccl16``; see also EQuARX, PAPERS.md). NOTE: XLA reduces in
    the operand dtype, so accumulation here is bf16 too — cheapest, but at
    large worker counts low-order gradient bits are lost; ``ring_bf16``
    is the bf16-wire / fp32-accumulate variant."""

    def strategy(grads):
        return jax.tree_util.tree_map(
            lambda g: lax.pmean(g.astype(jnp.bfloat16), axis_name).astype(g.dtype),
            grads,
        )

    return strategy


# --------------------------------------------------------------------------
# explicit segmented ring — ≙ Exch_asa32 / Exch_asa16
# --------------------------------------------------------------------------


def _ring_allreduce_flat(
    flat: jax.Array, axis_name: str, n: int, wire: Optional[str] = None
) -> jax.Array:
    """Alternating-segmented ring allreduce on a flat fp32 buffer:
    reduce-scatter (n-1 ppermute steps) + allgather (n-1 steps), the
    algorithm the reference hand-rolled over ``MPI.Sendrecv`` segments
    (reference: ``lib/exchanger_strategy.py`` — ``Exch_asa32``).

    ``wire`` compresses each transferred segment: ``"bf16"`` casts (≙ the
    fp16 compression of ``Exch_asa16``), ``"int8"`` quantizes with a
    per-segment scale through the Pallas kernels in ops/pallas_quant.py
    (EQuARX-style, 4x wire compression); accumulation stays fp32 either
    way. Returns the SUM; caller divides for the mean.
    """
    if n == 1:
        return flat
    L = flat.shape[0]
    seg = -(-L // n)
    if wire == "int8":
        # the quantizer's lane layout needs 128-multiple segments
        seg = -(-seg // 128) * 128
    buf = jnp.zeros((n, seg), flat.dtype).reshape(-1).at[:L].set(flat).reshape(n, seg)
    # mark the carry device-varying so the fori_loop carry types line up
    # under shard_map's varying-manual-axes checking
    buf = lax.pcast(buf, axis_name, to="varying")
    rank = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    if wire not in (None, "bf16", "int8"):
        raise ValueError(f"unknown wire compression {wire!r} (None|bf16|int8)")

    def send(chunk):
        if wire == "int8":
            # the codec layer owns the packed int8 wire format (block-
            # scaled values + scale tail rows); the ring is a consumer
            from theanompi_tpu.parallel.codec import wire_decode, wire_encode

            # ONE packed message per hop (values + scale bytes)
            return wire_decode(lax.ppermute(wire_encode(chunk), axis_name, fwd))
        if wire == "bf16":
            chunk = chunk.astype(jnp.bfloat16)
        out = lax.ppermute(chunk, axis_name, fwd)
        return out.astype(flat.dtype)

    def rs_step(t, b):
        idx_send = jnp.mod(rank - t, n)
        idx_recv = jnp.mod(rank - t - 1, n)
        recv = send(jnp.take(b, idx_send, axis=0))
        return b.at[idx_recv].add(recv)

    buf = lax.fori_loop(0, n - 1, rs_step, buf)
    # node r now owns the fully-reduced segment (r + 1) mod n

    if wire == "int8":
        # Allgather with PACKED forwarding: the owner quantizes its
        # reduced segment ONCE; the int8 bytes then travel every hop
        # UNCHANGED and every device (owner included) decodes the same
        # message. Re-quantizing at each hop is NOT bit-idempotent (the
        # re-derived scale fl(fl(127*s)/127) drifts 1 ulp on ~3% of
        # buffers — found empirically in review), which would leave
        # replicas at different hop distances holding different values
        # and break BSP's replicated-state invariant. Packed forwarding
        # is also cheaper: one quantize total instead of one per hop.
        from theanompi_tpu.parallel.codec import wire_decode, wire_encode

        own = jnp.mod(rank + 1, n)
        packed = wire_encode(jnp.take(buf, own, axis=0))
        buf = buf.at[own].set(wire_decode(packed))

        def ag_step_packed(t, carry):
            b, pk = carry
            pk = lax.ppermute(pk, axis_name, fwd)
            idx_recv = jnp.mod(rank - t, n)
            return b.at[idx_recv].set(wire_decode(pk)), pk

        buf, _ = lax.fori_loop(0, n - 1, ag_step_packed, (buf, packed))
        return buf.reshape(-1)[:L]

    if wire == "bf16":
        # bf16 re-cast IS exact (value already representable), so the
        # plain hop loop keeps replicas identical once the owner's kept
        # segment is cast-aligned with what receivers hold
        own = jnp.mod(rank + 1, n)
        buf = buf.at[own].set(
            jnp.take(buf, own, axis=0).astype(jnp.bfloat16).astype(flat.dtype)
        )

    def ag_step(t, b):
        idx_send = jnp.mod(rank + 1 - t, n)
        idx_recv = jnp.mod(rank - t, n)
        recv = send(jnp.take(b, idx_send, axis=0))
        return b.at[idx_recv].set(recv)

    buf = lax.fori_loop(0, n - 1, ag_step, buf)
    return buf.reshape(-1)[:L]


def ring(axis_name: str, axis_size: int) -> Strategy:
    return _packed(
        lambda flat: _ring_allreduce_flat(flat, axis_name, axis_size) / axis_size
    )


def ring_bf16(axis_name: str, axis_size: int) -> Strategy:
    return _packed(
        lambda flat: _ring_allreduce_flat(flat, axis_name, axis_size, wire="bf16")
        / axis_size
    )


def ring_int8(axis_name: str, axis_size: int) -> Strategy:
    """int8-wire ring: each segment quantized (Pallas kernel, per-segment
    absmax scale) before the hop, dequantized and accumulated in fp32 —
    4x less ICI/DCN traffic than fp32, 2x less than bf16. Quantization
    noise is bounded by amax/254 per hop; suitable for gradient exchange
    (EQuARX, PAPERS.md), not for exact parity checks."""
    return _packed(
        lambda flat: _ring_allreduce_flat(flat, axis_name, axis_size, wire="int8")
        / axis_size
    )


# --------------------------------------------------------------------------
# codec-compressed psum — the codec layer (parallel/codec.py) applied to
# the default in-step gradient allreduce: quantize each device's LOCAL
# grads (error-feedback residual threaded through engine state), mean
# in fp32. The stateful form is the generalization of psum_bf16 /
# ring_int8 that EVERY engine's exchange shares.
# --------------------------------------------------------------------------


def codec_psum_mean(axis_name, codec) -> Strategy:
    """Compressed allreduce ``(grads, ef) -> (mean grads, ef')``; the
    error-feedback residuals arrive STACKED ``[1, ...]`` per device
    (engine-state convention — see codec.compress_stacked). Marked
    ``stateful`` so train.make_train_step threads ``state.ef``."""

    def strategy(grads, ef):
        wire, ef = codec.compress_stacked(grads, ef)
        return lax.pmean(wire, axis_name), ef

    strategy.stateful = True
    return strategy


# --------------------------------------------------------------------------
# hierarchical two-hop exchange — the topology-aware 'hier' strategy
# (GC3-style staged schedule, arXiv:2201.11840; EQuARX's quantize-the-
# starved-hop result, arXiv:2506.17615): in-slice reduce-scatter over
# ICI, cross-slice allreduce over DCN on ONLY the scattered 1/s shards
# (the wire codec applies to this hop alone, where bytes dominate),
# then in-slice all-gather. Codec-off it moves exactly flat psum's
# 2(n-1)/n·N·b total wire, re-split (s-1)/s·N·b + (s-1)/s·N·b on ICI
# and 2(r-1)/r·(N/s)·b on DCN — but the DCN share shrinks by the slice
# width s, which is what keeps scaling efficiency up when a second
# slice joins the mesh (ROADMAP item 4).
# --------------------------------------------------------------------------


def hier_segment(n_elements: int, ici_size: int) -> int:
    """Per-device DCN shard length of the hierarchical exchange: the
    flat gradient buffer padded up to an ``ici_size`` multiple and
    reduce-scattered — ``ceil(N / s)``. The declared two-hop
    TrafficModel (obs/comm.py::bsp_traffic) prices the same geometry,
    which is what makes SPMD101 reconcile byte-exact."""
    return -(-int(n_elements) // max(1, int(ici_size)))


def _check_hier_axes(axis_name, axis_sizes, axis_size=None):
    if isinstance(axis_name, str) or len(tuple(axis_name)) != 2:
        raise ValueError(
            "strategy 'hier' needs a 2-axis (dcn, data) mesh — build it "
            "with make_multislice_mesh (the --slices knob); on a 1-D "
            "mesh there is no slice boundary to schedule around, use "
            "'psum'"
        )
    if not axis_sizes or len(tuple(axis_sizes)) != 2:
        raise ValueError(
            "strategy 'hier' needs axis_sizes=(n_slices, per_slice) in "
            "mesh-axis order (parallel/mesh.py::slice_topology)"
        )
    if axis_size is not None and \
            int(axis_sizes[0]) * int(axis_sizes[1]) != int(axis_size):
        raise ValueError(
            f"hier axis_sizes {tuple(axis_sizes)} do not multiply to the "
            f"mesh size {axis_size}"
        )


def _hier_exchange_flat(flat, dcn_axis, ici_axis, r: int, s: int,
                        dcn_wire=None):
    """One hierarchical allreduce (SUM — caller divides) on a flat fp32
    buffer: reduce-scatter over the in-slice ICI axis (each device ends
    holding the slice-local sum of its 1/s segment), allreduce over the
    cross-slice DCN axis on only that segment (``dcn_wire`` value-space
    compresses this hop alone), all-gather the reduced segments back
    over ICI."""
    L = flat.shape[0]
    seg = hier_segment(L, s)
    if s > 1:
        buf = jnp.zeros((s * seg,), flat.dtype).at[:L].set(flat)
        shard = lax.psum_scatter(buf, ici_axis, scatter_dimension=0,
                                 tiled=True)
    else:
        shard = flat
    if r > 1:
        if dcn_wire is not None:
            shard = dcn_wire(shard)
        shard = lax.psum(shard, dcn_axis)
    if s > 1:
        out = lax.all_gather(shard, ici_axis, tiled=True)
        return out[:L]
    return shard


def hierarchical_sync(axis_names, axis_sizes, codec=None) -> Strategy:
    """The ``hier`` Strategy: ``axis_names = (dcn_axis, ici_axis)`` and
    ``axis_sizes = (n_slices, per_slice)`` in mesh order
    (make_multislice_mesh rows are slices). Codec-off it is a flat pmean
    re-associated slice-first (allclose, not bit-identical — the
    summation tree differs). An active codec compresses ONLY the DCN
    hop: stateless codecs value-space-quantize the in-slice-reduced
    shard before the cross-slice psum; ``:ef`` threads a per-device
    residual on that shard through engine state (stacked ``(1, seg)``
    rows — hier_ef_template), so quantization error is fed back exactly
    where it is introduced."""
    from theanompi_tpu.parallel.codec import get_codec

    dcn_axis, ici_axis = tuple(axis_names)
    r, s = int(axis_sizes[0]), int(axis_sizes[1])
    n = r * s
    codec = get_codec(codec)

    if codec.active and codec.error_feedback:

        def strategy(grads, ef):
            flat, unravel = ravel_pytree(grads)
            fl = flat.astype(jnp.float32)
            L = fl.shape[0]
            seg = hier_segment(L, s)
            if s > 1:
                buf = jnp.zeros((s * seg,), fl.dtype).at[:L].set(fl)
                shard = lax.psum_scatter(buf, ici_axis,
                                         scatter_dimension=0, tiled=True)
            else:
                shard = fl
            if r > 1:
                wire, ef = codec.compress_stacked(shard, ef)
                shard = lax.psum(wire, dcn_axis)
            shard = shard / n
            out = (lax.all_gather(shard, ici_axis, tiled=True)[:L]
                   if s > 1 else shard)
            return unravel(out.astype(flat.dtype)), ef

        strategy.stateful = True
        return strategy

    qdq = codec.qdq if codec.active else None

    def strategy(grads):
        flat, unravel = ravel_pytree(grads)
        out = _hier_exchange_flat(
            flat.astype(jnp.float32), dcn_axis, ici_axis, r, s,
            dcn_wire=qdq,
        ) / n
        return unravel(out.astype(flat.dtype))

    return strategy


def hier_ef_template(params, axis_sizes, bucket_bytes=None):
    """Global error-feedback template for the hier ``:ef`` composition:
    the DCN-shard residual, stacked to one row per device — a single
    ``(n, seg)`` fp32 zeros array whose dim 0 the recipe's ef prefix
    spec shards, so each device holds its own ``(1, seg)`` row (the
    compress_stacked convention). With ``bucket_bytes`` (the bucketed+
    hier+``:ef`` composition) one such array per bucket, ordered like
    assign_buckets, each keyed to that bucket's packed flat segment."""
    r, s = int(axis_sizes[0]), int(axis_sizes[1])
    n = r * s
    leaves = jax.tree_util.tree_leaves(params)

    def _zeros(n_elements):
        return jnp.zeros((n, hier_segment(n_elements, s)), jnp.float32)

    if bucket_bytes is None:
        total = sum(
            int(math.prod(getattr(l, "shape", ()) or ()) or 1)
            for l in leaves
        )
        return _zeros(total)
    return tuple(
        _zeros(sum(
            int(math.prod(getattr(leaves[i], "shape", ()) or ()) or 1)
            for i in idx
        ))
        for idx in assign_buckets(leaves, bucket_bytes)
    )


def _pack_flat(leaves):
    """Concatenate leaves into one flat fp32 buffer (the per-bucket
    packing of the bucketed+hier composition)."""
    flats = [l.astype(jnp.float32).reshape(-1) for l in leaves]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def _unpack_flat(flat, leaves):
    """Inverse of _pack_flat against the original leaves' shapes/dtypes."""
    out, off = [], 0
    for l in leaves:
        sz = int(math.prod(getattr(l, "shape", ()) or ()) or 1)
        out.append(flat[off:off + sz].reshape(jnp.shape(l)).astype(l.dtype))
        off += sz
    return out


# --------------------------------------------------------------------------
# bucketed overlap-with-backward allreduce — GC3-style collective
# scheduling (PAPERS.md, arXiv:2201.11840): chunk the gradient pytree
# into ~MB-sized buckets and launch each bucket's psum AS SOON AS its
# grads are produced, so the collective overlaps the tail of backward
# instead of serializing after it. The ``--allreduce-buckets`` knob.
# --------------------------------------------------------------------------


def _leaf_wire_bytes(leaf) -> int:
    """fp32 wire bytes of one gradient leaf (grads cross the exchanger
    in fp32 regardless of param dtype — see _packed)."""
    return int(math.prod(getattr(leaf, "shape", ()) or ()) or 1) * 4


def assign_buckets(leaves, bucket_bytes: int) -> list:
    """Group leaf INDICES into contiguous buckets of ~``bucket_bytes``,
    walking leaves in REVERSE flatten order: backward produces grads
    for late-forward params first, so reverse-order buckets fill (and
    their collectives launch) in gradient-production order. Leaf
    granularity — a single leaf over the budget gets its own bucket
    (no intra-leaf chunking); deterministic in the leaf sizes."""
    buckets, cur, cur_b = [], [], 0
    for i in reversed(range(len(leaves))):
        b = _leaf_wire_bytes(leaves[i])
        if cur and cur_b + b > bucket_bytes:
            buckets.append(cur)
            cur, cur_b = [], 0
        cur.append(i)
        cur_b += b
    if cur:
        buckets.append(cur)
    return buckets


def bucket_overlap_frac(n_buckets: int) -> float:
    """Schedule-level overlap estimate for the attribution model
    (obs/attribution.py): with B buckets launched as their grads are
    produced, all but the LAST bucket's collective can hide under the
    remaining backward compute — the tail bucket is always exposed.
    ``(B-1)/B``; 0 for the single post-backward collective."""
    n = int(n_buckets or 0)
    return (n - 1) / n if n > 1 else 0.0


class BucketedOverlapSync:
    """Bucketed gradient allreduce with overlap-with-backward.

    Mechanism: each bucket's param leaves pass through a
    ``custom_vjp`` identity tag on the FORWARD side; the tag's backward
    applies the bucket's pmean to the cotangents at the exact point the
    backward pass produces them. Reverse-mode order then interleaves
    the B collectives with the remaining backward computation and XLA's
    async collective scheduling can hide all but the tail bucket
    (``train.make_train_step`` detects ``in_backward`` and wraps the
    params inside the differentiated loss instead of transforming grads
    after it). Numerics are IDENTICAL to the single ``psum_mean``:
    pmean is leafwise, so B per-bucket pmeans compute exactly the same
    per-leaf means (bit-identical — tests/test_bucketed.py).

    Codec composition (parallel/codec.py): stateless codecs (``bf16``,
    plain ``int8``) quantize each bucket's LOCAL cotangents value-space
    before the pmean — ``codec_psum_mean`` per bucket. Error feedback
    (``:ef``) is engine STATE the vjp boundary cannot thread (a
    backward rule yields cotangents, not residuals), so the ``:ef``
    path runs post-backward instead: per-bucket ``compress_stacked`` +
    pmean, stateful — bucketed wire scheduling without the structural
    overlap, EF residuals keyed per bucket's leaves. ``in_backward`` /
    ``stateful`` tell the step builder which contract applies.

    Hierarchical composition (``axis_sizes`` set): each bucket's
    cotangents pack into one flat buffer and run the two-hop
    hierarchical exchange instead of a flat pmean — so every bucket's
    DCN hop (the expensive one) overlaps the remaining backward, and a
    codec compresses only that hop. ``:ef`` residuals become one
    ``(1, seg_b)`` shard-row per bucket (hier_ef_template).
    """

    def __init__(self, axis_name, bucket_mb: float = 8.0, codec=None,
                 axis_sizes=None):
        from theanompi_tpu.parallel.codec import get_codec

        if not bucket_mb or bucket_mb <= 0:
            raise ValueError(
                f"--allreduce-buckets needs a positive bucket size in "
                f"MB, got {bucket_mb!r}"
            )
        self.axis_name = axis_name
        self.bucket_mb = float(bucket_mb)
        self.bucket_bytes = max(1, int(bucket_mb * 2 ** 20))
        self.codec = get_codec(codec)
        self.axis_sizes = (tuple(int(x) for x in axis_sizes)
                           if axis_sizes is not None else None)
        self.hier = self.axis_sizes is not None
        if self.hier:
            _check_hier_axes(axis_name, self.axis_sizes)
        self.stateful = self.codec.active and self.codec.error_feedback
        self.in_backward = not self.stateful

    # -- schedule geometry ---------------------------------------------------
    def buckets_for(self, tree) -> list:
        return assign_buckets(jax.tree_util.tree_leaves(tree),
                              self.bucket_bytes)

    def n_buckets(self, tree) -> int:
        return len(self.buckets_for(tree))

    def overlap_frac(self, tree) -> float:
        if not self.in_backward:
            return 0.0  # post-backward :ef path: nothing hides
        return bucket_overlap_frac(self.n_buckets(tree))

    # -- in-backward path (stateless codecs) ---------------------------------
    def _qdq(self, c):
        if not self.codec.active:
            return c
        # value-space wire compression of the LOCAL contribution, fp32
        # accumulation inside the collective — codec_psum_mean's
        # compress path, minus the residual state
        return self.codec.qdq(c.astype(jnp.float32)).astype(c.dtype)

    def _hier_mean(self, leaves):
        """One bucket's hierarchical exchange: pack the leaves into a
        flat fp32 buffer, two-hop mean (codec on the DCN hop only),
        unpack — the bucketed+hier composition's collective."""
        dcn_axis, ici_axis = tuple(self.axis_name)
        r, s = self.axis_sizes
        out = _hier_exchange_flat(
            _pack_flat(leaves), dcn_axis, ici_axis, r, s,
            dcn_wire=self.codec.qdq if self.codec.active else None,
        ) / (r * s)
        return _unpack_flat(out, leaves)

    def _make_tag(self):
        axis = self.axis_name
        qdq = self._qdq
        hier_mean = self._hier_mean if self.hier else None

        @jax.custom_vjp
        def tag(*leaves):
            return leaves

        def fwd(*leaves):
            return leaves, None

        def bwd(_, cts):
            if hier_mean is not None:
                return tuple(hier_mean(list(cts)))
            return tuple(lax.pmean(qdq(c), axis) for c in cts)

        tag.defvjp(fwd, bwd)
        return tag

    def wrap_params(self, params):
        """Tag the param pytree per bucket INSIDE the differentiated
        loss; the cotangents then arrive at each tag's backward already
        grouped, and the bucket's collective posts right there."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        out = list(leaves)
        tag = self._make_tag()
        for idx in assign_buckets(leaves, self.bucket_bytes):
            tagged = tag(*[leaves[i] for i in idx])
            for j, i in enumerate(idx):
                out[i] = tagged[j]
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- post-backward path (:ef — and the no-tag fallback) ------------------
    def __call__(self, grads, ef=None):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        buckets = assign_buckets(leaves, self.bucket_bytes)
        if not self.stateful:
            out = list(leaves)
            if self.hier:
                for idx in buckets:
                    red = self._hier_mean([leaves[i] for i in idx])
                    for j, i in enumerate(idx):
                        out[i] = red[j]
            else:
                for idx in buckets:
                    for i in idx:
                        out[i] = lax.pmean(self._qdq(leaves[i]),
                                           self.axis_name)
            return jax.tree_util.tree_unflatten(treedef, out)
        if self.hier:
            return self._hier_stateful(leaves, treedef, buckets, ef)
        ef_leaves = jax.tree_util.tree_leaves(ef)
        if len(ef_leaves) != len(leaves):
            raise ValueError(
                f"error-feedback state has {len(ef_leaves)} leaves for a "
                f"{len(leaves)}-leaf grad tree — engine state was not "
                "initialized with init_ef"
            )
        out = [None] * len(leaves)
        new_ef = [None] * len(leaves)
        for idx in buckets:
            # one codec application + one collective per bucket: the EF
            # residuals stay keyed to exactly this bucket's leaves
            sub = [leaves[i] for i in idx]
            esub = [ef_leaves[i] for i in idx]
            wire, e2 = self.codec.compress_stacked(sub, esub)
            red = lax.pmean(wire, self.axis_name)
            for j, i in enumerate(idx):
                out[i] = red[j]
                new_ef[i] = e2[j]
        return (
            jax.tree_util.tree_unflatten(treedef, out),
            jax.tree_util.tree_unflatten(treedef, new_ef),
        )

    def _hier_stateful(self, leaves, treedef, buckets, ef):
        """The bucketed+hier+``:ef`` composition: per bucket, pack the
        grads flat, in-slice reduce-scatter, ``compress_stacked`` the
        DCN shard against that bucket's residual row, cross-slice psum,
        in-slice all-gather, unpack. ``ef`` is one ``(1, seg_b)`` array
        per bucket (hier_ef_template ordering — assign_buckets order)."""
        dcn_axis, ici_axis = tuple(self.axis_name)
        r, s = self.axis_sizes
        n = r * s
        ef_leaves = jax.tree_util.tree_leaves(ef)
        if len(ef_leaves) != len(buckets):
            raise ValueError(
                f"hier error-feedback state has {len(ef_leaves)} shard "
                f"rows for a {len(buckets)}-bucket schedule — engine "
                "state was not initialized with hier_ef_template"
            )
        out = [None] * len(leaves)
        new_ef = []
        for b, idx in enumerate(buckets):
            sub = [leaves[i] for i in idx]
            flat = _pack_flat(sub)
            L = flat.shape[0]
            seg = hier_segment(L, s)
            if s > 1:
                buf = jnp.zeros((s * seg,), flat.dtype).at[:L].set(flat)
                shard = lax.psum_scatter(buf, ici_axis,
                                         scatter_dimension=0, tiled=True)
            else:
                shard = flat
            e2 = ef_leaves[b]
            if r > 1:
                wire, e2 = self.codec.compress_stacked(shard, e2)
                shard = lax.psum(wire, dcn_axis)
            shard = shard / n
            red = (lax.all_gather(shard, ici_axis, tiled=True)[:L]
                   if s > 1 else shard)
            for j, leaf in zip(idx, _unpack_flat(red, sub)):
                out[j] = leaf
            new_ef.append(e2)
        return (
            jax.tree_util.tree_unflatten(treedef, out),
            tuple(new_ef),
        )


def bucketed(name: str, axis_name, axis_size: int, bucket_mb: float,
             codec=None, axis_sizes=None) -> BucketedOverlapSync:
    """``--allreduce-buckets`` entry: validate the (strategy, codec)
    pair and return the bucketed scheduler. psum and hier only — the
    explicit ring variants already own a segmented hop schedule that a
    leaf-bucket layer would fight, and checked-mode AD has no exchanger
    collective to bucket (callers gate on that)."""
    codec = _resolve_codec(name, codec)
    key = _ALIASES.get(name, name)
    if key == "hier":
        _check_hier_axes(axis_name, axis_sizes, axis_size)
        return BucketedOverlapSync(axis_name, bucket_mb=bucket_mb,
                                   codec=codec, axis_sizes=axis_sizes)
    del axis_size  # collectives are axis-name driven; kept for symmetry
    if key != "psum":
        raise ValueError(
            f"--allreduce-buckets needs strategy 'psum' or 'hier' (got "
            f"{name!r}): the explicit ring variants already schedule "
            "their own segments, and compressed wires ride the codec "
            "knob (--wire-codec) on the psum path"
        )
    return BucketedOverlapSync(axis_name, bucket_mb=bucket_mb, codec=codec)


# --------------------------------------------------------------------------
# registry — reference config names kept as aliases (SURVEY.md §5.6:
# exch_strategy: 'ar'|'cudaaware'|'asa32'|'asa16'|'nccl32')
# --------------------------------------------------------------------------

_CANONICAL = {
    "psum": lambda axis, size: psum_mean(axis),
    "psum_bf16": lambda axis, size: psum_bf16(axis),
    "ring": ring,
    "ring_bf16": ring_bf16,
    "ring_int8": ring_int8,
}

_ALIASES = {
    "ar": "psum",
    "cudaaware": "psum",
    "copper": "psum",
    "nccl32": "psum",
    "nccl16": "psum_bf16",
    "asa32": "ring",
    "asa16": "ring_bf16",
}


_ALREADY_COMPRESSED = ("psum_bf16", "ring_bf16", "ring_int8")


def _resolve_codec(name: str, codec):
    """Validate a (strategy, codec) pair -> WireCodec. Strategies that
    hard-code their own wire compression refuse a second codec; the
    explicit ring takes its wire FROM the codec (the asa16 special case
    generalized) but has no leaf-level residual to feed back — each hop
    re-quantizes partial sums per segment — so ``:ef`` needs the psum
    path."""
    from theanompi_tpu.parallel.codec import get_codec

    codec = get_codec(codec)
    key = _ALIASES.get(name, name)
    if not codec.active:
        return codec
    if key in _ALREADY_COMPRESSED:
        raise ValueError(
            f"strategy {name!r} already compresses its wire; composing it "
            f"with --wire-codec {codec.spec!r} would quantize twice — use "
            "strategy 'psum' (or 'ring') with the codec, or the strategy "
            "alone"
        )
    if key == "ring" and codec.error_feedback:
        raise ValueError(
            "error feedback needs a per-leaf residual, but the explicit "
            "ring quantizes per segment per hop (no stable leaf mapping) "
            f"— use --wire-codec {codec.name!r} on the ring, or "
            f"{codec.spec!r} with strategy 'psum'"
        )
    return codec


def checked_mode_strategy(name: str, axis_name, axis_size: int,
                          codec=None) -> Strategy:
    """The ``check_vma=True`` exchanger (migration plan above, executed
    for the BSP engine in round 5 — ``parallel/bsp.py::_checked_vma``):
    AD already delivers the replicated-param cotangent globally SUMMED,
    so the psum family degenerates to division by the axis size with no
    collective. The explicit ring/compressed strategies have no wire to
    compress in this mode (there is no exchanger collective at all) and
    are refused — per the plan they survive only as weight-exchange
    collectives (EASGD/GoSGD averaging)."""
    del axis_name
    if _resolve_codec(name, codec).active:
        raise ValueError(
            "checked-mode (check_vma=True) gradient sync has no exchanger "
            "collective — there is no wire for a codec to compress; drop "
            "--wire-codec or run the classic semantics"
        )
    key = _ALIASES.get(name, name)
    # 'hier' degenerates with the psum family: AD already summed over
    # every mesh axis, so there is no two-hop schedule left to stage
    if key in ("psum", "psum_bf16", "hier"):
        return lambda grads: jax.tree_util.tree_map(
            lambda g: g / axis_size, grads
        )
    raise ValueError(
        f"strategy {name!r} has no checked-mode (check_vma=True) gradient-"
        "sync form: AD already summed the cotangents, so there is no "
        "exchanger collective to segment or compress — use 'psum', or run "
        "the classic semantics (TMPI_CHECKED_VMA unset)"
    )


def get_strategy(name: str, axis_name, axis_size: int,
                 codec=None, axis_sizes=None) -> Strategy:
    """``axis_name`` may be a tuple of mesh axes (multi-slice BSP): the
    psum family reduces over all of them (XLA lowers ICI-then-DCN); the
    explicit ring variants are single-axis algorithms by construction;
    ``hier`` REQUIRES the 2-axis ``(dcn, data)`` form plus
    ``axis_sizes=(n_slices, per_slice)`` and stages the hierarchy
    explicitly (codec on the DCN hop only).

    ``codec``: a wire codec spec/instance (parallel/codec.py). On the
    psum path it returns the STATEFUL compressed strategy (error
    feedback threaded through engine state); on the explicit ring it
    selects the ring's wire compression (the asa16 special case,
    generalized); strategies that already compress refuse it."""
    codec = _resolve_codec(name, codec)
    key = _ALIASES.get(name, name)
    if key == "hier":
        _check_hier_axes(axis_name, axis_sizes, axis_size)
        return hierarchical_sync(tuple(axis_name), tuple(axis_sizes),
                                 codec)
    if not isinstance(axis_name, str) and key in ("ring", "ring_bf16", "ring_int8"):
        raise ValueError(
            f"strategy {name!r} is a single-axis ring; on a multi-slice "
            "mesh use 'psum'/'psum_bf16' (XLA lowers the ICI/DCN "
            "hierarchy from the mesh layout) or 'hier' (explicit staged "
            "schedule, codec on the DCN hop)"
        )
    if codec.active:
        if key == "psum":
            return codec_psum_mean(axis_name, codec)
        # key == "ring" (every other pairing raised in _resolve_codec)
        return _packed(
            lambda flat: _ring_allreduce_flat(
                flat, axis_name, axis_size, wire=codec.name
            ) / axis_size
        )
    try:
        return _CANONICAL[key](axis_name, axis_size)
    except KeyError:
        raise ValueError(
            f"unknown exchange strategy {name!r}; available: "
            f"{sorted(_CANONICAL) + ['hier'] + sorted(_ALIASES)}"
        ) from None
