"""NDEngine — the launchable N-D parallelism rule engine.

BEYOND-PARITY EXTENSION. Round 3 left tensor/sequence/pipeline/expert
parallelism as a *library* (``make_nd_train_step`` etc.) reachable only
from tests; this engine gives them the same driver protocol the sync
rules use (``init_state`` / ``train_step`` / ``eval_step`` /
``place_batch``), so ``launch/worker.py::run_training`` — recorder,
prefetch loader, checkpointing, resume, CLI — drives an LM sharded over
any of:

- ``dp`` (data axis) x ``tp`` (Megatron tensor axis) x ``sp`` (ring /
  Ulysses sequence axis) for the dense :class:`TransformerLMModel`;
- ``pipe`` (GPipe pipeline axis, microbatched) x ``dp``;
- ``expert`` (Switch-MoE all-to-all axis, doubling as the batch axis)
  x ``dp`` (data parallelism over the expert groups — the batch dim
  shards over (dp, expert) jointly) x ``tp`` (Megatron sharding WITHIN
  each expert/attention block) x ``sp`` for :class:`MoELMModel`.

CLI: ``tmpi BSP 8 theanompi_tpu.models.lm TransformerLMModel --tp 2
--sp 2`` (see cli.py). The engine owns batch *placement* because its
token sharding — ``P(dp, sp)``, or microbatch-major ``[M, B, T]`` for
pipelines — differs from the image engines' leading-dim-only layout.

Gradient sync follows the universal spec rule
(models/transformer.py::sync_grads_by_spec) under ``check_vma=False``
(see train.make_train_step's AD-semantics note); the optimizer, LR
schedule, and step counter mirror ``train.make_train_step`` so recipes
and checkpoints behave identically across engines.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from theanompi_tpu.models.transformer import (
    nd_spec_setup,
    opt_state_specs,
    sync_grads_by_spec,
)
from theanompi_tpu.ops.optimizers import apply_updates
from theanompi_tpu.train import make_schedule_fn

PyTree = Any

# canonical axis names for the launchable ND meshes (the mesh builder in
# launch/worker.py uses these; tests may use their own)
DP_AXIS = "data"
TP_AXIS = "model"
SP_AXIS = "seq"


class NDTrainState(NamedTuple):
    """Params + optimizer state + step. ``params`` leaves are sharded
    per the engine's param specs (tp/pipe/expert sharding or
    replicated); ``opt_state`` accumulators shard exactly like their
    parameters (transformer.py::opt_state_specs).

    ``ef``: wire-codec error-feedback residuals (parallel/codec.py) of
    the sharded-axis grad psums. Each leaf carries a leading stack dim
    covering exactly the axes that leaf is PSUMMED over (the complement
    of its sharded axes), so every device owns its own residual block —
    spec ``P(psum_axes, *leaf_spec)``. ``()`` when the codec carries no
    state."""

    params: PyTree
    opt_state: PyTree
    step: jax.Array
    ef: PyTree = ()


class NDEngine:
    """Driver-protocol engine over the N-D parallel LM step builders.

    Exactly one of three branches is active:

    - dense ND: any of ``dp_axis``/``tp_axis``/``sp_axis``
    - pipeline: ``pipe_axis`` (+ optional ``dp_axis``); tokens are
      reshaped host-side to microbatch-major ``[M, B/M, T]``
    - expert:   ``ep_axis`` (+ optional ``dp_axis``/``sp_axis``/
      ``tp_axis``); the batch dim shards over (dp, expert) jointly
    """

    name = "nd"
    exchange_every = 0
    # overridden per-instance from the donate flag; the SPMD analyzer
    # (ISSUE 7) verifies whatever is declared against the lowered step's
    # donated_invars (SPMD201) and pins the per-leaf dp-axis psum
    # schedule in tools/analyze/golden/nd_*.json
    donates_state = True

    def __init__(
        self,
        model,
        mesh: Mesh,
        *,
        steps_per_epoch: int = 1,
        dp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
        sp_axis: Optional[str] = None,
        ep_axis: Optional[str] = None,
        pipe_axis: Optional[str] = None,
        microbatches: Optional[int] = None,
        pp_interleave: int = 1,
        donate: bool = True,
        wire_codec=None,
        fused_update: bool = False,
    ):
        if not hasattr(model, "arch"):
            raise ValueError(
                f"NDEngine needs an LM model exposing .arch (models/lm.py); "
                f"got {type(model).__name__}"
            )
        arch = model.arch
        self.model = model
        self.mesh = mesh
        self.microbatches = None
        self.schedule = None  # pipeline branch: schedule_report dict
        self._dp_axis = dp_axis  # kept for the analytic traffic model
        if fused_update:
            # fused epilogue over the spec-sharded leaves: inside
            # shard_map each leaf is its LOCAL shard, so the one-pass
            # kernel runs unchanged (ops/pallas_update.py). Refuses the
            # LM recipes' adam loudly — no fused kernel for it.
            from theanompi_tpu.ops.pallas_update import fuse_optimizer

            if model.recipe.opt_kwargs.get("clip_norm") is not None:
                # the fused clip is a GLOBAL grad norm; this step's
                # leaves are spec-sharded local shards, so each device
                # would clip by its own partial-norm coefficient
                raise ValueError(
                    "--fused-update clip_norm is not supported on the "
                    "ND engine: the fused global-norm clip would be "
                    "computed over each device's local param shards, "
                    "not the global gradient (drop clip_norm)"
                )
            opt = fuse_optimizer(model.recipe.optimizer,
                                 **model.recipe.opt_kwargs)
        else:
            opt = model.optimizer()
        schedule_lr = make_schedule_fn(model, steps_per_epoch)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

        if pipe_axis is not None:
            if ep_axis:
                raise ValueError(
                    "the pipeline branch composes with dp, tp and sp "
                    "(pipe x expert is not implemented)"
                )
            from theanompi_tpu.parallel.pipeline import (
                make_pipeline_loss,
                pipeline_param_specs,
                pipeline_schedule_report,
                stack_pipeline_params,
                validate_pp_mesh,
            )

            axes, n_total = validate_pp_mesh(
                arch, mesh, pipe_axis, dp_axis, pp_interleave, tp_axis,
                sp_axis,
            )
            param_specs = pipeline_param_specs(pipe_axis, tp_axis)
            loss_fn = make_pipeline_loss(
                arch, pipe_axis, pp_interleave, tp_axis, sp_axis
            )
            n_pipe = sizes[pipe_axis]
            init_params = lambda key: stack_pipeline_params(  # noqa: E731
                arch.init(key), n_stages=n_pipe, interleave=pp_interleave
            )
            self.microbatches = int(microbatches or n_pipe)
            if pp_interleave > 1 and self.microbatches % n_pipe:
                raise ValueError(
                    f"--pp-interleave needs --microbatches "
                    f"({self.microbatches}) in groups of --pp ({n_pipe})"
                )
            self.schedule = pipeline_schedule_report(
                n_pipe, self.microbatches, pp_interleave
            )
            if jax.process_index() == 0:  # once per pod, not per host
                print(
                    f"[nd] pipeline schedule: {self.schedule['ticks']} ticks, "
                    f"bubble {self.schedule['bubble_fraction']:.1%} "
                    f"(interleave={pp_interleave}; suggest >= "
                    f"{self.schedule['suggested_microbatches']} microbatches "
                    f"for <10%)"
                )
            # [M, B, T]: M replicated, B on dp, T on sp
            tok_entry = dp_axis
            microbatched = True
            batch_axes = (dp_axis,) if dp_axis else ()
        elif ep_axis is not None:
            from theanompi_tpu.models.moe import ep_spec_setup

            axes, n_total, param_specs = ep_spec_setup(
                arch, mesh, ep_axis, sp_axis, dp_axis, tp_axis
            )
            loss_fn = lambda p, t: arch.loss(  # noqa: E731
                p, t, sp_axis, ep_axis=ep_axis, dp_axis=dp_axis,
                tp_axis=tp_axis,
            )
            init_params = arch.init
            # batch dim over (dp, ep) jointly, dp-major: host slices
            # stay contiguous under multi-controller feeds
            tok_entry = (dp_axis, ep_axis) if dp_axis else ep_axis
            microbatched = False
            batch_axes = ((dp_axis,) if dp_axis else ()) + (ep_axis,)
        else:
            axes, n_total, param_specs = nd_spec_setup(
                arch, mesh, dp_axis, tp_axis, sp_axis
            )
            loss_fn = lambda p, t: arch.loss(p, t, sp_axis, tp_axis=tp_axis)  # noqa: E731
            init_params = arch.init
            tok_entry = dp_axis
            microbatched = False
            batch_axes = (dp_axis,) if dp_axis else ()

        from theanompi_tpu.parallel.codec import get_codec

        codec = get_codec(wire_codec)
        if n_total == 1:
            codec = get_codec(None)  # no sync collectives, no wire
        self.codec = codec
        use_ef = codec.active and codec.error_feedback

        from theanompi_tpu.parallel.recipe import (
            ShardingRecipe,
            psum_axes as _recipe_psum_axes,
        )

        _is_spec = lambda x: isinstance(x, P)  # noqa: E731
        self._spec_leaves = jax.tree_util.tree_leaves(
            param_specs, is_leaf=_is_spec
        )
        # which leaves actually cross a wire (psummed over >= 1 axis) —
        # the complement rule lives in parallel/recipe.py::psum_axes
        # (same rule transformer.sync_grads_by_spec applies)
        self._wire_axes = [_recipe_psum_axes(s, tuple(axes))
                           for s in self._spec_leaves]
        self._ef_stack = [
            int(np.prod([sizes[a] for a in ax_t])) if ax_t else 1
            for ax_t in self._wire_axes
        ]

        opt_template = jax.eval_shape(
            lambda: opt.init(jax.eval_shape(init_params, jax.random.PRNGKey(0)))
        )
        # THE spec source (parallel/recipe.py): the per-leaf param
        # specs (model spec setup), their like-sharded optimizer
        # accumulators, the ef residual stacks (leading dim over each
        # leaf's psummed axes), and the token sharding — one recipe the
        # step, analyzer, memory model, and topology stamp all consume
        self.sharding = ShardingRecipe.nd(
            mesh, tuple(axes), param_specs, opt_template, use_ef,
            tok_entry, sp_axis, microbatched=microbatched,
        )
        state_specs = self.sharding.state_spec(NDTrainState)
        tok_spec = self.sharding.batch_spec
        self._state_specs = state_specs
        self._init_params = init_params
        self._opt = opt
        self._tok_spec = tok_spec
        self._tok_sharding = NamedSharding(mesh, tok_spec)
        # fused dispatch: group dim replicated ahead of the token spec
        self._stacked_sharding = NamedSharding(
            mesh, self.sharding.stacked_batch_spec)
        self._donate = donate
        self.donates_state = bool(donate)
        self._fused: dict = {}
        # multi-controller feed fraction (lo, hi, B): set by
        # host_batch_part when hosts load only their slice of the global
        # batch; None = every host feeds the full batch (replicated
        # tokens, or the pipeline's interleaved microbatch-major layout)
        self._part = None

        wire_flags = [bool(ax_t) for ax_t in self._wire_axes]

        def compress_grads(grads, ef):
            """Wire codec over the leaves that actually cross an axis
            (per-leaf block quantize + error feedback); fully-sharded
            leaves (no psum) pass through untouched."""
            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            if use_ef:
                ef_leaves = jax.tree_util.tree_leaves(ef)
                out_g, out_ef = [], []
                for g, r, w in zip(g_leaves, ef_leaves, wire_flags):
                    if not w:
                        out_g.append(g)
                        out_ef.append(r)
                        continue
                    q, nr = codec.compress_leaf(g, r[0])
                    out_g.append(q)
                    out_ef.append(nr[None])
                return (
                    jax.tree_util.tree_unflatten(treedef, out_g),
                    jax.tree_util.tree_unflatten(treedef, out_ef),
                )
            out_g = [
                codec.compress_leaf(g, None)[0] if w else g
                for g, w in zip(g_leaves, wire_flags)
            ]
            return jax.tree_util.tree_unflatten(treedef, out_g), ef

        def make_sharded_step(numerics: bool):
            def sharded_step(state: NDTrainState, tokens, rng):
                del rng  # no dropout in the LM stack; kept for protocol parity
                loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
                new_ef = state.ef
                if codec.active:
                    # quantize each device's LOCAL contribution BEFORE
                    # the sharded-axis psums (EQuARX recipe; fp32
                    # accumulation inside the collective)
                    grads, new_ef = compress_grads(grads, state.ef)
                grads = sync_grads_by_spec(grads, param_specs, axes, n_total)
                for a in batch_axes:
                    loss = lax.pmean(loss, a)  # report the global batch mean
                lr = schedule_lr(state.step)
                if opt.apply is not None:
                    # fused one-pass update (ops/pallas_update.py); the
                    # gauges' update tree is reconstructed below, only
                    # in the numerics variant
                    new_params, new_opt = opt.apply(
                        grads, state.opt_state, state.params, lr
                    )
                    updates = None
                else:
                    updates, new_opt = opt.update(
                        grads, state.opt_state, state.params, lr
                    )
                    new_params = apply_updates(state.params, updates)
                metrics = {"loss": loss, "lr": lr}
                if numerics:
                    if updates is None:
                        from theanompi_tpu.ops.optimizers import (
                            update_delta,
                        )

                        updates = update_delta(new_params, state.params)
                    # sentinels over SPEC-SHARDED trees: per-leaf local
                    # squared sums psummed over exactly the axes that
                    # leaf shards over (obs/numerics.py) — scalar
                    # collectives, no gather of the sharded params
                    from theanompi_tpu.obs.numerics import sharded_sentinels

                    metrics = {
                        **metrics,
                        **sharded_sentinels(grads, updates, new_params,
                                            param_specs),
                    }
                return (
                    NDTrainState(new_params, new_opt, state.step + 1,
                                 new_ef),
                    metrics,
                )

            return sharded_step

        self._make_sharded_step = make_sharded_step

        def jit_step(numerics: bool):
            return jax.jit(
                jax.shard_map(
                    make_sharded_step(numerics),
                    mesh=mesh,
                    in_specs=(state_specs, tok_spec, self.sharding.scalar),
                    out_specs=(state_specs, self.sharding.scalar),
                    check_vma=False,
                ),
                donate_argnums=(0,) if donate else (),
            )

        self._jit_step = jit_step
        self._steps = {False: jit_step(False)}

        def sharded_eval(state: NDTrainState, tokens):
            loss = loss_fn(state.params, tokens)
            for a in batch_axes:
                loss = lax.pmean(loss, a)
            return {"loss": loss}

        self._eval = jax.jit(
            jax.shard_map(
                sharded_eval,
                mesh=mesh,
                in_specs=(state_specs, tok_spec),
                out_specs=self.sharding.scalar,
                check_vma=False,
            )
        )

    # -- driver protocol ------------------------------------------------
    @property
    def state_shardings(self) -> NDTrainState:
        """Per-leaf NamedShardings of the train state — used by the
        driver to re-place a restored (host-numpy) checkpoint under
        multi-controller launch, where a plain ``jnp.asarray`` would
        produce process-local arrays the SPMD step cannot consume."""
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._state_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def init_state(self, rng) -> NDTrainState:
        # jit with out_shardings: each process computes only its shards
        # (multi-controller correct, and the replicated-then-reshard
        # device_put round-trip is gone — init never materializes the
        # full parameter set per device)
        def build(rng):
            params = self._init_params(rng)
            ef: Any = ()
            if self.codec.active and self.codec.error_feedback:
                leaves, treedef = jax.tree_util.tree_flatten(params)
                ef = jax.tree_util.tree_unflatten(
                    treedef,
                    [jnp.zeros((stk, *p.shape), jnp.float32)
                     for p, stk in zip(leaves, self._ef_stack)],
                )
            return NDTrainState(
                params, self._opt.init(params), jnp.zeros((), jnp.int32), ef
            )

        return jax.jit(build, out_shardings=self.state_shardings)(rng)

    def host_batch_part(self, global_batch: int):
        """The slice of the global ``[B, T]`` token batch THIS controller
        process must produce (None = the full batch) — the ND analogue of
        ``mesh.host_local_batch_slice`` (reference: per-rank loader feed,
        ``lib/proc_load_mpi.py``), derived from the token sharding itself:

        - batch dim sharded over a process-spanning axis (dp / expert):
          the contiguous row range covered by this process's addressable
          devices;
        - batch dim replicated across processes (pure tp/sp) or the
          pipeline's microbatch-major layout (whose host rows interleave
          dp shards non-contiguously): every host feeds the full batch —
          tokens are int32 and host-cheap, and placement still moves only
          the addressable shards onto devices (zero cross-host copies).
        """
        if jax.process_count() == 1:
            return None
        if self.microbatches is not None:
            return None
        spec0 = self._tok_spec[0]
        if spec0 is None:
            return None
        idx_map = NamedSharding(
            self.mesh, self.sharding.leading_batch_spec
        ).addressable_devices_indices_map((global_batch,))
        rows: set[int] = set()
        for idx in idx_map.values():
            s = idx[0]
            rows.update(range(s.start or 0, s.stop if s.stop is not None
                              else global_batch))
        lo, hi = min(rows), max(rows) + 1
        if len(rows) != hi - lo:
            return None  # non-contiguous coverage: feed the full batch
        part = (lo, hi, global_batch)
        if self._part is not None and (
            self._part[0] * global_batch != lo * self._part[2]
            or self._part[1] * global_batch != hi * self._part[2]
        ):
            raise ValueError(
                f"inconsistent host batch fractions {self._part} vs {part} "
                "(train/val batches must shard proportionally)"
            )
        self._part = part
        return None if (lo, hi) == (0, global_batch) else slice(lo, hi)

    def _put_global(self, x: np.ndarray, sharding: NamedSharding, batch_dim: int):
        """Place host rows as a (possibly multi-process) global array.

        Single-controller: plain sharded device_put. Multi-controller:
        assemble the global array from this process's rows — the callback
        maps each addressable device's global index window into the host
        buffer, shifted by the host's feed offset."""
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        x = np.ascontiguousarray(x)
        if self._part is not None and self._part[1] - self._part[0] != self._part[2]:
            lo, hi, B = self._part
            g = x.shape[batch_dim] * B // (hi - lo)
            off = g * lo // B
        else:
            g, off = x.shape[batch_dim], 0
        gshape = list(x.shape)
        gshape[batch_dim] = g

        def cb(index):
            idx = list(index)
            s = idx[batch_dim]
            idx[batch_dim] = slice(
                (s.start or 0) - off,
                (s.stop if s.stop is not None else g) - off,
            )
            return x[tuple(idx)]

        return jax.make_array_from_callback(tuple(gshape), sharding, cb)

    def _split_microbatches(self, x, axis: int):
        """Reshape the batch dim at ``axis`` to microbatch-major
        ``[M, B/M]`` (no-op for non-pipeline engines) — the ONE place
        the pipeline host layout is defined, shared by the per-step and
        fused placement paths."""
        if self.microbatches is None:
            return x
        M = self.microbatches
        if x.shape[axis] % M:
            raise ValueError(
                f"global batch {x.shape[axis]} must be divisible by "
                f"microbatches={M}"
            )
        return x.reshape(
            *x.shape[:axis], M, x.shape[axis] // M, *x.shape[axis + 1:]
        )

    def place_batch(self, x, y):
        """Host tokens ``[B, T]`` -> device, sharded per the engine's
        token spec (microbatch-major for pipelines). Returns the SAME
        device array for x and y (labels are the tokens; zero extra
        transfer)."""
        del y  # labels ARE the tokens
        x = self._split_microbatches(np.asarray(x), axis=0)
        t = self._put_global(
            x, self._tok_sharding,
            batch_dim=1 if self.microbatches is not None else 0,
        )
        return t, t

    def train_step(self, state, tokens, labels, rng, numerics: bool = False):
        del labels
        numerics = bool(numerics)
        if numerics not in self._steps:
            self._steps[numerics] = self._jit_step(numerics)
        return self._steps[numerics](state, tokens, rng)

    def place_group(self, group):
        """Fused dispatch: stack ``g`` host token batches into ONE
        ``[g, ...]`` transfer sharded per the engine's token spec (group
        dim replicated; microbatch-major per batch for pipelines)."""
        xs = np.stack([np.asarray(b[0]) for b in group])
        t = self._put_global(
            self._split_microbatches(xs, axis=1), self._stacked_sharding,
            batch_dim=2 if self.microbatches is not None else 1,
        )
        return t, t

    def fused_train_step(self, state, tokens_g, labels_g, rngs,
                         numerics: bool = False):
        """``g`` steps in ONE compiled program (``lax.scan`` over the
        stacked group — same dispatch-amortization as
        ``parallel/bsp.py::make_bsp_fused_step``); per-step keys stacked
        ``[g]``, metrics returned stacked. Jit recompiles per distinct
        group size (the driver produces at most the configured k plus an
        epoch remainder)."""
        del labels_g
        numerics = bool(numerics)
        if numerics not in self._fused:
            from theanompi_tpu.parallel.fused import fuse_sharded_step

            self._fused[numerics] = fuse_sharded_step(
                self._make_sharded_step(numerics), self.mesh,
                self._state_specs,
                (self.sharding.stacked_batch_spec, self.sharding.scalar),
                self._donate,
            )
        return self._fused[numerics](state, tokens_g, rngs)

    def exchange(self, state):
        return state

    def eval_step(self, state, tokens, labels):
        del labels
        return self._eval(state, tokens)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.step))

    def sharding_recipe(self):
        """The engine's ShardingRecipe (parallel/recipe.py) — declared
        spec table for the sharding analyzer and the topology stamp."""
        return self.sharding

    def elastic_spec(self) -> dict:
        """Per-leaf reshard policies for the topology manifest
        (utils/checkpoint.load_resharded). ND params and their
        like-sharded optimizer accumulators keep mesh-invariant GLOBAL
        shapes (the sharding divides them, it never pads them), so the
        default ``global`` bounds-based move is exact for any axis
        regrouping; only the per-device error-feedback residual stacks
        are topology-bound and reset."""
        return {"policies": {".ef": {"policy": "reset"}}}

    def traffic_model(self, state):
        """Approximate ND wire model (obs/comm.py): the dp-axis grad
        allreduce over each device's local (1/shard_ways) param slice.
        Activation collectives (tp psum, sp ring/all-to-all, pipeline
        ppermute, MoE all-to-all) are NOT modeled — the returned model
        is flagged ``approx`` in its detail."""
        from theanompi_tpu.obs.comm import nd_traffic, pytree_num_elements
        from theanompi_tpu.parallel.mesh import slice_topology

        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        dp = sizes.get(self._dp_axis, 1) if self._dp_axis else 1
        shard_ways = max(1, self.mesh.devices.size // dp)
        return nd_traffic(
            pytree_num_elements(state.params), dp, shard_ways=shard_ways,
            codec=self.codec, n_slices=slice_topology(self.mesh)[0],
        )

    def memory_model(self, state):
        """Analytic per-leaf HBM residency (utils/flops.py
        ``MemoryModel``; see BSPEngine.memory_model). The ND engine is
        the spec-driven case: each leaf's shard factor is the mesh
        extent over the axes its own PartitionSpec names
        (``self._state_specs`` — the same per-leaf specs the
        checkpoint topology manifest stamps), so tp/pipe/expert-sharded
        params and their like-sharded accumulators divide by their
        sharding ways while replicated leaves count in full. Factors
        and specs are resolved per STATE leaf by the recipe, so prefix
        specs broadcast correctly (SHARD003 verifies the table against
        the compiled program)."""
        from theanompi_tpu.utils.flops import state_memory_model

        lf = self.sharding.leaf_factors(state)

        def factor(path, leaf):
            return lf.get(path, (1, None))[0]

        return state_memory_model(
            state, "nd", self.mesh.devices.size, factor,
            detail={"note": "per-leaf PartitionSpec extents "
                            "(tp/pipe/expert sharding)"},
            specs={p: s for p, (_f, s) in lf.items()},
        )

    def cost_model(self, state, global_batch: int):
        """XLA cost analysis of the compiled numerics-off ND step over
        an abstract global token batch (utils/flops.py ``CostModel``;
        see BSPEngine.cost_model) — tp/sp/pp/expert collectives are
        inside the executable, so its FLOPs/bytes include them even
        though ``traffic_model()`` models the dp grad sync only."""
        import jax as _jax

        from theanompi_tpu.utils.flops import abstract_batch, compiled_cost

        tok, _ = abstract_batch(self.model, int(global_batch))
        return compiled_cost(self._steps[False], state, tok,
                             _jax.random.PRNGKey(0))

    def numerics_model(self, state):
        """Numerics declaration (obs/numerics.py): sentinels computed
        spec-aware over the sharded param/grad trees (per-leaf scalar
        psums over each leaf's sharded axes); no divergence gauge — the
        sharding IS the single source of truth, there are no replicas
        to drift."""
        from theanompi_tpu.obs.numerics import NumericsModel

        del state
        return NumericsModel(
            rule="nd",
            detail={"note": "spec-aware sharded norms (scalar psums per "
                            "leaf); tp/sp/pp/expert layouts have no "
                            "replica divergence by construction"},
        )
