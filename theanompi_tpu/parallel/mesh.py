"""Device-mesh runtime.

Replaces the reference's per-process device/communicator setup
(reference: ``lib/base.py`` — ``MPI_GPU_Process.init_device``,
``get_internode_comm`` (MPI world), ``get_intranode_comm`` (NCCL clique);
SURVEY.md §1 L1). On TPU there is no process-per-device or dual
MPI/NCCL hierarchy: a named ``Mesh`` spans all chips, XLA lowers
collectives onto ICI within a slice and DCN across slices, and
``jax.distributed.initialize`` (multi-host) replaces ``mpirun``.

Axis naming: today's rules are pure data parallelism, so the mesh is
1-D ``('data',)`` — but everything takes the axis names from here so a
``('data', 'model')`` mesh is additive later (SURVEY.md §5.7 note).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
# Cross-slice axis for multi-slice (pod-scale) meshes: collectives over
# DATA_AXIS ride ICI inside a slice, collectives over DCN_AXIS cross the
# data-center network between slices. See make_multislice_mesh.
DCN_AXIS = "dcn"
# Async-rule worker axis for (worker, data) meshes: each elastic/gossip
# "worker" is itself a data-parallel GROUP of chips (EASGD group mode).
WORKER_AXIS = "worker"


def _slice_major(devs):
    """Canonical device linearization: slice-major, then id — shared by
    every mesh builder (changing it changes per-device RNG streams)."""
    return sorted(devs, key=lambda d: (getattr(d, "slice_index", 0), d.id))


def fold_linear_index(rng, axes, mesh: Mesh):
    """Fold this device's linearized mesh index (over ``axes``, row-major)
    into ``rng`` — THE per-device RNG stream definition shared by every
    rule engine (changing the linearization changes dropout/augment
    streams everywhere at once)."""
    from jax import lax

    idx = None
    for a in axes:
        i = lax.axis_index(a)
        idx = i if idx is None else idx * mesh.shape[a] + i
    return jax.random.fold_in(rng, idx)


def batch_axes(mesh: Mesh):
    """The axis spec batches shard over: the single data axis on a 1-D
    mesh, ALL axes on a multi-axis (multi-slice) mesh."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def make_mesh(
    devices: Union[int, Sequence, None] = None,
    axis_names: tuple[str, ...] = (DATA_AXIS,),
    shape: Optional[tuple[int, ...]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (count, explicit list, or None=all).

    ``shape`` reshapes the device list for multi-axis meshes; default is
    1-D over all requested devices.
    """
    if devices is None:
        # Order by (slice, device) so the 1-D data axis is slice-
        # contiguous: XLA then lowers the allreduce hierarchically —
        # reduce over ICI within each slice, exchange partials over DCN
        # across slices — instead of striding DCN hops through the ring.
        devs = _slice_major(jax.devices())
    elif isinstance(devices, int):
        all_devs = jax.devices()
        if devices > len(all_devs):
            raise ValueError(
                f"requested {devices} devices but only {len(all_devs)} present "
                f"({[d.platform for d in all_devs[:1]]}); for CPU-mesh testing set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N before jax import"
            )
        devs = all_devs[:devices]
    else:
        devs = list(devices)
    arr = np.array(devs)
    if shape is not None:
        arr = arr.reshape(shape)
    elif len(axis_names) > 1:
        raise ValueError("multi-axis mesh needs an explicit shape")
    return Mesh(arr, axis_names)


def make_multislice_mesh(
    devices: Union[int, Sequence, None] = None,
    n_slices: Optional[int] = None,
) -> Mesh:
    """2-D ``(DCN_AXIS, DATA_AXIS)`` mesh for multi-slice deployments —
    the 256-chip BASELINE shape (e.g. 4 slices x 64 chips).

    Rows are slices: a collective over ``DATA_AXIS`` stays on ICI inside
    one slice; a collective over ``DCN_AXIS`` crosses slices over DCN.
    The BSP gradient mean over BOTH axes is lowered by XLA into exactly
    that two-tier hierarchy — the reference built the same split by hand
    with NCCL cliques inside a node and MPI across nodes
    (``lib/exchanger_strategy.py``; SURVEY.md §5.8 "topology split").

    ``n_slices``: explicit row count — required on hardware without
    ``slice_index`` metadata (CPU simulation) and for carving a single
    real slice into virtual rows; defaults to the device-reported slice
    count.
    """
    if devices is None or isinstance(devices, int):
        devs = list(make_mesh(devices).devices.reshape(-1))
    else:
        devs = list(devices)
    # slice-contiguous ordering on EVERY path (make_mesh only sorts the
    # devices=None case): a row that straddles physical slices would put
    # DCN hops inside the 'data' axis and defeat the hierarchy
    devs = _slice_major(devs)
    slice_ids = [getattr(d, "slice_index", 0) for d in devs]
    if n_slices is None:
        n_slices = len(set(slice_ids))
    if n_slices < 1 or len(devs) % n_slices:
        raise ValueError(
            f"{len(devs)} devices do not divide into {n_slices} slices"
        )
    per = len(devs) // n_slices
    arr = np.array(devs).reshape(n_slices, per)
    if len(set(slice_ids)) > 1:
        # real slice metadata present: every row must be single-slice
        for r in range(n_slices):
            row_ids = {slice_ids[r * per + i] for i in range(per)}
            if len(row_ids) > 1:
                raise ValueError(
                    f"mesh row {r} would span physical slices {sorted(row_ids)} "
                    f"(device count {len(devs)} does not align with the "
                    "per-slice chip count); choose a device count that is a "
                    "whole number of slices"
                )
    return Mesh(arr, (DCN_AXIS, DATA_AXIS))


def slice_topology(mesh: Mesh) -> tuple[int, int]:
    """``(n_slices, per_slice)`` of a mesh, read off the DCN axis — the
    slice decomposition the hierarchical exchange strategy and the
    per-link-class traffic accounting share. A mesh without a
    ``DCN_AXIS`` is one slice: every hop is ICI."""
    names = tuple(mesh.axis_names)
    if DCN_AXIS not in names:
        return 1, int(mesh.devices.size)
    n_slices = int(mesh.shape[DCN_AXIS])
    per = 1
    for a in names:
        if a != DCN_AXIS:
            per *= int(mesh.shape[a])
    return n_slices, per


def make_worker_group_mesh(mesh: Mesh, group_size: int,
                           n_slices: Optional[int] = None):
    """Reshape a 1-D mesh for async-rule worker groups: ``(worker,
    data)`` rows are workers, columns the chips data-parallel WITHIN one
    worker. Returns ``(mesh2d, batch_spec, grad_sync)`` — the shared
    construction for EASGD/GoSGD group mode (a group must behave as ONE
    bigger worker: BSP psum inside, worker-axis collectives across).

    **Slice awareness** (BASELINE config #4 at pod scale — e.g. 16
    workers x 16 chips over multiple slices): devices are slice-major
    (the canonical ``make_mesh`` order), so with ``group_size`` dividing
    the per-slice chip count every group row sits INSIDE one slice — the
    per-step group psum rides ICI — while the worker axis spans slices,
    putting the cheap every-``avg_freq`` elastic/gossip collectives on
    DCN. The reference built the same split with NCCL-in-node /
    MPI-across-nodes (SURVEY.md §3.3, §5.8). ``n_slices`` simulates the
    slice boundaries on hardware without ``slice_index`` metadata (CPU
    meshes / carving one physical slice); with real metadata the
    physical boundaries are validated instead.
    """
    from jax.sharding import PartitionSpec

    from theanompi_tpu.parallel.strategies import get_strategy

    g = max(1, int(group_size))
    devs = list(np.asarray(mesh.devices).reshape(-1))
    n_dev = len(devs)
    if n_dev % g:
        raise ValueError(f"{n_dev} devices do not divide into groups of {g}")
    if n_slices is not None and n_slices > 1 and n_dev % n_slices:
        # validate the slice count even for ungrouped workers (g == 1),
        # so `tmpi EASGD --slices 3` fails like BSP's multislice path
        # does instead of silently ignoring the topology claim
        raise ValueError(
            f"{n_dev} devices do not divide into {n_slices} slices"
        )
    if g == 1:
        return mesh, None, None
    devs = _slice_major(devs)
    slice_ids = [getattr(d, "slice_index", 0) for d in devs]
    if n_slices is not None and n_slices > 1:
        per_slice = n_dev // n_slices
        if len(set(slice_ids)) <= 1:
            # no (or uniform) hardware metadata: impose virtual slice ids
            slice_ids = [i // per_slice for i in range(n_dev)]
    if len(set(slice_ids)) > 1:
        # every group row must be single-slice: a group straddling
        # slices would put its PER-STEP data-axis psum on DCN, defeating
        # the topology split (workers exchange rarely; groups every step)
        for w in range(n_dev // g):
            row = {slice_ids[w * g + i] for i in range(g)}
            if len(row) > 1:
                raise ValueError(
                    f"worker group {w} would span slices {sorted(row)}: "
                    f"group_size {g} must divide the per-slice chip count "
                    f"({n_dev} devices / {len(set(slice_ids))} slices)"
                )
    mesh2d = Mesh(
        np.array(devs).reshape(n_dev // g, g), (WORKER_AXIS, DATA_AXIS)
    )
    return (
        mesh2d,
        PartitionSpec((WORKER_AXIS, DATA_AXIS)),
        get_strategy("psum", DATA_AXIS, g),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


# --------------------------------------------------------------------------
# topology serialization + host-mediated redistribution (elastic PR):
# checkpoints stamp the mesh/spec metadata these helpers produce, and
# load_resharded (utils/checkpoint.py) rebuilds per-device shards on a
# DIFFERENT mesh from it — the collective-based redistribution scheme of
# "Memory-efficient array redistribution" (arXiv:2112.01075): every host
# materializes only the shards it owns under a computed transfer plan,
# never a full array.
# --------------------------------------------------------------------------


def mesh_topology(mesh: Mesh) -> dict:
    """JSON-serializable identity of a mesh: shape + axis names. Two
    meshes with equal topology dicts produce identical shard layouts
    for any PartitionSpec, so a checkpoint stamped with one can load on
    the other without resharding (bit-identical resume)."""
    return {
        "shape": [int(s) for s in mesh.devices.shape],
        "axes": [str(a) for a in mesh.axis_names],
    }


def spec_to_json(spec) -> Optional[list]:
    """``PartitionSpec -> per-dim JSON``: each entry is ``None``
    (replicated dim) or a list of axis names. ``None`` for a non-spec
    (fully replicated / non-NamedSharding leaf)."""
    if spec is None:
        return None
    out = []
    for dim in tuple(spec):
        if dim is None:
            out.append(None)
        elif isinstance(dim, str):
            out.append([dim])
        else:
            out.append([str(a) for a in dim])
    return out


def spec_from_json(dims: Optional[list]) -> PartitionSpec:
    if not dims:
        return PartitionSpec()
    return PartitionSpec(*[
        None if d is None else (d[0] if len(d) == 1 else tuple(d))
        for d in dims
    ])


def leaf_spec_json(leaf) -> Optional[list]:
    """The serialized PartitionSpec of one live array leaf, or None when
    the leaf carries no NamedSharding (host numpy, single-device plain
    placement) — which a reshard treats as replicated."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return spec_to_json(spec) if spec is not None else None


def put_resharded(mesh: Mesh, spec: PartitionSpec, shape, dtype, read_fn):
    """Build a global array on ``mesh`` where each addressable shard's
    content comes from ``read_fn(bounds)`` (bounds = ((start, stop), ...)
    in GLOBAL index space). This is the placement half of the
    arXiv:2112.01075 redistribution: each host materializes only the
    shards it owns — the cross-host "all-to-all" data movement happens
    through the shared checkpoint storage the read_fn reads from, so no
    host ever allocates the full array for a sharded leaf."""
    import jax.numpy as jnp
    import numpy as np

    sharding = NamedSharding(mesh, spec)

    def cb(idx):
        bounds = tuple(
            sl.indices(dim)[:2] for sl, dim in zip(idx, shape)
        )
        return np.asarray(read_fn(bounds), dtype=dtype)

    out = jax.make_array_from_callback(tuple(shape), sharding, cb)
    # The assembled shards can zero-copy-BORROW their host buffers
    # (checkpoint views / numpy temporaries) on the CPU backend, and
    # every engine donates its state into the first train step —
    # donating a borrowed buffer frees memory XLA does not own, which
    # surfaces as flaky heap corruption at the next compile. The jitted
    # per-shard copy re-materializes the array into XLA-owned,
    # donation-safe buffers; it is sharding-preserving, so still no
    # full-array gather on any host.
    return jax.jit(jnp.copy)(out)


def batch_sharding(mesh: Mesh, axis: Union[str, tuple, None] = None) -> NamedSharding:
    """Shard the leading (batch) dim across the data axis (1-D mesh) or
    across ALL mesh axes (multi-slice mesh)."""
    if axis is None:
        axis = batch_axes(mesh)
    return NamedSharding(mesh, PartitionSpec(axis))


def host_local_batch_slice(mesh: Mesh, global_batch: int) -> slice:
    """The slice of the global batch this host should produce.

    Single-controller: the whole batch. Multi-controller (one process
    per TPU host, reference: one loader per worker rank): each host
    feeds only its addressable shard — the analogue of the reference's
    per-rank batch-file partition (``models/data/imagenet.py``).
    """
    n_proc = jax.process_count()
    per_host = global_batch // n_proc
    idx = jax.process_index()
    return slice(idx * per_host, (idx + 1) * per_host)


def _place_batch(mesh: Mesh, x, sharding: NamedSharding, batch_dim: int,
                 global_rows: Optional[int]):
    """Shared placement core. Multi-controller: assemble the global array
    from per-process rows of ``batch_dim`` (no cross-host copy).
    Single-device meshes use a plain device placement: some backends
    (measured: the axon-tunneled v5e) run programs whose inputs carry a
    NamedSharding ~90x slower than identical unsharded programs, and with
    one device the sharding is vacuous anyway."""
    n_proc = jax.process_count()
    if n_proc > 1:
        x = np.asarray(x)
        rows = global_rows if global_rows is not None else x.shape[batch_dim] * n_proc
        shape = list(x.shape)
        shape[batch_dim] = rows
        return jax.make_array_from_process_local_data(sharding, x, tuple(shape))
    if mesh.devices.size == 1:
        return jax.device_put(x, mesh.devices.reshape(-1)[0])
    return jax.device_put(x, sharding)


def put_global_batch(mesh: Mesh, x, axis=None, global_rows: Optional[int] = None):
    """Place a host batch onto the mesh sharded along the data axis.

    ``x`` holds THIS PROCESS's rows: in single-controller runs that is
    the whole global batch; in multi-controller runs each host passes
    only its ``host_local_batch_slice`` rows (the analogue of the
    reference's per-rank batch-file partition). ``global_rows`` overrides
    the inferred global batch (defaults to ``rows_here * process_count``,
    the equal-split case)."""
    return _place_batch(mesh, x, batch_sharding(mesh, axis), 0, global_rows)


def put_stacked_batches(mesh: Mesh, x, axis=None, global_rows: Optional[int] = None):
    """Place a STACKED group of batches ``[k, batch, ...]`` — the fused
    multi-step dispatch ships k steps of data in one transfer; dim 0 (the
    step index) is replicated, dim 1 (the batch) shards across the mesh.
    Multi-controller hosts pass their local rows of dim 1 as usual."""
    if axis is None:
        axis = batch_axes(mesh)
    sharding = NamedSharding(mesh, PartitionSpec(None, axis))
    return _place_batch(mesh, x, sharding, 1, global_rows)


def first_local_value(x):
    """First element of a (possibly multi-host sharded) array, read from
    this process's first addressable shard — ``device_get`` of a global
    array raises on non-addressable shards, this never does. For values
    replicated or stacked per-worker (engine step counters), any shard's
    first element is the answer."""
    try:
        shard = x.addressable_shards[0].data
    except AttributeError:  # plain numpy / python scalar
        shard = x
    return np.asarray(shard).reshape(-1)[0]


def stack_replicas(tree, n: int):
    """Broadcast a pytree to ``n`` stacked replicas on a new leading axis
    (per-worker state for the EASGD/GoSGD rules)."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), tree
    )
