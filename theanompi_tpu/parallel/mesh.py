"""Device-mesh runtime.

Replaces the reference's per-process device/communicator setup
(reference: ``lib/base.py`` — ``MPI_GPU_Process.init_device``,
``get_internode_comm`` (MPI world), ``get_intranode_comm`` (NCCL clique);
SURVEY.md §1 L1). On TPU there is no process-per-device or dual
MPI/NCCL hierarchy: a named ``Mesh`` spans all chips, XLA lowers
collectives onto ICI within a slice and DCN across slices, and
``jax.distributed.initialize`` (multi-host) replaces ``mpirun``.

Axis naming: today's rules are pure data parallelism, so the mesh is
1-D ``('data',)`` — but everything takes the axis names from here so a
``('data', 'model')`` mesh is additive later (SURVEY.md §5.7 note).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
# EASGD runs on a 2-D ('group', 'data') mesh: see parallel/easgd.py
GROUP_AXIS = "group"


def make_mesh(
    devices: Union[int, Sequence, None] = None,
    axis_names: tuple[str, ...] = (DATA_AXIS,),
    shape: Optional[tuple[int, ...]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (count, explicit list, or None=all).

    ``shape`` reshapes the device list for multi-axis meshes; default is
    1-D over all requested devices.
    """
    if devices is None:
        devs = jax.devices()
    elif isinstance(devices, int):
        all_devs = jax.devices()
        if devices > len(all_devs):
            raise ValueError(
                f"requested {devices} devices but only {len(all_devs)} present "
                f"({[d.platform for d in all_devs[:1]]}); for CPU-mesh testing set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N before jax import"
            )
        devs = all_devs[:devices]
    else:
        devs = list(devices)
    arr = np.array(devs)
    if shape is not None:
        arr = arr.reshape(shape)
    elif len(axis_names) > 1:
        raise ValueError("multi-axis mesh needs an explicit shape")
    return Mesh(arr, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim across the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def host_local_batch_slice(mesh: Mesh, global_batch: int) -> slice:
    """The slice of the global batch this host should produce.

    Single-controller: the whole batch. Multi-controller (one process
    per TPU host, reference: one loader per worker rank): each host
    feeds only its addressable shard — the analogue of the reference's
    per-rank batch-file partition (``models/data/imagenet.py``).
    """
    n_proc = jax.process_count()
    per_host = global_batch // n_proc
    idx = jax.process_index()
    return slice(idx * per_host, (idx + 1) * per_host)


def put_global_batch(mesh: Mesh, x, axis: str = DATA_AXIS):
    """Place a host batch onto the mesh sharded along the data axis.

    Single-device meshes use a plain device placement: some backends
    (measured: the axon-tunneled v5e) run programs whose inputs carry a
    NamedSharding ~90x slower than identical unsharded programs, and with
    one device the sharding is vacuous anyway.
    """
    if mesh.devices.size == 1:
        return jax.device_put(x, mesh.devices.reshape(-1)[0])
    return jax.device_put(x, batch_sharding(mesh, axis))


def stack_replicas(tree, n: int):
    """Broadcast a pytree to ``n`` stacked replicas on a new leading axis
    (per-worker state for the EASGD/GoSGD rules)."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), tree
    )
