"""Pipeline parallelism (PP) over a named ``pipe`` mesh axis.

BEYOND-PARITY EXTENSION (SURVEY.md §2.3: PP "absent — not required" in
the 2016 reference; the named-mesh design note makes the axis additive).

TPU-idiomatic GPipe: transformer layers are stacked on a leading dim and
SHARDED over the ``pipe`` axis — each device owns a contiguous stage of
``n_layers / n_pipe`` layers and scans them locally. Microbatches stream
through the stages with ONE ``lax.ppermute`` hop per schedule tick
inside a ``lax.scan``; the whole schedule is a single differentiable
SPMD program, so the backward pass (activation cotangents flowing
backwards through the transposed ppermutes — reverse pipeline) comes
from AD, not hand-written schedule code. Memory and bubble profile are
GPipe's: ``M + n - 1`` ticks for ``M`` microbatches over ``n`` stages,
bubble fraction ``(n-1)/(M+n-1)``.

Embedding runs on stage 0, head + loss on the last stage; both weight
tensors are replicated (their gradients arrive via the universal
spec-sync rule — transformer.py::sync_grads_by_spec). Composes with
data parallelism on a 2-D ``(pipe, data)`` mesh.

``interleave=v`` switches to the Megatron-style interleaved schedule:
each device owns ``v`` non-contiguous layer chunks (device ``d`` holds
global stages ``d, d+n, …, d+(v-1)n``), microbatches stream in groups
of ``n`` and loop around the device ring ``v`` times (the ppermute ring
gains its wraparound edge), and the fill/drain bubble shrinks by the
factor ``v``: fraction ``(n-1)/(M·v + n - 1)``. The schedule is fully
static and collision-free — device ``d`` processes chunk ``c`` of
microbatch ``g·n + r`` exactly at tick ``g·n·v + c·n + r + d`` — so it
stays one differentiable ``lax.scan`` and the backward pass is still
pure AD. :func:`pipeline_schedule_report` quantifies the tradeoff and
recommends microbatch counts.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.models.transformer import (
    TransformerLM,
    _rms,
    attention_block,
    build_spec_step,
    cast_block_params,
    global_positions,
    next_token_loss,
    pick_nll,
    sync_grads_by_spec,
    validate_tp_divisibility,
    validate_ulysses_heads,
)

PIPE_AXIS = "pipe"


def _interleave_order(n_layers: int, n_stages: int, interleave: int):
    """Stacking order for the interleaved layout: device ``d``'s shard
    must hold its ``v`` chunks contiguously — chunk ``c`` of device
    ``d`` is global stage ``c·n + d``, i.e. layers
    ``[(c·n+d)·Lc, (c·n+d+1)·Lc)`` with ``Lc = L/(n·v)``."""
    if n_stages < 1:
        raise ValueError(
            f"interleave={interleave} needs the mesh's n_stages "
            f"(got {n_stages})"
        )
    lc = n_layers // (n_stages * interleave)
    order = []
    for d in range(n_stages):
        for c in range(interleave):
            base = (c * n_stages + d) * lc
            order.extend(range(base, base + lc))
    return order


def stack_pipeline_params(params, *, n_stages: int = 0, interleave: int = 1):
    """Convert TransformerLM params (list of per-layer block dicts) to
    the pipeline layout: block leaves stacked on a leading layer dim
    (shardable over the pipe axis); other leaves unchanged. With
    ``interleave > 1`` the layers are permuted so each device's shard
    holds its ``v`` round-robin chunks (pass the mesh's ``n_stages``)."""
    layers = params["blocks"]
    if interleave > 1:
        order = _interleave_order(len(layers), n_stages, interleave)
        layers = [layers[i] for i in order]
    blocks = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return {k: (blocks if k == "blocks" else v) for k, v in params.items()}


def unstack_pipeline_params(stacked, n_layers: int, *, n_stages: int = 0,
                            interleave: int = 1):
    """Inverse of :func:`stack_pipeline_params` (for checkpoint interop
    and test oracles)."""
    layers = [
        jax.tree_util.tree_map(lambda x: x[i], stacked["blocks"])
        for i in range(n_layers)
    ]
    if interleave > 1:
        order = _interleave_order(n_layers, n_stages, interleave)
        inv = [0] * n_layers
        for pos, src in enumerate(order):
            inv[src] = pos
        layers = [layers[inv[i]] for i in range(n_layers)]
    return {k: (layers if k == "blocks" else v) for k, v in stacked.items()}


def pipeline_schedule_report(n_stages: int, microbatches: int,
                             interleave: int = 1) -> dict:
    """Analytic schedule accounting (the numbers the scan actually
    executes — tick counts are exact, not asymptotic):

    - plain GPipe (``interleave=1``): ``M + n - 1`` ticks of one full
      stage each; bubble fraction ``(n-1)/(M+n-1)``.
    - interleaved: ``⌈M/n⌉·n·v + n - 1`` ticks of one CHUNK
      (``1/v`` stage) each; bubble fraction ``(n-1)/(⌈M/n⌉·n·v+n-1)``.

    ``suggested_microbatches`` is the smallest M keeping the bubble
    under 10%.
    """
    n, m, v = n_stages, microbatches, interleave
    if v == 1:
        ticks, work = m + n - 1, m
    else:
        groups = -(-m // n)
        ticks, work = groups * n * v + n - 1, m * v
    bubble = (ticks - work) / ticks
    # bubble < 10% (strict): (n-1)/(M·v + n - 1) < 0.1  =>  M > 9(n-1)/v
    suggest = max(n, 9 * (n - 1) // v + 1)
    if v > 1:
        suggest = -(-suggest // n) * n  # groups of n
    return {
        "n_stages": n,
        "microbatches": m,
        "interleave": v,
        "ticks": ticks,
        "tick_fraction_of_stage": 1.0 / v,
        "bubble_fraction": bubble,
        "suggested_microbatches": suggest,
    }


def pipeline_param_specs(pipe_axis: str = PIPE_AXIS,
                         tp_axis: Optional[str] = None):
    """Specs for the stacked layout: the layer dim sharded over pipe,
    embeddings/head replicated. With ``tp_axis``, each stage's blocks
    are ALSO Megatron-sharded within the stage (heads / d_ff / vocab —
    the stacked-layout shift of :meth:`TransformerLM.tp_param_specs`):
    the standard large-LM pp x tp layout."""
    if tp_axis is None:
        blk = jax.tree_util.tree_map(lambda _: P(pipe_axis), _BLOCK_TEMPLATE)
        head = P()
    else:
        blk = {
            "qkv": P(pipe_axis, None, None, tp_axis, None),  # heads
            "proj": P(pipe_axis, tp_axis, None, None),       # heads (row)
            "mlp_in": P(pipe_axis, None, tp_axis),           # d_ff cols
            "mlp_out": P(pipe_axis, tp_axis, None),          # d_ff rows
            "ln1": P(pipe_axis),
            "ln2": P(pipe_axis),
        }
        head = P(None, tp_axis)                              # vocab cols
    return {
        "tok_emb": P(),
        "pos_emb": P(),
        "head": head,
        "blocks": blk,
    }


# structure template for a block's param dict (leaf values unused)
_BLOCK_TEMPLATE = {
    "qkv": 0, "proj": 0, "mlp_in": 0, "mlp_out": 0, "ln1": 0, "ln2": 0
}


def _apply_stage(blocks_local, x, dtype=jnp.float32,
                 tp_axis: Optional[str] = None,
                 sp_axis: Optional[str] = None, attn: str = "ring"):
    """Scan this device's stacked layers over the activation. With
    ``tp_axis`` each layer's heads/FFN arrive stage-locally Megatron-
    sharded: one psum after the attention projection and one after the
    FFN out-projection per layer (the same two collectives as the dense
    TP forward — models/transformer.py::TransformerLM.forward). With
    ``sp_axis`` the activation's sequence dim is sharded and attention
    runs ring/Ulysses over it (the model's ``attn`` scheme), inside
    each schedule tick."""

    def body(h, blk):
        blk = cast_block_params(blk, dtype)
        # attention_block handles sp_axis=None for every scheme (flash
        # variants stay on the fused kernel; ring/ulysses degenerate to
        # the full reference) — pass the model's scheme through
        delta = attention_block(blk, h, attn, sp_axis)
        if tp_axis is not None:
            delta = lax.psum(delta, tp_axis)  # row-parallel proj
        h = h + delta
        hin = _rms(h, blk["ln2"])
        delta = jax.nn.gelu(hin @ blk["mlp_in"]) @ blk["mlp_out"]
        if tp_axis is not None:
            delta = lax.psum(delta, tp_axis)  # row-parallel mlp_out
        return h + delta, None

    h, _ = lax.scan(body, x, blocks_local)
    return h


def validate_pp_mesh(model: TransformerLM, mesh: Mesh, pipe_axis: str,
                     dp_axis: Optional[str], interleave: int = 1,
                     tp_axis: Optional[str] = None,
                     sp_axis: Optional[str] = None):
    """Shared mesh/shape validation for the pipeline step builders.
    Returns ``(axes, n_total)``."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if pipe_axis not in sizes:
        raise ValueError(f"axis {pipe_axis!r} not in mesh axes {mesh.axis_names}")
    for a in (dp_axis, tp_axis, sp_axis):
        if a is not None and a not in sizes:
            raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
    n_pipe = sizes[pipe_axis]
    if getattr(model, "loss_chunk", None):
        raise ValueError(
            "loss_chunk is not implemented for the pipeline branch "
            "(its head loss runs whole-sequence per microbatch)"
        )
    if interleave < 1:
        raise ValueError(f"interleave={interleave} must be >= 1")
    if model.n_layers % (n_pipe * interleave):
        raise ValueError(
            f"the {pipe_axis!r} axis size x interleave = "
            f"{n_pipe}x{interleave} must divide n_layers={model.n_layers}"
        )
    ntp = sizes[tp_axis] if tp_axis else 1
    if tp_axis is not None:
        validate_tp_divisibility(model, tp_axis, ntp)
    validate_ulysses_heads(model, sp_axis, sizes, model.n_heads // ntp)
    axes = [pipe_axis] + [a for a in (dp_axis, tp_axis, sp_axis) if a]
    n_total = 1
    for a in axes:
        n_total *= sizes[a]
    return axes, n_total


def make_pipeline_loss(model: TransformerLM, pipe_axis: str = PIPE_AXIS,
                       interleave: int = 1, tp_axis: Optional[str] = None,
                       sp_axis: Optional[str] = None):
    """``(stacked_params, tokens [M, B, T]) -> loss`` — the pipeline
    schedule (GPipe, or Megatron-interleaved when ``interleave > 1``)
    as one differentiable function (runs inside shard_map). Shared by
    :func:`make_pp_train_step` and the launchable
    ``parallel.nd.NDEngine`` pipeline branch. With ``tp_axis``, each
    stage's compute is Megatron-sharded within the stage and the head
    is vocab-sharded with the distributed softmax cross-entropy. With
    ``sp_axis``, the sequence dim is sharded over it: each schedule
    tick's attention runs ring/Ulysses across the axis and the
    next-token targets cross shard boundaries via the standard ppermute
    (transformer.py::next_token_loss — every sp/tp collective runs
    uniformly on all pipe ranks, SPMD; the pipe mask picks the real
    last-stage loss)."""

    def _head_loss(params, outs, tokens, rank, n):
        logits = outs @ params["head"].astype(model.dtype)  # [M, B, T, V(/tp)]
        M, Bb, T = tokens.shape
        # microbatches fold into the batch dim: the objective (mean over
        # batch rows x the GLOBAL sequence, boundary targets fetched
        # across sp shards, final global position masked) is exactly the
        # dense LM's next_token_loss
        local = next_token_loss(
            tokens.reshape(M * Bb, T), sp_axis,
            pick_nll(logits.reshape(M * Bb, T, logits.shape[-1]), tp_axis),
        )
        # only the last stage computed real logits; broadcast its loss
        return lax.psum(jnp.where(rank == n - 1, local, 0.0), pipe_axis)

    def pipeline_loss(params, tokens):
        M, B, T = tokens.shape
        n = lax.psum(1, pipe_axis)
        rank = lax.axis_index(pipe_axis)
        fwd_perm = [(i, i + 1) for i in range(n - 1)]

        # stage-0 inputs for every microbatch (other ranks' copies are
        # dead code XLA keeps cheap; grads gate on rank 0 via the where)
        emb = (
            params["tok_emb"][tokens]
            + params["pos_emb"][global_positions(sp_axis, T)][None, None]
        ).astype(model.dtype)

        outs0 = jnp.zeros((M, B, T, model.d_model), model.dtype)
        act0 = jnp.zeros((B, T, model.d_model), model.dtype)

        def tick(carry, t):
            act, outs = carry
            act_in = lax.ppermute(act, pipe_axis, fwd_perm)
            inject = emb[jnp.clip(t, 0, M - 1)]
            x = jnp.where(rank == 0, inject, act_in)
            y = _apply_stage(params["blocks"], x, model.dtype, tp_axis,
                             sp_axis, model.attn)
            m = t - (n - 1)
            take = (m >= 0) & (m < M) & (rank == n - 1)
            sel = (jnp.arange(M) == jnp.clip(m, 0, M - 1))[:, None, None, None]
            outs = jnp.where(take & sel, y[None], outs)
            return (y, outs), None

        (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(M + n - 1))
        return _head_loss(params, outs, tokens, rank, n)

    def interleaved_loss(params, tokens):
        # Schedule (see module docstring): device d runs chunk c of
        # microbatch m = g*n + r at tick g*n*v + c*n + r + d; the ring
        # hop INCLUDING the (n-1)->0 wraparound edge carries an
        # activation from chunk c's last device to chunk c+1's first.
        # Collision-free: two pairs (m,j),(m',j') with the same device
        # and tick need j-j' = (m'-m)*n*v + k*n with |j-j'| < n*v —
        # forcing m'=m. Fill/drain bubble: n-1 CHUNK-ticks.
        M, B, T = tokens.shape
        n = lax.psum(1, pipe_axis)
        rank = lax.axis_index(pipe_axis)
        v = interleave
        if M % n:
            raise ValueError(
                f"interleaved pipeline needs microbatches ({M}) in "
                f"groups of the stage count ({n})"
            )
        G = M // n
        ring = [(i, (i + 1) % n) for i in range(n)]

        emb = (
            params["tok_emb"][tokens]
            + params["pos_emb"][global_positions(sp_axis, T)][None, None]
        ).astype(model.dtype)
        outs0 = jnp.zeros((M, B, T, model.d_model), model.dtype)
        act0 = jnp.zeros((B, T, model.d_model), model.dtype)
        # local shard [L/n, ...] -> [v, Lc, ...]: chunk-major per device
        blocks = jax.tree_util.tree_map(
            lambda x: x.reshape(v, x.shape[0] // v, *x.shape[1:]),
            params["blocks"],
        )

        def tick(carry, t):
            act, outs = carry
            act_in = lax.ppermute(act, pipe_axis, ring)
            s = jnp.clip(t - rank, 0, G * n * v - 1)
            in_range = (t >= rank) & (t - rank < G * n * v)
            u = s % (n * v)
            c = u // n
            m = (s // (n * v)) * n + u % n
            inject = (rank == 0) & (c == 0)
            x = jnp.where(inject, emb[m], act_in)
            chunk = jax.tree_util.tree_map(lambda x_: x_[c], blocks)
            y = _apply_stage(chunk, x, model.dtype, tp_axis,
                             sp_axis, model.attn)
            take = in_range & (rank == n - 1) & (c == v - 1)
            sel = (jnp.arange(M) == m)[:, None, None, None]
            outs = jnp.where(take & sel, y[None], outs)
            return (y, outs), None

        total = G * n * v + n - 1
        (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(total))
        return _head_loss(params, outs, tokens, rank, n)

    return pipeline_loss if interleave == 1 else interleaved_loss


def make_pp_train_step(
    model: TransformerLM,
    mesh: Mesh,
    lr: float = 1e-2,
    *,
    pipe_axis: str = PIPE_AXIS,
    dp_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
    sp_axis: Optional[str] = None,
    optimizer=None,
    interleave: int = 1,
    donate: bool = False,
):
    """Jitted pipeline-parallel train step ``(stacked_params, tokens) ->
    (stacked_params, loss)`` (or over ``(params, opt_state)`` with
    ``optimizer``). ``tokens [M, B, T]`` is microbatch-major — build it
    by reshaping the global batch; ``B`` is sharded over ``dp_axis`` if
    given. Params use :func:`stack_pipeline_params`'s layout (pass the
    same ``interleave``/``n_stages`` to it when ``interleave > 1``).
    With ``tp_axis``, stages are internally Megatron-sharded
    (pp x tp (x dp) — the standard large-LM layout); with ``sp_axis``
    the sequence dim is additionally sharded (ring/Ulysses attention
    per schedule tick) — all four axes compose in one SPMD program."""
    axes, n_total = validate_pp_mesh(
        model, mesh, pipe_axis, dp_axis, interleave, tp_axis, sp_axis
    )
    param_specs = pipeline_param_specs(pipe_axis, tp_axis)
    pipeline_loss = make_pipeline_loss(
        model, pipe_axis, interleave, tp_axis, sp_axis
    )
    n_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[pipe_axis]

    def body(params, tokens):
        loss, grads = jax.value_and_grad(pipeline_loss)(params, tokens)
        grads = sync_grads_by_spec(grads, param_specs, axes, n_total)
        if dp_axis is not None:
            loss = lax.pmean(loss, dp_axis)
        return loss, grads

    tok_spec = (
        P(None, dp_axis, sp_axis) if (dp_axis or sp_axis) else P()
    )
    return build_spec_step(
        body, mesh, param_specs, tok_spec, lr, optimizer,
        lambda: stack_pipeline_params(
            model.init(jax.random.PRNGKey(0)),
            n_stages=n_stages, interleave=interleave,
        ),
        # ISSUE 2 donation audit: default False keeps the oracle-test
        # contract (inputs reusable); training loops that thread state
        # pass donate=True to hold one stacked-params(+opt) copy
        donate=donate,
    )
