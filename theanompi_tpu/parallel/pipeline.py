"""Pipeline parallelism (PP) over a named ``pipe`` mesh axis.

BEYOND-PARITY EXTENSION (SURVEY.md §2.3: PP "absent — not required" in
the 2016 reference; the named-mesh design note makes the axis additive).

TPU-idiomatic GPipe: transformer layers are stacked on a leading dim and
SHARDED over the ``pipe`` axis — each device owns a contiguous stage of
``n_layers / n_pipe`` layers and scans them locally. Microbatches stream
through the stages with ONE ``lax.ppermute`` hop per schedule tick
inside a ``lax.scan``; the whole schedule is a single differentiable
SPMD program, so the backward pass (activation cotangents flowing
backwards through the transposed ppermutes — reverse pipeline) comes
from AD, not hand-written schedule code. Memory and bubble profile are
GPipe's: ``M + n - 1`` ticks for ``M`` microbatches over ``n`` stages,
bubble fraction ``(n-1)/(M+n-1)``.

Embedding runs on stage 0, head + loss on the last stage; both weight
tensors are replicated (their gradients arrive via the universal
spec-sync rule — transformer.py::sync_grads_by_spec). Composes with
data parallelism on a 2-D ``(pipe, data)`` mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.models.transformer import (
    TransformerLM,
    _rms,
    build_spec_step,
    sync_grads_by_spec,
)
from theanompi_tpu.ops.ring_attention import full_attention_reference

PIPE_AXIS = "pipe"


def stack_pipeline_params(params):
    """Convert TransformerLM params (list of per-layer block dicts) to
    the pipeline layout: block leaves stacked on a leading layer dim
    (shardable over the pipe axis); other leaves unchanged."""
    blocks = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params["blocks"]
    )
    return {k: (blocks if k == "blocks" else v) for k, v in params.items()}


def unstack_pipeline_params(stacked, n_layers: int):
    """Inverse of :func:`stack_pipeline_params` (for checkpoint interop
    and test oracles)."""
    blocks = [
        jax.tree_util.tree_map(lambda x: x[i], stacked["blocks"])
        for i in range(n_layers)
    ]
    return {k: (blocks if k == "blocks" else v) for k, v in stacked.items()}


def pipeline_param_specs(pipe_axis: str = PIPE_AXIS):
    """Specs for the stacked layout: the layer dim sharded over pipe,
    embeddings/head replicated."""
    return {
        "tok_emb": P(),
        "pos_emb": P(),
        "head": P(),
        "blocks": jax.tree_util.tree_map(
            lambda _: P(pipe_axis), _BLOCK_TEMPLATE
        ),
    }


# structure template for a block's param dict (leaf values unused)
_BLOCK_TEMPLATE = {
    "qkv": 0, "proj": 0, "mlp_in": 0, "mlp_out": 0, "ln1": 0, "ln2": 0
}


def _apply_stage(blocks_local, x):
    """Scan this device's stacked layers over the activation."""

    def body(h, blk):
        hin = _rms(h, blk["ln1"])
        qkv = jnp.einsum("btd,dchk->btchk", hin, blk["qkv"])
        att = full_attention_reference(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True
        )
        h = h + jnp.einsum("bthk,hkd->btd", att, blk["proj"])
        hin = _rms(h, blk["ln2"])
        h = h + jax.nn.gelu(hin @ blk["mlp_in"]) @ blk["mlp_out"]
        return h, None

    h, _ = lax.scan(body, x, blocks_local)
    return h


def validate_pp_mesh(model: TransformerLM, mesh: Mesh, pipe_axis: str,
                     dp_axis: Optional[str]):
    """Shared mesh/shape validation for the pipeline step builders.
    Returns ``(axes, n_total)``."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if pipe_axis not in sizes:
        raise ValueError(f"axis {pipe_axis!r} not in mesh axes {mesh.axis_names}")
    if dp_axis is not None and dp_axis not in sizes:
        raise ValueError(f"axis {dp_axis!r} not in mesh axes {mesh.axis_names}")
    n_pipe = sizes[pipe_axis]
    if model.n_layers % n_pipe:
        raise ValueError(
            f"n_layers={model.n_layers} must divide the {pipe_axis!r} "
            f"axis size {n_pipe}"
        )
    axes = [pipe_axis] + ([dp_axis] if dp_axis else [])
    n_total = 1
    for a in axes:
        n_total *= sizes[a]
    return axes, n_total


def make_pipeline_loss(model: TransformerLM, pipe_axis: str = PIPE_AXIS):
    """``(stacked_params, tokens [M, B, T]) -> loss`` — the GPipe
    schedule as one differentiable function (runs inside shard_map).
    Shared by :func:`make_pp_train_step` and the launchable
    ``parallel.nd.NDEngine`` pipeline branch."""

    def pipeline_loss(params, tokens):
        M, B, T = tokens.shape
        n = lax.psum(1, pipe_axis)
        rank = lax.axis_index(pipe_axis)
        fwd_perm = [(i, i + 1) for i in range(n - 1)]

        # stage-0 inputs for every microbatch (other ranks' copies are
        # dead code XLA keeps cheap; grads gate on rank 0 via the where)
        emb = params["tok_emb"][tokens] + params["pos_emb"][jnp.arange(T)][None, None]

        outs0 = jnp.zeros((M, B, T, model.d_model))
        act0 = jnp.zeros((B, T, model.d_model))

        def tick(carry, t):
            act, outs = carry
            act_in = lax.ppermute(act, pipe_axis, fwd_perm)
            inject = emb[jnp.clip(t, 0, M - 1)]
            x = jnp.where(rank == 0, inject, act_in)
            y = _apply_stage(params["blocks"], x)
            m = t - (n - 1)
            take = (m >= 0) & (m < M) & (rank == n - 1)
            sel = (jnp.arange(M) == jnp.clip(m, 0, M - 1))[:, None, None, None]
            outs = jnp.where(take & sel, y[None], outs)
            return (y, outs), None

        (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(M + n - 1))

        logits = outs @ params["head"]  # [M, B, T, V]
        targets = jnp.concatenate([tokens[:, :, 1:], tokens[:, :, :1]], axis=-1)
        valid = jnp.broadcast_to(
            (jnp.arange(T) < T - 1).astype(jnp.float32), tokens.shape
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        local = jnp.sum(nll * valid) / jnp.sum(valid)
        # only the last stage computed real logits; broadcast its loss
        return lax.psum(jnp.where(rank == n - 1, local, 0.0), pipe_axis)

    return pipeline_loss


def make_pp_train_step(
    model: TransformerLM,
    mesh: Mesh,
    lr: float = 1e-2,
    *,
    pipe_axis: str = PIPE_AXIS,
    dp_axis: Optional[str] = None,
    optimizer=None,
):
    """Jitted pipeline-parallel train step ``(stacked_params, tokens) ->
    (stacked_params, loss)`` (or over ``(params, opt_state)`` with
    ``optimizer``). ``tokens [M, B, T]`` is microbatch-major — build it
    by reshaping the global batch; ``B`` is sharded over ``dp_axis`` if
    given. Params use :func:`stack_pipeline_params`'s layout.
    """
    axes, n_total = validate_pp_mesh(model, mesh, pipe_axis, dp_axis)
    param_specs = pipeline_param_specs(pipe_axis)
    pipeline_loss = make_pipeline_loss(model, pipe_axis)

    def body(params, tokens):
        loss, grads = jax.value_and_grad(pipeline_loss)(params, tokens)
        grads = sync_grads_by_spec(grads, param_specs, axes, n_total)
        if dp_axis is not None:
            loss = lax.pmean(loss, dp_axis)
        return loss, grads

    tok_spec = P(None, dp_axis) if dp_axis else P()
    return build_spec_step(
        body, mesh, param_specs, tok_spec, lr, optimizer,
        lambda: stack_pipeline_params(model.init(jax.random.PRNGKey(0))),
    )
