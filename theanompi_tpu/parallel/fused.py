"""Shared fused-dispatch builder: ``g`` engine steps in one program.

One ``lax.scan`` over stacked per-step inputs inside one ``shard_map``
— the dispatch-amortization pattern ``parallel/bsp.py``'s
``make_bsp_fused_step`` introduced (host dispatch costs ~10 ms on pods
against ~15 ms steps), factored out so the ND and ZeRO engines share a
single implementation. BSP itself keeps its bespoke builder: its fused
body is NOT its per-step function (it re-derives per-substep keys with
``_fold_linear_index`` and carries an n==1 special case), so forcing it
through this helper would change its key-derivation contract.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def fuse_sharded_step(step_fn, mesh: Mesh, state_specs, stacked_in_specs,
                      donate: bool):
    """Jitted ``(state, *stacked_inputs) -> (state, stacked_metrics)``:
    scans ``step_fn(state, *per_step_inputs) -> (state, metrics)`` over
    the leading (group) dim of every stacked input. ``stacked_in_specs``
    are the per-step input specs with the group dim prepended as
    replicated (``P(None, *spec)``) by the caller."""

    def sharded_fused(state, *stacked):
        def body(st, inp):
            return step_fn(st, *inp)

        return lax.scan(body, state, tuple(stacked))

    return jax.jit(
        jax.shard_map(
            sharded_fused,
            mesh=mesh,
            in_specs=(state_specs, *stacked_in_specs),
            out_specs=(state_specs, P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )
