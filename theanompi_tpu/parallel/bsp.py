"""BSP data-parallel training step.

TPU-native rebuild of the reference's BSP rule (reference:
``lib/exchanger.py`` — ``BSP_Exchanger.exchange()`` called between
Theano functions each iteration; SURVEY.md §3.2). Here the whole BSP
iteration — forward, backward, gradient allreduce, update — is ONE
``jax.jit``-compiled SPMD program over a ``('data',)`` mesh:

- the per-device batch shard comes in sharded along ``data``;
- params / optimizer state are replicated; every device computes the
  identical update after the gradient mean (lockstep by construction —
  the XLA program IS the barrier, where the reference relied on
  blocking MPI allreduce);
- the exchanger strategy is compiled into the step (``psum`` by
  default, explicit/compressed ring variants for parity with
  ``asa32``/``asa16``).
"""

from __future__ import annotations

import os

import jax
from jax import lax
from jax.sharding import Mesh

from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.parallel.strategies import (
    bucketed,
    checked_mode_strategy,
    get_strategy,
)
from theanompi_tpu.train import TrainState, init_train_state, make_eval_step, make_train_step


def _checked_vma() -> bool:
    """Module switch executing the check_vma migration plan for the BSP
    engine (parallel/strategies.py "check_vma pin & migration plan"):
    ``TMPI_CHECKED_VMA=1`` builds every BSP shard_map with
    ``check_vma=True`` and swaps the exchanger for its checked-mode form
    (division by the axis size — AD already summed the cotangents).
    Measured outcome (round 5, jax 0.9.0, 8-device CPU mesh): the full
    BSP oracle suite passes identically both ways, single-step params
    agree to float epsilon, forward cross-replica collectives (BN pmean)
    included — see tests/test_bsp.py::TestCheckedVmaBSP. Default stays
    classic semantics: the OTHER engines (easgd/gosgd/nd/zero/fused
    strategies) still assume local-grad AD, and the plan requires the
    flip to land everywhere at once."""
    return os.environ.get("TMPI_CHECKED_VMA", "") == "1"


def _axes_tuple(axis_name) -> tuple:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


from theanompi_tpu.parallel.mesh import fold_linear_index as _fold_linear_index


def _bsp_recipe(mesh, axis_name, codec):
    """The BSP :class:`~theanompi_tpu.parallel.recipe.ShardingRecipe`:
    everything replicated, EXCEPT the codec's error-feedback residuals,
    which are per-device (stacked ``[n, ...]``) and must be declared
    sharded over the data axes — a blanket replicated spec would stamp
    device-varying residuals as replicated with no error under
    ``check_vma=False``. THE single spec source for this engine's
    shard_map specs, memory factors, and topology stamp."""
    from theanompi_tpu.parallel.recipe import ShardingRecipe

    return ShardingRecipe.bsp(
        mesh, axis_name,
        ef_sharded=codec is not None and codec.error_feedback,
    )


def _bsp_grad_sync(strategy, axis_name, n, codec, checked,
                   allreduce_buckets, axis_sizes=None):
    """The one place the BSP step builders resolve their exchanger:
    ``--allreduce-buckets`` swaps the single psum for the bucketed
    overlap scheduler (parallel/strategies.py::BucketedOverlapSync);
    checked-mode AD has no exchanger collective to bucket and refuses.
    ``axis_sizes``: the per-axis mesh extents (mesh-axis order) the
    'hier' strategy needs to stage its two-hop schedule."""
    if allreduce_buckets:
        if checked:
            raise ValueError(
                "--allreduce-buckets has nothing to bucket under "
                "TMPI_CHECKED_VMA=1: checked-mode AD already summed the "
                "cotangents, there is no exchanger collective"
            )
        return bucketed(strategy, axis_name, n, allreduce_buckets,
                        codec=codec, axis_sizes=axis_sizes)
    return (
        checked_mode_strategy(strategy, axis_name, n, codec=codec) if checked
        else get_strategy(strategy, axis_name, n, codec=codec,
                          axis_sizes=axis_sizes)
    )


def make_bsp_train_step(
    model: Model,
    mesh: Mesh,
    steps_per_epoch: int = 1,
    strategy: str = "psum",
    axis_name=DATA_AXIS,
    donate: bool = True,
    input_transform=None,
    accum_steps: int = 1,
    numerics: bool = False,
    wire_codec=None,
    fused_update: bool = False,
    allreduce_buckets: float = 0.0,
):
    """Build the jitted BSP step: ``(state, images, labels, rng) ->
    (state, metrics)`` over global arrays. ``accum_steps``: gradient
    accumulation inside the step (see train.make_train_step) — the
    per-DEVICE batch splits into that many microbatches.

    ``images``/``labels`` hold the GLOBAL batch (sharded or shardable
    along ``data``); ``state`` is replicated; ``rng`` is a single key —
    each device folds in its axis index so dropout masks differ per
    shard (the reference's workers each had their own RNG stream).

    ``axis_name`` may be a TUPLE of mesh axes for multi-slice meshes
    (``('dcn', 'data')``): the gradient mean then reduces over ICI
    within each slice and DCN across slices — XLA lowers the hierarchy
    from the mesh layout (SURVEY.md §5.8 "topology split").

    ``fused_update``: one-pass optimizer epilogue (train.make_train_step
    / ops/pallas_update.py). ``allreduce_buckets`` (MB, 0 = off): chunk
    the gradient allreduce into ~MB buckets whose psums launch inside
    backward (parallel/strategies.py::BucketedOverlapSync) — same
    numerics as the single psum, strategy 'psum' only.
    """
    from theanompi_tpu.parallel.codec import get_codec

    codec = get_codec(wire_codec)
    allreduce_buckets = float(allreduce_buckets or 0.0)
    axes = _axes_tuple(axis_name)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    axis_sizes = tuple(int(mesh.shape[a]) for a in axes)
    if n == 1:
        # validate early (bucketed also checks the strategy/codec pair);
        # a 1-device mesh has no collectives, so buckets are a no-op
        if allreduce_buckets:
            bucketed(strategy, axis_name, n, allreduce_buckets, codec=codec,
                     axis_sizes=axis_sizes)
        else:
            get_strategy(strategy, axis_name, n, codec=codec,
                         axis_sizes=axis_sizes)
        # Single-device fast path: no collectives exist, so skip the
        # shard_map machinery entirely (it pays real dispatch overhead on
        # some backends) — the plain jitted step is semantically identical.
        # Donation is also disabled here: on the tunneled single-chip
        # backend donated buffers trigger a relayout-recompile and a
        # ~4x steady-state slowdown (measured), and the memory it would
        # save is not binding on one chip.
        base = make_train_step(model, steps_per_epoch,
                               input_transform=input_transform,
                               accum_steps=accum_steps, numerics=numerics,
                               fused_update=fused_update)

        def single_step(state, images, labels, rng):
            return base(state, images, labels, jax.random.fold_in(rng, 0))

        return jax.jit(single_step)

    checked = _checked_vma()
    grad_sync = _bsp_grad_sync(strategy, axis_name, n, codec, checked,
                               allreduce_buckets, axis_sizes=axis_sizes)
    base_step = make_train_step(
        model, steps_per_epoch, grad_sync=grad_sync,
        input_transform=input_transform, accum_steps=accum_steps,
        numerics=numerics, fused_update=fused_update,
    )

    def sharded_step(state: TrainState, images, labels, rng):
        rng = _fold_linear_index(rng, axes, mesh)
        new_state, metrics = base_step(state, images, labels, rng)
        # Per-replica BatchNorm stats diverge across shards; average them
        # so the output state is truly replicated (the reference kept
        # per-worker stats and checkpointed rank 0's — averaging is the
        # better-defined equivalent).
        new_state = new_state._replace(
            model_state=lax.pmean(new_state.model_state, axis_name)
        )
        metrics = lax.pmean(metrics, axis_name)
        return new_state, metrics

    # check_vma=False by default: the exchanger abstraction requires
    # classic pmap AD semantics (psum transpose = psum) — see
    # make_train_step's note. TMPI_CHECKED_VMA=1 flips this engine to
    # the migrated checked-mode semantics (_checked_vma docstring).
    recipe = _bsp_recipe(mesh, axis_name, codec)
    spec = recipe.batch_spec
    sspec = recipe.state_spec(TrainState)
    mapped = jax.shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(sspec, spec, spec, recipe.scalar),
        out_specs=(sspec, recipe.scalar),
        check_vma=checked,
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def make_bsp_fused_step(
    model: Model,
    mesh: Mesh,
    steps_per_epoch: int = 1,
    strategy: str = "psum",
    axis_name=DATA_AXIS,
    input_transform=None,
    accum_steps: int = 1,
    numerics: bool = False,
    wire_codec=None,
    fused_update: bool = False,
    allreduce_buckets: float = 0.0,
):
    """``k`` BSP steps fused into ONE compiled program via ``lax.scan``
    over stacked batches ``[k, batch, ...]`` — one host dispatch (and one
    H2D transfer) per k steps instead of per step. Host dispatch costs
    ~10ms on pods (~100ms on tunneled dev chips) against a ~15ms AlexNet
    step, so fusing is a large wall-clock win; the reference had no
    analogue (Python drove every iteration).

    Takes ``rngs`` STACKED ``[k]`` per-step keys (the driver derives them
    with the same sequential splits the per-step path uses), so each
    fused sub-step computes exactly the per-step math — a single step
    agrees to float epsilon; over a long run the two XLA programs'
    fusion choices accumulate ULP-level drift
    (tests/test_fused_dispatch.py). Returns ``(state, stacked_metrics)``.
    """
    from theanompi_tpu.parallel.codec import get_codec

    codec = get_codec(wire_codec)
    allreduce_buckets = float(allreduce_buckets or 0.0)
    axes = _axes_tuple(axis_name)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    axis_sizes = tuple(int(mesh.shape[a]) for a in axes)
    checked = _checked_vma()

    if n == 1:
        # same validation contract as make_bsp_train_step's n==1 path:
        # names/codec pairs are checked, but the checked-mode bucket
        # refusal does not apply — one device has no collective either
        # way, so the knob is the documented no-op
        if allreduce_buckets:
            bucketed(strategy, axis_name, n, allreduce_buckets, codec=codec,
                     axis_sizes=axis_sizes)
        else:
            get_strategy(strategy, axis_name, n, codec=codec,
                         axis_sizes=axis_sizes)
        base = make_train_step(
            model, steps_per_epoch, input_transform=input_transform,
            accum_steps=accum_steps, numerics=numerics,
            fused_update=fused_update,
        )

        def single(state, images, labels, rngs):
            def body(st, inp):
                x, y, r = inp
                return base(st, x, y, jax.random.fold_in(r, 0))

            return lax.scan(body, state, (images, labels, rngs))

        return jax.jit(single)
    grad_sync = _bsp_grad_sync(  # also validates the name
        strategy, axis_name, n, codec, checked, allreduce_buckets,
        axis_sizes=axis_sizes,
    )
    base_step = make_train_step(
        model, steps_per_epoch, grad_sync=grad_sync,
        input_transform=input_transform, accum_steps=accum_steps,
        numerics=numerics, fused_update=fused_update,
    )

    def sharded_step(state: TrainState, images, labels, rngs):
        def body(st, inp):
            x, y, r = inp
            new_state, metrics = base_step(
                st, x, y, _fold_linear_index(r, axes, mesh)
            )
            new_state = new_state._replace(
                model_state=lax.pmean(new_state.model_state, axis_name)
            )
            return new_state, lax.pmean(metrics, axis_name)

        return lax.scan(body, state, (images, labels, rngs))

    # dim 0 = step index (replicated), dim 1 = batch (sharded).
    # donate like the unfused n>1 step: without it every dispatch holds a
    # second full params+opt copy (the n==1 no-donate rationale in
    # make_bsp_train_step applies to single-chip tunneled backends only)
    recipe = _bsp_recipe(mesh, axis_name, codec)
    spec = recipe.stacked_batch_spec
    sspec = recipe.state_spec(TrainState)
    mapped = jax.shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(sspec, spec, spec, recipe.scalar),
        out_specs=(sspec, recipe.scalar),
        check_vma=checked,
    )
    return jax.jit(mapped, donate_argnums=(0,))


class BSPEngine:
    """Rule-engine wrapper over the BSP step (uniform driver protocol
    shared with EASGDEngine/GOSGDEngine).

    Collective schedule pinned by the SPMD analyzer (ISSUE 7): the
    in-step grad psum + metrics pmean signature is golden-snapshotted
    (tools/analyze/golden/bsp_*.json) and ``traffic_model()`` is
    cross-checked against the traced wire bytes — changing the
    exchange or the analytic model alone fails ``tmpi lint``
    (SPMD003/SPMD101); regenerate with ``tmpi lint --update-golden``."""

    name = "bsp"
    exchange_every = 0  # the allreduce is inside every step
    # donation audit (ISSUE 2): with donate_argnums=(0,) every in-flight
    # step under the async dispatch pipeline reuses the params+opt
    # buffers instead of doubling HBM; the single-device path opts out
    # (tunneled-backend relayout recompile — see make_bsp_train_step)
    # and __init__ overrides this flag accordingly
    donates_state = True

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        strategy: str = "psum",
        axis_name=None,
        input_transform=None,
        eval_views: int = 1,
        accum_steps: int = 1,
        wire_codec=None,
        fused_update: bool = False,
        allreduce_buckets: float = 0.0,
    ):
        from theanompi_tpu.parallel.codec import get_codec

        if axis_name is None:
            from theanompi_tpu.parallel.mesh import batch_axes

            axis_name = batch_axes(mesh)
        self.model = model
        self.mesh = mesh
        self.codec = get_codec(wire_codec)
        self._build = dict(
            steps_per_epoch=steps_per_epoch, strategy=strategy,
            axis_name=axis_name, input_transform=input_transform,
            accum_steps=accum_steps, wire_codec=self.codec,
            fused_update=bool(fused_update),
            allreduce_buckets=float(allreduce_buckets or 0.0),
        )
        # per-flag variants, built lazily: {numerics_flag: jitted step}.
        # The numerics step is a SECOND compiled program (sentinels are
        # extra outputs) — only runs where --numerics-freq selects it.
        self._fused_steps: dict = {}
        n = 1
        for a in _axes_tuple(axis_name):
            n *= mesh.shape[a]
        self.donates_state = n > 1  # single-device path does not donate
        # THE spec source for this engine (parallel/recipe.py): the
        # analyzer (SHARD001-004) verifies these declared specs against
        # the compiled executable, memory_model divides by their
        # extents, and the checkpoint topology stamp carries them
        self.sharding = _bsp_recipe(mesh, axis_name, self.codec)
        self._steps = {False: make_bsp_train_step(model, mesh, **self._build)}
        self._eval = make_bsp_eval_step(
            model, mesh, axis_name=axis_name, input_transform=input_transform,
            eval_views=eval_views,
        )

    def init_state(self, rng):
        state = init_train_state(self.model, rng)
        n = 1
        for a in _axes_tuple(self._build["axis_name"]):
            n *= self.mesh.shape[a]
        if n > 1 and self.codec.error_feedback:
            if self._build["strategy"] == "hier":
                # hier feeds quantization error back on the DCN shard,
                # not per grad leaf: one (n, seg) residual row-stack
                # (per bucket, when bucketed) — see hier_ef_template
                from theanompi_tpu.parallel.mesh import slice_topology
                from theanompi_tpu.parallel.strategies import (
                    hier_ef_template,
                )

                bb = None
                if self._build["allreduce_buckets"]:
                    bb = max(1, int(
                        self._build["allreduce_buckets"] * 2 ** 20))
                state = state._replace(ef=hier_ef_template(
                    state.params, slice_topology(self.mesh),
                    bucket_bytes=bb,
                ))
            else:
                # per-device quantization residuals, stacked [n, ...]
                # and sharded over the data axes by the step's state
                # spec — checkpointed with the rest of the state (exact
                # resume)
                state = state._replace(ef=self.codec.init_ef(state.params,
                                                             stack=n))
        return state

    def train_step(self, state, images, labels, rng, numerics: bool = False):
        numerics = bool(numerics)
        if numerics not in self._steps:
            self._steps[numerics] = make_bsp_train_step(
                self.model, self.mesh, numerics=numerics, **self._build
            )
        return self._steps[numerics](state, images, labels, rng)

    def fused_train_step(self, state, images, labels, rngs,
                         numerics: bool = False):
        """Run ``images.shape[0]`` fused steps on stacked batches
        ``[g, batch, ...]`` with stacked per-step keys (one dispatch).
        One jitted function per numerics flag; jit recompiles per
        distinct group size (the driver produces at most the configured
        k plus an epoch-remainder size)."""
        numerics = bool(numerics)
        if numerics not in self._fused_steps:
            self._fused_steps[numerics] = make_bsp_fused_step(
                self.model, self.mesh, numerics=numerics, **self._build
            )
        return self._fused_steps[numerics](state, images, labels, rngs)

    def exchange(self, state):
        return state

    def eval_step(self, state, images, labels):
        # strip the codec residuals: eval's state spec is a blanket P()
        # (replicated), and the sharded ef leaves are irrelevant to a
        # forward pass — passing them would force a gather per val batch
        return self._eval(state._replace(ef=()), images, labels)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.step))

    def sharding_recipe(self):
        """The engine's :class:`~theanompi_tpu.parallel.recipe.
        ShardingRecipe` — the declared spec table the sharding analyzer
        (tools/analyze/sharding.py) verifies against GSPMD's compiled
        truth and the worker stamps into the ``__topology__`` manifest."""
        return self.sharding

    def elastic_spec(self) -> dict:
        """Per-leaf reshard policies stamped into every checkpoint's
        topology manifest (utils/checkpoint.load_resharded). BSP state
        is replicated — mesh-invariant global content, the default
        ``global`` policy — except the codec's per-device error-feedback
        residuals, which pair with each device's own quantization
        history and are meaningless on a different world: reset."""
        return {"policies": {".ef": {"policy": "reset"}}}

    def traffic_model(self, state):
        """Analytic per-step wire volume of this engine's gradient
        allreduce (obs/comm.py): the in-step psum/ring over the data
        axes, sized by the grad pytree (= params) and the strategy's /
        codec's wire compression — raw AND effective bytes. With
        ``--allreduce-buckets`` the TOTAL volume is unchanged (the same
        bytes, chunked) but the schedule geometry — bucket count and the
        overlap fraction the attribution model prices comm at — rides
        the detail block, keeping the gauges and the SPMD101/102
        cross-checks truthful about the bucketed wire."""
        import math as _math

        from theanompi_tpu.obs.comm import bsp_traffic, pytree_num_elements
        from theanompi_tpu.parallel.mesh import slice_topology

        axes = _axes_tuple(self._build["axis_name"])
        n = 1
        for a in axes:
            n *= self.mesh.shape[a]
        axis_sizes = tuple(int(self.mesh.shape[a]) for a in axes)
        n_slices, _per = slice_topology(self.mesh)
        n_buckets = None
        overlap = None
        segments = None
        if self._build["allreduce_buckets"] and n > 1:
            from theanompi_tpu.parallel.strategies import (
                bucket_overlap_frac,
            )

            sync = bucketed(
                self._build["strategy"], self._build["axis_name"], n,
                self._build["allreduce_buckets"], codec=self.codec,
                axis_sizes=axis_sizes,
            )
            # one bucket walk serves both figures (this runs on the
            # metrics-snapshot path)
            buckets = sync.buckets_for(state.params)
            n_buckets = len(buckets)
            overlap = (
                bucket_overlap_frac(n_buckets) if sync.in_backward
                else 0.0
            )
            if self._build["strategy"] == "hier":
                # each bucket pads and reduce-scatters its own flat
                # buffer — the two-hop model prices the exact schedule
                import jax as _jax

                leaves = _jax.tree_util.tree_leaves(state.params)
                segments = [
                    sum(int(_math.prod(
                        getattr(leaves[i], "shape", ()) or ()) or 1)
                        for i in idx)
                    for idx in buckets
                ]
        return bsp_traffic(
            pytree_num_elements(state.params), n,
            strategy=self._build["strategy"], codec=self.codec,
            n_buckets=n_buckets, overlap_frac=overlap,
            n_slices=n_slices, segments=segments,
        )

    def memory_model(self, state):
        """Analytic per-leaf HBM residency of this engine's state
        (utils/flops.py ``MemoryModel``; the memory-side peer of
        ``traffic_model()``, consumed by ``tmpi preflight`` /
        tools/analyze/memory.py). BSP state is replicated on every
        device — shard factor 1 everywhere — except the codec's
        error-feedback residuals, stacked ``[n, ...]`` and sharded over
        the data axes. Factors and specs both come from the engine's
        ShardingRecipe (parallel/recipe.py), so the 1/n claims here can
        never drift from the specs the step actually shards with
        (SHARD003 verifies the pair against the compiled program).
        ``state`` may be abstract (eval_shape structs)."""
        from theanompi_tpu.utils.flops import state_memory_model

        n = 1
        for a in _axes_tuple(self._build["axis_name"]):
            n *= self.mesh.shape[a]
        lf = self.sharding.leaf_factors(state)

        def factor(path, leaf):
            return lf.get(path, (1, None))[0]

        return state_memory_model(
            state, "bsp", n, factor,
            detail={"note": "replicated state; ef stacked per-device"},
            specs={p: s for p, (_f, s) in lf.items()},
        )

    def cost_model(self, state, global_batch: int):
        """XLA cost analysis of this engine's compiled numerics-off
        train step over an abstract global batch (utils/flops.py
        ``CostModel``) — the per-executable FLOPs + HBM bytes behind
        the live ``tmpi_mfu``/attribution gauges (obs/attribution.py).
        Lowering over ShapeDtypeStructs compiles but never executes."""
        import jax as _jax

        from theanompi_tpu.utils.flops import abstract_batch, compiled_cost

        x, y = abstract_batch(self.model, int(global_batch))
        return compiled_cost(self._steps[False], state, x, y,
                             _jax.random.PRNGKey(0))

    def numerics_model(self, state):
        """Numerics declaration (obs/numerics.py): the standard sentinel
        set; no divergence gauge — BSP params are replicated by
        construction (the in-step pmean IS the consistency proof)."""
        from theanompi_tpu.obs.numerics import NumericsModel

        del state  # sentinel set is state-independent for this rule
        return NumericsModel(
            rule="bsp",
            detail={"note": "params replicated in-step; no divergence "
                            "gauge needed"},
        )


def make_bsp_eval_step(
    model: Model, mesh: Mesh, axis_name=DATA_AXIS, input_transform=None,
    eval_views: int = 1,
):
    """Jitted eval step over the mesh: metrics averaged across shards."""
    base = make_eval_step(model, input_transform=input_transform, views=eval_views)
    axes = _axes_tuple(axis_name)
    if all(mesh.shape[a] == 1 for a in axes):
        return jax.jit(base)

    def sharded(state: TrainState, images, labels):
        return lax.pmean(base(state, images, labels), axis_name)

    # eval states carry no codec residuals (the engine strips ef), so
    # the recipe's whole-state spec is replicated
    recipe = _bsp_recipe(mesh, axis_name, None)
    spec = recipe.batch_spec
    mapped = jax.shard_map(
        sharded,
        mesh=mesh,
        in_specs=(recipe.scalar, spec, spec),
        out_specs=recipe.scalar,
        check_vma=_checked_vma(),
    )
    return jax.jit(mapped)
