"""BSP data-parallel training step.

TPU-native rebuild of the reference's BSP rule (reference:
``lib/exchanger.py`` — ``BSP_Exchanger.exchange()`` called between
Theano functions each iteration; SURVEY.md §3.2). Here the whole BSP
iteration — forward, backward, gradient allreduce, update — is ONE
``jax.jit``-compiled SPMD program over a ``('data',)`` mesh:

- the per-device batch shard comes in sharded along ``data``;
- params / optimizer state are replicated; every device computes the
  identical update after the gradient mean (lockstep by construction —
  the XLA program IS the barrier, where the reference relied on
  blocking MPI allreduce);
- the exchanger strategy is compiled into the step (``psum`` by
  default, explicit/compressed ring variants for parity with
  ``asa32``/``asa16``).
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.parallel.strategies import get_strategy
from theanompi_tpu.train import TrainState, init_train_state, make_eval_step, make_train_step


def make_bsp_train_step(
    model: Model,
    mesh: Mesh,
    steps_per_epoch: int = 1,
    strategy: str = "psum",
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    input_transform=None,
):
    """Build the jitted BSP step: ``(state, images, labels, rng) ->
    (state, metrics)`` over global arrays.

    ``images``/``labels`` hold the GLOBAL batch (sharded or shardable
    along ``data``); ``state`` is replicated; ``rng`` is a single key —
    each device folds in its axis index so dropout masks differ per
    shard (the reference's workers each had their own RNG stream).
    """
    n = mesh.shape[axis_name]
    if n == 1:
        get_strategy(strategy, axis_name, n)  # validate the name early
        # Single-device fast path: no collectives exist, so skip the
        # shard_map machinery entirely (it pays real dispatch overhead on
        # some backends) — the plain jitted step is semantically identical.
        # Donation is also disabled here: on the tunneled single-chip
        # backend donated buffers trigger a relayout-recompile and a
        # ~4x steady-state slowdown (measured), and the memory it would
        # save is not binding on one chip.
        base = make_train_step(model, steps_per_epoch, input_transform=input_transform)

        def single_step(state, images, labels, rng):
            return base(state, images, labels, jax.random.fold_in(rng, 0))

        return jax.jit(single_step)

    grad_sync = get_strategy(strategy, axis_name, n)
    base_step = make_train_step(
        model, steps_per_epoch, grad_sync=grad_sync, input_transform=input_transform
    )

    def sharded_step(state: TrainState, images, labels, rng):
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))
        new_state, metrics = base_step(state, images, labels, rng)
        # Per-replica BatchNorm stats diverge across shards; average them
        # so the output state is truly replicated (the reference kept
        # per-worker stats and checkpointed rank 0's — averaging is the
        # better-defined equivalent).
        new_state = new_state._replace(
            model_state=lax.pmean(new_state.model_state, axis_name)
        )
        metrics = lax.pmean(metrics, axis_name)
        return new_state, metrics

    # check_vma=False: the exchanger abstraction requires classic pmap AD
    # semantics (psum transpose = identity) — see make_train_step's note.
    mapped = jax.shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class BSPEngine:
    """Rule-engine wrapper over the BSP step (uniform driver protocol
    shared with EASGDEngine/GOSGDEngine)."""

    name = "bsp"
    exchange_every = 0  # the allreduce is inside every step

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        strategy: str = "psum",
        axis_name: str = DATA_AXIS,
        input_transform=None,
    ):
        self.model = model
        self.mesh = mesh
        self._step = make_bsp_train_step(
            model, mesh, steps_per_epoch=steps_per_epoch, strategy=strategy,
            axis_name=axis_name, input_transform=input_transform,
        )
        self._eval = make_bsp_eval_step(
            model, mesh, axis_name=axis_name, input_transform=input_transform
        )

    def init_state(self, rng):
        return init_train_state(self.model, rng)

    def train_step(self, state, images, labels, rng):
        return self._step(state, images, labels, rng)

    def exchange(self, state):
        return state

    def eval_step(self, state, images, labels):
        return self._eval(state, images, labels)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.step))


def make_bsp_eval_step(
    model: Model, mesh: Mesh, axis_name: str = DATA_AXIS, input_transform=None
):
    """Jitted eval step over the mesh: metrics averaged across shards."""
    base = make_eval_step(model, input_transform=input_transform)
    if mesh.shape[axis_name] == 1:
        return jax.jit(base)

    def sharded(state: TrainState, images, labels):
        return lax.pmean(base(state, images, labels), axis_name)

    mapped = jax.shard_map(
        sharded,
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(mapped)
