"""Multi-controller (multi-host) runtime bootstrap.

Rebuild of the reference's L1 process runtime (reference: ``lib/base.py``
— ``MPI_GPU_Process`` with ``get_internode_comm()`` returning
``MPI.COMM_WORLD``, one OS process per GPU launched by ``mpirun``;
SURVEY.md §1 L1, §5.8). The TPU-native process model is JAX
multi-controller SPMD: ONE process per TPU host (not per chip), every
process runs the identical program, and ``jax.distributed.initialize``
replaces ``mpirun``'s world setup — after it, ``jax.devices()`` spans
the whole pod and collectives ride ICI/DCN picked by XLA.

Bootstrap sources, in precedence order:

1. Explicit kwargs to :func:`initialize_distributed`.
2. ``TMPI_COORDINATOR`` / ``TMPI_NUM_PROCESSES`` / ``TMPI_PROCESS_ID``
   env vars (set by ``tmpi --nproc`` / :mod:`launch.multihost`, the
   mpirun equivalent — also how tests run 2+ controller processes on
   CPU with ``--xla_force_host_platform_device_count``).
3. JAX's own cluster auto-detection (TPU pod metadata, SLURM, etc.):
   ``jax.distributed.initialize()`` with no args — used when
   ``TMPI_AUTO_INIT=1``.

On a single host with none of those set, this is a no-op: the framework
stays single-controller exactly as before.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list[int]] = None,
) -> bool:
    """Join the multi-controller world if configured; returns True iff
    ``jax.distributed`` was initialized (now or earlier this process).

    Must run BEFORE any JAX backend use (first jit/devices() call).
    Idempotent: a second call is a no-op.
    """
    global _initialized
    if _initialized:
        return True

    env = os.environ
    coordinator = coordinator or env.get("TMPI_COORDINATOR") or None
    if num_processes is None and env.get("TMPI_NUM_PROCESSES"):
        num_processes = int(env["TMPI_NUM_PROCESSES"])
    if process_id is None and env.get("TMPI_PROCESS_ID"):
        process_id = int(env["TMPI_PROCESS_ID"])

    if coordinator is None and num_processes is None:
        if env.get("TMPI_AUTO_INIT") == "1":
            # TPU pod / SLURM: let JAX's cluster detection fill everything
            jax.distributed.initialize()
            _initialized = True
            return True
        return False
    if num_processes is not None and num_processes <= 1 and coordinator is None:
        return False
    if coordinator is None or num_processes is None or process_id is None:
        raise ValueError(
            "multi-controller bootstrap needs coordinator, num_processes AND "
            f"process_id (got {coordinator=}, {num_processes=}, {process_id=}); "
            "set TMPI_COORDINATOR/TMPI_NUM_PROCESSES/TMPI_PROCESS_ID or pass "
            "them explicitly"
        )

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def assert_same_across_processes(value: float, name: str, atol: float = 0.0) -> None:
    """Debug guard: verify a host-side scalar is identical on every
    controller (e.g. the loss after a lockstep BSP step). Collective —
    every process must call it."""
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.float64(value))
    ref = np.asarray(gathered).reshape(-1)
    if not np.all(np.abs(ref - ref[0]) <= atol):
        raise AssertionError(
            f"{name} differs across processes: {ref.tolist()} (atol={atol})"
        )
