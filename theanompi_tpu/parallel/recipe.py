"""ShardingRecipe — the single source of PartitionSpecs.

ROADMAP item 5 names the refactor: every engine, the checkpoint
topology stamp, and serve used to hand-roll their own PartitionSpecs,
so nothing could verify that what one layer DECLARED (traffic_model /
memory_model / elastic_spec / the ``__topology__`` manifest) matched
what another layer BUILT — let alone what GSPMD actually compiled.
A :class:`ShardingRecipe` is one object holding the mesh axes plus the
per-leaf-role spec rules for a rule engine's state; everything that
needs a spec asks the recipe:

- the engines' ``shard_map`` in/out specs (``state_spec``,
  ``batch_spec``, ``stacked_batch_spec``, ``scalar``);
- the per-leaf declared spec table (``leaf_specs``) the sharding
  analyzer (tools/analyze/sharding.py, rules SHARD001-004) checks
  against the COMPILED truth read off the lowered executable;
- the per-leaf shard factors (``leaf_factors``) the engines'
  ``memory_model()`` divides HBM residency by — so the memory
  pre-flight's 1/n claims and the specs can no longer drift apart;
- the checkpoint topology stamp (``as_json`` rides the
  ``__topology__`` manifest next to the live-array specs);
- serve's template/load placement (``place_replicated`` /
  ``leaf_specs`` — the train->serve handoff SHARD004 verifies).

A *role* is a top-level state field (``params``, ``opt_state``,
``workers``, ``ef``, ...). Its rule is either one
:class:`~jax.sharding.PartitionSpec` (a pytree PREFIX — the whole
subtree shards that way) or a spec tree matching the field's structure
(ND's per-leaf param specs, ZeRO's flat-segment accumulators). The
shapes here follow the mesh+NamedSharding utility idiom of
SNIPPETS.md [1]/[3], generalized to role tables.

Engines must not construct PartitionSpecs directly: the sharding
analyzer's source guard flags any ``PartitionSpec(...)`` call in
``parallel/{bsp,zero,easgd,gosgd,nd}.py`` or ``serve/*`` — specs are
born here (or in parallel/mesh.py's topology helpers) and consumed
everywhere else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any


def _is_spec(x) -> bool:
    return isinstance(x, PartitionSpec)


def spec_axes(spec) -> tuple:
    """Every mesh axis a PartitionSpec names, in order of appearance."""
    out = []
    for entry in tuple(spec):
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            if ax is not None:
                out.append(str(ax))
    return tuple(out)


def psum_axes(spec, axes: tuple) -> tuple:
    """The participating ``axes`` a leaf's gradient is psummed over —
    the complement of the axes its spec shards it on (the universal
    rule models/transformer.py::sync_grads_by_spec applies). Shared by
    the ND engine's wire bookkeeping and the ef-residual spec rule."""
    sharded_on = set(spec_axes(spec))
    return tuple(a for a in axes if a not in sharded_on)


@dataclass(frozen=True)
class ShardingRecipe:
    """Mesh axes + per-leaf-role spec rules for one rule engine.

    ``roles`` maps each top-level state field to its spec rule: a
    single PartitionSpec prefix, a spec tree matching the field's
    structure, or ``()`` for fields that are empty in this
    configuration (codec-off ``ef``)."""

    rule: str
    mesh: Mesh
    axes: tuple  # the data/worker axes batches shard over
    roles: dict
    batch_spec: PartitionSpec = field(default_factory=PartitionSpec)

    # -- spec construction (the ONE sanctioned PartitionSpec factory) --
    @property
    def scalar(self) -> PartitionSpec:
        """Replicated spec — rng keys, scalar metrics, whole-state
        prefixes for replicated rules."""
        return PartitionSpec()

    @property
    def stacked_batch_spec(self) -> PartitionSpec:
        """Fused-dispatch batch spec: leading group/step dim replicated,
        the batch dims per ``batch_spec``."""
        return PartitionSpec(None, *self.batch_spec)

    @property
    def leading_batch_spec(self) -> PartitionSpec:
        """Spec of the batch dim ALONE (1-D) — host feed-range
        computations that only care how rows divide over processes."""
        entries = tuple(self.batch_spec)
        return PartitionSpec(entries[0]) if entries else PartitionSpec()

    def state_spec(self, state_cls):
        """The ``shard_map`` in/out spec tree for the engine's state
        NamedTuple — one rule per field, in field order."""
        return state_cls(*(self.roles[f] for f in state_cls._fields))

    def role_spec(self, role: str):
        return self.roles[role]

    # -- per-leaf resolution (what the analyzer/stamp/preflight read) --
    def _resolve(self, path) -> PartitionSpec:
        """The spec covering one state leaf: descend the role tree
        along the leaf's key path until a PartitionSpec prefix (or the
        path ends)."""
        entries = list(path)
        if not entries:
            raise ValueError("empty leaf path")
        head, rest = entries[0], entries[1:]
        name = getattr(head, "name", None) or getattr(head, "key", None)
        if name is None or name not in self.roles:
            raise ValueError(
                f"leaf path {jax.tree_util.keystr(tuple(path))!r} does "
                f"not start at a recipe role (roles: {sorted(self.roles)})"
            )
        node = self.roles[name]
        for e in rest:
            if _is_spec(node):
                return node
            if isinstance(node, dict):
                node = node[e.key]
            elif hasattr(node, "_fields"):
                node = getattr(node, e.name)
            elif isinstance(node, (tuple, list)):
                node = node[e.idx]
            else:
                raise ValueError(
                    f"role {name!r} spec tree cannot follow path entry "
                    f"{e!r}"
                )
        if not _is_spec(node):
            raise ValueError(
                f"role {name!r} resolved to a non-spec {type(node).__name__}"
                f" at {jax.tree_util.keystr(tuple(path))!r}"
            )
        return node

    def leaf_specs(self, state_template) -> list:
        """``[(path_str, PartitionSpec)]`` for every leaf of a
        (possibly abstract) state pytree — the DECLARED spec table the
        sharding analyzer verifies against the compiled executable and
        the checkpoint manifest stamps next to the live-array specs."""
        out = []
        for path, _leaf in jax.tree_util.tree_flatten_with_path(
                state_template)[0]:
            out.append((jax.tree_util.keystr(path), self._resolve(path)))
        return out

    def shard_factor(self, spec) -> int:
        """Mesh extent a leaf with ``spec`` is divided over (1 =
        replicated) — the denominator the memory pre-flight's per-leaf
        residency uses, derived from the SAME spec the engine shards
        with."""
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        ways = 1
        for ax in spec_axes(spec):
            ways *= int(sizes.get(ax, 1))
        return ways

    def leaf_factors(self, state_template) -> dict:
        """``{path_str: (shard_factor, spec)}`` over the state — what
        engine ``memory_model()`` hooks feed utils/flops.py with."""
        return {p: (self.shard_factor(s), s)
                for p, s in self.leaf_specs(state_template)}

    def as_json(self) -> dict:
        """Serializable identity for the checkpoint ``__topology__``
        manifest: rule + mesh + axes + batch spec (the per-leaf table
        is stamped separately off the live arrays)."""
        from theanompi_tpu.parallel.mesh import mesh_topology, spec_to_json

        return {
            "rule": self.rule,
            "mesh": mesh_topology(self.mesh),
            "axes": [str(a) for a in self.axes],
            "batch_spec": spec_to_json(self.batch_spec),
        }

    # -- placement ------------------------------------------------------
    def place_replicated(self, tree):
        """Place a host pytree replicated per this recipe. Single-device
        meshes use a plain ``device_put`` (a NamedSharding-carrying
        input runs ~90x slower on some tunneled single-chip backends —
        see mesh._place_batch); multi-device meshes commit to the
        replicated NamedSharding."""
        if self.mesh.devices.size == 1:
            return jax.device_put(tree)
        return jax.device_put(tree, NamedSharding(self.mesh, PartitionSpec()))

    def place_params(self, params):
        """Place the SERVED params tree per this recipe's ``params``
        role. The replicated serve recipe degenerates to
        :meth:`place_replicated`; the tensor-serve recipe commits each
        leaf to its Megatron spec's NamedSharding — the one sanctioned
        path for sharded-param serving (engines still never touch
        PartitionSpec)."""
        spec_tree = self.roles.get("params", PartitionSpec())
        if _is_spec(spec_tree):
            return self.place_replicated(params)
        if self.mesh.devices.size == 1:
            # degenerate 1-device tensor mesh: every spec shards over an
            # extent-1 axis — plain device_put, same array, faster path
            return jax.device_put(params)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        specs = treedef.flatten_up_to(spec_tree)
        placed = [
            jax.device_put(leaf, NamedSharding(self.mesh, spec))
            for leaf, spec in zip(leaves, specs)
        ]
        return jax.tree_util.tree_unflatten(treedef, placed)

    # -- constructors (one per rule family) -----------------------------
    @classmethod
    def bsp(cls, mesh: Mesh, axes, ef_sharded: bool) -> "ShardingRecipe":
        """Replicated state over a data mesh; the codec's per-device
        error-feedback residual stack (when present) shards over the
        data axes. ``axes`` may be a tuple (multi-slice meshes)."""
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        data = PartitionSpec(axes)
        return cls(
            rule="bsp", mesh=mesh, axes=axes_t,
            roles=dict(params=PartitionSpec(), model_state=PartitionSpec(),
                       opt_state=PartitionSpec(), step=PartitionSpec(),
                       ef=data if ef_sharded else ()),
            batch_spec=data,
        )

    @classmethod
    def zero1(cls, mesh: Mesh, axis: str, opt_template,
              use_ef: bool) -> "ShardingRecipe":
        """ZeRO-1: params/BN replicated, flat optimizer accumulators
        sharded 1/n over the data axis (scalar opt leaves replicate),
        error-feedback residuals per-device."""
        opt_specs = jax.tree_util.tree_map(
            lambda l: PartitionSpec(axis) if l.ndim else PartitionSpec(),
            opt_template,
        )
        ef = ({"g": PartitionSpec(axis), "p": PartitionSpec(axis)}
              if use_ef else ())
        return cls(
            rule="zero1", mesh=mesh, axes=(axis,),
            roles=dict(params=PartitionSpec(), model_state=PartitionSpec(),
                       opt_state=opt_specs, step=PartitionSpec(), ef=ef),
            batch_spec=PartitionSpec(axis),
        )

    @classmethod
    def easgd(cls, mesh: Mesh, worker_axis: str,
              group_batch_spec: Optional[PartitionSpec] = None,
              ) -> "ShardingRecipe":
        """Worker replicas stacked (n_workers, ...) and sharded over the
        worker axis; the elastic center replicated. Group mode passes
        the 2-D (worker, data) batch spec built by
        mesh.make_worker_group_mesh."""
        w = PartitionSpec(worker_axis)
        return cls(
            rule="easgd", mesh=mesh, axes=tuple(mesh.axis_names),
            roles=dict(workers=w, center_params=PartitionSpec(),
                       center_model_state=PartitionSpec(), ef=w),
            batch_spec=group_batch_spec if group_batch_spec is not None
            else w,
        )

    @classmethod
    def gosgd(cls, mesh: Mesh, worker_axis: str,
              group_batch_spec: Optional[PartitionSpec] = None,
              ) -> "ShardingRecipe":
        """Everything per-worker: replicas, gossip shares (alpha) and
        ef residuals all stacked over the worker axis."""
        w = PartitionSpec(worker_axis)
        return cls(
            rule="gosgd", mesh=mesh, axes=tuple(mesh.axis_names),
            roles=dict(workers=w, alpha=w, ef=w),
            batch_spec=group_batch_spec if group_batch_spec is not None
            else w,
        )

    @classmethod
    def nd(cls, mesh: Mesh, axes: tuple, param_specs, opt_template,
           use_ef: bool, batch_entry, sp_axis: Optional[str],
           microbatched: bool = False) -> "ShardingRecipe":
        """Spec-driven N-D parallelism: per-leaf param specs (from the
        model's spec setup), optimizer accumulators sharded exactly like
        their parameters, ef residuals stacked over each leaf's psummed
        axes, tokens sharded ``P(batch_entry, sp)`` (microbatch-major
        adds a leading replicated dim)."""
        from theanompi_tpu.models.transformer import opt_state_specs

        opt_specs = opt_state_specs(opt_template, param_specs)
        ef: Any = ()
        if use_ef:
            ef = jax.tree_util.tree_map(
                lambda spec: PartitionSpec(
                    psum_axes(spec, axes) or None, *spec),
                param_specs, is_leaf=_is_spec,
            )
        tok_entries = (batch_entry, sp_axis)
        tok_spec = (PartitionSpec(None, *tok_entries) if microbatched
                    else PartitionSpec(*tok_entries))
        return cls(
            rule="nd", mesh=mesh, axes=tuple(axes),
            roles=dict(params=param_specs, opt_state=opt_specs,
                       step=PartitionSpec(), ef=ef),
            batch_spec=tok_spec,
        )

    @classmethod
    def serve(cls, mesh: Optional[Mesh] = None) -> "ShardingRecipe":
        """The serving placement: params/BN replicated on the serving
        mesh (default: one device — PR-5's single-program engine). The
        train->serve handoff check (SHARD004) verifies this template
        against the training engine's stamped ``__topology__`` specs."""
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        return cls(
            rule="serve", mesh=mesh, axes=tuple(mesh.axis_names),
            roles=dict(params=PartitionSpec(),
                       model_state=PartitionSpec(),
                       opt_state=PartitionSpec(), step=PartitionSpec(),
                       ef=()),
            batch_spec=PartitionSpec(),
        )

    @classmethod
    def serve_tensor(cls, model, mesh: Optional[Mesh] = None,
                     tp_axis: Optional[str] = None) -> "ShardingRecipe":
        """Tensor-sharded serving (``tmpi serve --decode --shard
        tensor``): the model arch's Megatron param specs
        (``tp_param_specs`` — qkv/head column-sharded, proj/mlp_out
        row-sharded, embeddings and norms replicated) over a 1-axis
        serving mesh spanning every local device. On one device this
        degenerates to the replicated serve recipe (every spec shards
        an extent-1 axis), so the SAME CLI flags run on a CPU dev box
        and a multi-chip serving host. ``model`` is a zoo model whose
        ``arch`` exposes ``tp_param_specs`` (the LM stack)."""
        arch = getattr(model, "arch", model)
        specs_fn = getattr(arch, "tp_param_specs", None)
        if specs_fn is None:
            raise ValueError(
                f"{type(model).__name__} has no tp_param_specs — tensor-"
                "sharded serving needs the LM stack's Megatron spec "
                "table (use --shard none for replicated serving)"
            )
        if mesh is None:
            from theanompi_tpu.models.transformer import MODEL_AXIS

            mesh = Mesh(np.array(jax.devices()), (MODEL_AXIS,))
        axis = tp_axis if tp_axis is not None else mesh.axis_names[0]
        return cls(
            rule="serve_tensor", mesh=mesh, axes=tuple(mesh.axis_names),
            roles=dict(params=specs_fn(axis),
                       model_state=PartitionSpec(),
                       opt_state=PartitionSpec(), step=PartitionSpec(),
                       ef=()),
            batch_spec=PartitionSpec(),
        )
