"""GoSGD: randomized peer-to-peer gossip SGD.

Rebuild of the reference's GoSGD rule (reference: ``lib/exchanger.py`` —
``GOSGD_Exchanger``: after each local step, every worker draws
Bernoulli(p); on success it isends (params, share-weight/2) to one random
peer and halves its own share; the receiver merges by share-weighted
average ``w_j <- (a_i*w_i + a_j*w_j)/(a_i + a_j)`` and adds the received
share; SURVEY.md §3.5; algorithm: Blot et al. 2016, "Gossip training for
deep learning").

SPMD redesign: MPI isend/iprobe does not exist under gang scheduling.
A gossip round draws ONE shared uniform shift ``s in [1, n-1]`` (from
the round's shared rng); every worker that pushes this round sends to
the peer ``s`` hops forward. The round is realized as a SINGLE
``lax.ppermute`` of the packed (share*w, share) buffer, selected from
the n-1 static shift permutations by ``lax.switch`` (every device
computes the same ``s``, so all replicas take the same branch — safe
for a collective under SPMD). Round cost is O(|w|), independent of n —
the same wire cost as one reference point-to-point push.

Probability-law note (documented divergence, SURVEY.md §7 hard-part 1):
each sender's peer is still EXACTLY uniform over the other n-1 workers,
and the push decisions stay independent Bernoulli(p) per worker — the
per-(sender, receiver) marginal law matches the reference. What changes
is the joint law across senders within one round: peers are perfectly
correlated (everyone shifts by the same s), which makes the assignment
receiver-side conflict-free — at most one message per receiver per
round, where the reference could deliver several queued gossip messages
in one iteration. Merge algebra per delivered message is identical.

``gossip_every=k`` runs the gossip collective only every k-th step (two
compiled step variants; the host picks — no recompile), cutting gossip
bandwidth by k while applying the same per-round push law.

Batch semantics (reference meaning, SURVEY.md §3.5): each worker trains
on its OWN full ``recipe.batch_size`` stream — the incoming global
batch is ``n_workers x batch_size``, sharded so each device's shard IS
one worker's batch (the driver feeds this).

**Worker groups** (``group_size > 1``): as in EASGD, each gossip worker
is a data-parallel GROUP of chips on a 2-D ``(worker, data)`` mesh —
BSP inside the group, gossip ppermute over the worker axis (payloads
are group-replicated, the whole group pushes together). See
parallel/easgd.py's worker-group notes.

Share-weight invariant: sum_i alpha_i == 1 at all times (checked in
tests); consensus params = sum_i alpha_i * w_i. On a 1-device mesh
gossip is the identity (a push would otherwise leak share mass with no
possible recipient).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.train import TrainState, init_train_state, make_eval_step, make_train_step

PyTree = Any


class GOSGDState(NamedTuple):
    workers: TrainState  # stacked (n, ...), sharded over the mesh
    alpha: jax.Array  # (n,) share weights, sharded; sum == 1
    # wire-codec error-feedback residuals of the gossip payload values
    # (parallel/codec.py): (n, flat_params) sharded over the mesh; ()
    # when the codec carries no state. The share weight itself always
    # rides EXACT (gossip_encode) — quantizing it would leak the
    # sum(alpha) == 1 mass invariant.
    ef: PyTree = ()


class GOSGDEngine:
    """Rule engine: local step + in-step randomized gossip.

    ``p_push``: per-step push probability (reference drew Bernoulli(p)
    each iteration; its configs derived p from avg_freq ~ 1/p).
    """

    name = "gosgd"
    # donation audit (ISSUE 2): the gossip step donates its stacked
    # per-worker state — in-flight async dispatches reuse buffers.
    # Verified statically (ISSUE 7, SPMD201). The one-ppermute-per-round
    # gossip schedule is pinned by tools/analyze/golden/gosgd_*.json;
    # note the int8 gossip payload is PHYSICAL compression (the packed
    # int8 message is the ppermute operand), which the analyzer prices
    # by dtype, vs the value-space codec psums priced analytically.
    donates_state = True

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        p_push: float = 0.25,
        avg_freq: int | None = None,
        gossip_every: int = 1,
        axis_name: str = DATA_AXIS,
        input_transform=None,
        eval_views: int = 1,
        group_size: int = 1,
        accum_steps: int = 1,
        n_slices: "int | None" = None,
        wire_codec=None,
        fused_update: bool = False,
    ):
        from theanompi_tpu.parallel.codec import get_codec
        from theanompi_tpu.parallel.mesh import make_worker_group_mesh

        self.codec = get_codec(wire_codec)
        self.model = model
        self.group_size = g = max(1, int(group_size))
        # n_slices: pod topology validation (groups inside a slice, the
        # gossip ppermute across slices) — see make_worker_group_mesh
        mesh, gspec, grad_sync = make_worker_group_mesh(mesh, g, n_slices=n_slices)
        if g > 1:
            axis_name = mesh.axis_names[0]
        # THE spec source (parallel/recipe.py): replicas, gossip shares
        # and ef residuals all per-worker
        from theanompi_tpu.parallel.recipe import ShardingRecipe

        self.sharding = ShardingRecipe.gosgd(
            mesh, axis_name, group_batch_spec=gspec if g > 1 else None)
        bspec = self.sharding.batch_spec
        self.mesh = mesh
        self.axis_name = axis_name
        self.n = mesh.shape[axis_name]  # number of WORKERS
        if self.n == 1:
            self.codec = get_codec(None)  # gossip is the identity
        if avg_freq:  # reference-style configuration: p = 1/avg_freq
            p_push = 1.0 / avg_freq
        self.p_push = float(p_push)
        self.gossip_every = max(1, int(gossip_every))
        self._count: int | None = None

        def make_base_step(numerics: bool):
            return make_train_step(
                model, steps_per_epoch, grad_sync=grad_sync,
                input_transform=input_transform, accum_steps=accum_steps,
                numerics=numerics, fused_update=fused_update,
            )

        base_step = make_base_step(False)
        base_eval = make_eval_step(
            model, input_transform=input_transform, views=eval_views
        )
        ax, n, p = axis_name, self.n, float(p_push)
        codec = self.codec
        use_ef = codec.active and codec.error_feedback
        all_axes = tuple(mesh.axis_names)

        def gossip(params: PyTree, alpha: jax.Array, rng: jax.Array,
                   ef: PyTree):
            """One gossip round: ONE executed ppermute; returns merged
            (params, alpha, ef'). ``rng`` must be identical across
            devices — the shared shift comes straight from it,
            per-device push decisions from folding in the device index.
            Identity on a 1-device mesh (no recipient exists).

            With a wire codec the message IS the packed quantized
            layout (codec.gossip_encode — for int8 the int8 lanes ride
            the interconnect); the share weight travels exact. Error
            feedback applies only on rounds this worker PUSHES: a
            silent round ships exact zeros (a residual injected into a
            zero-share payload would hand the receiver mass-less junk
            values)."""
            if n == 1:
                return params, alpha, ef
            me = lax.axis_index(ax)
            hop_key, push_base = jax.random.split(rng)
            # shared across devices: every replica draws the same shift
            hop = jax.random.randint(hop_key, (), 1, n)
            push = jax.random.bernoulli(jax.random.fold_in(push_base, me), p)

            send_share = jnp.where(push, alpha * 0.5, 0.0)
            keep_share = alpha - send_share
            # big-buffer pack (reference: exchanger packed params into one
            # contiguous comm buffer): share rides in the last slot so the
            # whole round is a single collective
            from jax.flatten_util import ravel_pytree

            from theanompi_tpu.parallel.codec import (
                gossip_decode,
                gossip_encode,
            )

            flat, unravel = ravel_pytree(params)
            L = flat.shape[0]
            values = send_share * flat
            if use_ef:
                values = values + jnp.where(push, ef[0], 0.0)
            payload = gossip_encode(codec, values, send_share)
            # one ppermute, shift chosen at runtime: lax.switch over the
            # n-1 static shift permutations (ppermute's perm is static).
            # Uniform predicate across replicas => same branch everywhere.
            branches = [
                lambda x, _s=s: lax.ppermute(
                    x, ax, [(i, (i + _s) % n) for i in range(n)]
                )
                for s in range(1, n)
            ]
            received = lax.switch(hop - 1, branches, payload)
            recv_values, recv_share = gossip_decode(codec, received, L)
            new_ef = ef
            if use_ef:
                # residual = what MY quantizer discarded this round
                # (decode my own message — dequant is cheap; identical
                # to what my receiver reconstructs)
                sent_values, _ = gossip_decode(codec, payload, L)
                new_ef = jnp.where(push, values - sent_values, ef[0])[None]
            acc = keep_share * flat + recv_values
            acc_share = keep_share + recv_share
            return unravel(acc / acc_share), acc_share, new_ef

        def make_flag_fn(numerics: bool):
            """Factory per numerics flag: the sentinel variant adds the
            in-graph gauges (obs/numerics.py) including the GoSGD
            inter-replica disagreement — RMS distance of worker params
            to the unweighted replica mean, whose pmean costs one
            param-sized allreduce per numerics step (exactly what
            ``--numerics-freq > 1`` amortizes on this rule)."""
            from theanompi_tpu.obs.numerics import sentinels_across_workers

            bstep = make_base_step(numerics) if numerics else base_step

            def sharded_step_flag(state: GOSGDState, images, labels, rng,
                                  with_gossip):
                """``with_gossip`` may be a static Python bool (the cond
                folds at trace time — the per-step jit variants) or a
                traced bool (the fused scan decides per substep)."""
                local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
                a_local = state.alpha[0]
                step_rng, gossip_rng = jax.random.split(rng)
                from theanompi_tpu.parallel.mesh import fold_linear_index

                step_rng = fold_linear_index(step_rng, all_axes, mesh)
                new_local, metrics = bstep(local, images, labels, step_rng)
                if g > 1:
                    # group-replicated worker: average BN stats within
                    # the group (grads were already psummed)
                    new_local = new_local._replace(
                        model_state=lax.pmean(new_local.model_state, DATA_AXIS)
                    )
                if isinstance(with_gossip, bool):
                    # static flag (the per-step jit variants): keep the
                    # no-gossip program genuinely collective-free — lax.cond
                    # stages BOTH branches even for a concrete predicate
                    # (verified), which would put a dead ppermute switch in
                    # the local step and lean on XLA to simplify it out
                    merged, a_new, ef_new = (
                        gossip(new_local.params, a_local, gossip_rng,
                               state.ef)
                        if with_gossip
                        else (new_local.params, a_local, state.ef)
                    )
                else:
                    merged, a_new, ef_new = lax.cond(
                        with_gossip,
                        lambda: gossip(new_local.params, a_local,
                                       gossip_rng, state.ef),
                        lambda: (new_local.params, a_local, state.ef),
                    )
                new_local = new_local._replace(params=merged)
                if numerics:
                    wbar = jax.tree_util.tree_map(
                        lambda w: lax.pmean(w.astype(jnp.float32), ax), merged
                    )
                    d2 = sum(
                        jnp.sum(jnp.square(w.astype(jnp.float32) - wb))
                        for w, wb in zip(
                            jax.tree_util.tree_leaves(merged),
                            jax.tree_util.tree_leaves(wbar),
                        )
                    )
                    metrics["nm_divergence"] = jnp.sqrt(lax.pmean(d2, ax))
                    # per-worker sentinel aggregation (obs/numerics.py):
                    # count psums, norms RMS over workers — the blanket
                    # pmean below is then identity on the nm_ keys
                    metrics = sentinels_across_workers(metrics, ax)
                metrics = lax.pmean(metrics, all_axes)
                return (
                    GOSGDState(
                        jax.tree_util.tree_map(lambda v: v[None], new_local),
                        a_new[None], ef_new,
                    ),
                    metrics,
                )

            return sharded_step_flag

        self._make_flag_fn = make_flag_fn
        self._sharded_step_flag = make_flag_fn(False)
        self._state_spec = self.sharding.state_spec(GOSGDState)
        self._bspec = bspec
        self._fused: dict = {}

        def make_sharded_step(with_gossip: bool, numerics: bool = False):
            flag_fn = (
                self._sharded_step_flag if not numerics else make_flag_fn(True)
            )

            def sharded_step(state, images, labels, rng):
                return flag_fn(state, images, labels, rng, with_gossip)

            return jax.jit(
                jax.shard_map(
                    sharded_step,
                    mesh=mesh,
                    in_specs=(self._state_spec, bspec, bspec,
                              self.sharding.scalar),
                    out_specs=(self._state_spec, self.sharding.scalar),
                    check_vma=False,
                ),
                donate_argnums=(0,),
            )

        self._make_jit_step = make_sharded_step
        self._steps = {(True, False): make_sharded_step(True)}
        self._steps[(False, False)] = (
            make_sharded_step(False) if self.gossip_every > 1
            else self._steps[(True, False)]
        )

        # ---- eval on the consensus params: sum_i alpha_i w_i -------------
        def sharded_eval(state: GOSGDState, images, labels):
            local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
            a_local = state.alpha[0]
            consensus_params = jax.tree_util.tree_map(
                lambda w: lax.psum(a_local * w, ax), local.params
            )
            consensus_ms = lax.pmean(local.model_state, ax)
            consensus = TrainState(
                consensus_params, consensus_ms, opt_state=(), step=jnp.zeros((), jnp.int32)
            )
            return lax.pmean(base_eval(consensus, images, labels), all_axes)

        self._eval = jax.jit(
            jax.shard_map(
                sharded_eval,
                mesh=mesh,
                in_specs=(self._state_spec, bspec, bspec),
                out_specs=self.sharding.scalar,
                check_vma=False,
            )
        )

    # -- engine protocol ----------------------------------------------------
    exchange_every = 0  # gossip happens inside the step

    def init_state(self, rng) -> GOSGDState:
        from theanompi_tpu.parallel.mesh import stack_replicas

        ts = init_train_state(self.model, rng)
        # _count stays None: the first train_step derives it from the
        # state's step counter, which is also correct when the driver
        # swaps in a restored checkpoint after init_state (resume keeps
        # the gossip cadence aligned with the global step).
        self._count = None
        ef = ()
        if self.codec.active and self.codec.error_feedback:
            # one flat residual per worker, sized like the packed
            # gossip payload's values (ravel of the param pytree)
            from jax.flatten_util import ravel_pytree

            flat_size = jax.eval_shape(
                lambda p: ravel_pytree(p)[0], ts.params
            ).shape[0]
            ef = jnp.zeros((self.n, flat_size), jnp.float32)
        return GOSGDState(
            workers=stack_replicas(ts, self.n),
            alpha=jnp.full((self.n,), 1.0 / self.n),
            ef=ef,
        )

    def train_step(self, state, images, labels, rng, numerics: bool = False):
        if self._count is None:  # resumed state: derive from the step counter
            self._count = self.get_step(state)
        nxt = self._count + 1
        key = (nxt % self.gossip_every == 0, bool(numerics))
        if key not in self._steps:
            self._steps[key] = self._make_jit_step(*key)
        out = self._steps[key](state, images, labels, rng)
        # advance only after the dispatch succeeds: a raise (OOM on a new
        # shape) must not shift the gossip cadence off the applied steps
        self._count = nxt
        return out

    def fused_train_step(self, state, images, labels, rngs,
                         numerics: bool = False):
        """``g`` local-SGD-plus-gossip steps in ONE program; each
        substep's gossip decision follows the same ``gossip_every``
        cadence the per-step path applies (substep counters shipped as
        a stacked operand, uniform across devices so the in-cond
        collective cannot diverge)."""
        numerics = bool(numerics)
        if self._count is None:
            self._count = self.get_step(state)
        g_steps = int(images.shape[0])
        counts = jnp.arange(1, g_steps + 1, dtype=jnp.int32) + self._count
        if numerics not in self._fused:
            from theanompi_tpu.parallel.fused import fuse_sharded_step

            every = self.gossip_every
            flag_fn = self._make_flag_fn(numerics) if numerics else (
                self._sharded_step_flag
            )

            def substep(st, x, y, r, count):
                return flag_fn(st, x, y, r, count % every == 0)

            self._fused[numerics] = fuse_sharded_step(
                substep, self.mesh, self._state_spec,
                (self.sharding.stacked_batch_spec,
                 self.sharding.stacked_batch_spec,
                 self.sharding.scalar, self.sharding.scalar),
                True,
            )
        out = self._fused[numerics](state, images, labels, rngs, counts)
        # advance only after the fused dispatch returns: a raise (OOM on
        # a new trimmed-group shape) must not permanently shift the
        # gossip cadence off the actually-applied steps
        self._count += g_steps
        return out

    def exchange(self, state):
        return state

    def eval_step(self, state, images, labels):
        return self._eval(state, images, labels)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.workers.step))

    def sharding_recipe(self):
        """The engine's ShardingRecipe (parallel/recipe.py) — declared
        spec table for the sharding analyzer and the topology stamp."""
        return self.sharding

    def elastic_spec(self) -> dict:
        """Per-leaf reshard policies for the topology manifest
        (utils/checkpoint.load_resharded). Worker replicas resize by
        ``worker_consensus`` (mean over the saved stack — the unweighted
        stand-in for the alpha-weighted gossip consensus; parity, not
        exact); the share weights restart uniform at ``1/W`` so the
        ``sum(alpha) == 1`` mass invariant holds EXACTLY on the new
        world; error-feedback residuals are per-worker and reset."""
        return {"policies": {
            ".workers": {"policy": "worker_consensus"},
            ".alpha": {"policy": "worker_uniform"},
            ".ef": {"policy": "reset"},
        }}

    def traffic_model(self, state):
        """GoSGD wire model (obs/comm.py): one ppermute of the packed
        ``(share*w, share)`` buffer per gossip round (every
        ``gossip_every`` steps), plus the group-internal grad psum when
        workers are chip groups."""
        from theanompi_tpu.obs.comm import gosgd_traffic, pytree_num_elements
        from theanompi_tpu.parallel.mesh import slice_topology

        per_worker = pytree_num_elements(state.workers.params) // self.n
        return gosgd_traffic(
            per_worker, self.n, gossip_every=self.gossip_every,
            group_size=self.group_size, codec=self.codec,
            n_slices=slice_topology(self.mesh)[0],
        )

    def memory_model(self, state):
        """Analytic per-leaf HBM residency (utils/flops.py
        ``MemoryModel``; see BSPEngine.memory_model). Everything in
        GoSGD state is per-worker — the stacked replicas, the share
        weights, and the codec residuals all shard ``1/n`` over the
        worker axis; there is no replicated center. Factors/specs come
        from the engine's ShardingRecipe (SHARD003 checks them against
        the compiled program)."""
        from theanompi_tpu.utils.flops import state_memory_model

        n = self.n
        lf = self.sharding.leaf_factors(state)

        def factor(path, leaf):
            return lf.get(path, (1, None))[0]

        return state_memory_model(
            state, "gosgd", n, factor,
            detail={"note": "all state per-worker (stack + alpha + ef "
                            "sharded 1/n); no replicated center"},
            specs={p: s for p, (_f, s) in lf.items()},
        )

    def cost_model(self, state, global_batch: int):
        """XLA cost analysis of the compiled numerics-off WITH-GOSSIP
        step variant over an abstract global batch (utils/flops.py
        ``CostModel``; see BSPEngine.cost_model) — the gossip ppermute
        rides inside the step, so the representative executable is the
        gossip-round one (exact on ``gossip_every == 1``, a slight
        over-count of pack/unpack flops otherwise)."""
        import jax as _jax

        from theanompi_tpu.utils.flops import abstract_batch, compiled_cost

        x, y = abstract_batch(self.model, int(global_batch))
        return compiled_cost(self._steps[(True, False)], state, x, y,
                             _jax.random.PRNGKey(0))

    def numerics_model(self, state):
        """Numerics declaration (obs/numerics.py): standard sentinels
        plus the inter-replica disagreement gauge (RMS distance to the
        replica mean). The mean needs a param-sized pmean — one full
        allreduce of extra wire per numerics step, so size
        ``--numerics-freq`` accordingly on this rule."""
        from theanompi_tpu.obs.comm import allreduce_bytes, pytree_num_elements
        from theanompi_tpu.obs.numerics import NumericsModel

        per_worker = pytree_num_elements(state.workers.params) // self.n
        return NumericsModel(
            rule="gosgd",
            divergence="replica_disagreement",
            detail={"extra_wire": "param-sized pmean per numerics step",
                    "extra_bytes_per_numerics_step": allreduce_bytes(
                        per_worker, self.n)},
        )
