"""GoSGD: randomized peer-to-peer gossip SGD.

Rebuild of the reference's GoSGD rule (reference: ``lib/exchanger.py`` —
``GOSGD_Exchanger``: after each local step, every worker draws
Bernoulli(p); on success it isends (params, share-weight/2) to one random
peer and halves its own share; the receiver merges by share-weighted
average ``w_j <- (a_i*w_i + a_j*w_j)/(a_i + a_j)`` and adds the received
share; SURVEY.md §3.5; algorithm: Blot et al. 2016, "Gossip training for
deep learning").

SPMD redesign: MPI isend/iprobe does not exist under gang scheduling.
A gossip round runs as n-1 masked ``ppermute`` shifts — shift ``s``
delivers exactly the messages whose sender chose the peer ``s`` hops
away, so every sender still picks its peer independently and uniformly,
preserving the reference algorithm's probability law exactly. Messages
are (params * share/2, share/2) pairs; non-pushing senders contribute
zeros. Bandwidth per round is O(n * |w|) worst case versus the
reference's O(pushes * |w|) point-to-point — the price of SPMD; with
the default p = avg_freq^-1 ~ small, most rounds move only zeros and
XLA still ships them, so set ``gossip_every`` > 1 to thin rounds on
real hardware (p is then applied per-round, identical law).

``gossip_every=k`` runs the gossip collective only every k-th step (two
compiled step variants; the host picks — no recompile), cutting gossip
bandwidth by k while applying the same per-round push law.

Share-weight invariant: sum_i alpha_i == 1 at all times (checked in
tests); consensus params = sum_i alpha_i * w_i. On a 1-device mesh
gossip is the identity (a push would otherwise leak share mass with no
possible recipient).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.train import TrainState, init_train_state, make_eval_step, make_train_step

PyTree = Any


class GOSGDState(NamedTuple):
    workers: TrainState  # stacked (n, ...), sharded over the mesh
    alpha: jax.Array  # (n,) share weights, sharded; sum == 1


class GOSGDEngine:
    """Rule engine: local step + in-step randomized gossip.

    ``p_push``: per-step push probability (reference drew Bernoulli(p)
    each iteration; its configs derived p from avg_freq ~ 1/p).
    """

    name = "gosgd"

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        p_push: float = 0.25,
        avg_freq: int | None = None,
        gossip_every: int = 1,
        axis_name: str = DATA_AXIS,
    ):
        self.model = model
        self.mesh = mesh
        self.axis_name = axis_name
        self.n = mesh.shape[axis_name]
        if avg_freq:  # reference-style configuration: p = 1/avg_freq
            p_push = 1.0 / avg_freq
        self.p_push = float(p_push)
        self.gossip_every = max(1, int(gossip_every))
        self._count: int | None = None
        base_step = make_train_step(model, steps_per_epoch)
        base_eval = make_eval_step(model)
        ax, n, p = axis_name, self.n, float(p_push)

        def gossip(params: PyTree, alpha: jax.Array, rng: jax.Array):
            """One gossip round: masked ppermute shifts; returns merged
            (params, alpha). ``rng`` must be identical across devices —
            per-device decisions come from folding in the device index.
            Identity on a 1-device mesh (no recipient exists)."""
            if n == 1:
                return params, alpha
            me = lax.axis_index(ax)
            dev_rng = jax.random.fold_in(rng, me)
            push_key, peer_key = jax.random.split(dev_rng)
            push = jax.random.bernoulli(push_key, p)
            # uniform peer != me: draw in [1, n-1] hops forward
            hop = jax.random.randint(peer_key, (), 1, n)

            send_share = jnp.where(push, alpha * 0.5, 0.0)
            keep_share = alpha - send_share
            # big-buffer pack (reference: exchanger packed params into one
            # contiguous comm buffer): one ppermute per shift, not per leaf
            from jax.flatten_util import ravel_pytree

            flat, unravel = ravel_pytree(params)
            acc = keep_share * flat
            acc_share = keep_share
            for s in range(1, n):
                perm = [(i, (i + s) % n) for i in range(n)]
                mask = jnp.where(hop == s, send_share, 0.0)
                acc_share = acc_share + lax.ppermute(mask, ax, perm)
                acc = acc + lax.ppermute(mask * flat, ax, perm)
            return unravel(acc / acc_share), acc_share

        def make_sharded_step(with_gossip: bool):
            def sharded_step(state: GOSGDState, images, labels, rng):
                local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
                a_local = state.alpha[0]
                step_rng, gossip_rng = jax.random.split(rng)
                step_rng = jax.random.fold_in(step_rng, lax.axis_index(ax))
                new_local, metrics = base_step(local, images, labels, step_rng)
                a_new = a_local
                if with_gossip:
                    merged, a_new = gossip(new_local.params, a_local, gossip_rng)
                    new_local = new_local._replace(params=merged)
                metrics = lax.pmean(metrics, ax)
                return (
                    GOSGDState(
                        jax.tree_util.tree_map(lambda v: v[None], new_local), a_new[None]
                    ),
                    metrics,
                )

            return jax.jit(
                jax.shard_map(
                    sharded_step,
                    mesh=mesh,
                    in_specs=(GOSGDState(P(ax), P(ax)), P(ax), P(ax), P()),
                    out_specs=(GOSGDState(P(ax), P(ax)), P()),
                    check_vma=False,
                ),
                donate_argnums=(0,),
            )

        self._step_gossip = make_sharded_step(True)
        self._step_local = (
            make_sharded_step(False) if self.gossip_every > 1 else self._step_gossip
        )

        # ---- eval on the consensus params: sum_i alpha_i w_i -------------
        def sharded_eval(state: GOSGDState, images, labels):
            local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
            a_local = state.alpha[0]
            consensus_params = jax.tree_util.tree_map(
                lambda w: lax.psum(a_local * w, ax), local.params
            )
            consensus_ms = lax.pmean(local.model_state, ax)
            consensus = TrainState(
                consensus_params, consensus_ms, opt_state=(), step=jnp.zeros((), jnp.int32)
            )
            return lax.pmean(base_eval(consensus, images, labels), ax)

        self._eval = jax.jit(
            jax.shard_map(
                sharded_eval,
                mesh=mesh,
                in_specs=(GOSGDState(P(ax), P(ax)), P(ax), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )

    # -- engine protocol ----------------------------------------------------
    exchange_every = 0  # gossip happens inside the step

    def init_state(self, rng) -> GOSGDState:
        from theanompi_tpu.parallel.mesh import stack_replicas

        ts = init_train_state(self.model, rng)
        # _count stays None: the first train_step derives it from the
        # state's step counter, which is also correct when the driver
        # swaps in a restored checkpoint after init_state (resume keeps
        # the gossip cadence aligned with the global step).
        self._count = None
        return GOSGDState(
            workers=stack_replicas(ts, self.n),
            alpha=jnp.full((self.n,), 1.0 / self.n),
        )

    def train_step(self, state, images, labels, rng):
        if self._count is None:  # resumed state: derive from the step counter
            self._count = self.get_step(state)
        self._count += 1
        step = (
            self._step_gossip
            if self._count % self.gossip_every == 0
            else self._step_local
        )
        return step(state, images, labels, rng)

    def exchange(self, state):
        return state

    def eval_step(self, state, images, labels):
        return self._eval(state, images, labels)

    def get_step(self, state) -> int:
        return int(jax.device_get(state.workers.step)[0])
