"""EASGD: elastic-averaging SGD over a worker mesh.

Rebuild of the reference's EASGD rule (reference: ``lib/exchanger.py`` —
``EASGD_Exchanger`` / ``Exch_swap``: each worker trains locally and every
``avg_freq`` iterations does a pairwise Sendrecv with a central parameter
server, both sides applying the elastic update ``±alpha*(w - w~)``;
SURVEY.md §3.3). The reference's FCFS asynchrony cannot exist under
gang-scheduled SPMD; this is the **synchronous EASGD** variant from the
original paper (Zhang, Choromanska & LeCun 2015, Alg. 1 with all workers
communicating on the same round):

- every device holds its OWN worker replica (params + optimizer state),
  stacked on a leading worker axis and sharded over the mesh;
- the center w~ is replicated;
- local steps run with NO collectives at all (the EASGD selling point:
  comm every avg_freq steps only);
- at an exchange round:  ``w_i -= alpha*(w_i - w~)`` and
  ``w~ += alpha * sum_i (w_i - w~)`` — one psum of the elastic
  differences, the TPU equivalent of the reference's n pairwise swaps.

Timing-model divergence from the reference (documented per SURVEY.md §7
item 6): exchanges are gang-scheduled rather than FCFS-async, so every
worker exchanges on the same step. The per-worker algebra is identical.

Batch semantics (reference meaning, SURVEY.md §3.3): each worker trains
on its OWN full ``recipe.batch_size`` stream — the incoming global batch
must be ``n_workers x batch_size``, sharded so each device's shard IS
one worker's batch (the driver feeds this; config #4 "ResNet-50 EASGD,
16 workers, batch 256" means 256 examples per worker per local step).

**Worker groups** (``group_size > 1``): each EASGD worker is itself a
data-parallel GROUP of chips — the engine reshapes the mesh to 2-D
``(worker, data)``, runs BSP (in-step psum over the group's ``data``
axis) inside every group, and the elastic exchange couples the
group-replicated worker params with the center over the ``worker`` axis.
This is how a 256-chip pod runs "16 workers": 16 groups x 16 chips,
each group seeing the worker's full batch (SURVEY.md §7.6's
recommended subgroup-mesh shape). A group of g chips is numerically a
single bigger worker: per-worker trajectories match group_size=1 runs
with the same per-worker batch (tests/test_easgd_groups.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel.mesh import DATA_AXIS, stack_replicas
from theanompi_tpu.train import TrainState, init_train_state, make_eval_step, make_train_step

PyTree = Any


class EASGDState(NamedTuple):
    workers: TrainState  # leaves stacked (n_workers, ...), sharded over the mesh
    center_params: PyTree  # replicated
    center_model_state: PyTree  # replicated (refreshed at exchange rounds)
    # wire-codec error-feedback residuals of the elastic-difference psum
    # (parallel/codec.py): per-worker, stacked (n_workers, ...) and
    # sharded like the workers; () when the codec carries no state
    ef: PyTree = ()


class EASGDEngine:
    """Rule engine: local train step + periodic elastic exchange.

    ``alpha``: elastic rate per exchange. The EASGD paper uses
    ``alpha = beta/n`` with beta=0.9 as the stable default; that is the
    default here (reference configs exposed ``alpha`` directly).
    ``avg_freq``: steps between exchanges (reference: ``avg_freq``).
    """

    name = "easgd"
    # donation audit (ISSUE 2): local steps and the elastic exchange
    # both donate the stacked worker state, so async in-flight steps
    # reuse buffers instead of doubling HBM. Verified statically by the
    # SPMD analyzer (ISSUE 7, rule SPMD201); the silent-local-step +
    # every-avg_freq elastic-psum schedule is pinned by
    # tools/analyze/golden/easgd_*.json — both the step AND exchange
    # traces, amortized, must match traffic_model() (SPMD101).
    donates_state = True

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        avg_freq: int = 8,
        alpha: Optional[float] = None,
        axis_name: str = DATA_AXIS,
        input_transform=None,
        eval_views: int = 1,
        group_size: int = 1,
        accum_steps: int = 1,
        n_slices: Optional[int] = None,
        wire_codec=None,
        fused_update: bool = False,
    ):
        from theanompi_tpu.parallel.codec import get_codec
        from theanompi_tpu.parallel.mesh import make_worker_group_mesh

        self.codec = get_codec(wire_codec)
        self.model = model
        self.group_size = g = max(1, int(group_size))
        # n_slices: validate the pod topology split — groups (per-step
        # psum) inside a slice, the worker axis (every-avg_freq elastic
        # exchange) across slices; see make_worker_group_mesh
        mesh, gspec, grad_sync = make_worker_group_mesh(mesh, g, n_slices=n_slices)
        ax = mesh.axis_names[0] if g > 1 else axis_name
        self.mesh = mesh
        self.axis_name = ax
        self.n = mesh.shape[ax]  # number of WORKERS
        if self.n == 1:
            self.codec = get_codec(None)  # no peers, no wire to compress
        self.avg_freq = max(1, avg_freq)
        self.alpha = alpha if alpha is not None else 0.9 / self.n
        base_eval = make_eval_step(
            model, input_transform=input_transform, views=eval_views
        )
        a = self.alpha
        all_axes = tuple(mesh.axis_names)

        from theanompi_tpu.parallel.mesh import fold_linear_index

        def fold_all(rng):
            # distinct stream per DEVICE (worker identity + group slot)
            return fold_linear_index(rng, all_axes, mesh)

        # ---- local step: each worker trains its own replica; groups
        # ---- psum gradients over their internal data axis, no comm
        # ---- crosses workers. A factory per numerics flag: the
        # ---- sentinel variant adds the in-graph gauges (obs/numerics)
        # ---- including the EASGD-specific center<->worker L2 distance
        # ---- (one scalar psum — local steps stay otherwise silent) ----
        def make_sharded_step(numerics: bool):
            from theanompi_tpu.obs.numerics import sentinels_across_workers

            bstep = make_train_step(
                model, steps_per_epoch, grad_sync=grad_sync,
                input_transform=input_transform, accum_steps=accum_steps,
                numerics=numerics, fused_update=fused_update,
            )

            def sharded_step(state: EASGDState, images, labels, rng):
                local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
                new_local, metrics = bstep(local, images, labels, fold_all(rng))
                if g > 1:
                    # group-replicated state: average BN stats within the
                    # group (grads were already psummed; BN stats are not)
                    new_local = new_local._replace(
                        model_state=lax.pmean(new_local.model_state, DATA_AXIS)
                    )
                if numerics:
                    # divergence gauge: RMS over workers of the L2
                    # distance to the center — what the elastic force
                    # acts on; unbounded growth = replicas escaping the
                    # center's basin (raise alpha / lower avg_freq)
                    d2 = sum(
                        jnp.sum(jnp.square(w.astype(jnp.float32)
                                           - c.astype(jnp.float32)))
                        for w, c in zip(
                            jax.tree_util.tree_leaves(new_local.params),
                            jax.tree_util.tree_leaves(state.center_params),
                        )
                    )
                    metrics["nm_divergence"] = jnp.sqrt(lax.pmean(d2, ax))
                    # per-worker rule: aggregate the base-step sentinels
                    # across the worker axis with their own semantics —
                    # the non-finite COUNT psums (a fractional count
                    # would misstate magnitude), the norms combine as
                    # RMS over workers (comparable to a single worker's
                    # reading); the blanket pmean below is then identity
                    metrics = sentinels_across_workers(metrics, ax)
                workers = jax.tree_util.tree_map(lambda v: v[None], new_local)
                metrics = lax.pmean(metrics, all_axes)
                return state._replace(workers=workers), metrics

            return sharded_step

        self._make_sharded_step = make_sharded_step
        # THE spec source (parallel/recipe.py): worker stack + ef
        # residuals sharded over the worker axis, center replicated —
        # the worker-axis prefix broadcasts over an empty () ef subtree
        # when the codec is off
        from theanompi_tpu.parallel.recipe import ShardingRecipe

        self.sharding = ShardingRecipe.easgd(
            mesh, ax, group_batch_spec=gspec if g > 1 else None)
        self._state_spec = self.sharding.state_spec(EASGDState)
        sspec = self._state_spec
        scalar = self.sharding.scalar
        self._bspec = self.sharding.batch_spec
        bspec = self._bspec
        self._fused: dict = {}

        def jit_step(numerics: bool):
            return jax.jit(
                jax.shard_map(
                    make_sharded_step(numerics),
                    mesh=mesh,
                    in_specs=(sspec, bspec, bspec, scalar),
                    out_specs=(sspec, scalar),
                    check_vma=False,
                ),
                donate_argnums=(0,),
            )

        self._jit_step = jit_step
        self._steps = {False: jit_step(False)}

        # ---- elastic exchange: one psum of the elastic differences ----
        codec = self.codec

        def sharded_exchange(state: EASGDState):
            local = jax.tree_util.tree_map(lambda v: v[0], state.workers)
            diff = jax.tree_util.tree_map(
                lambda w, c: a * (w - c), local.params, state.center_params
            )
            # wire codec (parallel/codec.py): only the psum'd elastic
            # differences cross the worker axis — quantize them (error-
            # feedback residual per worker); the worker applies its OWN
            # exact difference locally, no wire involved
            wire_diff, new_ef = codec.compress_stacked(diff, state.ef)
            new_params = jax.tree_util.tree_map(lambda w, d: w - d, local.params, diff)
            center = jax.tree_util.tree_map(
                lambda c, d: c + lax.psum(d, ax), state.center_params, wire_diff
            )
            # center BN/eval state: average of worker states at exchange time
            center_ms = lax.pmean(local.model_state, ax)
            workers = jax.tree_util.tree_map(
                lambda v: v[None], local._replace(params=new_params)
            )
            return EASGDState(workers, center, center_ms, new_ef)

        self._sharded_exchange_fn = sharded_exchange
        self._exchange = jax.jit(
            jax.shard_map(
                sharded_exchange,
                mesh=mesh,
                in_specs=(sspec,),
                out_specs=sspec,
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

        # ---- eval on the CENTER params (reference: server validates center) ----
        def sharded_eval(state: EASGDState, images, labels):
            center = TrainState(
                state.center_params, state.center_model_state,
                opt_state=(), step=jnp.zeros((), jnp.int32),
            )
            return lax.pmean(base_eval(center, images, labels), all_axes)

        self._eval = jax.jit(
            jax.shard_map(
                sharded_eval,
                mesh=mesh,
                in_specs=(sspec, bspec, bspec),
                out_specs=scalar,
                check_vma=False,
            )
        )

    # -- engine protocol ----------------------------------------------------
    @property
    def exchange_every(self) -> int:
        return self.avg_freq

    def init_state(self, rng) -> EASGDState:
        ts = init_train_state(self.model, rng)
        return EASGDState(
            workers=stack_replicas(ts, self.n),
            center_params=ts.params,
            center_model_state=ts.model_state,
            ef=self.codec.init_ef(ts.params, stack=self.n),
        )

    def train_step(self, state, images, labels, rng, numerics: bool = False):
        numerics = bool(numerics)
        if numerics not in self._steps:
            self._steps[numerics] = self._jit_step(numerics)
        return self._steps[numerics](state, images, labels, rng)

    def fused_train_step(self, state, images, labels, rngs,
                         numerics: bool = False):
        """``g`` local steps in ONE program, with the elastic exchange
        embedded at the exact ``avg_freq`` boundaries the per-step
        driver would hit (``lax.cond`` on the in-program step counter) —
        identical trajectory, one dispatch. The driver must NOT call
        ``exchange()`` around fused groups; the recorder's comm bracket
        is subsumed into the step (documented tradeoff of fusion)."""
        numerics = bool(numerics)
        if numerics not in self._fused:
            from theanompi_tpu.parallel.fused import fuse_sharded_step

            freq = self.avg_freq
            step_fn = self._make_sharded_step(numerics)
            exchange_fn = self._sharded_exchange_fn

            def step_and_maybe_exchange(st, x, y, r):
                st, metrics = step_fn(st, x, y, r)
                # workers.step is the stacked [1] per-worker counter;
                # it matches the driver's step_count after each step
                st = lax.cond(
                    st.workers.step[0] % freq == 0,
                    exchange_fn, lambda s: s, st,
                )
                return st, metrics

            self._fused[numerics] = fuse_sharded_step(
                step_and_maybe_exchange, self.mesh, self._state_spec,
                (self.sharding.stacked_batch_spec,
                 self.sharding.stacked_batch_spec,
                 self.sharding.scalar), True,
            )
        return self._fused[numerics](state, images, labels, rngs)

    def exchange(self, state):
        return self._exchange(state)

    def eval_step(self, state, images, labels):
        return self._eval(state, images, labels)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.workers.step))

    def sharding_recipe(self):
        """The engine's ShardingRecipe (parallel/recipe.py) — declared
        spec table for the sharding analyzer and the topology stamp."""
        return self.sharding

    def elastic_spec(self) -> dict:
        """Per-leaf reshard policies for the topology manifest
        (utils/checkpoint.load_resharded). The center is replicated
        (``global``, exact across any world); the per-worker replicas
        are stacked ``(n_workers, ...)`` so a world change resizes the
        stack — ``worker_consensus`` re-seeds every new worker from the
        mean of the saved ones (int leaves like the per-worker step
        counter take the first worker's value), a parity-preserving
        approximation of the elastic consensus, not an exact resume.
        Error-feedback residuals are per-worker and reset."""
        return {"policies": {
            ".workers": {"policy": "worker_consensus"},
            ".ef": {"policy": "reset"},
        }}

    def traffic_model(self, state):
        """EASGD wire model (obs/comm.py): silent local steps (plus the
        group-internal grad psum when workers are chip groups), one
        param-sized psum of elastic differences every ``avg_freq``
        steps over the worker axis."""
        from theanompi_tpu.obs.comm import easgd_traffic, pytree_num_elements
        from theanompi_tpu.parallel.mesh import slice_topology

        # workers leaves are stacked (n_workers, ...): per-worker size
        per_worker = pytree_num_elements(state.workers.params) // self.n
        return easgd_traffic(
            per_worker, self.n, self.avg_freq, group_size=self.group_size,
            codec=self.codec, n_slices=slice_topology(self.mesh)[0],
        )

    def memory_model(self, state):
        """Analytic per-leaf HBM residency (utils/flops.py
        ``MemoryModel``; see BSPEngine.memory_model). The per-worker
        replicas are stacked ``(n_workers, ...)`` and sharded over the
        worker axis — each device holds ONE worker's params+opt — while
        the elastic center (params + refreshed BN state) is replicated
        on every device; error-feedback residuals are per-worker.
        Factors/specs come from the engine's ShardingRecipe (SHARD003
        checks them against the compiled program)."""
        from theanompi_tpu.utils.flops import state_memory_model

        n = self.n
        lf = self.sharding.leaf_factors(state)

        def factor(path, leaf):
            return lf.get(path, (1, None))[0]

        return state_memory_model(
            state, "easgd", n, factor,
            detail={"note": "worker stack sharded 1/n; center "
                            "replicated on every device"},
            specs={p: s for p, (_f, s) in lf.items()},
        )

    def cost_model(self, state, global_batch: int):
        """XLA cost analysis of the compiled numerics-off LOCAL step
        over an abstract global batch (utils/flops.py ``CostModel``;
        see BSPEngine.cost_model). The periodic elastic exchange is a
        separate executable and is NOT included — its wire time is the
        traffic model's amortized share (obs/attribution.py books it
        under comm, not compute)."""
        import jax as _jax

        from theanompi_tpu.utils.flops import abstract_batch, compiled_cost

        x, y = abstract_batch(self.model, int(global_batch))
        return compiled_cost(self._steps[False], state, x, y,
                             _jax.random.PRNGKey(0))

    def numerics_model(self, state):
        """Numerics declaration (obs/numerics.py): standard sentinels
        plus the EASGD divergence gauge — RMS-over-workers L2 distance
        of worker params to the center. Costs one scalar psum per
        numerics step; local steps stay otherwise collective-free."""
        from theanompi_tpu.obs.numerics import NumericsModel

        del state  # the gauge's cost is state-size independent (scalar)
        return NumericsModel(
            rule="easgd",
            divergence="center_worker_l2",
            detail={"extra_wire": "one scalar psum per numerics step",
                    "avg_freq": self.avg_freq},
        )
