"""Compressed-collectives codec layer: pluggable quantized exchange
with error feedback, for EVERY engine's wire.

Theano-MPI shipped exactly one compressed exchange — the fp16 segmented
ring (``Exch_asa16``) — and this repo reproduced it as a one-off inside
``parallel/strategies.py``'s explicit ring. This module generalizes it
the way EQuARX (arXiv:2506.17615) and "Efficient Communications in
Training Large Scale Neural Networks" (arXiv:1611.04255) prescribe:

    block-scaled low-bit quantize -> reduce -> dequant,
    with error-feedback residual accumulators

as a CODEC any exchange path opts into: BSP's gradient psum/ring, the
ZeRO-1 reduce-scatter + all-gather, EASGD's elastic-difference psum,
GoSGD's gossip ppermute, and the ND engine's sharded-axis grad psums —
selected by one ``--wire-codec {none,bf16,int8}[:ef]`` knob.

Codecs:

- ``none``  — identity (fp32 wire);
- ``bf16``  — round-to-nearest bf16 values (2 B/elem, the modern
  ``asa16``);
- ``int8``  — per-128-element-block absmax-scaled int8 via the Pallas
  kernels in ``ops/pallas_quant.py`` (~1.03 B/elem incl. scales,
  >= 3.8x wire compression).

``:ef`` turns on error feedback (Seide et al. 2014; 1611.04255 §3):
each device keeps the residual ``r' = (v + r) - Q(v + r)`` of what its
quantizer discarded and re-injects it next round, so the quantization
error telescopes instead of accumulating — the difference between int8
exchange that tracks the fp32 trajectory and one that stalls. The
residuals are an explicit field of ENGINE STATE (stacked per device,
sharded over the exchange axes): donation-safe, checkpointed with the
rest of the state, so a kill-and-resume run is bit-identical to an
uninterrupted one.

Wire honesty: on point-to-point exchanges (the explicit ring's hops,
GoSGD's gossip ppermute) the packed int8 message itself rides the
interconnect — physical compression. On XLA-owned reductions (psum,
psum_scatter, all_gather) the codec quantizes the OPERAND VALUES (the
algorithm and its numerics are exactly the compressed collective;
accumulation stays fp32) while XLA moves fp32 lanes — the analytic
traffic model (``obs/comm.py``) reports codec bytes, which is the wire
an implementation lowering the reduction to quantized segments (EQuARX)
would move. ``bf16`` values are exactly representable in bf16 either
way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_quant import (  # noqa: F401  (re-exported:
    dequantize_int8_block,  # the strategies ring + gossip consume the
    quantize_int8_block,  # packed wire through THIS layer)
    wire_decode,
    wire_encode,
    wire_rows,
)

PyTree = Any

_LANES = 128
# wire bytes per payload element, scale overhead included (int8: 1 B
# values + one 4 B f32 scale per 128-element block = 1/32 B amortized)
CODEC_WIRE_BYTES = {
    "none": 4.0,
    "bf16": 2.0,
    "int8": 1.0 + 4.0 / _LANES,
}


def _qdq_int8_block(x: jax.Array) -> jax.Array:
    """Value-space block quantize-dequantize of an arbitrary-shape f32
    array: flatten, zero-pad to (rows, 128) lanes, per-block absmax
    int8 round trip (ops/pallas_quant.py kernels), un-pad."""
    flat = x.reshape(-1)
    L = flat.shape[0]
    rows = -(-L // _LANES)
    pad = rows * _LANES - L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    vals, scales = quantize_int8_block(flat.reshape(rows, _LANES))
    back = dequantize_int8_block(vals, scales).reshape(-1)
    if pad:
        back = back[:L]
    return back.reshape(x.shape)


@dataclass(frozen=True)
class WireCodec:
    """One wire codec: a value-space quantizer ``Q`` plus the
    error-feedback policy and the analytic bytes-per-element it costs.
    Instances are cheap, stateless, and hashable (safe to close over in
    jitted step builders); the EF residual state lives in ENGINE state,
    threaded through :meth:`compress`."""

    name: str  # none | bf16 | int8
    error_feedback: bool = False

    def __post_init__(self):
        if self.name not in CODEC_WIRE_BYTES:
            raise ValueError(
                f"unknown wire codec {self.name!r}; available: "
                f"{sorted(CODEC_WIRE_BYTES)} (suffix ':ef' for error "
                "feedback)"
            )
        if self.name == "none" and self.error_feedback:
            raise ValueError(
                "'none:ef' is meaningless: the identity codec discards "
                "nothing, so there is no error to feed back"
            )

    # -- analytic wire cost ------------------------------------------------
    @property
    def active(self) -> bool:
        return self.name != "none"

    @property
    def wire_bytes_per_element(self) -> float:
        return CODEC_WIRE_BYTES[self.name]

    @property
    def spec(self) -> str:
        """The CLI spelling that round-trips through :func:`get_codec`."""
        return self.name + (":ef" if self.error_feedback else "")

    # -- value-space quantization ------------------------------------------
    def qdq(self, x: jax.Array) -> jax.Array:
        """Quantize-dequantize one f32 array (any shape): the value the
        far side of the wire reconstructs."""
        if self.name == "bf16":
            return x.astype(jnp.bfloat16).astype(jnp.float32)
        if self.name == "int8":
            return _qdq_int8_block(x)
        return x

    def compress_leaf(self, v: jax.Array, ef: Optional[jax.Array]):
        """One leaf through the codec: ``(wire_value, residual')``.
        With error feedback the carried residual is injected before
        quantization and the new residual is what this round's
        quantizer discarded (``r' = (v + r) - Q(v + r)``); without it
        the residual passes through untouched."""
        if not self.active:
            return v, ef
        x = v.astype(jnp.float32)
        if self.error_feedback:
            x = x + ef
        q = self.qdq(x)
        if self.error_feedback:
            ef = x - q
        return q.astype(v.dtype), ef

    def compress(self, tree: PyTree, ef: PyTree):
        """Tree-mapped :meth:`compress_leaf` -> ``(wire_tree, ef')``.
        ``ef`` must match ``tree``'s structure when error feedback is
        on (see :meth:`init_ef`); it is passed through untouched
        otherwise."""
        if not self.active:
            return tree, ef
        if not self.error_feedback:
            return (
                jax.tree_util.tree_map(
                    lambda v: self.compress_leaf(v, None)[0], tree
                ),
                ef,
            )
        # flatten-zip-unflatten (NOT a tuple-leaved tree_map: trees with
        # tuple internal nodes would confuse an is_leaf=tuple unzip)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        ef_leaves = jax.tree_util.tree_leaves(ef)
        if len(ef_leaves) != len(leaves):
            raise ValueError(
                f"error-feedback state has {len(ef_leaves)} leaves for a "
                f"{len(leaves)}-leaf wire tree — engine state was not "
                "initialized with init_ef (or a resumed checkpoint "
                "predates the codec run)"
            )
        pairs = [self.compress_leaf(v, r) for v, r in zip(leaves, ef_leaves)]
        wire = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
        new_ef = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
        return wire, new_ef

    def compress_stacked(self, tree: PyTree, ef_stacked: PyTree):
        """:meth:`compress` for engines that store per-device residuals
        STACKED on a leading axis of size 1 inside ``shard_map`` (the
        EASGD-worker convention: global ``[n, ...]`` sharded over the
        exchange axis, local view ``[1, ...]``)."""
        if not (self.active and self.error_feedback):
            return self.compress(tree, ef_stacked)
        ef_local = jax.tree_util.tree_map(lambda v: v[0], ef_stacked)
        wire, new_ef = self.compress(tree, ef_local)
        return wire, jax.tree_util.tree_map(lambda v: v[None], new_ef)

    # -- error-feedback state ----------------------------------------------
    def init_ef(self, tree: PyTree, stack: Optional[int] = None) -> PyTree:
        """Zero residual accumulators for ``tree`` (f32, one per leaf),
        or ``()`` when this codec carries no state — so codec-off
        engines pay nothing in state size, checkpoints, or donation.
        ``stack``: prepend a worker/replica axis of that size (the
        per-device residuals of a replicated exchange, sharded over the
        exchange axis by the engine's specs)."""
        if not (self.active and self.error_feedback):
            return ()
        if stack is None:
            return jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), tree
            )
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros((stack, *jnp.shape(p)), jnp.float32), tree
        )


def get_codec(spec: Union[str, WireCodec, None]) -> WireCodec:
    """Resolve a ``--wire-codec`` spec (``none`` / ``bf16`` / ``int8``,
    optional ``:ef`` suffix) to a :class:`WireCodec`; instances pass
    through, ``None`` means ``none``."""
    if isinstance(spec, WireCodec):
        return spec
    if spec is None:
        return WireCodec("none")
    name, _, flag = str(spec).partition(":")
    if flag not in ("", "ef"):
        raise ValueError(
            f"bad wire-codec suffix {flag!r} in {spec!r} (only ':ef')"
        )
    return WireCodec(name or "none", error_feedback=flag == "ef")


# --------------------------------------------------------------------------
# gossip payload packing (GoSGD): values compressed, the share weight
# rides EXACT — quantizing the share would leak the sum(alpha) == 1
# mass invariant the merge algebra depends on
# --------------------------------------------------------------------------


def gossip_encode(codec: WireCodec, values: jax.Array, share: jax.Array):
    """Pack one gossip message ``(flat f32 values, f32 share scalar)``
    for a single ppermute. ``int8``: the packed block-quantized wire
    message plus one tail row carrying the share's exact 4 bytes — the
    int8 lanes ARE what crosses the interconnect. ``bf16``: bf16 values
    with the share bitcast into two exact bf16 lanes. ``none``: the
    classic fp32 ``concat(values, share)`` payload."""
    if codec.name == "int8":
        packed = wire_encode(values)
        share_bytes = jax.lax.bitcast_convert_type(
            share.reshape(1), jnp.int8
        ).reshape(4)
        tail = jnp.zeros((1, _LANES), jnp.int8).at[0, :4].set(share_bytes)
        return jnp.concatenate([packed, tail], axis=0)
    if codec.name == "bf16":
        share_lanes = jax.lax.bitcast_convert_type(
            share.reshape(1), jnp.bfloat16
        ).reshape(2)
        return jnp.concatenate(
            [values.astype(jnp.bfloat16), share_lanes]
        )
    return jnp.concatenate([values, share.reshape(1)])


def gossip_decode(codec: WireCodec, message: jax.Array, length: int):
    """Inverse of :func:`gossip_encode` -> ``(values f32 [length],
    share f32 scalar)``."""
    if codec.name == "int8":
        share = jax.lax.bitcast_convert_type(
            message[-1, :4].reshape(1, 4), jnp.float32
        ).reshape(())
        return wire_decode(message[:-1], length=length), share
    if codec.name == "bf16":
        share = jax.lax.bitcast_convert_type(
            message[-2:].reshape(1, 2), jnp.float32
        ).reshape(())
        return message[:-2].astype(jnp.float32), share
    return message[:-1], message[-1]


def gossip_wire_bytes(codec: WireCodec, n_elements: int) -> float:
    """Analytic per-round gossip message size in bytes (values + share
    + codec overhead), matching :func:`gossip_encode`'s actual layout."""
    if codec.name == "int8":
        rows, srows = wire_rows(max(1, n_elements))
        return float((rows + srows + 1) * _LANES)  # +1 share tail row
    if codec.name == "bf16":
        return float((n_elements + 2) * 2)
    return float((n_elements + 1) * 4)
