"""ZeRO-1 data parallelism: optimizer state sharded over the data axis.

BEYOND-PARITY EXTENSION. The reference replicated optimizer state on
every GPU (Theano shared ``vels`` per rank — SURVEY.md §2.1 "two-phase
update"); at modern model sizes the accumulators dominate memory
(Adam on VGG16: ~1.1 GB fp32 of m/v per chip). ZeRO stage 1 (Rajbhandari
et al. 2020, PAPERS.md) shards them: each data-parallel rank owns ONE
``1/n`` segment of the flat parameter buffer and steps only that segment.

TPU-native realization — the whole exchange is two XLA collectives on
the packed buffer (same packing the exchanger strategies use,
``ravel_pytree``; reference: ``BSP_Exchanger``'s pre-concatenated comm
buffer):

    grads   --psum_scatter-->  my summed segment        (ICI, P/n wire)
    segment --optimizer.update (on the local 1/n flat slice)
    params  --all_gather-->    replicated new params    (ICI, P/n wire)

Per-step wire volume is the SAME as a plain allreduce (reduce-scatter +
all-gather IS the ring allreduce, just with the update between the two
halves), so ZeRO-1 costs nothing extra in communication — it only
removes ``(n-1)/n`` of the optimizer-state memory.

Composable with any registry optimizer; the train step mirrors
``theanompi_tpu.train.make_train_step`` semantics (loss/metrics, LR
schedule by epoch, BN state) and is oracle-tested for exact equivalence
with the replicated BSP step (tests/test_zero.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh

from theanompi_tpu.models.contract import Model
from theanompi_tpu.ops.optimizers import apply_updates, get_optimizer
from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.train import loss_and_grads, make_schedule_fn

PyTree = Any


class ZeroTrainState(NamedTuple):
    """Like train.TrainState, but ``opt_state`` holds accumulators over
    the flat 1/n parameter segment owned by each rank (global leaves are
    ``[n * seg]`` sharded over the data axis).

    ``ef``: wire-codec error-feedback residuals (parallel/codec.py) —
    ``{"g": [n, n*seg], "p": [n, seg]}`` sharded over the data axis:
    per-device residuals of the quantized grad reduce-scatter, and the
    per-owner master-correction residual of the quantized param
    all-gather (exact = gathered + ef_p, so the fp32 trajectory
    survives quantized replication). ``()`` when the codec carries no
    state."""

    params: PyTree  # replicated pytree
    model_state: PyTree
    opt_state: PyTree  # flat-segment accumulators, sharded
    step: jax.Array
    ef: PyTree = ()  # codec error-feedback residuals (or ())


def _resolve_optimizer(model, optimizer, fused_update: bool):
    """The one optimizer-resolution rule for ZeRO-1 (shared by the step
    builder and the engine's ShardingRecipe construction, so the spec
    table is derived from the SAME optimizer state the step runs)."""
    if fused_update:
        # fused one-pass epilogue over the flat 1/n segment: ZeRO-1
        # reuses the SAME kernel the replicated engines run, applied to
        # its flat-padded slice (ops/pallas_update.py; state layout
        # matches the unfused rule, so resume crosses the boundary)
        from theanompi_tpu.ops.pallas_update import fuse_optimizer

        if optimizer is not None and not isinstance(optimizer, str):
            raise ValueError(
                "fused_update composes with a named optimizer (the "
                "fused kernel is built from the recipe), not an "
                "Optimizer instance"
            )
        # mirror the classic path's kwarg scoping exactly: an explicit
        # name gets builder DEFAULTS (get_optimizer(optimizer) passes no
        # kwargs), only the recipe's own rule carries its opt_kwargs —
        # a momentum recipe's kwargs must not leak into an explicit
        # "sgd" override
        name = optimizer if isinstance(optimizer, str) else (
            model.recipe.optimizer
        )
        opt_kwargs = (
            {} if isinstance(optimizer, str) else model.recipe.opt_kwargs
        )
        if opt_kwargs.get("clip_norm") is not None:
            # the fused clip is a GLOBAL grad norm; inside this step the
            # optimizer only sees the rank's 1/n flat segment, so each
            # rank would clip by a different partial-norm coefficient —
            # silently wrong numerics, refused instead
            raise ValueError(
                "--fused-update clip_norm is not supported under ZeRO-1:"
                " the fused global-norm clip would be computed over each"
                " rank's local segment, not the global gradient (drop "
                "clip_norm or run the replicated engines)"
            )
        return fuse_optimizer(name, **opt_kwargs)
    return (
        get_optimizer(optimizer)
        if isinstance(optimizer, str)
        else (optimizer or model.optimizer())
    )


def _flat_geometry(model, n: int) -> tuple:
    """``(flat_size, seg)`` of the packed parameter buffer: total
    elements and the padded per-rank segment — from an abstract init
    (nothing materialized)."""
    import math

    params_shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0))[0]
    )
    flat_size = sum(
        math.prod(l.shape)
        for l in jax.tree_util.tree_leaves(params_shapes)
    )
    return flat_size, -(-flat_size // n)  # padded segment per rank


class _Zero1Setup(NamedTuple):
    """The ONE derivation of a ZeRO-1 configuration's codec, optimizer,
    flat geometry, and ShardingRecipe — shared by the step builder and
    the engine so the declared spec table can only describe the program
    that compiled (no second copy to drift)."""

    codec: Any
    use_ef: bool
    opt: Any
    flat_size: int
    seg: int
    opt_shapes: Any
    recipe: Any  # parallel/recipe.ShardingRecipe


def _zero1_setup(model, mesh, axis_name, optimizer, fused_update,
                 wire_codec) -> _Zero1Setup:
    from theanompi_tpu.parallel.codec import get_codec
    from theanompi_tpu.parallel.recipe import ShardingRecipe

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    codec = get_codec(wire_codec)
    if n == 1:
        codec = get_codec(None)  # no peers, no wire to compress
    use_ef = codec.active and codec.error_feedback
    opt = _resolve_optimizer(model, optimizer, fused_update)
    flat_size, seg = _flat_geometry(model, n)
    opt_shapes = jax.eval_shape(
        lambda: opt.init(jnp.zeros((seg,), jnp.float32))
    )
    return _Zero1Setup(
        codec=codec, use_ef=use_ef, opt=opt, flat_size=flat_size,
        seg=seg, opt_shapes=opt_shapes,
        recipe=ShardingRecipe.zero1(mesh, axis_name, opt_shapes, use_ef),
    )


def make_zero1_train_step(
    model: Model,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    optimizer=None,
    steps_per_epoch: int = 1,
    input_transform: Optional[Callable] = None,
    donate: bool = True,
    fused: bool = False,
    numerics: bool = False,
    wire_codec=None,
    fused_update: bool = False,
    _setup: "Optional[_Zero1Setup]" = None,
):
    """Build ``(init_state, train_step)`` for ZeRO-1 BSP training over
    ``mesh``'s ``axis_name``.

    ``_setup``: a pre-derived :class:`_Zero1Setup` for this EXACT
    configuration (the engine passes its own so builder and engine
    share one derivation — never pass one built from different args).

    ``init_state(key) -> ZeroTrainState`` (host-callable; jitted and
    sharded). ``train_step(state, x, y, rng) -> (state, metrics)`` with
    ``x``/``y`` sharded over the axis (the global batch, exactly like
    parallel/bsp.py). ``optimizer`` defaults to the model recipe's.
    With ``fused=True`` the returned step instead takes stacked
    ``[g, batch, ...]`` groups + ``[g]`` keys and scans ``g`` sub-steps
    in one program (``steps_per_dispatch``; metrics stacked).
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axis_name not in sizes:
        raise ValueError(f"axis {axis_name!r} not in mesh axes {mesh.axis_names}")
    if len(mesh.axis_names) > 1:
        # collectives here run over axis_name ONLY; on a multi-axis mesh
        # the P() out-specs would stamp dcn-divergent params as
        # replicated with no error
        raise ValueError(
            f"ZeRO-1 runs on a 1-D data mesh; got axes {mesh.axis_names} "
            "(for multi-slice, flatten to one data axis — XLA still "
            "routes the collectives hierarchically over ICI/DCN)"
        )
    n = sizes[axis_name]
    # THE one config derivation (codec, optimizer, geometry, recipe) —
    # the engine hands its own _Zero1Setup down, so the declared spec
    # table and the compiled program share one derivation
    setup = _setup if _setup is not None else _zero1_setup(
        model, mesh, axis_name, optimizer, fused_update, wire_codec)
    codec, use_ef, opt = setup.codec, setup.use_ef, setup.opt
    flat_size, seg = setup.flat_size, setup.seg
    schedule_lr = make_schedule_fn(model, steps_per_epoch)
    recipe = setup.recipe

    def _seg_slice(flat, rank):
        padded = jnp.pad(flat, (0, n * seg - flat_size))
        return lax.dynamic_slice(padded, (rank * seg,), (seg,))

    def sharded_init(key):
        params, model_state = model.init(key)
        opt_state = opt.init(jnp.zeros((seg,), jnp.float32))
        ef = (
            {"g": jnp.zeros((1, n * seg), jnp.float32),
             "p": jnp.zeros((1, seg), jnp.float32)}
            if use_ef else ()
        )
        return ZeroTrainState(
            params, model_state, opt_state, jnp.zeros((), jnp.int32), ef
        )

    state_specs = recipe.state_spec(ZeroTrainState)
    init_state = jax.jit(
        jax.shard_map(
            sharded_init,
            mesh=mesh,
            in_specs=(recipe.scalar,),
            out_specs=state_specs,
            check_vma=False,
        )
    )

    def sharded_step(state, images, labels, rng):
        if input_transform is not None:
            images = input_transform(images)

        loss, logits, new_model_state, grads = loss_and_grads(
            model, state.params, state.model_state, images, labels, rng
        )
        # BN running stats etc. are per-shard batch statistics — average
        # them across the data axis exactly like parallel/bsp.py (the
        # P() out-spec under check_vma=False would otherwise silently
        # emit device-divergent state as if replicated)
        new_model_state = jax.tree_util.tree_map(
            lambda s: lax.pmean(s, axis_name), new_model_state
        )

        rank = lax.axis_index(axis_name)
        flat_g, _ = ravel_pytree(grads)
        flat_g = jnp.pad(flat_g.astype(jnp.float32), (0, n * seg - flat_size))
        new_ef = state.ef
        if codec.active:
            # compressed reduce-scatter: quantize this rank's LOCAL
            # contribution (error-feedback residual re-injected first),
            # accumulate in fp32 — the 1611.04255 recipe on the scatter
            # half of the exchange
            if use_ef:
                flat_g = flat_g + state.ef["g"][0]
            g_wire = codec.qdq(flat_g)
            if use_ef:
                new_ef = dict(new_ef, g=(flat_g - g_wire)[None])
            flat_g = g_wire
        # reduce-scatter: each rank receives the SUM of its segment
        g_seg = lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                 tiled=True) / n

        flat_p, unravel = ravel_pytree(state.params)
        p_seg = _seg_slice(flat_p.astype(jnp.float32), rank)
        if use_ef:
            # master correction: the replicated params are the QUANTIZED
            # gather of last step; exact segment = quantized + residual,
            # so the optimizer walks the fp32 trajectory while replicas
            # carry the compressed copy
            p_seg = p_seg + state.ef["p"][0]

        lr = schedule_lr(state.step)
        if opt.apply is not None:
            # fused one-pass segment update (ops/pallas_update.py); the
            # gauges' update segment is reconstructed in the numerics
            # block below
            new_p_seg, new_opt = opt.apply(g_seg, state.opt_state, p_seg, lr)
            updates = None
        else:
            updates, new_opt = opt.update(g_seg, state.opt_state, p_seg, lr)
            new_p_seg = apply_updates(p_seg, updates)

        gather_seg = new_p_seg
        if codec.active:
            # compressed all-gather: every rank (owner included) adopts
            # the dequantized segment, so params stay bit-replicated;
            # the owner's residual preserves the exact master above
            gather_seg = codec.qdq(new_p_seg)
            if use_ef:
                new_ef = dict(new_ef, p=(new_p_seg - gather_seg)[None])
        new_flat = lax.all_gather(gather_seg, axis_name, tiled=True)[:flat_size]
        new_params = unravel(new_flat.astype(flat_p.dtype))

        metrics = {
            "loss": lax.pmean(loss, axis_name),
            "lr": lr,
            **{k: lax.pmean(v, axis_name)
               for k, v in model.metrics(logits, labels).items()},
        }
        if numerics:
            # sentinels over the SHARDED flat segments (obs/numerics.py
            # semantics): each rank owns one 1/n slice of the summed
            # grads/updates, so the global norms are psums of local
            # squared sums — scalar collectives only. param_norm reads
            # the freshly all-gathered full buffer (replicated), and
            # the non-finite count covers the synced grads exactly like
            # the replicated engines'.
            if updates is None:
                from theanompi_tpu.ops.optimizers import update_delta

                updates = update_delta(new_p_seg, p_seg)
            gsq = lax.psum(jnp.sum(jnp.square(g_seg)), axis_name)
            usq = lax.psum(
                jnp.sum(jnp.square(updates.astype(jnp.float32))), axis_name
            )
            nonf = lax.psum(
                jnp.sum((~jnp.isfinite(g_seg)).astype(jnp.float32)), axis_name
            )
            metrics = {
                **metrics,
                "nm_grad_norm": jnp.sqrt(gsq),
                "nm_update_norm": jnp.sqrt(usq),
                "nm_param_norm": jnp.sqrt(
                    jnp.sum(jnp.square(new_flat.astype(jnp.float32)))
                ),
                "nm_nonfinite": nonf,
            }
        return (
            ZeroTrainState(new_params, new_model_state, new_opt,
                           state.step + 1, new_ef),
            metrics,
        )

    if fused:
        # fused dispatch: lax.scan over stacked [g, batch, ...] groups,
        # same amortization as make_bsp_fused_step (stacked metrics out)
        from theanompi_tpu.parallel.fused import fuse_sharded_step

        return init_state, fuse_sharded_step(
            sharded_step, mesh, state_specs,
            (recipe.stacked_batch_spec, recipe.stacked_batch_spec,
             recipe.scalar), donate,
        )

    train_step = jax.jit(
        jax.shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=(state_specs, recipe.batch_spec, recipe.batch_spec,
                      recipe.scalar),
            out_specs=(state_specs, recipe.scalar),
            check_vma=False,
        ),
        # donate like parallel/bsp.py: without it every dispatch holds a
        # second params+opt copy, undercutting the memory saving that is
        # this module's point (donate=False for oracle tests that reuse
        # the input state)
        donate_argnums=(0,) if donate else (),
    )
    return init_state, train_step


class ZeroEngine:
    """Driver-protocol wrapper over the ZeRO-1 step, so ``tmpi BSP ...
    --zero 1`` runs optimizer-state-sharded training through the same
    ``run_training`` loop (recorder, loader, checkpoint/resume) as plain
    BSP. Eval reuses the BSP eval step on a view of the state WITHOUT
    the sharded accumulators (params/BN state are replicated), so no
    gather is paid per validation batch."""

    name = "zero1"
    exchange_every = 0
    # donation audit (ISSUE 2): make_zero1_train_step donates by default
    # (the sharded opt state + replicated params reuse their buffers).
    # The claim is now VERIFIED statically: the SPMD analyzer (ISSUE 7)
    # reads the lowered step's donated_invars and fails `tmpi lint`
    # (SPMD201) if this flag and the program disagree; the
    # reduce_scatter+all_gather schedule itself is pinned by
    # tools/analyze/golden/zero1_*.json (SPMD003).
    donates_state = True

    def __init__(
        self,
        model: Model,
        mesh: Mesh,
        steps_per_epoch: int = 1,
        input_transform=None,
        eval_views: int = 1,
        wire_codec=None,
        fused_update: bool = False,
    ):
        from theanompi_tpu.parallel.bsp import make_bsp_eval_step
        from theanompi_tpu.parallel.codec import get_codec

        self.model = model
        self.mesh = mesh
        self.codec = get_codec(wire_codec)
        # ONE _zero1_setup derivation, handed to every step variant the
        # engine builds (per-numerics + fused dispatch) via _build — the
        # declared spec table (sharding analyzer, memory_model, topology
        # stamp) and the compiled programs share it by construction
        setup = _zero1_setup(model, mesh, DATA_AXIS, None,
                             bool(fused_update), self.codec)
        self.sharding = setup.recipe
        self._build = dict(steps_per_epoch=steps_per_epoch,
                           input_transform=input_transform,
                           wire_codec=self.codec,
                           fused_update=bool(fused_update),
                           _setup=setup)
        self._init, step = make_zero1_train_step(model, mesh, **self._build)
        self._steps = {False: step}
        self._fused: dict = {}
        self._eval = make_bsp_eval_step(
            model, mesh, input_transform=input_transform, eval_views=eval_views,
        )

    def init_state(self, rng) -> ZeroTrainState:
        return self._init(rng)

    def train_step(self, state, images, labels, rng, numerics: bool = False):
        numerics = bool(numerics)
        if numerics not in self._steps:
            _, self._steps[numerics] = make_zero1_train_step(
                self.model, self.mesh, numerics=numerics, **self._build
            )
        return self._steps[numerics](state, images, labels, rng)

    def fused_train_step(self, state, images, labels, rngs,
                         numerics: bool = False):
        """``g`` ZeRO steps in one program (stacked batches + keys, like
        make_bsp_fused_step); jit recompiles per distinct group size."""
        numerics = bool(numerics)
        if numerics not in self._fused:
            _, self._fused[numerics] = make_zero1_train_step(
                self.model, self.mesh, fused=True, numerics=numerics,
                **self._build
            )
        return self._fused[numerics](state, images, labels, rngs)

    def exchange(self, state):
        return state

    def eval_step(self, state, images, labels):
        from theanompi_tpu.train import TrainState

        view = TrainState(state.params, state.model_state, (), state.step)
        return self._eval(view, images, labels)

    def get_step(self, state) -> int:
        from theanompi_tpu.parallel.mesh import first_local_value

        return int(first_local_value(state.step))

    def sharding_recipe(self):
        """The engine's ShardingRecipe (parallel/recipe.py) — declared
        spec table for the sharding analyzer and the topology stamp."""
        return self.sharding

    def elastic_spec(self) -> dict:
        """Per-leaf reshard policies for the topology manifest
        (utils/checkpoint.load_resharded). ZeRO is THE shape-changing
        case: the flat optimizer accumulators are padded to ``n``
        equal segments, so their global length is mesh-dependent
        (``n * ceil(F/n)``) — the ``flat_padded`` policy moves the
        logical ``F``-element prefix and re-pads for the target world.
        Params/BN state are replicated (``global``); error-feedback
        residuals are per-device and reset."""
        flat_size, _ = _flat_geometry(self.model, self.mesh.devices.size)
        return {"policies": {
            ".opt_state": {"policy": "flat_padded",
                           "logical": int(flat_size)},
            ".ef": {"policy": "reset"},
        }}

    def traffic_model(self, state):
        """ZeRO-1 wire model (obs/comm.py): psum_scatter + all_gather
        over the flat fp32 buffer padded to n segments — same volume as
        the plain allreduce, which is the module's headline claim; the
        codec compresses both halves."""
        from theanompi_tpu.obs.comm import pytree_num_elements, zero1_traffic
        from theanompi_tpu.parallel.mesh import slice_topology

        return zero1_traffic(
            pytree_num_elements(state.params), self.mesh.devices.size,
            codec=self.codec, n_slices=slice_topology(self.mesh)[0],
        )

    def memory_model(self, state):
        """Analytic per-leaf HBM residency (utils/flops.py
        ``MemoryModel``; see BSPEngine.memory_model). ZeRO-1's point IS
        this table: params/BN state replicated (factor 1), the flat
        optimizer accumulators sharded ``1/n`` over the data axis, the
        codec's error-feedback residuals likewise per-device. Factors
        and specs come from the engine's ShardingRecipe — the 1/n claim
        and the step's actual sharding are one declaration (SHARD003
        checks it against the compiled program)."""
        from theanompi_tpu.utils.flops import state_memory_model

        n = self.mesh.devices.size
        lf = self.sharding.leaf_factors(state)

        def factor(path, leaf):
            return lf.get(path, (1, None))[0]

        return state_memory_model(
            state, "zero1", n, factor,
            detail={"note": "optimizer state flat-sharded 1/n "
                            "(the ZeRO-1 memory claim)"},
            specs={p: s for p, (_f, s) in lf.items()},
        )

    def cost_model(self, state, global_batch: int):
        """XLA cost analysis of the compiled ZeRO-1 step over an
        abstract global batch (utils/flops.py ``CostModel``; see
        BSPEngine.cost_model) — scatter/update/gather included, since
        they are inside the same executable."""
        import jax as _jax

        from theanompi_tpu.utils.flops import abstract_batch, compiled_cost

        x, y = abstract_batch(self.model, int(global_batch))
        return compiled_cost(self._steps[False], state, x, y,
                             _jax.random.PRNGKey(0))

    def numerics_model(self, state):
        """Numerics declaration (obs/numerics.py): standard sentinels
        computed over the sharded flat segments (scalar psums); no
        divergence gauge — the all_gather re-replicates params every
        step, so sharded-consistency holds by construction."""
        from theanompi_tpu.obs.numerics import NumericsModel

        del state
        return NumericsModel(
            rule="zero1",
            detail={"note": "segment-sharded norms via scalar psums; "
                            "params re-replicated by the in-step "
                            "all_gather"},
        )
