"""Deterministic fault injection: every recovery path gets exercised.

The reference framework had no failure story at all — a dead worker or
a NaN burst killed the whole mpirun (SURVEY.md §5.4) — and a recovery
path that is never executed is a recovery path that does not work. This
module is the registry behind ``--inject-fault KIND@STEP`` (repeatable):
a :class:`FaultInjector` armed with one or more :class:`FaultSpec`\\ s
fires each of them exactly once, at a deterministic global step, so the
supervisor's retry loop, the checkpoint integrity chain, the anomaly
rollback policy, and the SIGTERM grace path are all proven by tier-1
tests instead of trusted on faith.

Fault kinds (``KIND@STEP`` or ``KIND@STEP:ARG``):

- ``crash``        raise :class:`InjectedCrash` before dispatching STEP
                   (an in-process worker-loop exception: OOM, loader
                   bug, poisoned collective — the supervisor retries it)
- ``sigterm``      ``os.kill(self, SIGTERM)`` before STEP (preemption;
                   with ``--sigterm-grace`` the driver checkpoints and
                   exits cleanly, marking the run resumable)
- ``sigkill``      ``os.kill(self, SIGKILL)`` before STEP (hard host
                   death: no finally, no grace — resume must come from
                   the last durable checkpoint)
- ``ckpt_truncate`` truncate the newest checkpoint file after the first
                   save at/after STEP (torn write / died mid-replace:
                   ``latest_checkpoint(verify=True)`` must walk back)
- ``nan_batch``    poison the data batch feeding STEP with NaN (a bad
                   shard / corrupted record: the numerics sentinels and
                   the rollback policy must absorb it)
- ``loader_stall`` sleep ARG seconds (default 2.0) before STEP (a hung
                   data source: the stall watchdog's territory)
- ``shrink``       raise :class:`TopologyChanged` before STEP with the
                   world shrunk to ARG devices (a slice died; the
                   elastic supervisor must reshard-and-resume onto the
                   smaller mesh — ``shrink@3:2``)
- ``grow``         like ``shrink`` but ARG grows the world (capacity
                   returned; ``grow@3:4``)
- ``slice_down``   whole-slice loss: :class:`TopologyChanged` before
                   STEP with ARG slices (default 1) removed from the
                   CURRENT mesh topology (``slice_down@3`` /
                   ``slice_down@3:2``). Unlike ``shrink`` the arg is
                   slice-granular — the surviving world is computed
                   from the topology the run registered via
                   :meth:`FaultInjector.set_topology`, so the same spec
                   exercises reshard-to-survivors on any ``--slices N``
                   shape (a DCN partition isolating a whole ICI domain,
                   the failure unit real pods lose)

Storage-level kinds (chaos PR) — the fault matrix used to stop at the
process boundary; these reach into the checkpoint write path itself:

- ``enospc``       the first checkpoint save at/after STEP raises
                   ``OSError(ENOSPC)`` MID-WRITE via the injectable
                   writer shim in ``utils/checkpoint.py`` (disk full /
                   quota: the torn attempt must read as absent and the
                   keep-chain must stay restorable)
- ``slow_write``   the first save at/after STEP stalls ARG seconds
                   (default 2.0) inside the writer (a degraded NFS
                   mount: the async checkpointer's NEXT save blocks the
                   driver — the stall watchdog's territory)
- ``bitrot``       flip bytes in the newest COMMITTED keep-chain member
                   after the first save at/after STEP (at-rest
                   bit-corruption: the CRC32 chain must catch it and
                   the scrubber must quarantine it)
- ``partial_set``  delete one member of the newest sharded checkpoint
                   set after the first save at/after STEP (a host's
                   file lost: completeness-by-counting must read the
                   torn set as absent)

Injection points live in ``launch/worker.py``'s train loops; all hooks
are host-side and sync-free (``tools/check_hot_loop.py`` stays green).

Cross-process once-only semantics: the in-process supervisor threads
ONE injector through every retry, so fired flags persist. A run that is
relaunched as a NEW process (SIGKILL under an outer chaos campaign,
rc-75 preemption) would re-fire every fault — unless the injector is
armed with a ``ledger`` file: every fired spec is appended (and fsynced
BEFORE the fault's side effect, so even a SIGKILL cannot lose the
entry) and specs already in the ledger arm as fired. ``--fault-ledger``
on the CLI wires it; ``tools/chaos.py`` relies on it to relaunch killed
runs without replaying their faults.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass
from typing import Optional, Sequence, Union


class InjectedFault(RuntimeError):
    """Base class for failures raised by the fault injector."""


class InjectedCrash(InjectedFault):
    """The ``crash`` fault: an ordinary worker-loop exception, exactly
    what the run supervisor's bounded-retry loop exists to absorb."""


class TopologyChanged(InjectedFault):
    """The ``shrink``/``grow``/``slice_down`` faults: the visible device
    world changed mid-run (a slice died, or capacity came back). The
    attempt dies like
    any infrastructure fault; under ``supervise_training(elastic=True)``
    the retry re-probes the world (honoring :meth:`FaultInjector.
    world_override` in tests), rebuilds the mesh at ``new_world``
    devices, and reshards the checkpoint onto it
    (utils/checkpoint.load_resharded)."""

    def __init__(self, kind: str, step: int, new_world: int):
        self.kind = str(kind)
        self.step = int(step)
        self.new_world = int(new_world)
        super().__init__(
            f"injected {kind} before step {step}: world is now "
            f"{new_world} device(s)"
        )


class Preempted(RuntimeError):
    """Graceful SIGTERM exit: the driver checkpointed inside the grace
    window and marked the run resumable (``launch/worker.py``). The
    supervisor records it as a resumable attempt and exits — the next
    invocation auto-resumes from the marker."""

    def __init__(self, step: int):
        self.step = int(step)
        super().__init__(
            f"preempted (SIGTERM) at step {step}: checkpointed and "
            "marked resumable"
        )


FAULT_KINDS = (
    "crash", "sigterm", "sigkill", "ckpt_truncate", "nan_batch",
    "loader_stall", "shrink", "grow", "slice_down",
    # storage-level kinds (chaos PR): enospc/slow_write fire INSIDE the
    # write via the checkpoint writer shim; bitrot/partial_set mutate a
    # COMMITTED file after the save lands (like ckpt_truncate)
    "enospc", "slow_write", "bitrot", "partial_set",
)

# post-save mutators: applied to a durable checkpoint after the first
# save at/after the spec's step (the ckpt_truncate family)
STORAGE_MUTATION_KINDS = ("ckpt_truncate", "bitrot", "partial_set")

# during-write faults: consulted by the checkpoint writer shim
# (utils/checkpoint.set_write_fault_hook) at each save's step
WRITE_FAULT_KINDS = ("enospc", "slow_write")


@dataclass
class FaultSpec:
    """One armed fault: ``kind`` fires once at global step ``step``.
    ``fired_seq`` stamps the ORDER the injector fired specs in (-1 =
    not fired) — what "the LAST fired topology fault" means cannot
    depend on the order specs were listed on the command line."""

    kind: str
    step: int
    arg: Optional[float] = None
    fired: bool = False
    fired_seq: int = -1
    # slice_down resolves its survivor world from the registered
    # topology AT FIRE TIME (the spec's arg is slices lost, not a world
    # size) — recorded here so world_override can replay the answer
    resolved_world: Optional[int] = None


def parse_fault_spec(spec: Union[str, FaultSpec]) -> FaultSpec:
    """``KIND@STEP`` / ``KIND@STEP:ARG`` -> :class:`FaultSpec`."""
    if isinstance(spec, FaultSpec):
        return spec
    kind, sep, rest = str(spec).partition("@")
    if not sep:
        raise ValueError(
            f"fault spec {spec!r} must be KIND@STEP (e.g. crash@5); "
            f"kinds: {FAULT_KINDS}"
        )
    if kind not in FAULT_KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; kinds: {FAULT_KINDS}")
    step_s, sep2, arg_s = rest.partition(":")
    try:
        step = int(step_s)
    except ValueError:
        raise ValueError(f"fault spec {spec!r}: step {step_s!r} is not an int")
    if step < 1:
        raise ValueError(f"fault spec {spec!r}: steps are 1-based")
    arg = None
    if sep2:
        try:
            arg = float(arg_s)
        except ValueError:
            raise ValueError(f"fault spec {spec!r}: arg {arg_s!r} is not a number")
    if kind in ("shrink", "grow"):
        # the arg IS the post-fault world size — elastic recovery is
        # only testable against a deterministic target topology
        if arg is None or int(arg) != arg or arg < 1:
            raise ValueError(
                f"fault spec {spec!r}: {kind} needs an integer target "
                f"world size >= 1 (e.g. {kind}@{step}:2)"
            )
    if kind == "slice_down" and arg is not None and (
            int(arg) != arg or arg < 1):
        raise ValueError(
            f"fault spec {spec!r}: slice_down's arg is the number of "
            f"slices lost, an integer >= 1 (e.g. slice_down@{step}:1)"
        )
    return FaultSpec(kind=kind, step=step, arg=arg)


class FaultInjector:
    """Fires each armed :class:`FaultSpec` exactly once at its step.

    The driver calls :meth:`check_step` with the 1-based step it is
    ABOUT to dispatch (fused dispatch passes the group's step range),
    :meth:`poison_batch` on the batch feeding that step,
    :meth:`storage_mutations_due`/:meth:`apply_storage_mutation` around
    checkpoint saves, and installs :meth:`write_fault` as the
    checkpoint writer shim for the during-write kinds. Deterministic by
    construction: same specs + same step sequence = same failures.

    ``ledger``: optional path of a fired-fault ledger (module
    docstring) — specs already recorded there arm as fired, and every
    fire appends+fsyncs its line BEFORE the fault's side effect, so a
    relaunched process armed with the same ledger never replays a
    fault that already happened.
    """

    def __init__(self, specs: Sequence[Union[str, FaultSpec]],
                 ledger: Optional[str] = None):
        self.specs = [parse_fault_spec(s) for s in (specs or [])]
        self._fire_seq = 0
        self._topology: Optional[tuple] = None  # (n_slices, per_slice)
        self._ledger = ledger
        if ledger and os.path.exists(ledger):
            # arm-as-fired anything a previous incarnation already did.
            # Duplicate specs (crash@3 twice) consume ledger entries
            # positionally: two recorded fires mark two specs fired.
            with open(ledger) as f:
                seen = [ln.strip() for ln in f if ln.strip()]
            for entry in seen:
                for s in self.specs:
                    if not s.fired and f"{s.kind}@{s.step}" == entry:
                        s.fired = True
                        s.fired_seq = self._fire_seq
                        self._fire_seq += 1
                        break

    def set_topology(self, n_slices: int, per_slice: int) -> None:
        """Register the CURRENT mesh shape (``parallel.mesh.
        slice_topology``) so slice-granular faults can resolve survivor
        worlds. The driver calls this each attempt, after building its
        mesh — an elastic retry re-registers the shrunk shape, so a
        second ``slice_down`` removes a slice of the world that
        actually survived the first."""
        self._topology = (int(n_slices), int(per_slice))

    def _record_fire(self, s: FaultSpec) -> None:
        """Durably note a fired spec BEFORE its side effect (a SIGKILL
        one line later must not lose the entry)."""
        if not self._ledger:
            return
        with open(self._ledger, "a") as f:
            f.write(f"{s.kind}@{s.step}\n")
            f.flush()
            os.fsync(f.fileno())

    def _take(self, kind: str, first: int, last: Optional[int] = None
              ) -> Optional[FaultSpec]:
        """The unfired spec of ``kind`` whose step falls in
        ``[first, last]`` (marked fired), or None."""
        last = first if last is None else last
        for s in self.specs:
            if s.kind == kind and not s.fired and first <= s.step <= last:
                s.fired = True
                s.fired_seq = self._fire_seq
                self._fire_seq += 1
                self._record_fire(s)
                return s
        return None

    def check_step(self, first: int, last: Optional[int] = None) -> None:
        """Fire crash/sigterm/sigkill/loader_stall faults due before
        dispatching steps ``[first, last]`` (a fused group passes its
        whole substep range)."""
        s = self._take("loader_stall", first, last)
        if s is not None:
            time.sleep(2.0 if s.arg is None else float(s.arg))
        s = self._take("crash", first, last)
        if s is not None:
            raise InjectedCrash(f"injected crash before step {s.step}")
        for kind in ("shrink", "grow"):
            s = self._take(kind, first, last)
            if s is not None:
                raise TopologyChanged(kind, s.step, int(s.arg))
        s = self._take("slice_down", first, last)
        if s is not None:
            lost = 1 if s.arg is None else int(s.arg)
            if self._topology is None or self._topology[0] <= 1:
                raise ValueError(
                    f"slice_down@{s.step}: no multislice topology "
                    "registered — the run must build a --slices N mesh "
                    "(N > 1) and call set_topology() for whole-slice "
                    "loss to have a surviving world"
                )
            n_slices, per_slice = self._topology
            survivors = (n_slices - lost) * per_slice
            if survivors < 1:
                raise ValueError(
                    f"slice_down@{s.step}:{lost}: losing {lost} of "
                    f"{n_slices} slice(s) leaves no survivors — elastic "
                    "recovery needs at least one live slice"
                )
            s.resolved_world = survivors
            raise TopologyChanged("slice_down", s.step, survivors)
        s = self._take("sigterm", first, last)
        if s is not None:
            os.kill(os.getpid(), signal.SIGTERM)
        s = self._take("sigkill", first, last)
        if s is not None:
            os.kill(os.getpid(), signal.SIGKILL)

    def poison_batch(self, x, first: int, last: Optional[int] = None):
        """``nan_batch``: return ``x`` poisoned with NaN when a spec is
        due in ``[first, last]``, else ``x`` unchanged. Device-side op
        (adds NaN to the already-placed batch) — no host sync, and the
        result keeps ``x``'s sharding. Float batches only (token
        batches raise: an int stream cannot carry NaN)."""
        import jax.numpy as jnp

        s = self._take("nan_batch", first, last)
        if s is None:
            return x
        if not jnp.issubdtype(x.dtype, jnp.floating):
            raise ValueError(
                f"nan_batch@{s.step}: batch dtype {x.dtype} cannot carry "
                "NaN (token/int batches); inject on a float-input model"
            )
        return x + jnp.asarray(float("nan"), x.dtype)

    def world_override(self) -> Optional[int]:
        """The world size the MOST RECENTLY FIRED shrink/grow/
        slice_down fault left behind (by firing order, not command-line
        spec order), or None when no topology fault has fired. Sticky
        by design: the supervisor reuses ONE injector across attempts,
        so a shrunk world stays shrunk for every subsequent elastic
        retry — the CPU-simulation stand-in for re-probing real device
        liveness."""
        fired = [s for s in self.specs
                 if s.kind in ("shrink", "grow", "slice_down") and s.fired]
        if not fired:
            return None
        last = max(fired, key=lambda s: s.fired_seq)
        if last.kind == "slice_down":
            # resolved at fire time from the then-registered topology;
            # a ledger-rearmed spec never fired in THIS process and
            # carries no resolution — fall back to the next-most-recent
            # resolved fault (the world it left is the one that ran)
            resolved = [s for s in fired if s.kind != "slice_down"
                        or s.resolved_world is not None]
            if not resolved:
                return None
            last = max(resolved, key=lambda s: s.fired_seq)
            if last.kind == "slice_down":
                return int(last.resolved_world)
        return int(last.arg)

    def _take_at_or_after(self, kind: str, step: int) -> Optional[FaultSpec]:
        """The unfired spec of ``kind`` due at/after ``step`` (marked
        fired + ledgered) — the save-boundary firing rule: a save can
        land later than the spec's step (epoch cadence), and the fault
        applies to the first save that reaches it."""
        for s in self.specs:
            if s.kind == kind and not s.fired and step >= s.step:
                s.fired = True
                s.fired_seq = self._fire_seq
                self._fire_seq += 1
                self._record_fire(s)
                return s
        return None

    def truncate_due(self, step: int) -> bool:
        """True once when a ``ckpt_truncate`` spec is due at/after
        ``step`` (the driver checks after each checkpoint save)."""
        return self._take_at_or_after("ckpt_truncate", step) is not None

    def storage_mutations_due(self, step: int) -> list:
        """Every post-save storage mutation (``ckpt_truncate`` /
        ``bitrot`` / ``partial_set``) due at/after ``step``, each fired
        once — the driver applies them with
        :meth:`apply_storage_mutation` after the save is DURABLE (an
        async save must be waited first, or the previous file would be
        the one mutated)."""
        out = []
        for kind in STORAGE_MUTATION_KINDS:
            s = self._take_at_or_after(kind, step)
            if s is not None:
                out.append(s)
        return out

    @staticmethod
    def apply_storage_mutation(spec: FaultSpec, ckpt_dir: str) -> Optional[str]:
        """Apply one fired post-save mutation to ``ckpt_dir``; returns
        the mangled/removed path (None when nothing qualified)."""
        if spec.kind == "ckpt_truncate":
            return FaultInjector.truncate_newest(ckpt_dir)
        if spec.kind == "bitrot":
            return FaultInjector.bitrot_newest(ckpt_dir)
        if spec.kind == "partial_set":
            return FaultInjector.drop_sharded_member(ckpt_dir)
        raise ValueError(f"{spec.kind!r} is not a storage mutation")

    def write_fault(self, step: int) -> Optional[tuple]:
        """The checkpoint writer shim hook
        (``utils/checkpoint.set_write_fault_hook``): called by the save
        path with the step being saved; returns ``(kind, arg)`` for a
        due ``enospc``/``slow_write`` spec (fired once), else None. May
        run on the async writer thread — the injector's firing state is
        only ever advanced from one save at a time (the writer
        serializes saves)."""
        for kind in WRITE_FAULT_KINDS:
            s = self._take_at_or_after(kind, step)
            if s is not None:
                return (kind, s.arg)
        return None

    @staticmethod
    def truncate_newest(ckpt_dir: str) -> Optional[str]:
        """Truncate the newest checkpoint file to half its size (a torn
        write: the file exists, the zip central directory is gone).
        Returns the mangled path."""
        from theanompi_tpu.utils.checkpoint import latest_checkpoint

        path = latest_checkpoint(ckpt_dir)  # unverified: the raw newest
        if path is None:
            return None
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return path

    @staticmethod
    def bitrot_newest(ckpt_dir: str) -> Optional[str]:
        """Flip bytes in the middle of the newest COMMITTED checkpoint
        file (at-rest bit-rot: size and name intact, content corrupt —
        only the CRC32 integrity chain can tell). Returns the path."""
        from theanompi_tpu.utils.checkpoint import latest_checkpoint

        path = latest_checkpoint(ckpt_dir)
        if path is None:
            return None
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(8)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        return path

    @staticmethod
    def drop_sharded_member(ckpt_dir: str) -> Optional[str]:
        """Delete one member of the newest COMPLETE sharded checkpoint
        set (a host's file lost after the save landed): the set must
        then read as ABSENT via completeness-by-counting. Returns the
        removed path (None when no sharded set exists)."""
        from theanompi_tpu.utils.checkpoint import _sharded_sets

        sets = _sharded_sets(ckpt_dir)
        if not sets:
            return None
        files = sets[max(sets)]
        victim = files[-1]  # the highest-proc member: deterministic
        os.unlink(victim)
        return victim
