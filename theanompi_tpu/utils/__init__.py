"""Cross-cutting utilities: recorder (timing/metrics), async dispatch
pipeline, checkpointing, fault injection, logging."""

from theanompi_tpu.utils.dispatch import MetricsDispatcher  # noqa: F401
from theanompi_tpu.utils.recorder import Recorder  # noqa: F401
from theanompi_tpu.utils.checkpoint import (  # noqa: F401
    checkpoint_step,
    load_checkpoint,
    latest_checkpoint,
    newer_verified_checkpoint,
    save_checkpoint,
    verify_checkpoint,
    wrap_saved_rng,
)
from theanompi_tpu.utils.faults import (  # noqa: F401
    FaultInjector,
    InjectedCrash,
    Preempted,
    parse_fault_spec,
)
