"""Asynchronous dispatch pipeline: deferred step metrics + amortized timing.

The reference hid host work behind device compute on the INPUT side
(``lib/proc_load_mpi.py`` double-buffering; our ``data/loader.py``
PrefetchLoader) — and then the per-step driver threw the win away on the
OUTPUT side: ``rec.end("step", sync=metrics["loss"])`` forced a full
host<->device round trip per step, so the host could not enqueue step
N+1 until step N's loss had been materialized. On a tunneled dev chip
that round trip is ~100 ms against a ~15 ms step; on pods it is ~10 ms —
either way it serializes dispatch.

:class:`MetricsDispatcher` removes the per-step sync. The driver pushes
each step's DEVICE-RESIDENT metric pytree into a ring buffer of
``depth`` in-flight entries; pushing entry N drains entry N-depth+1 —
whose D2H fetch blocks only if the device has not yet finished a step
that is ``depth-1`` dispatches old (in steady state: never). The drain
is the ONLY host<->device sync in the train loop
(``tools/check_hot_loop.py`` lints that it stays that way).

Timing semantics (amortized spaced syncs): each drain IS a spaced sync,
and the per-step wall time attributed to the drained step is the
interval between consecutive drain returns minus the data-wait time the
driver reported via :meth:`note_wait` in that interval. In steady state
the device completes exactly one step per drain interval, so the
attributed time converges to the true device step time whether the
device or the host is the bottleneck. ``flush()`` (epoch / exchange /
checkpoint boundaries) blocks once on the newest in-flight step and
attributes the remaining window evenly across the drained entries.

With ``depth=1`` every push drains immediately — the attributed time is
dispatch + block, exactly what the old ``end("step", sync=...)`` bracket
measured, and rows are emitted at the same points in the JSONL stream.
Deeper pipelines emit the SAME rows (same steps, same values, same
n_images attribution), just later — tests/test_dispatch.py proves the
streams bit-identical modulo the wall-clock ``images_per_sec`` field.

``host_blocked_s`` accumulates the time the host actually spent blocked
inside drains — ``host_blocked_frac`` in the run summary / bench output
is this over the train-loop wall time, the direct measurement of the
per-step host tax this module exists to remove.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

import numpy as np


def _block_on(metrics: dict) -> None:
    """Block until the step that produced ``metrics`` has executed
    (device arrays expose ``block_until_ready``; host values no-op)."""
    for v in metrics.values():
        block = getattr(v, "block_until_ready", None)
        if block is not None:
            block()  # one leaf suffices: all values share the program
            return


class MetricsDispatcher:
    """Ring buffer of in-flight step metrics (see module docstring).

    ``recorder``: the run's :class:`~theanompi_tpu.utils.recorder.Recorder`
    — drains call ``recorder.note_time("step", dt)`` then
    ``recorder.train_metrics(...)``, so rows carry the amortized
    per-step throughput exactly like sync-mode rows carry the bracketed
    one. ``on_step_seconds``: optional callback receiving the amortized
    per-substep seconds at each sync point (the driver wires
    ``Observability.note_step_seconds`` so the comm-GB/s gauge stays
    live under deferred timing).
    """

    def __init__(
        self,
        recorder,
        depth: int = 1,
        on_step_seconds: Optional[Callable[[float], None]] = None,
        on_row: Optional[Callable[[int, dict, dict], None]] = None,
    ):
        self.rec = recorder
        self.depth = max(1, int(depth))
        self._buf: deque = deque()
        self._t_mark: Optional[float] = None
        self._wait_s = 0.0
        self._on_step_seconds = on_step_seconds
        # per-emitted-row hook ``(step, metrics, numerics)`` — the obs
        # facade's flight-ring/anomaly entry point (obs/numerics.py).
        # Called AFTER the recorder row lands, with host floats from the
        # SAME D2H fetch the row came from: numerics detection adds no
        # sync of its own, it rides the drain.
        self._on_row = on_row
        # time the host spent actually blocked inside drains (the tax)
        self.host_blocked_s = 0.0
        self.n_syncs = 0
        # newest step whose row has been emitted (heartbeat telemetry:
        # in_flight + this distinguish a wedged device program from a
        # stalled host driver)
        self.last_drained_step = -1
        # amortized per-substep seconds of the most recent sync; None
        # while steps are in flight without a completed sync
        self.last_step_seconds: Optional[float] = None

    @property
    def in_flight(self) -> int:
        """Entries pushed but not yet drained."""
        return len(self._buf)

    # -- driver hooks --------------------------------------------------------
    def note_wait(self, dt: float) -> None:
        """Report data-wait time (the recorder's ``wait`` bracket) so the
        amortized step attribution excludes it — keeping the wait/step
        split's meaning identical to sync mode."""
        self._wait_s += float(dt)

    def push(self, step: int, metrics: dict, n_images: int = 0,
             substeps: int = 1) -> None:
        """Enqueue one dispatched step (or fused group of ``substeps``)
        whose ``metrics`` are still device-resident futures. Drains the
        oldest entry once ``depth`` entries are in flight."""
        if self._t_mark is None:
            # window opens at the first in-flight push; waits before it
            # (epoch-boundary eval/checkpoint, first batch load) are not
            # part of any step's attribution
            self._t_mark = time.perf_counter()
            self._wait_s = 0.0
        self._buf.append((int(step), metrics, int(n_images), max(1, int(substeps))))
        while len(self._buf) >= self.depth:
            self._drain_one()

    def flush(self) -> None:
        """Drain every in-flight entry: ONE block on the newest step
        (which implies all older steps finished), remaining window time
        attributed evenly. Call at epoch ends, before an engine
        exchange, and before checkpoints — the recorder stream then
        holds exactly the rows sync mode would hold at the same point."""
        if not self._buf:
            # close the timing window even with nothing in flight: with
            # depth=1 the buffer is ALWAYS empty here (push drains
            # immediately), and a stale _t_mark would hand the whole
            # boundary's wall time (eval/val/checkpoint, or an EASGD
            # exchange) to the first step drained after it
            self._t_mark = None
            self._wait_s = 0.0
            return
        entries = list(self._buf)
        self._buf.clear()
        t0 = time.perf_counter()
        err: Optional[Exception] = None
        try:
            _block_on(entries[-1][1])
        except Exception as e:  # noqa: BLE001
            # a buffered step's program faulted (OOM, NaN check, ...) —
            # the newest entry's sync surfaces it, but OLDER steps may
            # have completed fine; persist their rows (exactly what
            # depth=1 would already have written) before re-raising
            err = e
        now = time.perf_counter()
        self.host_blocked_s += now - t0
        self.n_syncs += 1
        total = max(0.0, (now - self._t_mark) - self._wait_s)
        per_entry = total / len(entries)
        self._t_mark = None
        self._wait_s = 0.0
        for step, metrics, n_images, substeps in entries:
            if err is not None:
                # oldest-first salvage: materializing the first poisoned
                # entry re-raises; everything older is already emitted
                try:
                    metrics = {k: np.asarray(v) for k, v in metrics.items()}
                except Exception:  # noqa: BLE001
                    raise err
            self.last_step_seconds = per_entry / substeps
            self.rec.note_time("step", per_entry)
            self._emit_rows(step, metrics, n_images, substeps)
        if err is not None:
            raise err
        if self._on_step_seconds is not None and entries:
            self._on_step_seconds(self.last_step_seconds)

    def discard(self) -> None:
        """Drop every in-flight entry WITHOUT draining and close the
        timing window. The anomaly-rollback path (launch/worker.py)
        uses this: the buffered entries belong to steps the restore is
        about to erase, and draining them would re-run anomaly
        detection on the very rows that triggered the rollback."""
        self._buf.clear()
        self._t_mark = None
        self._wait_s = 0.0

    # -- internals -----------------------------------------------------------
    def _drain_one(self) -> None:
        step, metrics, n_images, substeps = self._buf.popleft()
        t0 = time.perf_counter()
        host = {k: np.asarray(v) for k, v in metrics.items()}  # D2H sync
        now = time.perf_counter()
        self.host_blocked_s += now - t0
        self.n_syncs += 1
        dt = max(0.0, (now - self._t_mark) - self._wait_s)
        self._t_mark = now
        self._wait_s = 0.0
        self.last_step_seconds = dt / substeps
        self.rec.note_time("step", dt)
        self._emit_rows(step, host, n_images, substeps)
        if self._on_step_seconds is not None:
            self._on_step_seconds(self.last_step_seconds)

    def _emit_rows(self, step: int, metrics: dict, n_images: int,
                   substeps: int) -> None:
        from theanompi_tpu.obs.numerics import split_numerics

        if substeps == 1:
            plain, nm = split_numerics(metrics)
            self.rec.train_metrics(step, plain, n_images=n_images)
            self.last_drained_step = step
            if self._on_row is not None:
                # row first, hook second: an --on-anomaly halt raised
                # here still leaves the anomalous step's row persisted
                self._on_row(
                    step,
                    {k: float(v) for k, v in plain.items()},
                    {k: float(v) for k, v in nm.items()},
                )
            return
        # fused group: one JSONL row PER SUBSTEP from the stacked
        # metrics (same-resolution loss/LR curves as per-step runs);
        # the group's throughput is attributed to its final row
        host = {k: np.asarray(v) for k, v in metrics.items()}
        for i in range(substeps):
            sub = {k: a[i] for k, a in host.items()}
            plain, nm = split_numerics(sub)
            sub_step = step - substeps + i + 1
            self.rec.train_metrics(
                sub_step, plain,
                n_images=n_images if i == substeps - 1 else 0,
            )
            self.last_drained_step = sub_step
            if self._on_row is not None:
                self._on_row(
                    sub_step,
                    {k: float(v) for k, v in plain.items()},
                    {k: float(v) for k, v in nm.items()},
                )
