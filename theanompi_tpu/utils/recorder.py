"""Recorder: per-iteration timing split + train/val metric history.

Rebuild of the reference's observability layer (reference:
``lib/recorder.py`` — ``Recorder`` with ``start()``/``end('calc'|'comm')``
wall-clock brackets, train cost/error accumulation, val cost/error/top-5,
periodic console prints, pickled history; SURVEY.md §5.1, §5.5). The API
is kept because it was good; additions over the reference:

- JSONL event log (machine-readable) instead of pickle-only;
- images/sec and cumulative epoch timing (the BASELINE.json metric);
- correct device-timing semantics for XLA: an async dispatch means
  host-side brackets measure nothing unless the caller synchronizes —
  ``end()`` optionally blocks on a ``jax.Array`` for honest splits;
- optional delegation to the obs subsystem (ISSUE 1): pass ``registry``
  (obs/metrics.py) and the brackets feed timing histograms + last-value
  gauges; pass ``spans`` (obs/spans.py) and every ``start``/``end``
  bracket ALSO opens/closes a trace span (wait -> ``data_wait``,
  comm -> ``grad_sync``, others by name) — the Recorder stays the
  single emission point, the obs files the machine-readable sinks.

Note on calc/comm split: in the reference these were separate host
phases (Theano call, then MPI). Here the collective is fused INSIDE the
compiled step, so per-phase brackets cannot separate them; the honest
equivalents are ``step`` (whole-iteration device time) plus
``jax.profiler`` traces for the in-step breakdown. The bracket API
remains for the host-visible phases (data wait / step / eval).
"""

from __future__ import annotations

import json
import os
import pickle
import time
from collections import defaultdict
from typing import Optional

import numpy as np


class Recorder:
    # bracket category -> obs span kind (obs/spans.py SPAN_KINDS); the
    # reference's 'comm' bracket is the gradient exchange, hence grad_sync
    SPAN_NAMES = {"wait": "data_wait", "comm": "grad_sync"}

    def __init__(
        self,
        rank: int = 0,
        print_freq: int = 40,
        save_dir: Optional[str] = None,
        run_name: str = "run",
        tensorboard: bool = False,
        registry=None,
        spans=None,
    ):
        self.rank = rank
        self.print_freq = print_freq
        self.save_dir = save_dir
        self.run_name = run_name
        self.registry = registry  # obs.MetricsRegistry or None
        self.spans = spans  # obs.SpanRecorder or None
        self._span_tokens: dict[str, object] = {}
        self._t0: dict[str, float] = {}
        self.timings: dict[str, list[float]] = defaultdict(list)
        self.history: dict[str, list] = defaultdict(list)
        self.epoch_start: Optional[float] = None
        self._jsonl = None
        self._tb = None
        if save_dir:
            os.makedirs(save_dir, exist_ok=True)
            self._jsonl = open(os.path.join(save_dir, f"{run_name}.jsonl"), "a")
        if tensorboard and save_dir:
            # optional TensorBoard scalars (SURVEY.md §5.5 "TPU
            # equivalent": JSONL + optional TensorBoard) — soft
            # dependency, JSONL remains the source of truth
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(
                    os.path.join(save_dir, "tb", f"{run_name}_rank{rank}")
                )
            except Exception as e:  # broken installs raise beyond ImportError
                print(
                    f"[rank {rank}] tensorboard=True but tensorboardX is "
                    f"unavailable ({type(e).__name__}: {e}) — JSONL/pickle "
                    "history only",
                    flush=True,
                )

    # -- XLA trace capture ---------------------------------------------------
    # The reference's calc/comm split came from host brackets around
    # separate Theano/MPI phases (lib/recorder.py). Here the collective
    # is fused inside one XLA program, so the in-step breakdown comes
    # from a jax.profiler device trace instead (SURVEY.md §5.1 "TPU
    # equivalent"): view with tensorboard/xprof to read the comm vs
    # compute fraction of each step.
    def enable_profile(
        self, profile_dir: str, start_offset: int = 2, n_steps: int = 4
    ) -> None:
        """Arm a ``jax.profiler`` trace capture of ``n_steps`` steps,
        starting ``start_offset`` steps after the FIRST
        :meth:`profile_tick` (relative, so resumed runs still skip the
        recompile/warmup steps)."""
        self._prof = {
            "dir": profile_dir,
            "offset": int(start_offset),
            "n": int(n_steps),
            "state": "armed",
            "base": None,
            "started_at": None,
        }

    def profile_tick(self, step: int) -> None:
        """Start/stop the armed trace based on the global step count.
        Call once per training step, before dispatching it."""
        p = getattr(self, "_prof", None)
        if p is None or p["state"] == "done":
            return
        if p["state"] == "armed":
            if p["base"] is None:
                p["base"] = step
            if step >= p["base"] + p["offset"]:
                import jax

                os.makedirs(p["dir"], exist_ok=True)
                jax.profiler.start_trace(p["dir"])
                p["state"] = "tracing"
                p["started_at"] = step
        elif p["state"] == "tracing" and step >= p["started_at"] + p["n"]:
            self._profile_stop()

    def _profile_stop(self, reason: str = "") -> None:
        p = self._prof
        import jax

        jax.profiler.stop_trace()
        p["state"] = "done"
        print(
            f"[rank {self.rank}] wrote XLA trace to {p['dir']}"
            + (f" ({reason})" if reason else "")
            + " (view: tensorboard --logdir)",
            flush=True,
        )

    # -- timing brackets (reference API) ------------------------------------
    def start(self, category: str = "calc") -> None:
        if self.spans is not None:
            self._span_tokens[category] = self.spans.begin(
                self.SPAN_NAMES.get(category, category)
            )
        self._t0[category] = time.perf_counter()

    def end(self, category: str = "calc", sync=None) -> float:
        """Close a bracket. Pass a ``jax.Array`` (e.g. the loss) as
        ``sync`` to block until the device work really finished —
        without it the bracket only measures dispatch.

        An ``end`` without a matching ``start`` warns (naming the
        category) and returns 0.0 instead of raising — an accounting
        slip must not kill a training run."""
        if sync is not None:
            try:
                sync.block_until_ready()
            except AttributeError:
                pass
        t0 = self._t0.pop(category, None)
        if t0 is None:
            import warnings

            warnings.warn(
                f"Recorder.end({category!r}) without a matching "
                f"start({category!r}); returning 0.0",
                RuntimeWarning, stacklevel=2,
            )
            self._span_tokens.pop(category, None)
            return 0.0
        dt = time.perf_counter() - t0
        self.timings[category].append(dt)
        token = self._span_tokens.pop(category, None)
        if token is not None and self.spans is not None:
            self.spans.finish(token)
        if self.registry is not None:
            name = self.SPAN_NAMES.get(category, category)
            self.registry.histogram(
                f"tmpi_{name}_seconds",
                help=f"Recorder '{category}' bracket wall time",
            ).observe(dt)
        return dt

    def note_time(self, category: str, dt: float) -> float:
        """Record an externally measured bracket duration without a
        ``start``/``end`` pair — the dispatch pipeline's amortized
        spaced-sync timing (utils/dispatch.py). Feeds the same sinks a
        bracket would: the timings list, the obs histogram, and an
        ``amortized``-flagged span line (the duration must already
        EXCLUDE overlapping owner-thread spans, e.g. data waits, so the
        span summary's fraction invariant holds)."""
        dt = float(dt)
        self.timings[category].append(dt)
        name = self.SPAN_NAMES.get(category, category)
        if self.spans is not None:
            self.spans.note(name, dt)
        if self.registry is not None:
            self.registry.histogram(
                f"tmpi_{name}_seconds",
                help=f"Recorder '{category}' bracket wall time",
            ).observe(dt)
        return dt

    # -- metric accumulation -------------------------------------------------
    def train_metrics(self, step: int, metrics: dict, n_images: int = 0) -> None:
        rec = {k: float(v) for k, v in metrics.items()}
        rec["step"] = int(step)
        if n_images and self.registry is not None:
            self.registry.counter(
                "tmpi_images_total", help="training examples consumed"
            ).inc(n_images)
        if n_images and self.timings.get("step"):
            rec["images_per_sec"] = n_images / self.timings["step"][-1]
        self.history["train"].append(rec)
        self._emit("train", rec)
        if self.print_freq and len(self.history["train"]) % self.print_freq == 0:
            self._print_train(rec)

    def val_metrics(self, epoch: int, metrics: dict) -> None:
        rec = {k: float(v) for k, v in metrics.items()}
        rec["epoch"] = int(epoch)
        self.history["val"].append(rec)
        self._emit("val", rec)
        loss = rec.get("loss", float("nan"))
        msg = f"[rank {self.rank}] epoch {epoch} val: loss={loss:.4f}"
        # print only the metrics the engine produced (LM engines report
        # loss only; classifiers add error/top5)
        if "error" in rec:
            msg += f" err={rec['error']:.4f}"
        if "top5_error" in rec:
            msg += f" top5_err={rec['top5_error']:.4f}"
        print(msg, flush=True)

    # -- epoch accounting ----------------------------------------------------
    def start_epoch(self) -> None:
        self.epoch_start = time.perf_counter()

    def end_epoch(self, epoch: int, n_images: int = 0) -> float:
        p = getattr(self, "_prof", None)
        if p is not None and p["state"] == "tracing":
            # never let the trace run through validation/checkpoint I/O —
            # it exists to read the train-step comm/compute split
            self._profile_stop("stopped at epoch end")
        dt = time.perf_counter() - (self.epoch_start or time.perf_counter())
        rec = {"epoch": int(epoch), "seconds": dt}
        if n_images:
            rec["images_per_sec"] = n_images / dt
        self.history["epoch"].append(rec)
        self._emit("epoch", rec)
        print(
            f"[rank {self.rank}] epoch {epoch} done in {dt:.1f}s"
            + (f" ({rec['images_per_sec']:.0f} img/s)" if n_images else ""),
            flush=True,
        )
        return dt

    # -- summaries -----------------------------------------------------------
    def mean_time(self, category: str, last_n: Optional[int] = None) -> float:
        ts = self.timings.get(category, [])
        if not ts:
            return 0.0
        return float(np.mean(ts[-last_n:] if last_n else ts))

    def _print_train(self, rec: dict) -> None:
        parts = [f"step {rec['step']}"]
        for k in ("loss", "error", "lr"):
            if k in rec:
                parts.append(f"{k}={rec[k]:.4f}")
        for cat in ("wait", "step"):
            if self.timings.get(cat):
                parts.append(f"{cat}={1000*self.mean_time(cat, self.print_freq):.1f}ms")
        if "images_per_sec" in rec:
            parts.append(f"{rec['images_per_sec']:.0f} img/s")
        print(f"[rank {self.rank}] " + " ".join(parts), flush=True)

    def _emit(self, kind: str, rec: dict) -> None:
        if self._jsonl:
            self._jsonl.write(json.dumps({"kind": kind, **rec}) + "\n")
            self._jsonl.flush()
        if self.registry is not None:
            # last-value gauges per metric (tmpi_train_loss, tmpi_val_error,
            # tmpi_epoch_seconds, ...) so obs snapshots carry the training
            # curve's current point next to the comm/health telemetry;
            # images ride a counter (throughput = rate(tmpi_images_total))
            for k, v in rec.items():
                if k in ("step", "epoch") or not isinstance(v, float):
                    continue
                if k == "images_per_sec":
                    self.registry.gauge(
                        "tmpi_images_per_sec", help="recent throughput"
                    ).set(v)
                else:
                    self.registry.gauge(f"tmpi_{kind}_{k}").set(v)
        if self._tb is not None:
            x = rec.get("step", rec.get("epoch", 0))
            for k, v in rec.items():
                if k not in ("step", "epoch") and isinstance(v, float):
                    self._tb.add_scalar(f"{kind}/{k}", v, int(x))

    def save(self, path: Optional[str] = None) -> None:
        """Pickle the full history (reference: ``Recorder.save`` pickled
        its lists for offline plotting)."""
        if path is None:
            if not self.save_dir:
                return
            path = os.path.join(self.save_dir, f"{self.run_name}_history.pkl")
        with open(path, "wb") as f:
            pickle.dump(
                {"history": dict(self.history), "timings": dict(self.timings)}, f
            )

    @staticmethod
    def load_history(path: str) -> dict:
        with open(path, "rb") as f:
            return pickle.load(f)

    def close(self) -> None:
        p = getattr(self, "_prof", None)
        if p is not None and p["state"] == "tracing":  # run ended mid-capture
            self._profile_stop("run ended mid-capture")
        elif p is not None and p["state"] == "armed":
            print(
                f"[rank {self.rank}] WARNING: profile was armed but the run "
                f"ended before the capture window opened — no trace in "
                f"{p['dir']} (need > {p['offset']} steps)",
                flush=True,
            )
            p["state"] = "done"
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
