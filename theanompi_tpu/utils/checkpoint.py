"""Checkpoint / resume.

Rebuild of the reference's weight persistence (reference:
``lib/helper_funcs.py`` — ``save_weights``/``load_weights``: one ``.npy``
per Theano shared param, saved each epoch from rank 0, no atomicity;
SURVEY.md §5.4). Here the WHOLE TrainState pytree (params + BatchNorm
state + optimizer state + step) plus the RNG key goes into one ``.npz``
written atomically (tmp + rename), so resume restores training exactly —
including the LR schedule, which is a pure function of the restored step.

Arrays are pulled to host with ``jax.device_get``; on restore the caller
re-places them (replicated or sharded) via its usual device_put path.
Multi-host: only process 0 writes (same contract as the reference's
rank-0 save); sharded-per-host formats can layer on later without
changing this API.
"""

from __future__ import annotations

import errno
import os
import re
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

PyTree = Any

_CKPT_RE = re.compile(r"ckpt_(\d+)\.npz$")
_SHARD_RE = re.compile(r"ckpt_(\d+)\.proc(\d+)of(\d+)\.npz$")

# per-array integrity manifest key (fault-tolerance PR): JSON map of
# array name -> {crc32, nbytes}, embedded IN the .npz at save time so a
# checkpoint copied anywhere carries its own verification chain
_INTEGRITY_KEY = "__integrity__"

# versioned topology manifest key (elastic PR): JSON record of the mesh
# the state was saved under (shape + axis names), the per-leaf
# PartitionSpecs, and the engine's elastic reshard policies — what
# :func:`load_resharded` needs to move a checkpoint onto a DIFFERENT
# mesh without ever materializing a full array on one host. Single-file
# saves carry it as an .npz entry; per-host sharded saves embed it in
# their ``__meta__`` JSON.
_TOPOLOGY_KEY = "__topology__"
TOPOLOGY_VERSION = 1


def _path_key(path) -> str:
    """Tree path -> the flat '/'-joined leaf key used by every format."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _topology_manifest(state: PyTree, topology: Optional[dict]) -> Optional[dict]:
    """The versioned ``__topology__`` manifest for one save: the caller's
    mesh identity + elastic policies (``topology`` =
    ``{"mesh": parallel.mesh.mesh_topology(mesh), "elastic": {...}}``)
    extended with the per-leaf PartitionSpec of every LIVE leaf (read
    off the arrays before the host pull — a NamedSharding-less leaf
    records None = replicated). :func:`load_resharded` validates its
    transfer plan against the stamped leaf SET (an unstamped leaf in
    the target template is a structure mismatch); the spec values are
    for inspection/debugging — the plan's source bounds come from the
    sharded-set ``__meta__`` catalogues, not from here. None when the
    caller stamps nothing (API users saving plain host trees keep the
    pre-elastic format)."""
    if topology is None:
        return None
    from theanompi_tpu.parallel.mesh import leaf_spec_json

    leaves = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        leaves[_path_key(path)] = {"spec": leaf_spec_json(leaf)}
    out = {
        "version": TOPOLOGY_VERSION,
        "mesh": topology.get("mesh"),
        "elastic": topology.get("elastic") or {},
        "leaves": leaves,
    }
    if topology.get("recipe") is not None:
        # the engine's ShardingRecipe identity (parallel/recipe.py
        # ``as_json``): the DECLARED spec source the live-array specs
        # above were placed by — the sharding analyzer's SHARD004
        # train->serve handoff check keys on this declaration
        out["recipe"] = topology["recipe"]
    return out


def _array_crc(arr: np.ndarray) -> dict:
    """{crc32, nbytes} of one saved array's raw bytes."""
    buf = np.ascontiguousarray(arr).tobytes()
    return {"crc32": zlib.crc32(buf) & 0xFFFFFFFF, "nbytes": len(buf)}


def _with_integrity(flat: dict) -> dict:
    """Append the CRC32 manifest over every entry already in ``flat``
    (called LAST before np.savez, so the manifest covers rng/meta too)."""
    import json as _json

    manifest = {k: _array_crc(np.asarray(v)) for k, v in flat.items()}
    flat[_INTEGRITY_KEY] = np.asarray(_json.dumps(manifest))
    return flat


# --------------------------------------------------------------------------
# injectable writer shim (chaos PR): storage faults — ENOSPC mid-write,
# slow/stalled writes — happen INSIDE the filesystem write, where no
# step-loop hook can reach. Both save formats funnel their serialize+
# rename through _atomic_savez, which consults the installed hook with
# the step being saved; utils/faults.FaultInjector.write_fault is the
# one production hook (deterministic KIND@STEP semantics), but any
# callable ``step -> Optional[(kind, arg)]`` works.
# --------------------------------------------------------------------------

_WRITE_FAULT_HOOK: Optional[Callable[[int], Optional[tuple]]] = None


def set_write_fault_hook(hook: Optional[Callable[[int], Optional[tuple]]]
                         ) -> None:
    """Install (or clear, with None) the process-wide checkpoint write
    fault hook. The driver installs its FaultInjector's ``write_fault``
    for the run and clears it in its finally — the hook is global
    because the async writer thread has no per-save plumbing."""
    global _WRITE_FAULT_HOOK
    _WRITE_FAULT_HOOK = hook


class _EnospcWriter:
    """File wrapper that raises ``OSError(ENOSPC)`` once ``limit``
    bytes have been written — the injected 'disk filled up mid-write':
    a torn partial file exists under the TMP name when the error
    surfaces, exactly what a real quota hit leaves behind.

    After the failure the wrapper goes DEAD: writes are absorbed into a
    simulated position instead of touching the (by then closed) real
    file. np.savez's internal ZipFile survives the exception holding
    this object as its ``fp``; its garbage-collected ``close()`` then
    flushes a central directory into the void coherently instead of
    spraying 'Exception ignored in ZipFile.__del__' noise over the
    real error."""

    def __init__(self, f, limit: int):
        self._f = f
        self._limit = int(limit)
        self._written = 0
        self._dead = False
        self._pos = 0  # simulated position once dead

    def write(self, data):
        if self._dead:
            self._pos += len(data)
            return len(data)
        if self._written + len(data) > self._limit:
            space = max(0, self._limit - self._written)
            if space:
                self._f.write(data[:space])
                self._written += space
            self._dead = True
            self._pos = self._written
            raise OSError(errno.ENOSPC,
                          "No space left on device (injected enospc)")
        self._written += len(data)
        return self._f.write(data)

    def seek(self, offset, whence=0):
        if not self._dead:
            return self._f.seek(offset, whence)
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        return self._pos

    def tell(self):
        return self._pos if self._dead else self._f.tell()

    def flush(self):
        if not self._dead:
            self._f.flush()

    def __getattr__(self, name):
        return getattr(self._f, name)


def _atomic_savez(directory: str, path: str, flat: dict, step: int) -> None:
    """The one serialize+rename both save formats use: np.savez into a
    tmp file in ``directory``, then atomic ``os.replace`` onto ``path``.
    Any failure (a real OSError or an injected write fault) removes the
    torn tmp — the chain is never left holding a partial file under a
    final name."""
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            sink = f
            fault = _WRITE_FAULT_HOOK(step) if _WRITE_FAULT_HOOK else None
            if fault is not None:
                kind, arg = fault
                if kind == "slow_write":
                    time.sleep(2.0 if arg is None else float(arg))
                elif kind == "enospc":
                    # default low enough that even a tiny state's save
                    # tears mid-write (any real .npz exceeds it)
                    sink = _EnospcWriter(f, 256 if arg is None else int(arg))
            np.savez(sink, **flat)
        os.replace(tmp, path)  # atomic on POSIX
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _pull_to_host(leaf) -> np.ndarray:
    """Materialize one leaf on the host. Leaves sharded across OTHER
    processes (EASGD/GoSGD per-worker state under multi-controller) are
    gathered with a cross-host collective — so this is collective: every
    process must reach it, even though only rank 0 writes the file."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_with_paths(tree: PyTree) -> dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = _pull_to_host(leaf)
    return flat


def save_checkpoint(
    directory: str,
    state: PyTree,
    step: int,
    rng: Optional[jax.Array] = None,
    keep: int = 3,
    extra_meta: Optional[dict] = None,
    topology: Optional[dict] = None,
) -> Optional[str]:
    """Atomically write ``ckpt_{step}.npz``; prune to the newest ``keep``.
    COLLECTIVE in multi-host runs: every process must call it (sharded
    leaves are gathered cross-host), then only process 0 writes; returns
    the path (or None on non-writer processes).

    ``extra_meta`` (JSON-serializable dict) is embedded in the file and
    readable via :func:`read_checkpoint_meta` — the driver records the
    pipeline stack layout here so a checkpoint copied into a fresh dir
    (without its ``pipeline_layout.json`` sidecar) still refuses to load
    layer-permuted.

    ``topology`` (``{"mesh": mesh_topology(mesh), "elastic": {...}}``)
    stamps the versioned ``__topology__`` manifest that makes the
    checkpoint mesh-portable via :func:`load_resharded`; the per-leaf
    PartitionSpecs are read off the live state before the host pull."""
    from theanompi_tpu.obs.spans import obs_span

    topo = _topology_manifest(state, topology)
    # checkpoint_gather span (obs/spans.py): the device->host gather,
    # the expensive half of a save — runs on whichever thread calls
    # (the AsyncCheckpointer's writer thread under async saves). Named
    # apart from the driver's 'checkpoint' bracket so a SYNC save does
    # not double-count the same wall time under one kind.
    with obs_span("checkpoint_gather"):
        flat = _flatten_with_paths(state)
    if topo is not None:
        import json as _json

        flat[_TOPOLOGY_KEY] = np.asarray(_json.dumps(topo))
    if extra_meta:
        import json as _json

        flat["__usermeta__"] = np.asarray(_json.dumps(extra_meta))
    if rng is not None:
        # record WHICH impl produced the key data: width alone is
        # ambiguous (rbg and unsafe_rbg share width 4 but derive
        # split/fold_in differently), and resume must reproduce the
        # exact stream of an uninterrupted run
        if jnp.issubdtype(getattr(rng, "dtype", None), jax.dtypes.prng_key):
            impl = str(jax.random.key_impl(rng))
            rng = jax.random.key_data(rng)  # typed key -> raw uint32 data
            raw = np.asarray(jax.device_get(rng))
        else:
            # raw key data: assume the process default impl, unless the
            # data width contradicts it (e.g. an explicit threefry
            # PRNGKey under the rbg default) — then infer from width so
            # the checkpoint stays loadable
            raw = np.asarray(jax.device_get(rng))
            impl = jax.config.jax_default_prng_impl
            width = raw.shape[-1] if raw.ndim else None
            if width != _KEY_WIDTH_BY_IMPL.get(impl):
                impl = _KEY_IMPL_BY_WIDTH.get(width)
                if impl is None:
                    raise ValueError(
                        f"rng has unrecognized key-data shape {raw.shape}"
                    )
        flat["__rng__"] = raw
        flat["__rng_impl__"] = np.asarray(impl)
    if jax.process_index() != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{step}.npz")
    _atomic_savez(directory, path, _with_integrity(flat), step)
    _prune(directory, keep)
    _prune_sharded(directory, keep)  # a dir toggled from --ckpt-sharded
    return path


def _prune(directory: str, keep: int) -> None:
    ckpts = sorted(
        (int(m.group(1)), f)
        for f in os.listdir(directory)
        if (m := _CKPT_RE.search(f))
    )
    for _, f in ckpts[:-keep] if keep else []:
        try:
            os.unlink(os.path.join(directory, f))
        except FileNotFoundError:
            # the background scrubber may have quarantined (moved) the
            # member between our listing and this unlink — gone either
            # way, and a hygiene race must not fail a save
            pass


# --------------------------------------------------------------------------
# per-host sharded checkpoints (SURVEY.md §5.4 "written per-host for
# sharded arrays"; round-3 verdict item 8)
# --------------------------------------------------------------------------


def _norm_index(index, shape) -> tuple:
    """Normalize a shard's index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"strided shard index {sl} unsupported")
        out.append((start, stop))
    return tuple(out)


def save_checkpoint_sharded(
    directory: str,
    state: PyTree,
    step: int,
    rng: Optional[jax.Array] = None,
    keep: int = 3,
    extra_meta: Optional[dict] = None,
    topology: Optional[dict] = None,
) -> Optional[str]:
    """Per-host sharded save: each process writes ONLY the shards it
    holds — no cross-host gather and no rank-0 host-memory spike, unlike
    :func:`save_checkpoint` (which pulls every leaf to one host; fine at
    138M params, a ceiling for ZeRO-sharded or pod-scale states).

    Layout: ``ckpt_{step}.proc{k}of{n}.npz`` per process. Array keys are
    ``{leafpath}::s{j}`` with a ``__meta__`` JSON entry recording, per
    leaf, the global shape/dtype and each saved shard's index bounds.
    Each unique shard is written by exactly ONE process (the
    minimum-process owner, decided from ``global_shards`` metadata — no
    communication). Restore (:func:`load_checkpoint`, which dispatches on
    the filename) reassembles full arrays from the complete file set
    under ANY process count — reshard-on-restore is the caller's normal
    device_put. A set missing any of its n files is ignored by
    :func:`latest_checkpoint` (atomicity without barriers: per-file
    tmp+rename, completeness by counting).
    """
    import json as _json

    n_proc = jax.process_count()
    me = jax.process_index()
    flat: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {"leaves": {}, "step": int(step)}
    if extra_meta:
        # every member file carries it: read_checkpoint_meta must work
        # from any process's file under any later process count
        meta["user"] = extra_meta
    topo = _topology_manifest(state, topology)
    if topo is not None:
        # every member carries the full manifest (like "user"): the
        # reshard plan must be computable from any one member file
        meta["topology"] = topo
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves_with_paths:
        key = _path_key(path)
        if not isinstance(leaf, jax.Array):
            if me == 0:  # host scalars/numpy: rank 0 records them whole
                arr = np.asarray(leaf)
                flat[f"{key}::s0"] = arr
                meta["leaves"][key] = {
                    "shape": list(arr.shape), "dtype": str(arr.dtype),
                    "shards": [{"bounds": [[0, d] for d in arr.shape], "file": 0}],
                }
            continue
        shape = leaf.shape
        # owner = minimum process holding each unique shard index
        owners: dict[tuple, int] = {}
        for sh in leaf.global_shards:
            b = _norm_index(sh.index, shape)
            p = sh.device.process_index
            owners[b] = min(owners.get(b, p), p)
        entry = {"shape": list(shape), "dtype": str(leaf.dtype), "shards": []}
        mine = {}
        for sh in leaf.addressable_shards:
            b = _norm_index(sh.index, shape)
            if owners[b] == me and b not in mine:
                mine[b] = np.asarray(sh.data)
        for j, (b, arr) in enumerate(sorted(mine.items())):
            flat[f"{key}::s{len(entry['shards'])}"] = arr
            entry["shards"].append({"bounds": [list(x) for x in b], "file": me})
        # every process records the SAME leaf catalogue structure for its
        # own shards only; load merges catalogues across files
        meta["leaves"][key] = entry
    if rng is not None and me == 0:
        if jnp.issubdtype(getattr(rng, "dtype", None), jax.dtypes.prng_key):
            meta["rng_impl"] = str(jax.random.key_impl(rng))
            flat["__rng__"] = np.asarray(jax.device_get(jax.random.key_data(rng)))
        else:
            raw = np.asarray(jax.device_get(rng))
            impl = jax.config.jax_default_prng_impl
            width = raw.shape[-1] if raw.ndim else None
            if width != _KEY_WIDTH_BY_IMPL.get(impl):
                impl = _KEY_IMPL_BY_WIDTH.get(width)
            meta["rng_impl"] = impl
            flat["__rng__"] = raw
    flat["__meta__"] = np.asarray(_json.dumps(meta))
    from theanompi_tpu.obs.spans import obs_span

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{step}.proc{me}of{n_proc}.npz")
    # checkpoint_write span (obs/spans.py): the serialize+rename of
    # this host's shard files (distinct from the driver's
    # 'checkpoint' bracket — see save_checkpoint's gather span note)
    with obs_span("checkpoint_write"):
        _atomic_savez(directory, path, _with_integrity(flat), step)
    _prune_sharded(directory, keep)
    if jax.process_index() == 0:
        _prune(directory, keep)  # a dir toggled from single-file saves
    return path


def _readable_nonempty(path: str) -> bool:
    """False for a zero-byte or stat-unreadable file — on some
    filesystems a host dying mid-``os.replace`` leaves a zero-length
    entry under the final name; resume discovery must treat it as
    ABSENT (an incomplete save), not raise on it."""
    try:
        return os.path.getsize(path) > 0
    except OSError:
        return False


def _sharded_sets(directory: str) -> dict[int, list[str]]:
    """step -> sorted COMPLETE file sets (all n present); incomplete
    sets (a host died mid-save) are excluded, and a zero-byte or
    unreadable member counts as missing (see :func:`_readable_nonempty`)."""
    by_step: dict[int, dict[int, tuple[int, str]]] = {}
    # sorted: listing order is filesystem/attribute-cache dependent per
    # host; the dict fill is order-insensitive today, but resume-step
    # agreement across controllers must not hinge on that staying true
    for f in sorted(os.listdir(directory)):
        if m := _SHARD_RE.search(f):
            if not _readable_nonempty(os.path.join(directory, f)):
                continue
            step, k, n = int(m.group(1)), int(m.group(2)), int(m.group(3))
            by_step.setdefault(step, {})[k] = (n, f)
    out = {}
    for step, files in by_step.items():
        n = next(iter(files.values()))[0]
        if len(files) == n and all(v[0] == n for v in files.values()):
            out[step] = [
                os.path.join(directory, files[k][1]) for k in range(n)
            ]
    return out


def _prune_sharded(directory: str, keep: int) -> None:
    if not keep:
        return
    sets = _sharded_sets(directory)
    for step in sorted(sets)[:-keep]:
        for f in sets[step]:
            try:
                os.unlink(f)
            except FileNotFoundError:
                pass


def _load_sharded(path: str, state_template: PyTree):
    """Reassemble a sharded set from its proc-0 member path."""
    import json as _json

    m = _SHARD_RE.search(os.path.basename(path))
    if not m:
        raise ValueError(f"{path!r} is not a sharded checkpoint member")
    directory = os.path.dirname(path) or "."
    step = int(m.group(1))
    files = _sharded_sets(directory).get(step)
    if files is None:
        raise FileNotFoundError(
            f"sharded checkpoint set for step {step} in {directory} is "
            "incomplete (a host's file is missing)"
        )
    datas = [np.load(f) for f in files]
    metas = [_json.loads(str(d["__meta__"])) for d in datas]
    # merged catalogue: leaf -> (shape, dtype, [(bounds, file_idx, key)])
    catalogue: dict[str, Any] = {}
    for fi, meta in enumerate(metas):
        for key, entry in meta["leaves"].items():
            cat = catalogue.setdefault(
                key, {"shape": tuple(entry["shape"]), "dtype": entry["dtype"],
                      "pieces": []}
            )
            for j, sh in enumerate(entry["shards"]):
                cat["pieces"].append((sh["bounds"], fi, f"{key}::s{j}"))
    rng = None
    if "__rng__" in datas[0].files:
        rng = wrap_saved_rng(datas[0]["__rng__"], impl=metas[0].get("rng_impl"))

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for p, leaf in leaves_with_paths:
        key = "/".join(str(getattr(q, "key", getattr(q, "idx", q))) for q in p)
        if key not in catalogue:
            raise KeyError(
                f"sharded checkpoint step {step} is missing {key!r} — "
                f"structure mismatch (available: {sorted(catalogue)[:8]}...)"
            )
        cat = catalogue[key]
        want_shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        want_dtype = getattr(leaf, "dtype", None) or np.result_type(leaf)
        if cat["shape"] != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {cat['shape']}, "
                f"expected {want_shape}"
            )
        full = np.empty(cat["shape"], dtype=cat["dtype"])
        filled = 0
        for bounds, fi, akey in cat["pieces"]:
            sl = tuple(slice(b[0], b[1]) for b in bounds)
            piece = datas[fi][akey]
            full[sl] = piece
            filled += piece.size
        if filled < full.size:
            raise ValueError(
                f"checkpoint leaf {key!r}: shards cover {filled} of "
                f"{full.size} elements — incomplete save"
            )
        new_leaves.append(full.astype(want_dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves), rng


def read_checkpoint_meta(path: str) -> dict:
    """The ``extra_meta`` dict embedded at save time (empty dict if the
    checkpoint predates the field). Dispatches on the filename like
    :func:`load_checkpoint`; for per-host sharded sets any member file
    carries the meta, so the given member alone suffices."""
    import json as _json

    data = np.load(path)
    if _SHARD_RE.search(os.path.basename(path)):
        meta = _json.loads(str(data["__meta__"]))
        return meta.get("user", {})
    if "__usermeta__" in data.files:
        return _json.loads(str(data["__usermeta__"]))
    return {}


def checkpoint_step(path: Optional[str]) -> int:
    """The step number encoded in a checkpoint filename; -1 for None
    (used to compare resume decisions across controller processes)."""
    if path is None:
        return -1
    base = os.path.basename(path)
    m = _SHARD_RE.search(base) or _CKPT_RE.search(base)
    if not m:
        raise ValueError(f"{path!r} is not a checkpoint path")
    return int(m.group(1))


def _verify_npz(path: str) -> bool:
    """One .npz member checks out: every array decompresses, and when an
    integrity manifest is embedded (post-fault-tolerance saves) each
    array's CRC32 matches it exactly. Truncation is caught either way
    (np.savez's zip central directory lives at the END of the file);
    the manifest adds end-to-end bit-corruption coverage and detects a
    manifest/content mismatch. Never raises — a corrupt file is a False,
    not an exception out of resume discovery."""
    import json as _json

    if not _readable_nonempty(path):
        return False
    try:
        data = np.load(path)
        manifest = None
        if _INTEGRITY_KEY in data.files:
            manifest = _json.loads(str(data[_INTEGRITY_KEY]))
            if set(manifest) != {k for k in data.files if k != _INTEGRITY_KEY}:
                return False
        for k in data.files:
            if k == _INTEGRITY_KEY:
                continue
            arr = data[k]  # decompress (zip-level CRC checked here)
            if manifest is not None and _array_crc(arr) != manifest[k]:
                return False
        return True
    except Exception:  # noqa: BLE001 — any read failure means corrupt
        return False


def verify_checkpoint(path: str) -> bool:
    """True when ``path`` is a restorable checkpoint: for a single-file
    save, the file itself verifies (:func:`_verify_npz`); for a per-host
    sharded member, EVERY member of its complete set verifies (one
    host's corrupt shard poisons the whole step). Filename-dispatched
    like :func:`load_checkpoint`."""
    if _SHARD_RE.search(os.path.basename(path)):
        directory = os.path.dirname(path) or "."
        m = _SHARD_RE.search(os.path.basename(path))
        files = _sharded_sets(directory).get(int(m.group(1)))
        if files is None:
            return False
        return all(_verify_npz(f) for f in files)
    return _verify_npz(path)


def _keep_chain(directory: str) -> list[tuple[int, int, str]]:
    """The keep-chain, newest first: every restorable-looking candidate
    as ``(step, tie_break, path)`` — single-file ``ckpt_N.npz`` plus
    COMPLETE per-host sharded sets (as their proc-0 member path).
    Zero-byte files (a host died mid-``os.replace``) are absent. The
    tie-break makes a single file win a step tie with a sharded set
    (matches the pre-verify resolution order). Shared by
    :func:`latest_checkpoint` and :func:`newer_verified_checkpoint` so
    the two discovery paths can never order the chain differently."""
    if not os.path.isdir(directory):
        return []
    candidates: list[tuple[int, int, str]] = []
    # sorted for cross-host determinism: every controller must walk the
    # keep-chain in the same order (rank-divergence lint SPMD302)
    for f in sorted(os.listdir(directory)):
        if m := _CKPT_RE.search(f):
            p = os.path.join(directory, f)
            if _readable_nonempty(p):
                candidates.append((int(m.group(1)), 1, p))
    for step, files in _sharded_sets(directory).items():
        candidates.append((step, 0, files[0]))
    return sorted(candidates, reverse=True)


def _walk_verified(candidates, verify: bool) -> Optional[str]:
    """First candidate that verifies (or the first outright when
    ``verify`` is False); corrupt entries are skipped loudly."""
    for _, _, path in candidates:
        if not verify or verify_checkpoint(path):
            return path
        print(
            f"[checkpoint] skipping corrupt/truncated {path!r} "
            "(integrity check failed); walking back the keep-chain",
            flush=True,
        )
    return None


def latest_checkpoint(directory: str, verify: bool = False) -> Optional[str]:
    """Newest restorable checkpoint: single-file ``ckpt_N.npz`` or a
    COMPLETE per-host sharded set (returned as its proc-0 member path;
    ``load_checkpoint`` dispatches on the name). Zero-byte files (a
    host died mid-``os.replace``) are treated as absent.

    ``verify=True`` walks BACK the keep-chain past corrupt/truncated
    checkpoints (per-array CRC manifest + decompress check,
    :func:`verify_checkpoint`) instead of returning a newest file that
    will explode at load — the resume/rollback contract."""
    return _walk_verified(_keep_chain(directory), verify)


def newer_verified_checkpoint(directory: str, than_step: int) -> Optional[str]:
    """Newest VERIFIED checkpoint strictly newer than ``than_step``, or
    None — the serving hot-reloader's poll (serve/reload.py): "is there
    a newer verified step than the one I already serve?".

    Short-circuits at ``than_step``: the walk stops BEFORE reaching the
    file the caller already holds, so a steady-state poll (no new saves)
    verifies nothing at all — it never re-decompresses and re-CRCs the
    multi-hundred-MB checkpoint it is already serving, and a corrupt
    NEWER file is skipped (walking back) without ever touching the
    served one. Always verifies: an unverified path handed to a live
    serving engine would explode mid-swap."""
    return _walk_verified(
        [c for c in _keep_chain(directory) if c[0] > than_step], verify=True
    )


def load_checkpoint(
    path: str, state_template: PyTree
) -> tuple[PyTree, Optional[np.ndarray]]:
    """Restore a pytree matching ``state_template``'s structure (the
    template supplies structure + dtypes; values are ignored). Returns
    ``(state, rng_or_None)``: state leaves as host numpy arrays (caller
    device_puts), rng as a typed PRNG key wrapped with the impl that
    wrote it (see :func:`wrap_saved_rng`).

    A structure mismatch (renamed layer, different optimizer) raises
    KeyError naming the missing entry, rather than silently reinitializing
    — resume must be exact or explicit.

    Dispatches on the filename: per-host sharded sets
    (``ckpt_N.procKofM.npz``, :func:`save_checkpoint_sharded`) are
    reassembled from ALL member files — restorable under any process
    count.
    """
    if _SHARD_RE.search(os.path.basename(path)):
        return _load_sharded(path, state_template)
    data = np.load(path)
    rng = None
    if "__rng__" in data.files:
        impl = str(data["__rng_impl__"]) if "__rng_impl__" in data.files else None
        rng = wrap_saved_rng(data["__rng__"], impl=impl)

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for p, leaf in leaves_with_paths:
        key = "/".join(str(getattr(q, "key", getattr(q, "idx", q))) for q in p)
        if key not in data.files:
            raise KeyError(
                f"checkpoint {path} is missing {key!r} — structure mismatch "
                f"(available: {sorted(data.files)[:8]}...)"
            )
        arr = data[key]
        # Read shape/dtype WITHOUT materializing the template leaf: a
        # non-fully-addressable (multi-host sharded) template would raise
        # on np.asarray, and resume templates are allowed to be the live
        # sharded state.
        want_shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        want_dtype = getattr(leaf, "dtype", None) or np.result_type(leaf)
        if tuple(arr.shape) != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {arr.shape}, expected {want_shape}"
            )
        new_leaves.append(arr.astype(want_dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves), rng


# key-data width -> the impl that produced it (rbg and unsafe_rbg share a
# width; rbg is what this framework defaults to, see theanompi_tpu.__init__)
_KEY_IMPL_BY_WIDTH = {2: "threefry2x32", 4: "rbg"}
_KEY_WIDTH_BY_IMPL = {"threefry2x32": 2, "rbg": 4, "unsafe_rbg": 4}


# --------------------------------------------------------------------------
# mesh-portable restore (elastic PR): read the __topology__ manifest and
# rebuild the state on a DIFFERENT mesh via a computed transfer plan —
# the collective-based redistribution scheme of "Memory-efficient array
# redistribution" (arXiv:2112.01075). Each host materializes only the
# shard regions its target devices own; the cross-host data movement
# rides the shared checkpoint storage (the npz members double as the
# all-to-all buffers), so no host ever assembles a full array for a
# sharded leaf in the per-host sharded-set format.
# --------------------------------------------------------------------------


def read_topology_manifest(path: str) -> Optional[dict]:
    """The versioned ``__topology__`` manifest stamped at save time, or
    None for a pre-elastic checkpoint. Filename-dispatched like
    :func:`load_checkpoint`; any member of a sharded set carries the
    full manifest."""
    import json as _json

    data = np.load(path)
    if _SHARD_RE.search(os.path.basename(path)):
        meta = _json.loads(str(data["__meta__"]))
        return meta.get("topology")
    if _TOPOLOGY_KEY in data.files:
        return _json.loads(str(data[_TOPOLOGY_KEY]))
    return None


def _intersect(a, b):
    """Intersection of two ``((start, stop), ...)`` bound tuples, or
    None when empty along any dim."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


class _ShardedSource:
    """Region reader over a per-host sharded checkpoint set: the member
    files' ``__meta__`` catalogues record every saved shard's GLOBAL
    bounds, so any ``(key, bounds)`` region is assembled from exactly
    the overlapping pieces — never the whole leaf. ``reads`` records the
    largest single region fetched per key (the no-full-materialization
    proof hook tests assert on)."""

    def __init__(self, path: str):
        import json as _json

        m = _SHARD_RE.search(os.path.basename(path))
        directory = os.path.dirname(path) or "."
        step = int(m.group(1))
        files = _sharded_sets(directory).get(step)
        if files is None:
            raise FileNotFoundError(
                f"sharded checkpoint set for step {step} in {directory} "
                "is incomplete"
            )
        self._datas = [np.load(f) for f in files]
        self._metas = [_json.loads(str(d["__meta__"])) for d in self._datas]
        # key -> {shape, dtype, pieces: [(bounds, file_idx, array_key)]}
        self.catalogue: dict[str, Any] = {}
        for fi, meta in enumerate(self._metas):
            for key, entry in meta["leaves"].items():
                cat = self.catalogue.setdefault(
                    key, {"shape": tuple(entry["shape"]),
                          "dtype": entry["dtype"], "pieces": []}
                )
                for j, sh in enumerate(entry["shards"]):
                    cat["pieces"].append(
                        (tuple(tuple(b) for b in sh["bounds"]), fi,
                         f"{key}::s{j}")
                    )
        self._cache: dict = {}
        self.reads: dict[str, int] = {}

    def shape(self, key):
        return self.catalogue[key]["shape"]

    def read(self, key: str, bounds) -> np.ndarray:
        if key not in self.catalogue:
            raise KeyError(
                f"sharded checkpoint is missing {key!r} — structure "
                f"mismatch (available: {sorted(self.catalogue)[:8]}...)"
            )
        cat = self.catalogue[key]
        bounds = tuple(tuple(b) for b in bounds)
        shape = tuple(hi - lo for lo, hi in bounds)
        out = np.zeros(shape, dtype=cat["dtype"])
        want = int(np.prod(shape)) if shape else 1
        self.reads[key] = max(self.reads.get(key, 0), want)
        covered = 0
        for pbounds, fi, akey in cat["pieces"]:
            inter = _intersect(pbounds, bounds) if bounds else ()
            if bounds and inter is None:
                continue
            piece = self._cache.get((fi, akey))
            if piece is None:
                piece = self._cache[(fi, akey)] = self._datas[fi][akey]
            if not bounds:  # scalar leaf
                return np.asarray(piece)
            dst = tuple(slice(lo - b[0], hi - b[0])
                        for (lo, hi), b in zip(inter, bounds))
            srcsl = tuple(slice(lo - p[0], hi - p[0])
                          for (lo, hi), p in zip(inter, pbounds))
            out[dst] = piece[srcsl]
            covered += int(np.prod([hi - lo for lo, hi in inter]))
        if covered < want:
            raise ValueError(
                f"checkpoint leaf {key!r}: saved shards cover only "
                f"{covered} of {want} requested elements — incomplete set"
            )
        return out

    def end_leaf(self) -> None:
        """Drop decompressed piece buffers between leaves — the reshard
        holds at most one leaf's touched pieces in host memory."""
        self._cache.clear()

    def rng(self):
        if "__rng__" in self._datas[0].files:
            return wrap_saved_rng(self._datas[0]["__rng__"],
                                  impl=self._metas[0].get("rng_impl"))
        return None


class _SingleFileSource:
    """Region reader over a single-file checkpoint. The npz member IS
    the full array, so a read materializes the whole leaf on this host
    (the format already implies that — it was saved by a rank-0 gather);
    the per-host memory guarantee belongs to the sharded-set format."""

    def __init__(self, path: str):
        self._data = np.load(path)
        self._cache: dict = {}
        self.reads: dict[str, int] = {}

    def shape(self, key):
        if key not in self._data.files:
            raise KeyError(
                f"checkpoint is missing {key!r} — structure mismatch"
            )
        arr = self._cache.get(key)
        if arr is None:
            arr = self._cache[key] = self._data[key]
        return tuple(arr.shape)

    def read(self, key: str, bounds) -> np.ndarray:
        arr = self._cache.get(key)
        if arr is None:
            arr = self._cache[key] = self._data[key]
        shape = tuple(hi - lo for lo, hi in bounds)
        self.reads[key] = max(self.reads.get(key, 0),
                              int(np.prod(shape)) if shape else 1)
        return arr[tuple(slice(lo, hi) for lo, hi in bounds)]

    def end_leaf(self) -> None:
        self._cache.clear()

    def rng(self):
        if "__rng__" in self._data.files:
            impl = (str(self._data["__rng_impl__"])
                    if "__rng_impl__" in self._data.files else None)
            return wrap_saved_rng(self._data["__rng__"], impl=impl)
        return None


def _policy_for(key: str, policies: dict) -> dict:
    """Longest-prefix policy entry for one leaf key (prefixes are leaf-
    path prefixes like ``.opt_state``); default is ``global`` — the
    leaf's global content is mesh-invariant and moves by bounds."""
    best, best_len = {"policy": "global"}, -1
    for prefix, entry in policies.items():
        if (key == prefix or key.startswith(prefix + "/")) and \
                len(prefix) > best_len:
            best, best_len = entry, len(prefix)
    return best


def _region_reader(src, key: str, policy: dict, tgt_shape, tgt_dtype):
    """``read_fn(bounds) -> np.ndarray`` for one target leaf under its
    reshard policy (bounds in TARGET global index space):

    - ``global``: source and target global shapes are identical; the
      region is read straight through.
    - ``flat_padded``: a flat 1-D buffer whose logical content is its
      first ``logical`` elements, zero-padded to a mesh-dependent
      length (ZeRO's per-rank segment padding) — reads clip to the
      logical prefix and zero-fill the target's own padding.
    - ``reset``: state that is meaningless across a topology change
      (wire-codec error-feedback residuals): zeros at the target shape.
    - ``worker_consensus``: leading worker/replica axis resized by
      consensus — float leaves get the mean over the saved workers,
      integer leaves (per-worker step counters) the first worker's
      value, broadcast to the new worker count.
    - ``worker_uniform``: fresh uniform share weights ``1/W`` (GoSGD's
      ``alpha``; re-seeding mass uniformly keeps ``sum == 1`` exact).
    """
    kind = policy.get("policy", "global")
    if kind == "reset":
        def read_reset(bounds):
            return np.zeros(tuple(hi - lo for lo, hi in bounds), tgt_dtype)
        return read_reset
    if kind == "worker_uniform":
        w = int(tgt_shape[0]) if tgt_shape else 1

        def read_uniform(bounds):
            return np.full(tuple(hi - lo for lo, hi in bounds),
                           1.0 / w, tgt_dtype)
        return read_uniform
    src_shape = src.shape(key)
    if kind == "worker_consensus" and tuple(src_shape) != tuple(tgt_shape):
        w_src = int(src_shape[0])

        def read_consensus(bounds):
            (w0, w1), rest = bounds[0], tuple(bounds[1:])
            stack = src.read(key, ((0, w_src),) + rest)
            one = (stack[:1] if np.issubdtype(np.dtype(tgt_dtype), np.integer)
                   else stack.mean(axis=0, keepdims=True))
            return np.broadcast_to(
                one.astype(tgt_dtype), (w1 - w0, *one.shape[1:])
            )
        return read_consensus
    if kind == "flat_padded" and tuple(src_shape) != tuple(tgt_shape):
        logical = int(policy["logical"])

        def read_flat(bounds):
            (a, b), = bounds
            out = np.zeros((b - a,), tgt_dtype)
            hi = min(b, logical)
            if a < hi:
                out[: hi - a] = src.read(key, ((a, hi),))
            return out
        return read_flat
    # identical global shape (covers same-shape leaves under any policy)
    if tuple(src_shape) != tuple(tgt_shape):
        raise ValueError(
            f"checkpoint leaf {key!r} has global shape {src_shape}, "
            f"expected {tuple(tgt_shape)} and no shape-adapting elastic "
            "policy covers it — the saving engine must declare one in "
            "its elastic_spec()"
        )

    def read_global(bounds):
        return src.read(key, tuple(bounds))
    return read_global


def load_resharded(
    path: str, state_template: PyTree, target_mesh,
) -> tuple[PyTree, Optional[jax.Array], dict]:
    """Restore a checkpoint onto ``target_mesh``, resharding if the mesh
    it was saved under differs. Returns ``(state, rng, info)``.

    - Saved and target topologies equal (or the checkpoint predates
      topology manifests but loads cleanly): behaves exactly like
      :func:`load_checkpoint` — host arrays the caller places, so a
      same-mesh resume stays bit-identical. ``info['resharded']`` is
      False.
    - Topologies differ: every leaf of ``state_template`` (whose live
      arrays define the TARGET shapes and shardings — build it with the
      engine's ``init_state`` on the target mesh) is rebuilt with
      :func:`~theanompi_tpu.parallel.mesh.put_resharded`: each
      addressable target shard's content is read from the checkpoint by
      GLOBAL bounds under the leaf's elastic policy (see
      ``_region_reader``), so the sharded-set format never assembles a
      full array on one host. Returns device-placed global arrays;
      ``info`` carries from/to world sizes, the leaf count, and the
      per-key max read sizes (``reads``).

    A pre-elastic checkpoint (no ``__topology__`` manifest) that does
    NOT load on the target mesh raises a ValueError naming the missing
    metadata — there is no plan to compute without it.
    """
    manifest = read_topology_manifest(path)
    from theanompi_tpu.parallel.mesh import mesh_topology, put_resharded

    tgt_topo = mesh_topology(target_mesh)
    if manifest is None:
        try:
            state, rng = load_checkpoint(path, state_template)
        except (KeyError, ValueError) as e:
            raise ValueError(
                f"checkpoint {path!r} carries no {_TOPOLOGY_KEY!r} "
                "topology manifest (it was saved before elastic-resume "
                "stamping) and its leaves do not match the current mesh "
                f"{tgt_topo} — a reshard cannot be planned without the "
                "saved mesh/PartitionSpec metadata. Resume on the "
                "original topology, or re-save once with a stamped "
                "save_checkpoint(..., topology=...) first."
            ) from e
        return state, rng, {"resharded": False, "reason": "no-manifest"}
    if manifest.get("mesh") == tgt_topo:
        state, rng = load_checkpoint(path, state_template)
        return state, rng, {"resharded": False, "reason": "same-mesh"}

    from jax.sharding import NamedSharding, PartitionSpec

    src = (_ShardedSource(path)
           if _SHARD_RE.search(os.path.basename(path))
           else _SingleFileSource(path))
    policies = (manifest.get("elastic") or {}).get("policies") or {}
    leaves_with_paths, treedef = \
        jax.tree_util.tree_flatten_with_path(state_template)
    # The stamped per-leaf block describes the SOURCE layout — validate
    # the plan against it before any region read: every target leaf
    # whose policy reads the checkpoint must have been stamped at save
    # time, so an engine/structure mismatch fails as one batched error
    # naming the leaves instead of a KeyError deep in the first read.
    stamped = manifest.get("leaves")
    if stamped is not None:
        _READLESS = ("reset", "worker_uniform")
        missing = sorted(
            k for k in (_path_key(p) for p, _ in leaves_with_paths)
            if k not in stamped
            and _policy_for(k, policies).get("policy", "global")
            not in _READLESS
        )
        if missing:
            raise ValueError(
                f"cannot plan a reshard of {path!r}: the target state "
                f"template has leaves the checkpoint's {_TOPOLOGY_KEY!r} "
                f"manifest never stamped: {missing} — the saving and "
                "resuming engines disagree on the state structure "
                "(same rule/model/wire-codec on both sides?)"
            )
    new_leaves = []
    for p, leaf in leaves_with_paths:
        key = _path_key(p)
        policy = _policy_for(key, policies)
        tgt_shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        tgt_dtype = getattr(leaf, "dtype", None) or np.result_type(leaf)
        read_fn = _region_reader(src, key, policy, tgt_shape, tgt_dtype)
        if not isinstance(leaf, jax.Array):
            new_leaves.append(
                read_fn(tuple((0, d) for d in tgt_shape)).astype(tgt_dtype)
            )
            src.end_leaf()
            continue
        sharding = getattr(leaf, "sharding", None)
        spec = (sharding.spec if isinstance(sharding, NamedSharding)
                else PartitionSpec())
        new_leaves.append(
            put_resharded(target_mesh, spec, tgt_shape, tgt_dtype, read_fn)
        )
        src.end_leaf()
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    saved_shape = (manifest.get("mesh") or {}).get("shape") or [0]
    info = {
        "resharded": True,
        "from_world": int(np.prod(saved_shape)),
        "to_world": int(target_mesh.devices.size),
        "from_mesh": manifest.get("mesh"),
        "leaves": len(new_leaves),
        "reads": dict(src.reads),
    }
    return state, src.rng(), info


class AsyncCheckpointer:
    """Checkpoint writes overlapped with training (beyond-parity: the
    reference saved synchronously from rank 0 each epoch, stalling the
    workers for the full serialize+write — SURVEY.md §5.4 "no async
    checkpointing").

    ``save()`` first takes a DEVICE-SIDE snapshot (an HBM->HBM copy of
    every ``jax.Array`` leaf, ~ms) and hands that to a single background
    thread for the host pull + write. The copy is what makes overlap
    sound under buffer DONATION: every multi-device engine jits its step
    with ``donate_argnums=(0,)``, so the next dispatched step marks the
    live state's buffers deleted — a background ``device_get`` on the
    originals would race it and crash ("Array has been deleted"); the
    snapshot buffers are referenced only by the writer. Costs one
    transient extra TrainState in HBM until the pull completes.
    Semantics match :func:`save_checkpoint` (atomic tmp+rename, rank-0
    writes, prune-to-keep), with orbax-style discipline:

    - ONE save in flight: a new ``save()`` first waits for the previous
      one, so checkpoints land in step order.
    - worker errors don't vanish: they re-raise at the next ``save()`` /
      ``wait()`` / ``close()`` — EXCEPT *transient* storage-exhaustion
      errors (ENOSPC, EDQUOT, EIO, ESTALE: a full disk, a flaky NFS
      mount), which fail the ATTEMPT without failing the run: the torn
      tmp was already cleaned (``os.replace`` never ran, the keep-chain
      is untouched), so the failure is logged, counted in
      ``storage_failures`` (newest exception in ``last_storage_error``),
      and training continues to the next boundary save — a full disk
      must degrade checkpoint cadence, not kill a healthy training run
      whose older checkpoints remain valid. Configuration errors
      (ENOTDIR, EACCES, EEXIST...) are NOT transient: they still
      re-raise, because every future attempt would fail identically
      and an epoch whose checkpoint silently never lands must not
      return a success summary.
    - ``close()`` drains the queue — call before reading "the latest
      checkpoint" or letting the process exit.

    Multi-host: leaves that are NOT fully addressable need cross-host
    collectives to gather; those must stay on the thread that issues the
    training step's collectives (two threads interleaving collectives
    deadlock). Such saves transparently run synchronously instead.
    """

    def __init__(self, sharded: bool = False):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(1, thread_name_prefix="tmpi-ckpt")
        self._pending = None  # (future, step) of the in-flight save
        self.storage_failures = 0
        self.last_storage_error: Optional[OSError] = None
        # per-host sharded writes touch only ADDRESSABLE shards, so they
        # are collective-free and async-safe even in multi-host runs —
        # the gather-to-rank-0 sync fallback below applies to the
        # single-file format only
        self._sharded = bool(sharded)

    def save(
        self,
        directory: str,
        state: PyTree,
        step: int,
        rng: Optional[jax.Array] = None,
        keep: int = 3,
        extra_meta: Optional[dict] = None,
        topology: Optional[dict] = None,
    ) -> None:
        self.wait()
        save_fn = save_checkpoint_sharded if self._sharded else save_checkpoint
        if not self._sharded:
            leaves = jax.tree_util.tree_leaves(state)
            if any(
                isinstance(l, jax.Array) and not l.is_fully_addressable
                for l in leaves
            ):
                # cross-host gather required -> synchronous, on this thread
                save_checkpoint(directory, state, step, rng=rng, keep=keep,
                                extra_meta=extra_meta, topology=topology)
                return

        def snap(leaf):
            # new device buffer: immune to donation of the original
            # (jnp.copy preserves the sharding, so the topology
            # manifest's per-leaf specs read identically off the copy)
            return jnp.copy(leaf) if isinstance(leaf, jax.Array) else leaf

        state = jax.tree_util.tree_map(snap, state)
        if rng is not None:
            rng = snap(rng)
        self._pending = (self._pool.submit(
            save_fn, directory, state, step, rng, keep, extra_meta, topology
        ), int(step))

    # errnos that mean "storage is full/flaky RIGHT NOW", not "this
    # path will never work" — the only failures an attempt may absorb
    _TRANSIENT_ERRNOS = frozenset(
        e for e in (errno.ENOSPC, getattr(errno, "EDQUOT", None),
                    errno.EIO, getattr(errno, "ESTALE", None))
        if e is not None
    )

    def wait(self) -> None:
        """Block until the in-flight save (if any) is durable; re-raises
        its error here if it failed — except transient storage-
        exhaustion errors (class docstring), which fail only the
        attempt: logged, counted, swallowed, keep-chain intact."""
        if self._pending is None:
            return
        (pending, step), self._pending = self._pending, None
        try:
            pending.result()
        except OSError as e:
            if e.errno not in self._TRANSIENT_ERRNOS:
                raise
            self.storage_failures += 1
            self.last_storage_error = e
            print(
                f"[checkpoint] async save at step {step} failed on a "
                f"storage error ({e!r}); the torn attempt left the "
                "keep-chain intact — training continues, next boundary "
                "save retries",
                flush=True,
            )

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._pool.shutdown(wait=True)


# --------------------------------------------------------------------------
# checkpoint scrubber (chaos PR): at-rest bit-rot is silent until the
# moment of resume — and a corrupt member sitting in the keep-chain
# makes EVERY verify=True discovery re-pay a decompress+CRC walk past
# it. The scrubber re-verifies the chain in the background and moves
# corrupt members into <ckpt_dir>/quarantine/ (moved, not deleted: the
# bytes stay available for forensics), so the next latest_checkpoint
# walk-back is O(1) and a flipped-bit newest file can never shadow the
# last good checkpoint. The supervisor also runs one synchronous pass
# before each retry's resume discovery (launch/supervisor.py).
# --------------------------------------------------------------------------

QUARANTINE_DIR = "quarantine"


def scrub_checkpoint_dir(directory: str,
                         quarantine: str = QUARANTINE_DIR,
                         memo: Optional[dict] = None) -> dict:
    """One scrub pass over ``directory``'s keep-chain: every
    checkpoint-looking file (single-file saves AND individual sharded
    members — a set with one bad member is poisoned whole, but only the
    bad member is quarantined) is re-verified (:func:`_verify_npz`) and
    corrupt members are MOVED into ``<directory>/<quarantine>/``.
    Files pruned underneath the pass are skipped silently. Returns
    ``{"checked", "corrupt", "quarantined": [names], "seconds"}``.

    ``memo`` (a dict the caller owns across passes): members already
    verified at an unchanged ``(size, mtime_ns)`` are skipped — a
    steady-state pass over multi-GB checkpoints then costs stats, not
    a full decompress+CRC of every byte. The memo deliberately canNOT
    see disk-level rot that leaves metadata untouched, so a periodic
    memo-free full pass is still required (the background scrubber
    does one every :data:`CheckpointScrubber.FULL_EVERY` passes; the
    supervisor's retry-time call is always memo-free).

    Safe against a concurrent writer: visible final-name files are
    complete (tmp+rename atomicity), ``.tmp`` spill files never match
    the checkpoint patterns, and a valid file can never fail verify.
    Quarantined names keep their filename (suffixed ``.N`` on
    collision), so a quarantined member is inert: nothing under
    ``quarantine/`` matches the keep-chain walk."""
    t0 = time.perf_counter()
    out = {"checked": 0, "corrupt": 0, "quarantined": [], "seconds": 0.0}
    if not os.path.isdir(directory):
        return out
    names = [f for f in sorted(os.listdir(directory))
             if _CKPT_RE.search(f) or _SHARD_RE.search(f)]
    for f in names:
        p = os.path.join(directory, f)
        try:
            st = os.stat(p)
        except OSError:
            continue  # pruned underneath the listing
        out["checked"] += 1
        sig = (st.st_size, st.st_mtime_ns)
        if memo is not None and memo.get(f) == sig:
            continue  # verified before at this exact size+mtime
        if _verify_npz(p):
            if memo is not None:
                memo[f] = sig
            continue
        if not os.path.exists(p):
            continue  # pruned mid-verify: absence is not corruption
        qdir = os.path.join(directory, quarantine)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, f)
        n = 1
        while os.path.exists(dst):
            dst = os.path.join(qdir, f"{f}.{n}")
            n += 1
        try:
            os.replace(p, dst)
        except OSError:
            continue  # raced a prune; the member is gone either way
        out["quarantined"].append(f)
        print(f"[scrub] quarantined corrupt checkpoint member {f!r} "
              f"-> {dst!r}", flush=True)
    out["corrupt"] = len(out["quarantined"])
    out["seconds"] = time.perf_counter() - t0
    return out


class CheckpointScrubber:
    """Background keep-chain scrubber: run
    :func:`scrub_checkpoint_dir` every ``interval`` seconds until
    :meth:`stop`. ``on_result`` (e.g. ``Observability.note_scrub``)
    receives each pass's result dict — ``kind=scrub`` records and the
    ``tmpi_scrub_*`` gauges ride it; a callback failure is suppressed
    (telemetry must never take down the scrubber, and the scrubber
    must never take down training). ``scrub_once()`` is the
    deterministic unit tests drive directly.

    Passes are memoized on ``(size, mtime_ns)`` so steady-state scrubs
    of multi-GB checkpoints cost stats, not bytes — with a memo-FREE
    full pass every :data:`FULL_EVERY` passes (and on the first), since
    disk-level rot can flip bits without touching file metadata."""

    FULL_EVERY = 10

    def __init__(self, ckpt_dir: str, *, interval: float = 60.0,
                 on_result=None):
        self.ckpt_dir = ckpt_dir
        self.interval = float(interval)
        self.on_result = on_result
        self.runs = 0
        self.quarantined_total = 0
        self._memo: dict = {}
        # serializes passes: scrub_once is both the background loop's
        # body AND a public entry (the supervisor's retry-time pass,
        # unit tests) — two concurrent passes would race on the memo
        # dict and the counters
        self._pass_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scrub_once(self) -> dict:
        with self._pass_lock:
            if self.runs % self.FULL_EVERY == 0:
                self._memo.clear()  # periodic full re-verify (docstring)
            res = scrub_checkpoint_dir(self.ckpt_dir, memo=self._memo)
            self.runs += 1
            self.quarantined_total += res["corrupt"]
        if self.on_result is not None:
            try:
                self.on_result(res)
            except Exception as e:  # noqa: BLE001
                print(f"[scrub] result callback failed (suppressed): "
                      f"{e!r}", flush=True)
        return res

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("scrubber already started")
        self._thread = threading.Thread(
            target=self._loop, name="tmpi-ckpt-scrub", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scrub_once()
            except Exception as e:  # noqa: BLE001
                print(f"[scrub] pass failed ({e!r}); retrying next "
                      "interval", flush=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


# --------------------------------------------------------------------------
# resumable-run marker (fault-tolerance PR): the SIGTERM grace path
# (launch/worker.py) checkpoints and drops this marker; the supervisor
# (launch/supervisor.py) reads it to auto-resume the next invocation.
# --------------------------------------------------------------------------

_RESUMABLE_MARKER = "resumable.json"


def write_resumable_marker(ckpt_dir: str, step: int, reason: str) -> str:
    """Atomically mark the run in ``ckpt_dir`` as cleanly-interrupted-
    and-resumable (rank 0 only, like the checkpoint writes)."""
    import json as _json

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, _RESUMABLE_MARKER)
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            _json.dump({"step": int(step), "reason": str(reason),
                        "t": time.time()}, f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def read_resumable_marker(ckpt_dir: str) -> Optional[dict]:
    """The marker dict, or None when absent/unreadable (an unreadable
    marker is treated as absent — it only gates an auto-resume hint)."""
    import json as _json

    try:
        with open(os.path.join(ckpt_dir, _RESUMABLE_MARKER)) as f:
            return _json.load(f)
    except (OSError, ValueError):
        return None


def clear_resumable_marker(ckpt_dir: str) -> None:
    try:
        os.unlink(os.path.join(ckpt_dir, _RESUMABLE_MARKER))
    except OSError:
        pass


def wrap_saved_rng(raw: np.ndarray, impl: Optional[str] = None) -> jax.Array:
    """Turn a checkpoint's raw ``__rng__`` uint32 data back into a usable
    PRNG key, honoring the impl that WROTE it rather than the process
    default — a checkpoint saved under threefry (width-2 key data) must
    resume correctly in a process whose default impl is rbg (width 4) and
    vice versa. ``impl`` comes from the checkpoint's ``__rng_impl__``
    entry; pre-impl-tracking checkpoints fall back to width inference.
    Returns a typed key; all jax.random consumers accept it."""
    arr = jnp.asarray(raw)
    impl = impl or _KEY_IMPL_BY_WIDTH.get(arr.shape[-1] if arr.ndim else None)
    if impl is None:
        raise ValueError(
            f"checkpoint rng has unrecognized key-data shape {np.shape(raw)}"
        )
    return jax.random.wrap_key_data(arr, impl=impl)
