"""Checkpoint / resume.

Rebuild of the reference's weight persistence (reference:
``lib/helper_funcs.py`` — ``save_weights``/``load_weights``: one ``.npy``
per Theano shared param, saved each epoch from rank 0, no atomicity;
SURVEY.md §5.4). Here the WHOLE TrainState pytree (params + BatchNorm
state + optimizer state + step) plus the RNG key goes into one ``.npz``
written atomically (tmp + rename), so resume restores training exactly —
including the LR schedule, which is a pure function of the restored step.

Arrays are pulled to host with ``jax.device_get``; on restore the caller
re-places them (replicated or sharded) via its usual device_put path.
Multi-host: only process 0 writes (same contract as the reference's
rank-0 save); sharded-per-host formats can layer on later without
changing this API.
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import Any, Optional

import numpy as np

import jax

PyTree = Any

_CKPT_RE = re.compile(r"ckpt_(\d+)\.npz$")


def _pull_to_host(leaf) -> np.ndarray:
    """Materialize one leaf on the host. Leaves sharded across OTHER
    processes (EASGD/GoSGD per-worker state under multi-controller) are
    gathered with a cross-host collective — so this is collective: every
    process must reach it, even though only rank 0 writes the file."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_with_paths(tree: PyTree) -> dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = _pull_to_host(leaf)
    return flat


def save_checkpoint(
    directory: str,
    state: PyTree,
    step: int,
    rng: Optional[jax.Array] = None,
    keep: int = 3,
) -> Optional[str]:
    """Atomically write ``ckpt_{step}.npz``; prune to the newest ``keep``.
    COLLECTIVE in multi-host runs: every process must call it (sharded
    leaves are gathered cross-host), then only process 0 writes; returns
    the path (or None on non-writer processes)."""
    flat = _flatten_with_paths(state)
    if rng is not None:
        flat["__rng__"] = np.asarray(jax.device_get(rng))
    if jax.process_index() != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{step}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, path)  # atomic on POSIX
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int) -> None:
    ckpts = sorted(
        (int(m.group(1)), f)
        for f in os.listdir(directory)
        if (m := _CKPT_RE.search(f))
    )
    for _, f in ckpts[:-keep] if keep else []:
        os.unlink(os.path.join(directory, f))


def checkpoint_step(path: Optional[str]) -> int:
    """The step number encoded in a checkpoint filename; -1 for None
    (used to compare resume decisions across controller processes)."""
    if path is None:
        return -1
    m = _CKPT_RE.search(os.path.basename(path))
    if not m:
        raise ValueError(f"{path!r} is not a checkpoint path")
    return int(m.group(1))


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        (int(m.group(1)), f)
        for f in os.listdir(directory)
        if (m := _CKPT_RE.search(f))
    )
    return os.path.join(directory, ckpts[-1][1]) if ckpts else None


def load_checkpoint(
    path: str, state_template: PyTree
) -> tuple[PyTree, Optional[np.ndarray]]:
    """Restore a pytree matching ``state_template``'s structure (the
    template supplies structure + dtypes; values are ignored). Returns
    ``(state, rng_or_None)`` as host numpy arrays — caller device_puts.

    A structure mismatch (renamed layer, different optimizer) raises
    KeyError naming the missing entry, rather than silently reinitializing
    — resume must be exact or explicit.
    """
    data = np.load(path)
    rng = data["__rng__"] if "__rng__" in data.files else None

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for p, leaf in leaves_with_paths:
        key = "/".join(str(getattr(q, "key", getattr(q, "idx", q))) for q in p)
        if key not in data.files:
            raise KeyError(
                f"checkpoint {path} is missing {key!r} — structure mismatch "
                f"(available: {sorted(data.files)[:8]}...)"
            )
        arr = data[key]
        # Read shape/dtype WITHOUT materializing the template leaf: a
        # non-fully-addressable (multi-host sharded) template would raise
        # on np.asarray, and resume templates are allowed to be the live
        # sharded state.
        want_shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        want_dtype = getattr(leaf, "dtype", None) or np.result_type(leaf)
        if tuple(arr.shape) != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {arr.shape}, expected {want_shape}"
            )
        new_leaves.append(arr.astype(want_dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves), rng
