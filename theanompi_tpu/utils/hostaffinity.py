"""Host CPU binding — the ``hwloc`` equivalent.

The reference bound each worker process (and its spawned loader child)
to cores near its GPU for NUMA locality (reference:
``lib/hwloc_utils.py``; SURVEY.md §2.1 "CPU binding"). On TPU the
runtime owns accelerator placement, so the only binding that matters is
the HOST side: keep the input-pipeline (prefetch/preprocess) threads off
the cores the controller and the XLA host runtime are using.

Config is one env var, same spirit as the reference's launcher flags:

    TMPI_LOADER_CPUS="4-7"     # cpuset for loader threads (range/list)
    TMPI_LOADER_CPUS="2,3,6"   #   ...explicit list form

Unset means no pinning (the OS scheduler usually does fine on a
dedicated host; pinning matters when the controller shares the host
with other ranks or heavy services). ``parse_cpuset``/``pin_thread``
are safe no-ops on platforms without ``sched_setaffinity``.
"""

from __future__ import annotations

import os
from typing import Optional


def parse_cpuset(spec: str) -> set[int]:
    """``"0-3,8,10-11"`` -> {0,1,2,3,8,10,11} (taskset list syntax)."""
    cpus: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            cpus.update(range(int(lo), int(hi) + 1))
        else:
            cpus.add(int(part))
    if not cpus:
        raise ValueError(f"empty cpuset {spec!r}")
    return cpus


def loader_cpuset() -> Optional[set[int]]:
    """The configured loader cpuset, intersected with this process's
    affinity mask (a cpuset outside the container's share is an error
    the kernel would reject); None when unconfigured."""
    spec = os.environ.get("TMPI_LOADER_CPUS")
    if not spec:
        return None
    want = parse_cpuset(spec)
    try:
        allowed = os.sched_getaffinity(0)
    except AttributeError:
        return None
    usable = want & allowed
    return usable or None


def pin_thread(cpus: Optional[set[int]] = None) -> bool:
    """Pin the CALLING thread to ``cpus`` (default: the configured
    loader cpuset). Returns True iff a pin was applied. Linux pins
    per-thread when called from within that thread."""
    if cpus is None:
        cpus = loader_cpuset()
    if not cpus:
        return False
    try:
        os.sched_setaffinity(0, cpus)
        return True
    except (AttributeError, OSError):
        return False
