"""FLOP + HBM cost accounting and MFU (model FLOPs utilization) — the
single per-compiled-executable cost authority.

The reference had no FLOPs accounting at all — its recorder reported
images/sec only (reference: ``lib/recorder.py``, SURVEY.md §5.1). On TPU
the honest scaling story needs achieved TFLOP/s vs the chip's peak, so
the bench and recorder report MFU alongside img/s (BASELINE metric
"scaling eff" is defined in those terms).

FLOPs and HBM bytes come from XLA's own cost model on the COMPILED
program (``Compiled.cost_analysis()``: ``flops`` + ``bytes accessed``) —
the same HLO the chip executes, so fusion/rematerialization are
accounted for. Peak numbers are small device-kind tables (public
spec-sheet bf16 FLOP/s and HBM GB/s); unknown devices (CPU test meshes)
report ``mfu=None`` rather than a made-up number.

Every consumer shares this module (attribution-profiler PR): bench.py's
compute mode, the ``tmpi profile`` subcommand (tools/profile.py), the
live ``tmpi_mfu``/``tmpi_hbm_gbps`` gauges (obs/attribution.py via each
engine's ``cost_model()`` hook), and the run summary's ``mfu`` field —
one :class:`CostModel` per compiled step, no hand-rolled duplicates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# public spec-sheet dense bf16 peak FLOP/s per chip; substring-matched
# against jax.Device.device_kind (ORDER MATTERS: first match wins)
_PEAK_BF16 = (
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v6 lite", 918e12),  # v6e / Trillium
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# public spec-sheet HBM bandwidth (bytes/s) per chip — the roofline's
# other ceiling; same substring-match convention as _PEAK_BF16
_PEAK_HBM = (
    ("v5 lite", 819e9),  # v5e: 819 GB/s
    ("v5litepod", 819e9),
    ("v5e", 819e9),
    ("v6 lite", 1640e9),  # v6e / Trillium: 1640 GB/s
    ("v6e", 1640e9),
    ("v5p", 2765e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def _match_table(table, device) -> Optional[float]:
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


def peak_flops(device=None) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for ``device`` (default: first visible
    device); None when unknown (e.g. CPU)."""
    return _match_table(_PEAK_BF16, device)


def peak_hbm_bytes_per_sec(device=None) -> Optional[float]:
    """Per-chip peak HBM bytes/s (spec sheet); None when unknown."""
    return _match_table(_PEAK_HBM, device)


@dataclass
class CostModel:
    """XLA's cost analysis of ONE compiled executable invocation (one
    training step, usually), paired with the device's spec-sheet peaks.

    ``flops``/``hbm_bytes`` are per-invocation totals from the compiled
    HLO (``cost_analysis()``: ``flops`` + ``bytes accessed``). Peaks are
    None on devices without a spec entry (CPU test meshes) — consumers
    must then either skip utilization ratios (:meth:`mfu` returns None)
    or calibrate against measured time (obs/attribution.py documents
    that convention)."""

    flops: float
    hbm_bytes: float
    device_kind: str = ""
    peak_flops_per_sec: Optional[float] = None
    peak_hbm_bytes_per_sec: Optional[float] = None

    def mfu(self, step_seconds: Optional[float]) -> Optional[float]:
        """Achieved / peak FLOP/s for a measured per-step time; None
        when the peak is unknown or the time unmeasurable."""
        if not step_seconds or step_seconds <= 0 or not self.peak_flops_per_sec:
            return None
        return mfu(self.flops / step_seconds,
                   peak=self.peak_flops_per_sec)

    def hbm_gbps(self, step_seconds: Optional[float]) -> Optional[float]:
        """Achieved HBM GB/s implied by a measured per-step time (bytes
        accessed / time) — computable on every backend."""
        if not step_seconds or step_seconds <= 0:
            return None
        return self.hbm_bytes / step_seconds / 1e9

    def compute_seconds(self) -> Optional[float]:
        """Roofline lower bound on the step's device time: the larger of
        the FLOP time at peak compute and the HBM time at peak
        bandwidth. None when the peaks are unknown."""
        if not self.peak_flops_per_sec or not self.peak_hbm_bytes_per_sec:
            return None
        return max(self.flops / self.peak_flops_per_sec,
                   self.hbm_bytes / self.peak_hbm_bytes_per_sec)

    def hbm_bound(self) -> Optional[bool]:
        """True when the roofline's binding ceiling is HBM bandwidth,
        False when compute; None when the peaks are unknown."""
        if not self.peak_flops_per_sec or not self.peak_hbm_bytes_per_sec:
            return None
        return (self.hbm_bytes / self.peak_hbm_bytes_per_sec
                > self.flops / self.peak_flops_per_sec)

    def as_metrics(self) -> dict:
        """Numeric gauge map (obs facade prefixes ``tmpi_``)."""
        out = {
            "cost_flops_per_step": self.flops,
            "cost_hbm_bytes_per_step": self.hbm_bytes,
        }
        if self.peak_flops_per_sec:
            out["cost_peak_tflops"] = self.peak_flops_per_sec / 1e12
        if self.peak_hbm_bytes_per_sec:
            out["cost_peak_hbm_gbps"] = self.peak_hbm_bytes_per_sec / 1e9
        return out


def compiled_cost(jitted, *args, device=None, **kwargs) -> Optional[CostModel]:
    """:class:`CostModel` of one invocation of an already-jitted
    function, from XLA's cost analysis of the lowered+compiled program
    (abstract ``ShapeDtypeStruct`` args work — nothing executes). None
    when the backend provides no cost model or the lowering fails."""
    import jax

    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        if flops <= 0:
            return None
        if device is None:
            device = jax.devices()[0]
        return CostModel(
            flops=flops,
            hbm_bytes=float(ca.get("bytes accessed", 0.0)),
            device_kind=getattr(device, "device_kind", ""),
            peak_flops_per_sec=peak_flops(device),
            peak_hbm_bytes_per_sec=peak_hbm_bytes_per_sec(device),
        )
    except Exception:
        return None


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one invocation of an already-jitted function
    (thin view over :func:`compiled_cost`). None when the backend
    provides no cost model."""
    cost = compiled_cost(jitted, *args, **kwargs)
    return cost.flops if cost is not None else None


def abstract_batch(model, global_batch: int):
    """``(x, y)`` ShapeDtypeStructs for one global training batch of
    ``model`` — the abstract operands every engine's ``cost_model()``
    lowers its compiled step over (LM models: x IS the label stream)."""
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    ishape = tuple(model.recipe.input_shape)
    if getattr(model, "is_lm", False):
        x = sds((global_batch, *ishape), jnp.int32)
        return x, x
    return (sds((global_batch, *ishape), jnp.float32),
            sds((global_batch,), jnp.int32))


def mfu(flops_per_sec: Optional[float], device=None,
        peak: Optional[float] = None) -> Optional[float]:
    """Achieved / peak FLOP/s. ``peak`` overrides the device-table
    lookup (CostModel carries its own)."""
    if peak is None:
        peak = peak_flops(device)
    if not peak or not flops_per_sec:
        return None
    return flops_per_sec / peak
