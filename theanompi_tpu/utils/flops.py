"""FLOP accounting and MFU (model FLOPs utilization).

The reference had no FLOPs accounting at all — its recorder reported
images/sec only (reference: ``lib/recorder.py``, SURVEY.md §5.1). On TPU
the honest scaling story needs achieved TFLOP/s vs the chip's peak, so
the bench and recorder report MFU alongside img/s (BASELINE metric
"scaling eff" is defined in those terms).

FLOPs come from XLA's own cost model on the COMPILED program
(``Compiled.cost_analysis()``) — the same HLO the chip executes, so
fusion/rematerialization are accounted for. Peak numbers are a small
device-kind table (public spec-sheet bf16 peaks); unknown devices (CPU
test meshes) report ``mfu=None`` rather than a made-up number.
"""

from __future__ import annotations

from typing import Optional

# public spec-sheet dense bf16 peak FLOP/s per chip; substring-matched
# against jax.Device.device_kind (ORDER MATTERS: first match wins)
_PEAK_BF16 = (
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v6 lite", 918e12),  # v6e / Trillium
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device=None) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for ``device`` (default: first visible
    device); None when unknown (e.g. CPU)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one invocation of an already-jitted function, from
    XLA's cost analysis of the lowered+compiled program. None when the
    backend provides no cost model."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def mfu(flops_per_sec: Optional[float], device=None) -> Optional[float]:
    peak = peak_flops(device)
    if not peak or not flops_per_sec:
        return None
    return flops_per_sec / peak
