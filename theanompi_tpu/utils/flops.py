"""FLOP + HBM cost accounting and MFU (model FLOPs utilization) — the
single per-compiled-executable cost authority.

The reference had no FLOPs accounting at all — its recorder reported
images/sec only (reference: ``lib/recorder.py``, SURVEY.md §5.1). On TPU
the honest scaling story needs achieved TFLOP/s vs the chip's peak, so
the bench and recorder report MFU alongside img/s (BASELINE metric
"scaling eff" is defined in those terms).

FLOPs and HBM bytes come from XLA's own cost model on the COMPILED
program (``Compiled.cost_analysis()``: ``flops`` + ``bytes accessed``) —
the same HLO the chip executes, so fusion/rematerialization are
accounted for. Peak numbers are small device-kind tables (public
spec-sheet bf16 FLOP/s and HBM GB/s); unknown devices (CPU test meshes)
report ``mfu=None`` rather than a made-up number.

Every consumer shares this module (attribution-profiler PR): bench.py's
compute mode, the ``tmpi profile`` subcommand (tools/profile.py), the
live ``tmpi_mfu``/``tmpi_hbm_gbps`` gauges (obs/attribution.py via each
engine's ``cost_model()`` hook), and the run summary's ``mfu`` field —
one :class:`CostModel` per compiled step, no hand-rolled duplicates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# public spec-sheet dense bf16 peak FLOP/s per chip; substring-matched
# against jax.Device.device_kind (ORDER MATTERS: first match wins)
_PEAK_BF16 = (
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v6 lite", 918e12),  # v6e / Trillium
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# public spec-sheet HBM bandwidth (bytes/s) per chip — the roofline's
# other ceiling; same substring-match convention as _PEAK_BF16
_PEAK_HBM = (
    ("v5 lite", 819e9),  # v5e: 819 GB/s
    ("v5litepod", 819e9),
    ("v5e", 819e9),
    ("v6 lite", 1640e9),  # v6e / Trillium: 1640 GB/s
    ("v6e", 1640e9),
    ("v5p", 2765e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

# public spec-sheet HBM CAPACITY (bytes) per chip — the memory
# pre-flight's budget ceiling (tools/analyze/memory.py, `tmpi
# preflight`); same substring-match convention as the peak tables.
# Unknown devices (CPU test meshes) report None — the pre-flight then
# needs an explicit ``--budget-gb``.
_HBM_CAPACITY = (
    ("v5 lite", 16e9),  # v5e: 16 GB
    ("v5litepod", 16e9),
    ("v5e", 16e9),
    ("v6 lite", 32e9),  # v6e / Trillium: 32 GB
    ("v6e", 32e9),
    ("v5p", 95e9),
    ("v5", 95e9),
    ("v4", 32e9),
    ("v3", 32e9),
    ("v2", 16e9),
)


def _match_table(table, device) -> Optional[float]:
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


def peak_flops(device=None) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for ``device`` (default: first visible
    device); None when unknown (e.g. CPU)."""
    return _match_table(_PEAK_BF16, device)


def peak_hbm_bytes_per_sec(device=None) -> Optional[float]:
    """Per-chip peak HBM bytes/s (spec sheet); None when unknown."""
    return _match_table(_PEAK_HBM, device)


def hbm_capacity_bytes(device=None) -> Optional[float]:
    """Per-chip HBM capacity in bytes (spec sheet); None when unknown
    (e.g. CPU test meshes — the memory pre-flight then requires an
    explicit ``--budget-gb``)."""
    return _match_table(_HBM_CAPACITY, device)


@dataclass
class CostModel:
    """XLA's cost analysis of ONE compiled executable invocation (one
    training step, usually), paired with the device's spec-sheet peaks.

    ``flops``/``hbm_bytes`` are per-invocation totals from the compiled
    HLO (``cost_analysis()``: ``flops`` + ``bytes accessed``). Peaks are
    None on devices without a spec entry (CPU test meshes) — consumers
    must then either skip utilization ratios (:meth:`mfu` returns None)
    or calibrate against measured time (obs/attribution.py documents
    that convention)."""

    flops: float
    hbm_bytes: float
    device_kind: str = ""
    peak_flops_per_sec: Optional[float] = None
    peak_hbm_bytes_per_sec: Optional[float] = None

    def mfu(self, step_seconds: Optional[float]) -> Optional[float]:
        """Achieved / peak FLOP/s for a measured per-step time; None
        when the peak is unknown or the time unmeasurable."""
        if not step_seconds or step_seconds <= 0 or not self.peak_flops_per_sec:
            return None
        return mfu(self.flops / step_seconds,
                   peak=self.peak_flops_per_sec)

    def hbm_gbps(self, step_seconds: Optional[float]) -> Optional[float]:
        """Achieved HBM GB/s implied by a measured per-step time (bytes
        accessed / time) — computable on every backend."""
        if not step_seconds or step_seconds <= 0:
            return None
        return self.hbm_bytes / step_seconds / 1e9

    def compute_seconds(self) -> Optional[float]:
        """Roofline lower bound on the step's device time: the larger of
        the FLOP time at peak compute and the HBM time at peak
        bandwidth. None when the peaks are unknown."""
        if not self.peak_flops_per_sec or not self.peak_hbm_bytes_per_sec:
            return None
        return max(self.flops / self.peak_flops_per_sec,
                   self.hbm_bytes / self.peak_hbm_bytes_per_sec)

    def hbm_bound(self) -> Optional[bool]:
        """True when the roofline's binding ceiling is HBM bandwidth,
        False when compute; None when the peaks are unknown."""
        if not self.peak_flops_per_sec or not self.peak_hbm_bytes_per_sec:
            return None
        return (self.hbm_bytes / self.peak_hbm_bytes_per_sec
                > self.flops / self.peak_flops_per_sec)

    def as_metrics(self) -> dict:
        """Numeric gauge map (obs facade prefixes ``tmpi_``)."""
        out = {
            "cost_flops_per_step": self.flops,
            "cost_hbm_bytes_per_step": self.hbm_bytes,
        }
        if self.peak_flops_per_sec:
            out["cost_peak_tflops"] = self.peak_flops_per_sec / 1e12
        if self.peak_hbm_bytes_per_sec:
            out["cost_peak_hbm_gbps"] = self.peak_hbm_bytes_per_sec / 1e9
        return out


def compiled_cost(jitted, *args, device=None, **kwargs) -> Optional[CostModel]:
    """:class:`CostModel` of one invocation of an already-jitted
    function, from XLA's cost analysis of the lowered+compiled program
    (abstract ``ShapeDtypeStruct`` args work — nothing executes). None
    when the backend provides no cost model or the lowering fails."""
    import jax

    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        if flops <= 0:
            return None
        if device is None:
            device = jax.devices()[0]
        return CostModel(
            flops=flops,
            hbm_bytes=float(ca.get("bytes accessed", 0.0)),
            device_kind=getattr(device, "device_kind", ""),
            peak_flops_per_sec=peak_flops(device),
            peak_hbm_bytes_per_sec=peak_hbm_bytes_per_sec(device),
        )
    except Exception:
        return None


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one invocation of an already-jitted function
    (thin view over :func:`compiled_cost`). None when the backend
    provides no cost model."""
    cost = compiled_cost(jitted, *args, **kwargs)
    return cost.flops if cost is not None else None


def abstract_batch(model, global_batch: int):
    """``(x, y)`` ShapeDtypeStructs for one global training batch of
    ``model`` — the abstract operands every engine's ``cost_model()``
    lowers its compiled step over (LM models: x IS the label stream)."""
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    ishape = tuple(model.recipe.input_shape)
    if getattr(model, "is_lm", False):
        x = sds((global_batch, *ishape), jnp.int32)
        return x, x
    return (sds((global_batch, *ishape), jnp.float32),
            sds((global_batch,), jnp.int32))


def mfu(flops_per_sec: Optional[float], device=None,
        peak: Optional[float] = None) -> Optional[float]:
    """Achieved / peak FLOP/s. ``peak`` overrides the device-table
    lookup (CostModel carries its own)."""
    if peak is None:
        peak = peak_flops(device)
    if not peak or not flops_per_sec:
        return None
    return flops_per_sec / peak


# --------------------------------------------------------------------------
# per-leaf state HBM residency — the `memory_model()` engine hook
# (mirrors obs/comm.py's `traffic_model()`: an ANALYTIC declaration the
# static analyzer cross-checks against the lowered program;
# tools/analyze/memory.py, `tmpi preflight`)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class MemoryLeaf:
    """One engine-state leaf's HBM residency: the global logical array
    and the slice of it each device actually holds (``global_bytes /
    shard_factor``, the mesh extent over the leaf's sharded axes)."""

    path: str  # jax.tree_util.keystr of the leaf (".params['h']['w']")
    dtype: str
    shape: tuple  # global logical shape
    global_bytes: int
    shard_factor: int  # mesh extent the leaf is divided over (>= 1)
    # serialized PartitionSpec (parallel/mesh.spec_to_json) the factor
    # derives from — the engine's ShardingRecipe declaration, so the
    # preflight byte table and the sharding analyzer read ONE source
    # (None on legacy callers that still pass bare factors)
    spec: Optional[list] = None

    @property
    def per_device_bytes(self) -> int:
        return -(-self.global_bytes // max(1, self.shard_factor))

    @property
    def category(self) -> str:
        """Top-level state field the leaf lives under (params,
        opt_state, workers, ef, ...)."""
        return self.path.lstrip(".").split("[")[0].split(".")[0]

    def as_json(self) -> dict:
        return {"path": self.path, "dtype": self.dtype,
                "shape": list(self.shape),
                "global_bytes": int(self.global_bytes),
                "per_device_bytes": int(self.per_device_bytes),
                "shard_factor": int(self.shard_factor),
                "spec": self.spec}


@dataclass
class MemoryModel:
    """An engine's declared per-leaf state residency on ONE device —
    what the persistent training state costs in HBM before any
    activations/temps (XLA's `memory_analysis()` adds those;
    tools/analyze/memory.py reconciles the two)."""

    rule: str
    n_devices: int
    leaves: list  # list[MemoryLeaf]
    detail: dict = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.detail is None:
            self.detail = {}

    @property
    def state_bytes_per_device(self) -> int:
        return sum(l.per_device_bytes for l in self.leaves)

    @property
    def state_bytes_global(self) -> int:
        return sum(l.global_bytes for l in self.leaves)

    def category_bytes_per_device(self) -> dict:
        out: dict = {}
        for l in self.leaves:
            out[l.category] = out.get(l.category, 0) + l.per_device_bytes
        return out

    def params_bytes_per_device(self) -> int:
        """Bytes of the parameter leaves proper on one device (the
        MEM003 rematerialization-smell denominator). Worker-stacked
        engines keep their replicas under ``.workers`` — those count
        too (each device's slice of the stack IS its params)."""
        total = 0
        for l in self.leaves:
            if l.category in ("params", "workers", "center_params"):
                total += l.per_device_bytes
        return total

    def top_leaves(self, k: int = 10) -> list:
        return sorted(self.leaves, key=lambda l: -l.per_device_bytes)[:k]

    def as_json(self) -> dict:
        return {"rule": self.rule, "n_devices": int(self.n_devices),
                "state_bytes_per_device": int(self.state_bytes_per_device),
                "leaves": [l.as_json() for l in self.leaves],
                "detail": dict(self.detail)}


def state_memory_model(state, rule: str, n_devices: int, shard_factor,
                       detail: Optional[dict] = None,
                       specs: Optional[dict] = None) -> MemoryModel:
    """Build a :class:`MemoryModel` from a (possibly abstract) engine
    state pytree. ``shard_factor(path_str, leaf) -> int`` is the
    engine's own per-leaf sharding knowledge — the mesh extent the
    leaf's global shape is divided over (1 = replicated). ``specs``
    optionally maps each leaf path to the declared PartitionSpec the
    factor derives from (the engine's ShardingRecipe table — see
    parallel/recipe.py ``leaf_factors``); it rides every leaf into the
    preflight byte table and the residency goldens. Works on
    ``jax.eval_shape`` structs: only ``.shape``/``.dtype`` are read."""
    import jax

    from theanompi_tpu.parallel.mesh import spec_to_json

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        import numpy as _np

        n_elems = 1
        for d in shape:
            n_elems *= int(d)
        nbytes = int(n_elems * _np.dtype(dtype).itemsize)
        pstr = jax.tree_util.keystr(path)
        spec = (specs or {}).get(pstr)
        leaves.append(MemoryLeaf(
            path=pstr, dtype=str(dtype), shape=shape,
            global_bytes=nbytes,
            shard_factor=max(1, int(shard_factor(pstr, leaf))),
            spec=spec_to_json(spec) if spec is not None else None,
        ))
    return MemoryModel(rule=rule, n_devices=int(n_devices), leaves=leaves,
                       detail=dict(detail or {}))
