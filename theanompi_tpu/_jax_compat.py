"""Version bridge for the jax APIs this package pins.

The framework is written against the modern surface (``jax.shard_map``
with ``check_vma=``); older installs (< 0.6) ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the ``check_rep=`` knob.
Everything here maps one spelling onto the other and nothing else —
semantics are the classic ones either way, because every shard_map in
this tree pins the check OFF (parallel/strategies.py "check_vma pin &
migration plan"; the checked-mode paths are canary-gated and simply
stay unavailable on old jax).

Imported for its side effect from ``theanompi_tpu/__init__.py``.
"""

from __future__ import annotations

import jax


def _install() -> None:
    if not hasattr(jax.lax, "pcast"):
        # pcast exists only in the vma type system (newer jax); it is
        # the identity on VALUES — on old jax there is no varying-axes
        # typing to convert, so the identity IS the bridge
        jax.lax.pcast = lambda x, axis_name, to: x
    if hasattr(jax, "shard_map"):
        return  # modern jax: nothing else to bridge
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known jax lacks both
        return

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            # old jax predates the vma type system; check_rep is the
            # closest knob (False = the classic semantics this tree pins)
            kw.setdefault("check_rep", bool(check_vma))
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

    jax.shard_map = shard_map


_install()
