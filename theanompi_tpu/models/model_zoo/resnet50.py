"""ResNet-50 — BASELINE config #4 (EASGD, 1 center + 16 workers) and the
second headline benchmark model (images/sec + 90% scaling efficiency).

Reference: ``models/lasagne_model_zoo/resnet50.py`` — ``ResNet50`` with
residual-block builders (SURVEY.md §2.1). He et al. 2015 architecture:
7x7/2 stem, four stages of bottleneck blocks [3,4,6,3] at widths
256/512/1024/2048, post-activation BN, projection shortcuts on stage
entry. Stride placement follows the v1.5 convention (stride on the 3x3)
— the variant every modern throughput baseline quotes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.nn import init as initializers
from theanompi_tpu.nn.layers import Layer

_he = initializers.he_normal()


class Bottleneck(Layer):
    """1x1 -> 3x3(stride) -> 1x1 with BN after each conv; relu after the
    residual add (post-activation v1 form, as the lasagne zoo built it)."""

    def __init__(self, in_c, width, out_c, stride=1, bn_axis=None, name="bneck"):
        self.name = name
        self.needs_proj = stride != 1 or in_c != out_c
        mk = lambda c, k, s, nm: nn.Conv(c, k, stride=s, padding="SAME", use_bias=False, w_init=_he, name=nm)
        self.conv1, self.bn1 = mk(width, 1, 1, "c1"), nn.BatchNorm(axis_name=bn_axis)
        self.conv2, self.bn2 = mk(width, 3, stride, "c2"), nn.BatchNorm(axis_name=bn_axis)
        self.conv3, self.bn3 = mk(out_c, 1, 1, "c3"), nn.BatchNorm(axis_name=bn_axis)
        if self.needs_proj:
            self.proj, self.bnp = mk(out_c, 1, stride, "proj"), nn.BatchNorm(axis_name=bn_axis)

    def init(self, key, in_shape):
        keys = jax.random.split(key, 4)
        params, state = {}, {}
        shape = in_shape
        for i, (conv, bn) in enumerate(
            [(self.conv1, self.bn1), (self.conv2, self.bn2), (self.conv3, self.bn3)], 1
        ):
            p, _ = conv.init(keys[i - 1], shape)
            shape = conv.out_shape(shape)
            bp, bs = bn.init(keys[i - 1], shape)
            params[f"c{i}"], params[f"bn{i}"], state[f"bn{i}"] = p, bp, bs
        if self.needs_proj:
            p, _ = self.proj.init(keys[3], in_shape)
            pshape = self.proj.out_shape(in_shape)
            bp, bs = self.bnp.init(keys[3], pshape)
            params["proj"], params["bnp"], state["bnp"] = p, bp, bs
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        h = x
        for i, (conv, bn) in enumerate(
            [(self.conv1, self.bn1), (self.conv2, self.bn2), (self.conv3, self.bn3)], 1
        ):
            h, _ = conv.apply(params[f"c{i}"], {}, h)
            h, new_state[f"bn{i}"] = bn.apply(params[f"bn{i}"], state[f"bn{i}"], h, train=train)
            if i < 3:
                h = jax.nn.relu(h)
        if self.needs_proj:
            sc, _ = self.proj.apply(params["proj"], {}, x)
            sc, new_state["bnp"] = self.bnp.apply(params["bnp"], state["bnp"], sc, train=train)
        else:
            sc = x
        return jax.nn.relu(h + sc), new_state

    def out_shape(self, in_shape):
        s = self.conv2.out_shape(self.conv1.out_shape(in_shape))
        return self.conv3.out_shape(s)


_STAGES = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]


class ResNet50(Model):
    name = "resnet50"

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=256,
            n_epochs=90,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9, "weight_decay": 1e-4},
            schedule="step",
            sched_kwargs={"lr": 0.1, "boundaries": [30, 60, 80], "factor": 0.1},
            lr_unit="epoch",
            input_shape=(224, 224, 3),
            num_classes=1000,
            compute_dtype=jnp.bfloat16,
            dataset="imagenet",
        )

    def build(self):
        bn_axis = self.recipe.bn_axis_name
        layers: list[Layer] = [
            nn.Conv(64, 7, stride=2, padding="SAME", use_bias=False, w_init=_he, name="stem"),
            nn.BatchNorm(axis_name=bn_axis, name="stem_bn"),
            nn.Activation("relu"),
            nn.Pool(3, stride=2, padding=1, mode="max"),
        ]
        in_c = 64
        for si, (reps, width, out_c, stride) in enumerate(_STAGES, 2):
            for ri in range(reps):
                layers.append(
                    Bottleneck(
                        in_c, width, out_c,
                        stride=stride if ri == 0 else 1,
                        bn_axis=bn_axis,
                        name=f"res{si}{chr(97 + ri)}",
                    )
                )
                in_c = out_c
        layers += [
            nn.GlobalAvgPool(),
            nn.Dense(self.recipe.num_classes, name="fc1000"),
        ]
        return nn.Sequential(layers, name="resnet50")
