"""Wide Residual Network (WRN-28-10) on CIFAR-10.

Reference: ``models/lasagne_model_zoo/wrn.py`` — the single-worker BSP
smoke config, BASELINE config #1 (SURVEY.md §2.1, §6). Architecture per
Zagoruyko & Komodakis 2016: pre-activation residual blocks, 3 stages of
``(depth-4)/6`` blocks at widths ``16k/32k/64k``, strides 1/2/2.

Recipe (the standard WRN CIFAR-10 recipe the reference's lasagne port
used): batch 128, SGD momentum 0.9 (Nesterov), weight decay 5e-4,
LR 0.1 stepped x0.2 at epochs 60/120/160, 200 epochs, he-normal init.
"""

from __future__ import annotations

import jax

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.nn import init as initializers
from theanompi_tpu.nn.layers import Layer


class PreActBlock(Layer):
    """BN-ReLU-Conv3x3-(Dropout)-BN-ReLU-Conv3x3 + shortcut.

    The projection shortcut (1x1 conv on the pre-activated input) is used
    when shape changes, as in the WRN paper.
    """

    def __init__(self, in_c: int, out_c: int, stride: int = 1, dropout: float = 0.0,
                 bn_axis=None, name: str = "preact"):
        self.name = name
        self.needs_proj = stride != 1 or in_c != out_c
        he = initializers.he_normal()
        self.bn1 = nn.BatchNorm(axis_name=bn_axis)
        self.conv1 = nn.Conv(out_c, 3, stride=stride, padding="SAME", use_bias=False, w_init=he)
        self.dropout = nn.Dropout(dropout) if dropout > 0 else None
        self.bn2 = nn.BatchNorm(axis_name=bn_axis)
        self.conv2 = nn.Conv(out_c, 3, stride=1, padding="SAME", use_bias=False, w_init=he)
        self.proj = (
            nn.Conv(out_c, 1, stride=stride, padding="VALID", use_bias=False, w_init=he)
            if self.needs_proj
            else None
        )

    def init(self, key, in_shape):
        keys = jax.random.split(key, 3)
        params, state = {}, {}
        p, s = self.bn1.init(keys[0], in_shape)
        params["bn1"], state["bn1"] = p, s
        p, _ = self.conv1.init(keys[0], in_shape)
        params["conv1"] = p
        mid_shape = self.conv1.out_shape(in_shape)
        p, s = self.bn2.init(keys[1], mid_shape)
        params["bn2"], state["bn2"] = p, s
        p, _ = self.conv2.init(keys[1], mid_shape)
        params["conv2"] = p
        if self.proj is not None:
            p, _ = self.proj.init(keys[2], in_shape)
            params["proj"] = p
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        h, new_state["bn1"] = self.bn1.apply(params["bn1"], state["bn1"], x, train=train)
        h = jax.nn.relu(h)
        shortcut = x if self.proj is None else self.proj.apply(params["proj"], {}, h)[0]
        h, _ = self.conv1.apply(params["conv1"], {}, h)
        h, new_state["bn2"] = self.bn2.apply(params["bn2"], state["bn2"], h, train=train)
        h = jax.nn.relu(h)
        if self.dropout is not None and train:
            h, _ = self.dropout.apply({}, {}, h, train=train, rng=rng)
        h, _ = self.conv2.apply(params["conv2"], {}, h)
        return h + shortcut, new_state

    def out_shape(self, in_shape):
        return self.conv1.out_shape(in_shape)


class WRN(Model):
    """Wide-ResNet; ``depth``/``widen`` default to the reference's 28-10."""

    name = "wrn"
    depth = 28
    widen = 10
    dropout = 0.0

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=128,
            n_epochs=200,
            optimizer="nesterov",
            opt_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
            schedule="step",
            sched_kwargs={"lr": 0.1, "boundaries": [60, 120, 160], "factor": 0.2},
            lr_unit="epoch",
            input_shape=(32, 32, 3),
            num_classes=10,
            dataset="cifar10",
        )

    def build(self):
        assert (self.depth - 4) % 6 == 0, "WRN depth must be 6n+4"
        n = (self.depth - 4) // 6
        k = self.widen
        bn_axis = self.recipe.bn_axis_name
        he = initializers.he_normal()

        layers: list[Layer] = [
            nn.Conv(16, 3, padding="SAME", use_bias=False, w_init=he, name="stem")
        ]
        in_c = 16
        for stage, (width, stride) in enumerate(
            [(16 * k, 1), (32 * k, 2), (64 * k, 2)]
        ):
            for block in range(n):
                layers.append(
                    PreActBlock(
                        in_c,
                        width,
                        stride=stride if block == 0 else 1,
                        dropout=self.dropout,
                        bn_axis=bn_axis,
                        name=f"s{stage}b{block}",
                    )
                )
                in_c = width
        layers += [
            nn.BatchNorm(axis_name=bn_axis, name="final_bn"),
            nn.Activation("relu"),
            nn.GlobalAvgPool(),
            nn.Dense(self.recipe.num_classes, name="classifier"),
        ]
        return nn.Sequential(layers, name="wrn")


class WRN_16_4(WRN):
    """Smaller WRN for quick experiments and CI smoke tests."""

    name = "wrn_16_4"
    depth = 16
    widen = 4
