"""VGG16 — BASELINE config #5 (GoSGD, 64 workers).

Reference: ``models/lasagne_model_zoo/vgg.py`` — ``build_model_vgg``
(SURVEY.md §2.1). Simonyan & Zisserman 2014 configuration D: thirteen
3x3 convs in five blocks (64/128/256/512/512) with 2x2 max pools, three
FC layers (4096/4096/1000) with 0.5 dropout.
"""

from __future__ import annotations

import jax.numpy as jnp

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.nn import init as initializers

_BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]


class VGG16(Model):
    name = "vgg16"

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=256,
            n_epochs=74,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
            schedule="step",
            sched_kwargs={"lr": 0.01, "boundaries": [50, 65], "factor": 0.1},
            lr_unit="epoch",
            input_shape=(224, 224, 3),
            num_classes=1000,
            compute_dtype=jnp.bfloat16,
            dataset="imagenet",
        )

    def build(self):
        he = initializers.he_normal()
        layers = []
        for bi, (reps, width) in enumerate(_BLOCKS):
            for ri in range(reps):
                layers += [
                    nn.Conv(width, 3, padding="SAME", w_init=he, name=f"conv{bi + 1}_{ri + 1}"),
                    nn.Activation("relu"),
                ]
            layers.append(nn.Pool(2, stride=2, mode="max"))
        layers += [
            nn.Flatten(),
            nn.Dense(4096, w_init=initializers.gaussian(0.01), name="fc6"),
            nn.Activation("relu"),
            nn.Dropout(0.5),
            nn.Dense(4096, w_init=initializers.gaussian(0.01), name="fc7"),
            nn.Activation("relu"),
            nn.Dropout(0.5),
            nn.Dense(self.recipe.num_classes, w_init=initializers.gaussian(0.01), name="fc8"),
        ]
        return nn.Sequential(layers, name="vgg16")
