"""The reference's lasagne model zoo, rebuilt natively.

Reference: ``models/lasagne_model_zoo/{vgg.py,resnet50.py,wrn.py}``
(SURVEY.md §2.1). Nothing lasagne remains — these are idiomatic JAX
modules over :mod:`theanompi_tpu.nn` — but the zoo inventory and the
training recipes match the reference model-for-model.
"""
