"""Tiny MLP — the CPU-profileable smoke model.

The attribution profiler (``tmpi profile``, tools/profile.py) needs a
model whose compiled step is cheap enough to lower + run warm on a CPU
test mesh in seconds, yet has a multi-leaf param pytree so every
engine's exchange/codec paths carry real (if small) wire volume. The
convnets in the zoo compile for minutes on XLA:CPU; this two-hidden-
layer MLP compiles in well under a second and is the ``--model mlp``
default the acceptance path exercises (it is also a perfectly ordinary
contract model — ``tmpi BSP 8 theanompi_tpu.models.mlp MLP`` trains)."""

from __future__ import annotations

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe


class MLP(Model):
    name = "mlp"

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=64,
            n_epochs=5,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9},
            schedule="constant",
            sched_kwargs={"lr": 0.01},
            input_shape=(16, 16, 3),
            num_classes=10,
            dataset="synthetic",
        )

    def build(self):
        return nn.Sequential(
            [
                nn.Flatten(),
                nn.Dense(128, name="fc1"),
                nn.Activation("relu"),
                nn.Dense(64, name="fc2"),
                nn.Activation("relu"),
                nn.Dense(self.recipe.num_classes, name="out"),
            ],
            name="mlp",
        )
