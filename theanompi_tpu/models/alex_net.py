"""AlexNet — the reference's primary benchmark model.

Reference: ``models/alex_net.py`` — ``AlexNet`` with ``build_model``,
``compile_iter_fns``, ``train_iter``, ``val_iter``, ``adjust_hyperp``
(SURVEY.md §2.1; BASELINE config #2: ImageNet-1k, BSP allreduce,
8 workers, batch 128). Krizhevsky et al. 2012 architecture in the
one-tower grouped form the reference used (channel groups=2 on
conv2/4/5, LRN after conv1/conv2, overlapping 3x3/s2 max pools,
4096-wide FC with 0.5 dropout).

Recipe per the reference: batch 128, momentum 0.9, weight decay 5e-4,
LR 0.01 stepped /10 on a fixed epoch schedule, gaussian(0.01) conv init
with constant biases (1.0 on conv2/4/5 and FC per the paper). Compute
in bf16 on TPU (params fp32).
"""

from __future__ import annotations

import jax.numpy as jnp

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.nn import init as initializers


class AlexNet(Model):
    name = "alexnet"

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=128,
            n_epochs=70,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9, "weight_decay": 5e-4},
            schedule="step",
            sched_kwargs={"lr": 0.01, "boundaries": [30, 50, 65], "factor": 0.1},
            lr_unit="epoch",
            input_shape=(227, 227, 3),
            num_classes=1000,
            compute_dtype=jnp.bfloat16,
            dataset="imagenet",
        )

    def build(self):
        g = initializers.gaussian
        one = initializers.constant(1.0)
        ncls = self.recipe.num_classes
        return nn.Sequential(
            [
                nn.Conv(96, 11, stride=4, padding="VALID", w_init=g(0.01), name="conv1"),
                nn.Activation("relu"),
                nn.LRN(n=5, alpha=1e-4, beta=0.75, k=2.0),
                nn.Pool(3, stride=2, mode="max"),
                nn.Conv(256, 5, padding=2, groups=2, w_init=g(0.01), b_init=one, name="conv2"),
                nn.Activation("relu"),
                nn.LRN(n=5, alpha=1e-4, beta=0.75, k=2.0),
                nn.Pool(3, stride=2, mode="max"),
                nn.Conv(384, 3, padding=1, w_init=g(0.01), name="conv3"),
                nn.Activation("relu"),
                nn.Conv(384, 3, padding=1, groups=2, w_init=g(0.01), b_init=one, name="conv4"),
                nn.Activation("relu"),
                nn.Conv(256, 3, padding=1, groups=2, w_init=g(0.01), b_init=one, name="conv5"),
                nn.Activation("relu"),
                nn.Pool(3, stride=2, mode="max"),
                nn.Flatten(),
                nn.Dense(4096, w_init=g(0.005), b_init=one, name="fc6"),
                nn.Activation("relu"),
                nn.Dropout(0.5),
                nn.Dense(4096, w_init=g(0.005), b_init=one, name="fc7"),
                nn.Activation("relu"),
                nn.Dropout(0.5),
                nn.Dense(ncls, w_init=g(0.01), name="fc8"),
            ],
            name="alexnet",
        )
