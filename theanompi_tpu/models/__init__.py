"""Model zoo.

TPU-native rebuild of the reference zoo (SURVEY.md §2.1): AlexNet,
GoogLeNet, cifar10 CNN (reference: ``models/{alex_net,googlenet,cifar10}.py``)
plus the lasagne-built models VGG16, ResNet-50, Wide-ResNet
(reference: ``models/lasagne_model_zoo/{vgg,resnet50,wrn}.py`` — here
``model_zoo/`` since nothing lasagne remains).

Every model owns its training recipe (batch size, LR schedule, optimizer,
augmentation) exactly as in the reference, where hyperparams lived inside
each model file and the framework never interpreted them (SURVEY.md §5.6).
"""

from theanompi_tpu.models.contract import Model, Recipe, softmax_cross_entropy  # noqa: F401


# short name -> (module path, class name); imported lazily so one missing
# model never breaks lookups of the others
MODEL_REGISTRY = {
    "cifar10": ("theanompi_tpu.models.cifar10", "Cifar10_model"),
    "wrn": ("theanompi_tpu.models.model_zoo.wrn", "WRN"),
    "wrn_16_4": ("theanompi_tpu.models.model_zoo.wrn", "WRN_16_4"),
    "alexnet": ("theanompi_tpu.models.alex_net", "AlexNet"),
    "googlenet": ("theanompi_tpu.models.googlenet", "GoogLeNet"),
    "vgg16": ("theanompi_tpu.models.model_zoo.vgg", "VGG16"),
    "resnet50": ("theanompi_tpu.models.model_zoo.resnet50", "ResNet50"),
    "transformer_lm": ("theanompi_tpu.models.lm", "TransformerLMModel"),
    "transformer_lm_136m": ("theanompi_tpu.models.lm", "TransformerLM_136M"),
    "moe_lm": ("theanompi_tpu.models.lm", "MoELMModel"),
}


def get_model(name: str) -> type:
    """Resolve a model class by zoo short name (used by
    ``launch.session.resolve_model`` for ``tmpi BSP 8 wrn WRN``-style
    invocations)."""
    import importlib

    try:
        modpath, clsname = MODEL_REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        ) from None
    return getattr(importlib.import_module(modpath), clsname)
