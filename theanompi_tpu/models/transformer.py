"""Sequence-parallel transformer LM — the long-context demonstrator.

BEYOND-PARITY EXTENSION (the reference is a 2016 CNN framework;
SURVEY.md §5.7). This module proves the framework's long-context story
end to end: a decoder-only transformer whose attention is
:func:`theanompi_tpu.ops.ring_attention.ring_attention`, trained with
the SEQUENCE dimension sharded over a named mesh axis — each device
holds T/n tokens of every example, K/V blocks stream around the ring,
activations never materialize the full sequence on one chip. The
training step is one SPMD program like every other rule here: params
replicated, token shards local, gradients psum'd over the seq axis.

Deliberately small and self-contained (the image zoo's ``Model``
contract is classifier-shaped); the point is the PARALLELISM pattern:
``make_sp_train_step`` is to sequence parallelism what
``parallel/bsp.py`` is to data parallelism.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.ops.ring_attention import ring_attention

PyTree = Any

SEQ_AXIS = "seq"


class TransformerLM(NamedTuple):
    """Architecture config (params live in a plain dict pytree)."""

    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_len: int = 1024

    def init(self, key: jax.Array) -> PyTree:
        ks = jax.random.split(key, 3 + 4 * self.n_layers)
        d, h = self.d_model, self.d_ff
        s = 0.02
        params = {
            "tok_emb": s * jax.random.normal(ks[0], (self.vocab, d)),
            "pos_emb": s * jax.random.normal(ks[1], (self.max_len, d)),
            "head": s * jax.random.normal(ks[2], (d, self.vocab)),
            "blocks": [],
        }
        for i in range(self.n_layers):
            k0, k1, k2, k3 = ks[3 + 4 * i : 7 + 4 * i]
            params["blocks"].append(
                {
                    "qkv": s * jax.random.normal(k0, (d, 3 * d)),
                    "proj": s * jax.random.normal(k1, (d, d)),
                    "mlp_in": s * jax.random.normal(k2, (d, h)),
                    "mlp_out": s * jax.random.normal(k3, (h, d)),
                    "ln1": jnp.ones((d,)),
                    "ln2": jnp.ones((d,)),
                }
            )
        return params

    def apply(
        self, params: PyTree, tokens: jax.Array, axis_name: str = SEQ_AXIS
    ) -> jax.Array:
        """``tokens [B, T_local] -> logits [B, T_local, V]``; must run
        inside ``shard_map`` with the sequence sharded over
        ``axis_name`` (positions are global via the axis index)."""
        B, T = tokens.shape
        rank = lax.axis_index(axis_name)
        pos = rank * T + jnp.arange(T)
        x = params["tok_emb"][tokens] + params["pos_emb"][pos][None]

        def rms(x, g):
            return x * lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * g

        nh = self.n_heads
        hd = self.d_model // nh
        for blk in params["blocks"]:
            hin = rms(x, blk["ln1"])
            qkv = hin @ blk["qkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, T, nh, hd)
            k = k.reshape(B, T, nh, hd)
            v = v.reshape(B, T, nh, hd)
            att = ring_attention(q, k, v, axis_name, causal=True)
            x = x + att.reshape(B, T, self.d_model) @ blk["proj"]
            hin = rms(x, blk["ln2"])
            x = x + jax.nn.gelu(hin @ blk["mlp_in"]) @ blk["mlp_out"]
        return x @ params["head"]

    def loss(
        self, params: PyTree, tokens: jax.Array, axis_name: str = SEQ_AXIS
    ) -> jax.Array:
        """Next-token cross-entropy over the GLOBAL sequence. The target
        of a shard's last position is the NEXT shard's first token —
        fetched with one backward ppermute; the final global position
        has no target and is masked. Returns the global mean loss
        (identical on every device)."""
        n = lax.psum(1, axis_name)
        rank = lax.axis_index(axis_name)
        logits = self.apply(params, tokens, axis_name)
        # neighbor's first token (shard r receives from shard r+1)
        nxt = lax.ppermute(
            tokens[:, 0], axis_name, [((i + 1) % n, i) for i in range(n)]
        )
        targets = jnp.concatenate([tokens[:, 1:], nxt[:, None]], axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        is_last_shard = rank == n - 1
        T = tokens.shape[1]
        valid = jnp.where(
            is_last_shard & (jnp.arange(T) == T - 1)[None, :], 0.0, 1.0
        ) * jnp.ones_like(nll)
        # global mean over valid positions
        total = lax.psum(jnp.sum(nll * valid), axis_name)
        count = lax.psum(jnp.sum(valid), axis_name)
        return total / count


def make_sp_train_step(model: TransformerLM, mesh: Mesh, lr: float = 1e-2):
    """Jitted sequence-parallel SGD step ``(params, tokens) -> (params,
    loss)``: params replicated, tokens ``[B, T]`` sharded over the seq
    axis, gradients psum'd over it (each shard contributes its tokens'
    cotangents — the sum IS the global-loss gradient)."""

    def sharded(params, tokens):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens)
        grads = lax.psum(grads, SEQ_AXIS)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return jax.jit(
        jax.shard_map(
            sharded,
            mesh=mesh,
            in_specs=(P(), P(None, SEQ_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
