"""N-D parallel transformer LM — the long-context / multi-axis demonstrator.

BEYOND-PARITY EXTENSION (the reference is a 2016 CNN framework;
SURVEY.md §5.7). This module proves the framework's named-mesh design
carries every classic parallelism axis, composably, in ONE SPMD program:

- **SP** (sequence/context): tokens sharded over a ``seq`` axis; attention
  is :func:`~theanompi_tpu.ops.ring_attention.ring_attention` (K/V ring)
  or :func:`~theanompi_tpu.ops.ring_attention.ulysses_attention`
  (head<->sequence all-to-all) — activations never materialize the full
  sequence on one chip.
- **TP** (tensor/model, Megatron-style): attention heads and FFN hidden
  units column/row-sharded over a ``model`` axis, with ONE psum after the
  attention projection and one after the FFN per block; the vocabulary
  head is vocab-sharded with a distributed softmax cross-entropy (max and
  normalizer psum'd over the axis) so full logits never exist anywhere.
- **DP**: batch sharded over ``data``; gradients psum'd — exactly
  parallel/bsp.py's rule, composed with the above.

``make_nd_train_step`` builds the train step for any subset of
``(dp, tp, sp)`` axes on one mesh; ``make_sp_train_step`` is the
seq-only convenience used by the long-context tests. Pipeline (``pipe``)
and expert (``expert``) axes live in :mod:`theanompi_tpu.parallel.pipeline`
and :mod:`theanompi_tpu.ops.moe`, reusing this model's blocks.

Deliberately small and self-contained (the image zoo's ``Model``
contract is classifier-shaped); the point is the PARALLELISM patterns.
"""

from __future__ import annotations

import functools
import math
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.ops.pallas_attention import flash_attention, ring_flash_attention
from theanompi_tpu.ops.ring_attention import (
    full_attention_reference,
    ring_attention,
    ulysses_attention,
)

PyTree = Any

SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def _rms(x, g):
    # statistics in fp32 even when x is bf16 (the normalizer is a
    # variance sweep — bf16's 8-bit mantissa visibly degrades it);
    # output returns to x's compute dtype for the next matmul
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6) * g
    return y.astype(x.dtype)


def cast_block_params(blk: dict, dtype) -> dict:
    """Mixed-precision cast for one block's param dict: matmul weights
    to the compute ``dtype`` (XLA fuses the cast into the MXU op; AD
    accumulates their grads back in fp32), norm gains left fp32 — they
    are consumed inside :func:`_rms`'s fp32 statistics path. No-op for
    fp32 compute. Works for dense and MoE blocks (any non-``ln*`` leaf
    is a matmul operand)."""
    if dtype == jnp.float32:
        return blk
    # 'gate' (MoE router) also stays fp32: routing is an argmax over its
    # logits and the d x E matmul is negligible next to the experts
    skip = ("ln1", "ln2", "gate")
    return {k: (v if k in skip else v.astype(dtype)) for k, v in blk.items()}


def attention_block(blk, x, attn: str, sp_axis: Optional[str]):
    """Pre-norm attention sub-block shared by the dense and MoE LMs:
    qkv projection (TP-native ``[d, 3, H, hd]`` layout), causal
    (ring | ulysses | flash | local full) attention, output projection.
    Returns the residual delta BEFORE any tp-axis psum (the caller owns
    that)."""
    hin = _rms(x, blk["ln1"])
    qkv = jnp.einsum("btd,dchk->btchk", hin, blk["qkv"])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, T, H_local, hd]
    if sp_axis is not None:
        if attn == "flash":
            raise ValueError(
                "attn='flash' is the fused LOCAL kernel; under sequence "
                "parallelism pick attn='ring_flash' (K/V rotation, each "
                "hop folded by the fused kernel) or attn='ulysses_flash' "
                "(all-to-all with the fused local step) — 'ring'/'ulysses' "
                "are their unfused variants"
            )
        sp_attn = {
            "ring": ring_attention,
            "ring_flash": ring_flash_attention,
            "ulysses": ulysses_attention,
            "ulysses_flash": functools.partial(
                ulysses_attention, local_fn=flash_attention
            ),
        }[attn]
        att = sp_attn(q, k, v, sp_axis, causal=True)
    elif attn in ("flash", "ulysses_flash", "ring_flash"):
        # no SP axis: both SP schemes degenerate to their local step —
        # the fused kernel
        att = flash_attention(q, k, v, causal=True)
    else:
        att = full_attention_reference(q, k, v, causal=True)
    return jnp.einsum("bthk,hkd->btd", att, blk["proj"])


def global_positions(sp_axis: Optional[str], T: int) -> jax.Array:
    """Global position ids for a (possibly sequence-sharded) window of
    ``T`` local positions — THE shard-offset rule, shared by the dense,
    MoE, and pipeline forwards (changing position handling changes all
    three at once)."""
    if sp_axis is not None:
        return lax.axis_index(sp_axis) * T + jnp.arange(T)
    return jnp.arange(T)


def next_token_loss(tokens, sp_axis: Optional[str], nll_fn):
    """Next-token objective plumbing shared by the dense and MoE LMs:
    builds the target sequence (the target of a shard's last position is
    the NEXT shard's first token, fetched with one backward ppermute),
    masks the final global position (no target), and reduces to the mean
    over this device's batch rows x the GLOBAL sequence. ``nll_fn(targets)
    -> [B, T]`` supplies the per-position negative log-likelihood."""
    B, T = tokens.shape
    if sp_axis is not None:
        n = lax.psum(1, sp_axis)
        rank = lax.axis_index(sp_axis)
        nxt = lax.ppermute(
            tokens[:, 0], sp_axis, [((i + 1) % n, i) for i in range(n)]
        )
        targets = jnp.concatenate([tokens[:, 1:], nxt[:, None]], axis=1)
        last_shard = rank == n - 1
    else:
        targets = jnp.concatenate(
            [tokens[:, 1:], tokens[:, :1]], axis=1
        )  # wrapped value is masked out below
        last_shard = True
    valid = jnp.where(
        last_shard & (jnp.arange(T) == T - 1)[None, :], 0.0, 1.0
    ) * jnp.ones((B, T))
    nll = nll_fn(targets)
    total = jnp.sum(nll * valid)
    count = jnp.sum(valid)
    if sp_axis is not None:
        total = lax.psum(total, sp_axis)
        count = lax.psum(count, sp_axis)
    return total / count


def chunked_nll(x, head, chunk: int, dtype):
    """Per-position NLL computed per sequence CHUNK: each chunk's
    ``[B, C, V]`` logits are built (head matmul), reduced to the
    logsumexp-form NLL, and — via ``jax.checkpoint`` on the chunk body —
    DISCARDED; the backward recomputes them chunk by chunk. Peak memory
    for the loss drops from O(T x V) to O(chunk x V) in both passes
    (at T=16k x 32k-vocab that is the difference between 2 x 2.1 GB
    fp32 and 2 x 132 MB at chunk=1024). The math is exactly
    :func:`softmax_nll` on the full logits — pinned by an equality
    test."""

    def nll_fn(targets):
        B, T = targets.shape
        if T % chunk:
            raise ValueError(
                f"loss_chunk={chunk} must divide the local sequence "
                f"length {T}"
            )
        nC = T // chunk
        xc = x.reshape(B, nC, chunk, x.shape[-1]).swapaxes(0, 1)
        tc = targets.reshape(B, nC, chunk).swapaxes(0, 1)
        hd = head.astype(dtype)

        @jax.checkpoint
        def body(carry, inp):
            xb, tb = inp  # [B, C, d], [B, C]
            lf = (xb @ hd).astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lf, axis=-1)
            tl = jnp.take_along_axis(lf, tb[..., None], axis=-1)[..., 0]
            return carry, lse - tl

        _, nll = lax.scan(body, 0.0, (xc, tc))
        return nll.swapaxes(0, 1).reshape(B, T)

    return nll_fn


def softmax_nll(logits):
    """Standard per-position NLL from full (unsharded) logits, computed
    as ``logsumexp(logits) - logits[target]`` in fp32 regardless of the
    compute dtype (softmax statistics are the one place bf16 rounding
    visibly moves the loss). The logsumexp form skips materializing the
    full [B, T, V] log-probability tensor the naive
    ``log_softmax``-then-gather does — measured +6% tokens/s on the
    136M/32k-vocab config on v5e; the gradient (softmax - onehot) is
    identical."""

    def nll_fn(targets):
        lf = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        tl = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
        return lse - tl

    return nll_fn


class TransformerLM(NamedTuple):
    """Architecture config (params live in a plain dict pytree).

    ``attn`` picks the attention scheme: ``"ring"`` (K/V rotation,
    O(T/n) memory under SP; plain full attention without an SP axis),
    ``"ring_flash"`` (same ring, each hop folded by the fused Pallas
    flash kernel — no per-hop score materialization either),
    ``"ulysses"`` (head<->sequence all-to-all; needs ``n_heads``
    divisible by the seq-axis size), ``"ulysses_flash"`` (same, with
    the local step fused via the Pallas flash kernel), or ``"flash"``
    (single-device / DP-TP-only: the fused Pallas kernel,
    ops/pallas_attention.py).
    ``remat=True`` checkpoints each block (jax.checkpoint): backward
    recomputes block activations instead of storing them — combine with
    the seq axis for long-context training beyond HBM.

    Param layout is TP-native: ``qkv`` is ``[d, 3, H, hd]`` and ``proj``
    ``[H, hd, d]`` so sharding their head dim over the ``model`` axis is
    a plain PartitionSpec (no resharding); the FFN shards ``d_ff``; the
    head shards the vocab."""

    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_len: int = 1024
    attn: str = "ring"
    remat: bool = False
    # compute dtype: params are STORED fp32; activations and matmul
    # weights are cast to this at use (cast_block_params), softmax /
    # norm statistics stay fp32. bfloat16 doubles MXU throughput on TPU.
    dtype: Any = jnp.float32
    # chunked loss: apply head + CE per sequence chunk of this many
    # positions (rematerialized — backward recomputes each chunk's
    # logits), so the full [B, T, V] logits NEVER materialize. At
    # T=16384 x 32k vocab the logits + their softmax cotangent are
    # 2 x 2.1 GB fp32 — the dominant long-context memory after remat.
    # None = whole-sequence logits (short-T default); ignored under
    # tp_axis (the vocab-sharded CE already avoids full logits).
    loss_chunk: Optional[int] = None

    def init(self, key: jax.Array) -> PyTree:
        ks = jax.random.split(key, 3 + 4 * self.n_layers)
        d, h = self.d_model, self.d_ff
        nh, hd = self.n_heads, self.d_model // self.n_heads
        s = 0.02
        params = {
            "tok_emb": s * jax.random.normal(ks[0], (self.vocab, d)),
            "pos_emb": s * jax.random.normal(ks[1], (self.max_len, d)),
            "head": s * jax.random.normal(ks[2], (d, self.vocab)),
            "blocks": [],
        }
        for i in range(self.n_layers):
            k0, k1, k2, k3 = ks[3 + 4 * i : 7 + 4 * i]
            params["blocks"].append(
                {
                    "qkv": s * jax.random.normal(k0, (d, 3, nh, hd)),
                    "proj": s * jax.random.normal(k1, (nh, hd, d)),
                    "mlp_in": s * jax.random.normal(k2, (d, h)),
                    "mlp_out": s * jax.random.normal(k3, (h, d)),
                    "ln1": jnp.ones((d,)),
                    "ln2": jnp.ones((d,)),
                }
            )
        return params

    # -- parallel forward/loss ------------------------------------------

    def forward(
        self,
        params: PyTree,
        tokens: jax.Array,  # [B_local, T_local]
        *,
        sp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
    ) -> jax.Array:
        """``tokens -> logits [B_local, T_local, V_local]``.

        Runs inside ``shard_map``. With ``sp_axis``, the sequence dim is
        sharded over it (global positions come from the axis index); with
        ``tp_axis``, ``params`` leaves arrive pre-sharded per
        :meth:`tp_param_specs` and the returned logits are sharded over
        the vocab (use :meth:`loss` for the distributed cross-entropy).
        """
        return self.forward_hidden(
            params, tokens, sp_axis=sp_axis, tp_axis=tp_axis
        ) @ params["head"].astype(self.dtype)

    def forward_hidden(
        self,
        params: PyTree,
        tokens: jax.Array,
        *,
        sp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
    ) -> jax.Array:
        """:meth:`forward` without the vocabulary head: ``tokens ->
        hidden [B, T, d]`` — the hook for the chunked loss
        (:func:`chunked_nll`), which applies head + cross-entropy per
        sequence chunk so the full ``[B, T, V]`` logits never
        materialize."""
        B, T = tokens.shape
        pos = global_positions(sp_axis, T)
        # cast AFTER the gathers (cheaper than casting the [V, d] table)
        x = (params["tok_emb"][tokens] + params["pos_emb"][pos][None]).astype(
            self.dtype
        )

        def block(x, blk):
            blk = cast_block_params(blk, self.dtype)
            delta = attention_block(blk, x, self.attn, sp_axis)
            if tp_axis is not None:
                delta = lax.psum(delta, tp_axis)  # row-parallel proj
            x = x + delta
            hin = _rms(x, blk["ln2"])
            delta = jax.nn.gelu(hin @ blk["mlp_in"]) @ blk["mlp_out"]
            if tp_axis is not None:
                delta = lax.psum(delta, tp_axis)  # row-parallel mlp_out
            return x + delta

        if self.remat:
            # rematerialize per block: backward recomputes the block's
            # activations (incl. its collectives) instead of keeping
            # them — O(sqrt-ish) activation memory for long sequences,
            # the standard jax.checkpoint trade of FLOPs for HBM
            block = jax.checkpoint(block)
        for blk in params["blocks"]:
            x = block(x, blk)
        return x

    def loss(
        self,
        params: PyTree,
        tokens: jax.Array,
        axis_name: Optional[str] = SEQ_AXIS,
        *,
        tp_axis: Optional[str] = None,
    ) -> jax.Array:
        """Next-token cross-entropy over the GLOBAL sequence.

        With ``axis_name`` (the seq axis): the target of a shard's last
        position is the NEXT shard's first token — fetched with one
        backward ppermute; the final global position has no target and
        is masked. With ``tp_axis``: logits arrive vocab-sharded and the
        log-softmax runs distributed (pmax/psum over the axis) — full
        logits never materialize. Returns the mean loss over this
        device's batch rows x the global sequence (identical on every
        sp/tp peer)."""
        sp_axis = axis_name
        if self.loss_chunk and tp_axis is None:
            x = self.forward_hidden(params, tokens, sp_axis=sp_axis)
            nll_fn = chunked_nll(
                x, params["head"], self.loss_chunk, self.dtype
            )
            return next_token_loss(tokens, sp_axis, nll_fn)
        logits = self.forward(params, tokens, sp_axis=sp_axis, tp_axis=tp_axis)
        return next_token_loss(tokens, sp_axis, pick_nll(logits, tp_axis))

    # -- TP sharding spec ------------------------------------------------

    def tp_param_specs(self, tp_axis: str = MODEL_AXIS) -> PyTree:
        """PartitionSpec pytree for Megatron-style tensor parallelism:
        attention heads column-sharded in ``qkv`` / row-sharded in
        ``proj``, FFN hidden col/row-sharded, vocab head col-sharded;
        embeddings and layernorms replicated."""
        blk = {
            "qkv": P(None, None, tp_axis, None),   # heads
            "proj": P(tp_axis, None, None),        # heads (row side)
            "mlp_in": P(None, tp_axis),            # d_ff columns
            "mlp_out": P(tp_axis, None),           # d_ff rows
            "ln1": P(),
            "ln2": P(),
        }
        return {
            "tok_emb": P(),
            "pos_emb": P(),
            "head": P(None, tp_axis),              # vocab columns
            "blocks": [blk] * self.n_layers,
        }

    # -- paged-KV incremental decode (serve/decode subsystem) ------------

    def prefill_cache(self, params, tokens, pages, k_pool, v_pool, *,
                      page_size: int):
        """See :func:`paged_prefill` — dense-FFN binding."""
        return paged_prefill(
            self, params, tokens, pages, k_pool, v_pool, page_size
        )

    def decode_step(self, params, k_pool, v_pool, page_tables, seq_lens,
                    last_tokens, active, temperature, key, *,
                    page_size: int):
        """See :func:`paged_decode_step` — dense-FFN binding."""
        return paged_decode_step(
            self, params, k_pool, v_pool, page_tables, seq_lens,
            last_tokens, active, temperature, key, page_size
        )


# -- paged-KV incremental decode ----------------------------------------
#
# The serving-side counterpart of the training forward above
# (serve/decode: continuous batching over a paged KV-cache). Two
# programs, compiled ONCE each for fixed shapes:
#   * paged_prefill — one padded prompt per call, one static bucket
#     length per compiled program; writes per-layer K/V pages.
#   * paged_decode_step — ONE token per active batch slot, every slot
#     every iteration; reads the cache through per-slot page tables,
#     writes the current position's K/V, samples the next token.
# Both take an ``ffn(blk, hin) -> delta`` hook so the MoE LM
# (models/moe.py) reuses the attention/cache plumbing unchanged.


def dense_ffn(blk, hin):
    """The dense block's FFN residual delta (shared with the training
    forward's MLP; ``blk`` arrives already cast)."""
    return jax.nn.gelu(hin @ blk["mlp_in"]) @ blk["mlp_out"]


def paged_prefill(arch, params, tokens, pages, k_pool, v_pool,
                  page_size: int, ffn=dense_ffn):
    """Cache one prompt's per-layer K/V into the paged pools.

    ``tokens`` is ONE padded prompt ``[T_b] int32`` (``T_b`` a static
    bucket length, a multiple of ``page_size``), ``pages
    [T_b/page_size] int32`` routes each page-worth of positions to its
    physical page (the scratch index for the padding tail), and the
    pools are ``[L, n_pages+1, page_size, H, hd]``. Runs the full
    causal forward minus the vocabulary head, so every position below
    the true prompt length produces K/V bit-identical to the training
    forward — causality means the padding tail cannot contaminate them,
    and its garbage K/V land on read-masked offsets or the scratch
    page. Returns ``(k_pool, v_pool)`` updated.
    """
    T = tokens.shape[0]
    x = (params["tok_emb"][tokens] + params["pos_emb"][:T]).astype(arch.dtype)
    x = x[None]  # [1, T, d]
    for li, blk in enumerate(params["blocks"]):
        blk = cast_block_params(blk, arch.dtype)
        hin = _rms(x, blk["ln1"])
        qkv = jnp.einsum("btd,dchk->btchk", hin, blk["qkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [1, T, H, hd]
        kp = k[0].reshape(-1, page_size, k.shape[2], k.shape[3])
        vp = v[0].reshape(-1, page_size, v.shape[2], v.shape[3])
        k_pool = k_pool.at[li, pages].set(kp.astype(k_pool.dtype))
        v_pool = v_pool.at[li, pages].set(vp.astype(v_pool.dtype))
        att = full_attention_reference(q, k, v, causal=True)
        x = x + jnp.einsum("bthk,hkd->btd", att, blk["proj"])
        x = x + ffn(blk, _rms(x, blk["ln2"]))
    return k_pool, v_pool


def paged_decode_step(arch, params, k_pool, v_pool, page_tables, seq_lens,
                      last_tokens, active, temperature, key,
                      page_size: int, ffn=dense_ffn):
    """One continuous-batching decode iteration over ALL batch slots.

    Per slot ``s``: embed ``last_tokens[s]`` at position ``seq_lens[s]``,
    write its K/V at (page ``page_tables[s, pos//page_size]``, offset
    ``pos % page_size``) — inactive slots write to the scratch page —
    then attend over cached positions ``0..seq_lens[s]`` inclusive
    (gathered through the slot's page table, fp32 softmax, same
    ``1/sqrt(hd)`` scale as :func:`full_attention_reference`), and
    sample: greedy argmax where ``temperature[s] == 0``, else
    categorical on ``logits/temperature`` under ``key``. All shapes are
    static in ``(S, M)`` so ONE compiled program serves every iteration.

    Returns ``(next_tokens [S] int32, logits [S, V] fp32, k_pool,
    v_pool)``.
    """
    S, M = page_tables.shape
    scratch = k_pool.shape[1] - 1
    pos = jnp.clip(seq_lens, 0, params["pos_emb"].shape[0] - 1)
    x = (params["tok_emb"][last_tokens] + params["pos_emb"][pos]).astype(
        arch.dtype
    )
    pidx = jnp.clip(seq_lens // page_size, 0, M - 1)
    write_page = jnp.where(
        active, page_tables[jnp.arange(S), pidx], scratch
    )
    write_off = seq_lens % page_size
    for li, blk in enumerate(params["blocks"]):
        blk = cast_block_params(blk, arch.dtype)
        hin = _rms(x, blk["ln1"])
        qkv = jnp.einsum("sd,dchk->schk", hin, blk["qkv"])
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [S, H, hd]
        k_pool = k_pool.at[li, write_page, write_off].set(
            k.astype(k_pool.dtype)
        )
        v_pool = v_pool.at[li, write_page, write_off].set(
            v.astype(v_pool.dtype)
        )
        k_ctx = k_pool[li][page_tables].reshape(
            S, M * page_size, k.shape[1], k.shape[2]
        )
        v_ctx = v_pool[li][page_tables].reshape(
            S, M * page_size, v.shape[1], v.shape[2]
        )
        sc = 1.0 / math.sqrt(q.shape[-1])
        s_ = jnp.einsum(
            "shd,sthd->sht", q.astype(jnp.float32), k_ctx.astype(jnp.float32)
        ) * sc
        valid = jnp.arange(M * page_size)[None, :] <= seq_lens[:, None]
        s_ = jnp.where(valid[:, None, :], s_, -1e30)
        p = jax.nn.softmax(s_, axis=-1)
        att = jnp.einsum(
            "sht,sthd->shd", p, v_ctx.astype(jnp.float32)
        ).astype(x.dtype)
        x = x + jnp.einsum("shk,hkd->sd", att, blk["proj"])
        x = x + ffn(blk, _rms(x, blk["ln2"]))
    logits = (x @ params["head"].astype(arch.dtype)).astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, logits / safe_t).astype(jnp.int32)
    next_tokens = jnp.where(temperature > 0, sampled, greedy)
    return next_tokens, logits, k_pool, v_pool


def _vocab_sharded_nll(logits: jax.Array, targets: jax.Array, tp_axis: str):
    """-log softmax(target) with the vocab dim sharded over ``tp_axis``:
    the classic Megatron parallel cross-entropy (global max via pmax,
    normalizer via psum, target logit gathered on its owner shard).
    Statistics run in fp32 (logits may arrive bf16)."""
    logits = logits.astype(jnp.float32)
    V_local = logits.shape[-1]
    start = lax.axis_index(tp_axis) * V_local
    # stabilizer only — mathematically cancels in log z + m, so AD may
    # skip it (pmax also has no differentiation rule)
    m = lax.pmax(lax.stop_gradient(jnp.max(logits, axis=-1)), tp_axis)  # [B, T]
    z = lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), tp_axis)
    local_ids = targets - start
    in_range = (local_ids >= 0) & (local_ids < V_local)
    idx = jnp.clip(local_ids, 0, V_local - 1)
    tl = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
    tl = lax.psum(jnp.where(in_range, tl, 0.0), tp_axis)
    return jnp.log(z) + m - tl


def validate_tp_divisibility(model, tp_axis: str, ntp: int) -> None:
    """The Megatron sharding's divisibility contract, shared by every
    tp-capable setup (dense nd, MoE ep, pipeline): heads column/row
    split, FFN (or per-expert) hidden split, vocab head split."""
    if model.n_heads % ntp or model.d_ff % ntp or model.vocab % ntp:
        raise ValueError(
            f"the {tp_axis!r} axis size {ntp} must divide each of "
            f"n_heads/d_ff/vocab ({model.n_heads}/{model.d_ff}/"
            f"{model.vocab})"
        )


def pick_nll(logits, tp_axis: Optional[str]):
    """The per-position NLL function for (possibly vocab-sharded)
    logits — the dispatch shared by every tp-capable loss (dense LM,
    MoE, pipeline head): Megatron distributed CE when ``tp_axis`` is
    set, the logsumexp form otherwise."""
    if tp_axis is not None:
        return lambda t: _vocab_sharded_nll(logits, t, tp_axis)
    return softmax_nll(logits)


def validate_ulysses_heads(model, sp_axis, sizes, heads_local):
    """Friendly build-time error for the Ulysses all-to-all's head
    divisibility requirement (otherwise it surfaces as an opaque
    lax.all_to_all trace error deep inside the attention)."""
    if sp_axis and getattr(model, "attn", None) in ("ulysses", "ulysses_flash") and (
        heads_local % sizes[sp_axis]
    ):
        raise ValueError(
            f"ulysses attention needs local heads ({heads_local}) divisible "
            f"by the {sp_axis!r} axis size {sizes[sp_axis]}"
        )


def opt_state_specs(opt_template, param_specs):
    """PartitionSpec tree for an optimizer state: any sub-tree whose
    structure matches the params tree (accumulators built with
    zeros_like) inherits ``param_specs``; everything else (step
    counters, empty states like plain sgd's ``()``) replicates.
    ``opt_template`` may be abstract (from ``jax.eval_shape``)."""
    params_treedef = jax.tree_util.tree_structure(param_specs)

    def match(sub):
        if jax.tree_util.tree_structure(sub) == params_treedef:
            return param_specs
        if isinstance(sub, dict):
            return {k: match(v) for k, v in sub.items()}
        return jax.tree_util.tree_map(lambda _: P(), sub)

    return match(opt_template)


def build_spec_step(body, mesh, param_specs, tok_spec, lr, optimizer, init_fn,
                    donate: bool = False):
    """Shared plumbing for the spec-sharded train steps (nd/ep/pp):
    ``body(params, tokens) -> (loss, synced_grads)`` becomes a jitted
    shard_map step — ``(params, tokens) -> (params, loss)`` for plain
    SGD, or over ``(params, opt_state)`` when ``optimizer`` (registry
    name or Optimizer) is given. ``init_fn()`` supplies a params
    template for sizing the opt state (evaluated abstractly — nothing
    is materialized).

    ``donate`` (ISSUE 2 donation audit): when True the state argument's
    buffers are donated so a training loop threading state through the
    step holds ONE params(+opt) copy instead of two. Default False —
    these builders also serve the oracle tests and probes, which reuse
    the input state across calls (a donated input is deleted). The
    driver-facing engines (parallel/nd.py NDEngine) donate by default."""
    if optimizer is None:

        def sharded(params, tokens):
            loss, grads = body(params, tokens)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )
            return new_params, loss

        return jax.jit(
            jax.shard_map(
                sharded,
                mesh=mesh,
                in_specs=(param_specs, tok_spec),
                out_specs=(param_specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate else (),
        )

    from theanompi_tpu.ops.optimizers import apply_updates, get_optimizer

    opt = get_optimizer(optimizer) if isinstance(optimizer, str) else optimizer
    opt_template = jax.eval_shape(lambda: opt.init(init_fn()))
    opt_specs = opt_state_specs(opt_template, param_specs)

    def sharded_opt(state, tokens):
        params, opt_state = state
        loss, grads = body(params, tokens)
        updates, new_opt = opt.update(grads, opt_state, params, lr)
        return (apply_updates(params, updates), new_opt), loss

    return jax.jit(
        jax.shard_map(
            sharded_opt,
            mesh=mesh,
            in_specs=((param_specs, opt_specs), tok_spec),
            out_specs=((param_specs, opt_specs), P()),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )


def sync_grads_by_spec(grads, param_specs, axes, n_total):
    """The universal gradient-sync rule for collective-containing losses
    under ``check_vma=False`` (see make_nd_train_step's docstring): psum
    each leaf over every participating axis its spec does NOT shard it
    on, then divide by the product of all participating axis sizes."""

    def per_leaf(g, spec):
        sharded_on = set()
        for entry in spec:
            if isinstance(entry, (tuple, list)):
                sharded_on.update(entry)
            elif entry is not None:
                sharded_on.add(entry)
        for a in axes:
            if a not in sharded_on:
                g = lax.psum(g, a)
        return g / n_total

    return jax.tree_util.tree_map(per_leaf, grads, param_specs)


def make_sp_train_step(model: TransformerLM, mesh: Mesh, lr: float = 1e-2):
    """Jitted sequence-parallel SGD step ``(params, tokens) -> (params,
    loss)``: params replicated, tokens ``[B, T]`` sharded over the seq
    axis, gradients psum'd over it and divided by the axis size (see
    make_nd_train_step — the per-device backward already carries the
    device-sum objective, so psum/n is the true global-loss gradient;
    earlier revisions applied the raw psum, i.e. an n x larger step at
    the same lr)."""
    return make_nd_train_step(model, mesh, lr=lr, sp_axis=SEQ_AXIS)


def nd_spec_setup(
    model: TransformerLM,
    mesh: Mesh,
    dp_axis: Optional[str],
    tp_axis: Optional[str],
    sp_axis: Optional[str],
):
    """Shared mesh/shape validation + sharding-spec construction for the
    dense N-D step builders (:func:`make_nd_train_step` and the
    launchable ``parallel.nd.NDEngine``). Returns ``(axes, n_total,
    param_specs)``."""
    axes = [a for a in (dp_axis, tp_axis, sp_axis) if a is not None]
    if not axes:
        raise ValueError("need at least one of dp_axis/tp_axis/sp_axis")
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for a in axes:
        if a not in sizes:
            raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
    if tp_axis:
        validate_tp_divisibility(model, tp_axis, sizes[tp_axis])
    validate_ulysses_heads(
        model, sp_axis, sizes, model.n_heads // (sizes[tp_axis] if tp_axis else 1)
    )
    n_total = 1
    for a in axes:
        n_total *= sizes[a]
    param_specs = (
        model.tp_param_specs(tp_axis)
        if tp_axis
        else jax.tree_util.tree_map(
            lambda _: P(),
            jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0))),
        )
    )
    return axes, n_total, param_specs


def make_nd_train_step(
    model: TransformerLM,
    mesh: Mesh,
    lr: float = 1e-2,
    *,
    dp_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
    sp_axis: Optional[str] = None,
    optimizer=None,
):
    """Jitted train step over any subset of (data, model, seq) axes of
    one mesh.

    With ``optimizer=None`` (plain SGD): ``(params, tokens) ->
    (new_params, loss)``. With ``optimizer`` (a name from
    ops.optimizers.get_optimizer or an Optimizer): ``((params,
    opt_state), tokens) -> ((params, opt_state), loss)`` — build the
    initial opt_state with ``get_optimizer(name).init(params)``;
    accumulators shard exactly like their parameters.

    Sharding: tokens ``[B, T]`` are ``P(dp_axis, sp_axis)``; params
    follow :meth:`TransformerLM.tp_param_specs` when ``tp_axis`` is set,
    else fully replicated.

    Gradient sync. Under ``check_vma=False`` the transpose of a forward
    psum is itself a psum (measured on jax 0.9 — NOT the identity), so
    each device's AD yields exactly ``d(sum over devices of
    loss_device)/d theta_local``: cotangents really flow across the
    collectives. With loss_device replicated over tp/sp within each dp
    group and the global objective the mean over dp groups, the true
    gradient of every leaf is therefore

        psum(g) over every participating axis the leaf is NOT sharded
        over, divided by the product of ALL participating axis sizes

    (a leaf sharded on an axis already carries that axis's full
    contribution; summing its copies over the axes it is replicated on
    completes the total, and the division converts the device-sum
    objective to the mean). The dp-only case reduces to BSP's classic
    psum-mean.
    """
    axes, n_total, param_specs = nd_spec_setup(
        model, mesh, dp_axis, tp_axis, sp_axis
    )
    init_fn = lambda: model.init(jax.random.PRNGKey(0))  # noqa: E731

    def body(params, tokens):
        loss, grads = jax.value_and_grad(model.loss)(
            params, tokens, sp_axis, tp_axis=tp_axis
        )
        grads = sync_grads_by_spec(grads, param_specs, axes, n_total)
        if dp_axis is not None:
            loss = lax.pmean(loss, dp_axis)  # report the global batch mean
        return loss, grads

    return build_spec_step(
        body, mesh, param_specs, P(dp_axis, sp_axis), lr, optimizer, init_fn
    )
