"""Zoo-registered transformer LM models — the launchable face of the
N-D parallelism stack.

BEYOND-PARITY EXTENSION (SURVEY.md §5.7: the reference has no attention
anywhere). :class:`TransformerLMModel` wraps
:class:`theanompi_tpu.models.transformer.TransformerLM` in the standard
``Model`` contract, so the SAME drivers that run the CNN zoo run an LM:

- ``tmpi BSP 8 theanompi_tpu.models.lm TransformerLMModel`` — plain
  data-parallel LM training through BSPEngine (and EASGD/GoSGD work the
  same way: the sync rules never look inside the model).
- ``tmpi BSP 8 ... --tp 2 --sp 2`` — the CLI's mesh flags route to
  :class:`theanompi_tpu.parallel.nd.NDEngine`, which trains with
  Megatron tensor sharding, ring/Ulysses sequence parallelism, GPipe
  pipelining (``--pp``), or Switch-MoE expert parallelism (``--expert``,
  with :class:`MoELMModel`).

Token batches come from the ``lm_synthetic`` / ``lm_text`` datasets
(data/lm.py): "images" are token windows ``[B, T] int32`` and labels are
the same windows (next-token targets are computed in-model, shifted —
the target of position t is the token at t+1; the final position is
masked).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.models.transformer import (
    TransformerLM,
    next_token_loss,
    softmax_nll,
)


@dataclasses.dataclass
class LMRecipe(Recipe):
    """Recipe with the LM architecture knobs. ``input_shape`` is
    ``(seq_len,)`` and ``num_classes`` the vocabulary size (mirroring
    the image recipes so the driver's shape checks apply unchanged)."""

    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    # "ring" = exact full attention locally, ring K/V rotation under SP;
    # "flash"/"ring_flash"/"ulysses"/"ulysses_flash" per TransformerLM
    attn: str = "ring"
    remat: bool = False
    # chunked loss (transformer.py::chunked_nll): CE per sequence chunk,
    # full [B, T, V] logits never materialize — the long-context memory
    # knob alongside remat. None = whole-sequence logits.
    loss_chunk: int | None = None
    # MoE knobs (MoELMModel only)
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_weight: float = 0.01


class TransformerLMModel(Model):
    """Dense decoder-only LM under the zoo contract. ``self.arch`` is
    the functional :class:`TransformerLM`; the parallel engines
    (``NDEngine``) reach through to it for tp/sp/pp sharding, while the
    plain contract surface below serves the DP/EASGD/GoSGD paths."""

    name = "transformer_lm"
    is_lm = True
    is_moe = False
    # serve/decode contract: the incremental prefill/decode surface
    # below exists (DecodeEngine checks this flag at construction)
    supports_decode = True

    def __init__(self, recipe: LMRecipe | None = None):
        self.recipe = recipe or self.default_recipe()
        r = self.recipe
        self.arch = TransformerLM(
            vocab=r.num_classes,
            d_model=r.d_model,
            n_heads=r.n_heads,
            n_layers=r.n_layers,
            d_ff=r.d_ff,
            max_len=r.input_shape[0],
            attn=r.attn,
            remat=r.remat,
            dtype=r.compute_dtype,
            loss_chunk=r.loss_chunk,
        )

    @classmethod
    def default_recipe(cls) -> LMRecipe:
        return LMRecipe(
            batch_size=32,
            n_epochs=5,
            optimizer="adam",
            schedule="constant",
            sched_kwargs={"lr": 1e-3},
            lr_unit="step",
            input_shape=(128,),
            num_classes=64,
            dataset="lm_synthetic",
        )

    # -- contract surface (DP / async-rule path) ------------------------
    def init(self, key):
        return self.arch.init(key), {}

    def apply(self, params, state, tokens, *, train: bool = False, rng=None):
        del train, rng  # no dropout in this LM
        if self.recipe.loss_chunk:
            raise ValueError(
                "loss_chunk runs on the ND-engine path (arch.loss — "
                "tmpi --sp/--tp or the make_*_train_step builders); the "
                "classifier-contract path materializes the full logits "
                "this knob exists to avoid — unset loss_chunk here"
            )
        return self.arch.forward(params, tokens.astype(jnp.int32)), state

    def loss(self, logits, labels):
        # labels ARE the token window [B, T]; shifted targets in-model
        return next_token_loss(labels.astype(jnp.int32), None, softmax_nll(logits))

    def metrics(self, logits, labels) -> dict:
        labels = labels.astype(jnp.int32)
        preds = jnp.argmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        err = jnp.mean((preds != labels[:, 1:]).astype(jnp.float32))
        return {"error": err}

    # -- incremental decode surface (serve/decode DecodeEngine) ---------
    # Decode-mode apply, split at the prefill/decode program boundary the
    # paged KV-cache needs (one compiled program per prompt bucket, ONE
    # single-token program for every decode iteration). Both delegate to
    # the functional arch so the MoE subclass inherits them unchanged
    # (its arch binds the dense top-1 Switch FFN).

    def decode_prefill(self, params, tokens, pages, k_pool, v_pool, *,
                       page_size: int):
        """Cache one padded prompt's K/V pages; see
        ``transformer.paged_prefill``."""
        return self.arch.prefill_cache(
            params, tokens, pages, k_pool, v_pool, page_size=page_size
        )

    def decode_step(self, params, k_pool, v_pool, page_tables, seq_lens,
                    last_tokens, active, temperature, key, *,
                    page_size: int):
        """One continuous-batching decode iteration; see
        ``transformer.paged_decode_step``."""
        return self.arch.decode_step(
            params, k_pool, v_pool, page_tables, seq_lens, last_tokens,
            active, temperature, key, page_size=page_size
        )


class MoELMModel(TransformerLMModel):
    """Switch-MoE LM. Trains via ``--expert N`` (expert-parallel
    NDEngine path, which uses ``arch.loss`` including the load-balance
    auxiliary); the plain contract path is blocked because the aux loss
    cannot flow through ``loss(logits, labels)``."""

    name = "moe_lm"
    is_moe = True

    def __init__(self, recipe: LMRecipe | None = None):
        from theanompi_tpu.models.moe import MoETransformerLM

        self.recipe = recipe or self.default_recipe()
        r = self.recipe
        if r.loss_chunk:
            raise ValueError(
                "loss_chunk is not implemented for the MoE stack "
                "(dense TransformerLMModel only)"
            )
        self.arch = MoETransformerLM(
            vocab=r.num_classes,
            d_model=r.d_model,
            n_heads=r.n_heads,
            n_layers=r.n_layers,
            d_ff=r.d_ff,
            max_len=r.input_shape[0],
            n_experts=r.n_experts,
            capacity_factor=r.capacity_factor,
            aux_weight=r.aux_weight,
            attn=r.attn,
            dtype=r.compute_dtype,
        )

    def apply(self, params, state, tokens, *, train: bool = False, rng=None):
        raise ValueError(
            "MoELMModel trains expert-parallel only (tmpi BSP ... --expert N); "
            "for plain data parallelism use TransformerLMModel — the Switch "
            "load-balance auxiliary loss cannot flow through the classifier "
            "contract's loss(logits, labels)"
        )


class TransformerLM_136M(TransformerLMModel):
    """GPT-2-small-scale benchable config (~136M params): the
    single-chip throughput row for the beyond-parity LM stack
    (``python bench.py --model transformer_lm``). 12 layers x d=768,
    T=1024, 32k vocab, fused Pallas flash attention; bf16 compute
    (params stored fp32, matmuls/activations bf16 with fp32 softmax
    statistics — transformer.py::cast_block_params), so the reported
    MFU is measured against the bf16 peak the math actually runs at.
    Sized so TWO full f32 states (params + adam m/v) fit one v5e
    alongside the un-sharded 32k-vocab logits: the bench runner cannot
    donate its input state (it re-times from the same state), so a
    350M config OOMs."""

    name = "transformer_lm_136m"

    @classmethod
    def default_recipe(cls) -> LMRecipe:
        return LMRecipe(
            batch_size=8,
            n_epochs=1,
            optimizer="adam",
            schedule="constant",
            sched_kwargs={"lr": 3e-4},
            lr_unit="step",
            input_shape=(1024,),
            num_classes=32768,
            dataset="lm_synthetic",
            compute_dtype=jnp.bfloat16,
            d_model=768,
            n_heads=12,
            n_layers=12,
            d_ff=3072,
            attn="flash",
        )


class TransformerLM_350M(TransformerLMModel):
    """GPT-2-medium-scale benchable config (~360M params): 24 layers x
    d=1024, T=1024, 32k vocab, fused Pallas flash attention, bf16
    compute, per-block remat (activation memory, not weights, is what
    remains after donation). This size only fits one v5e because the
    bench runner DONATES and threads the train state through its timed
    trials for this row (``bench.py --model transformer_lm_350m``) —
    without donation two full f32 states (params + adam m/v ~ 4.3 GB)
    coexist and OOM, which is why the 136M row was the round-4 cap."""

    name = "transformer_lm_350m"

    @classmethod
    def default_recipe(cls) -> LMRecipe:
        return LMRecipe(
            batch_size=8,
            n_epochs=1,
            optimizer="adam",
            schedule="constant",
            sched_kwargs={"lr": 3e-4},
            lr_unit="step",
            input_shape=(1024,),
            num_classes=32768,
            dataset="lm_synthetic",
            compute_dtype=jnp.bfloat16,
            d_model=1024,
            n_heads=16,
            n_layers=24,
            d_ff=4096,
            attn="flash",
            remat=True,
        )
