"""GoogLeNet (Inception v1) — BASELINE config #3 (32-worker BSP).

Reference: ``models/googlenet.py`` — ``GoogLeNet`` with inception-module
builders (SURVEY.md §2.1). Szegedy et al. 2015 architecture: stem
(7x7/2 conv, LRN, 1x1+3x3 convs, LRN), nine inception modules with the
paper's channel table, two auxiliary classifiers during training
(weighted 0.3), global average pool + dropout 0.4 + linear.

Recipe per the reference: batch 32/worker scaled to the 32-worker BSP
config, momentum 0.9, weight decay 1e-4(ish), polynomial LR decay.

Single-chip performance ceiling (round-5 profile + layout probe,
experiments/results/googlenet_layout.json): the step is ~35% max-pool
sweeps (select-and-scatter backward 18% — already the measured optimum,
see ops/pallas_pool.py) + 46% conv/elementwise fusions; channels-major
trunk and concat-free inception were measured and REJECTED (XLA:TPU
layout assignment makes both moot), batch 512 adopted for the
single-chip bench row (+10% over 1024). The residual MFU gap vs the
big-conv models is the inception architecture's pool-heavy,
small-channel-conv structure itself, not a missing kernel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from theanompi_tpu import nn
from theanompi_tpu.models.contract import (
    Model,
    Recipe,
    classification_metrics,
    softmax_cross_entropy,
)
from theanompi_tpu.nn import init as initializers
from theanompi_tpu.nn.layers import Layer

_he = initializers.he_normal()


def _conv_relu(out_c, kernel, stride=1, padding="SAME", name="conv"):
    return [
        nn.Conv(out_c, kernel, stride=stride, padding=padding, w_init=_he, name=name),
        nn.Activation("relu"),
    ]


class Inception(Layer):
    """One inception module: 1x1 / 1x1-3x3 / 1x1-5x5 / pool-1x1 branches,
    channel-concatenated (reference: inception-module builders)."""

    def __init__(self, c1, c3r, c3, c5r, c5, cp, name="incept"):
        self.name = name
        self.c1, self.c3r, self.c5r = c1, c3r, c5r
        self.b1 = nn.Sequential(_conv_relu(c1, 1, name="b1"), name="b1")
        self.b3 = nn.Sequential(
            _conv_relu(c3r, 1, name="b3r") + _conv_relu(c3, 3, name="b3"), name="b3"
        )
        self.b5 = nn.Sequential(
            _conv_relu(c5r, 1, name="b5r") + _conv_relu(c5, 5, name="b5"), name="b5"
        )
        self.bp = nn.Sequential(
            [nn.Pool(3, stride=1, padding=1, mode="max")] + _conv_relu(cp, 1, name="bp"),
            name="bp",
        )
        self.branches = {"b1": self.b1, "b3": self.b3, "b5": self.b5, "bp": self.bp}
        # The fused-front apply slices each branch at the end of its
        # leading conv+relu pair; pin that structural assumption HERE so
        # a change to _conv_relu's composition fails at build time, not
        # by silently misaligning the tail slicing below.
        self._front_len = len(_conv_relu(1, 1))
        for bname in ("b1", "b3", "b5"):
            branch = self.branches[bname]
            if not isinstance(branch.layers[0], nn.Conv):
                raise AssertionError(
                    f"Inception fused front expects branch {bname!r} to "
                    f"start with a Conv; got {type(branch.layers[0]).__name__}"
                )

    def init(self, key, in_shape):
        params, state = {}, {}
        keys = jax.random.split(key, 4)
        for k, (bname, branch) in zip(keys, self.branches.items()):
            p, s = branch.init(k, in_shape)
            params[bname] = p
            if bname != "bp" and not {"w", "b"} <= set(p[branch._keys[0]]):
                raise AssertionError(
                    f"Inception fused front expects branch {bname!r}'s "
                    f"leading conv params to carry 'w'/'b'; got "
                    f"{sorted(p[branch._keys[0]])}"
                )
            if s and bname != "bp":
                # the fused apply below does not thread state through the
                # b1/b3/b5 tails — fail at build time, not silently, if a
                # stateful layer (BatchNorm) ever lands in those branches
                raise NotImplementedError(
                    f"Inception branch {bname!r} carries layer state "
                    f"({list(s)}); the fused-front apply only threads "
                    "state for the pool branch"
                )
            if s:
                state[bname] = s
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        # TPU MXU shaping: the b1 / b3-reduce / b5-reduce 1x1 convs all
        # read the SAME input, and their output channels are small
        # (16..208) — run them as ONE conv with c1+c3r+c5r outputs so
        # the matmul fills 128-wide MXU tiles instead of three
        # fragments, then split. Same math (concat of weights along
        # HWIO's O axis == concat of the three convs), same param tree.
        p1 = params["b1"][self.b1._keys[0]]
        p3r = params["b3"][self.b3._keys[0]]
        p5r = params["b5"][self.b5._keys[0]]
        w = jnp.concatenate([p1["w"], p3r["w"], p5r["w"]], axis=-1)
        b = jnp.concatenate([p1["b"], p3r["b"], p5r["b"]], axis=-1)
        y = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = jax.nn.relu(y + b.astype(y.dtype))
        y1 = y[..., : self.c1]
        y3r = y[..., self.c1 : self.c1 + self.c3r]
        y5r = y[..., self.c1 + self.c3r :]

        def _tail(branch, bname, h):
            # remaining layers of the branch (conv 3x3/5x5 + relu); the
            # split point is _conv_relu's OWN length, asserted in __init__
            fl = self._front_len
            for lname, layer in zip(branch._keys[fl:], branch.layers[fl:]):
                h, _ = layer.apply(
                    params[bname].get(lname, {}), {}, h, train=train, rng=rng
                )
            return h

        y3 = _tail(self.b3, "b3", y3r)
        y5 = _tail(self.b5, "b5", y5r)
        yp, _ = self.bp.apply(
            params["bp"], state.get("bp", {}), x, train=train, rng=rng
        )
        return jnp.concatenate([y1, y3, y5, yp], axis=-1), state

    def out_shape(self, in_shape):
        n, h, w, _ = in_shape
        c = sum(b.out_shape(in_shape)[-1] for b in self.branches.values())
        return (n, h, w, c)


class AuxHead(Layer):
    """Auxiliary classifier: 5x5/3 avg pool, 1x1 conv 128, FC 1024,
    dropout 0.7, linear (training-time only)."""

    def __init__(self, num_classes, name="aux"):
        self.name = name
        self.net = nn.Sequential(
            [
                nn.Pool(5, stride=3, mode="avg"),
                *_conv_relu(128, 1, name="proj"),
                nn.Flatten(),
                nn.Dense(1024, w_init=_he, name="fc"),
                nn.Activation("relu"),
                nn.Dropout(0.7),
                nn.Dense(num_classes, name="out"),
            ],
            name=name,
        )

    def init(self, key, in_shape):
        return self.net.init(key, in_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.net.apply(params, state, x, train=train, rng=rng)

    def out_shape(self, in_shape):
        return self.net.out_shape(in_shape)


# (name, module config or pool marker); channel table per the paper
_INCEPTION_TABLE = [
    ("3a", (64, 96, 128, 16, 32, 32)),
    ("3b", (128, 128, 192, 32, 96, 64)),
    ("pool3", None),
    ("4a", (192, 96, 208, 16, 48, 64)),
    ("4b", (160, 112, 224, 24, 64, 64)),  # aux1 taps the output of 4a
    ("4c", (128, 128, 256, 24, 64, 64)),
    ("4d", (112, 144, 288, 32, 64, 64)),
    ("4e", (256, 160, 320, 32, 128, 128)),  # aux2 taps the output of 4d
    ("pool4", None),
    ("5a", (256, 160, 320, 32, 128, 128)),
    ("5b", (384, 192, 384, 48, 128, 128)),
]


class GoogLeNet(Model):
    name = "googlenet"
    aux_weight = 0.3

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=1024,  # 32 workers x 32/worker, BASELINE config #3
            n_epochs=60,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9, "weight_decay": 1e-4},
            schedule="poly",
            sched_kwargs={"lr": 0.04, "total_steps": 60, "power": 0.5},
            lr_unit="epoch",
            input_shape=(224, 224, 3),
            num_classes=1000,
            compute_dtype=jnp.bfloat16,
            dataset="imagenet",
        )

    def build(self):
        ncls = self.recipe.num_classes
        self.stem = nn.Sequential(
            [
                *_conv_relu(64, 7, stride=2, name="conv1"),
                nn.Pool(3, stride=2, mode="max", padding=1),
                nn.LRN(),
                *_conv_relu(64, 1, name="conv2r"),
                *_conv_relu(192, 3, name="conv2"),
                nn.LRN(),
                nn.Pool(3, stride=2, mode="max", padding=1),
            ],
            name="stem",
        )
        self.blocks: list[tuple[str, Optional[Layer]]] = []
        for bname, cfg in _INCEPTION_TABLE:
            if cfg is None:
                self.blocks.append((bname, nn.Pool(3, stride=2, mode="max", padding=1)))
            else:
                self.blocks.append((bname, Inception(*cfg, name=bname)))
        self.head = nn.Sequential(
            [nn.GlobalAvgPool(), nn.Dropout(0.4), nn.Dense(ncls, name="out")],
            name="head",
        )
        self.aux1 = AuxHead(ncls, name="aux1")
        self.aux2 = AuxHead(ncls, name="aux2")
        return None  # custom apply below

    # -- custom init/apply (branching graph, aux heads) ---------------------
    def init(self, key):
        keys = iter(jax.random.split(key, len(self.blocks) + 4))
        params, state = {}, {}
        shape = self.input_shape
        p, s = self.stem.init(next(keys), shape)
        params["stem"], shape = p, self.stem.out_shape(shape)
        if s:
            state["stem"] = s
        aux_shapes = {}
        for bname, block in self.blocks:
            p, s = block.init(next(keys), shape)
            if p:
                params[bname] = p
            if s:
                state[bname] = s
            shape = block.out_shape(shape)
            if bname == "4a":
                aux_shapes["aux1"] = shape
            if bname == "4d":
                aux_shapes["aux2"] = shape
        p, s = self.head.init(next(keys), shape)
        params["head"] = p
        if s:
            state["head"] = s
        for aux_name, aux in (("aux1", self.aux1), ("aux2", self.aux2)):
            p, s = aux.init(next(keys), aux_shapes[aux_name])
            params[aux_name] = p
            if s:
                state[aux_name] = s
        return params, state

    def apply(self, params, state, images, *, train=False, rng=None):
        x = images.astype(self.recipe.compute_dtype)
        rngs = iter(
            jax.random.split(rng, len(self.blocks) + 4)
            if rng is not None
            else [None] * (len(self.blocks) + 4)
        )
        new_state = dict(state)
        x, s = self.stem.apply(params["stem"], state.get("stem", {}), x, train=train, rng=next(rngs))
        if s:
            new_state["stem"] = s
        aux_in = {}
        for bname, block in self.blocks:
            x, s = block.apply(
                params.get(bname, {}), state.get(bname, {}), x, train=train, rng=next(rngs)
            )
            if s:
                new_state[bname] = s
            if bname == "4a":
                aux_in["aux1"] = x
            if bname == "4d":
                aux_in["aux2"] = x
        logits, s = self.head.apply(params["head"], state.get("head", {}), x, train=train, rng=next(rngs))
        if s:
            new_state["head"] = s
        if not train:
            return logits, new_state
        aux_logits = []
        for aux_name, aux in (("aux1", self.aux1), ("aux2", self.aux2)):
            al, _ = aux.apply(
                params[aux_name], state.get(aux_name, {}), aux_in[aux_name],
                train=train, rng=next(rngs),
            )
            aux_logits.append(al)
        return (logits, *aux_logits), new_state

    def loss(self, logits, labels):
        if isinstance(logits, tuple):
            main, *aux = logits
            loss = softmax_cross_entropy(main, labels)
            for a in aux:
                loss = loss + self.aux_weight * softmax_cross_entropy(a, labels)
            return loss
        return softmax_cross_entropy(logits, labels)

    def metrics(self, logits, labels):
        if isinstance(logits, tuple):
            logits = logits[0]
        return classification_metrics(logits, labels)
