"""Benchable model registry: name -> (model class, single-chip global
batch). Shared by the root ``bench.py`` harness and
``tools/op_profile.py`` so the batch policy lives in one place.

Batch policy: AlexNet runs the reference workload's GLOBAL batch
(BASELINE config #2: 8 workers x 128 = 1024 — same SGD trajectory, and
a v5e only reaches full MXU utilization ~batch 1024); GoogLeNet runs
config #3's global batch 1024 — round 3 capped it at 256 because the
scanned multi-step program silently no-opped above that on the
tunneled dev backend, but the round-4 re-test (2026-07-30, jax 0.9.0:
8-step scan at batch 512 AND 1024, step counter 8/8, losses finite,
~4.2k img/s) shows the backend fault is gone; bench.py now carries a
hard executed-work assertion either way, and
tools/repro_tunnel_fault.py is the probe to re-run if it ever trips.
ResNet-50 uses config #4's batch 256; VGG16/WRN use the largest
power-of-two that fits one chip's HBM comfortably."""

from __future__ import annotations


def zoo_entry(name: str):
    """``(model_cls, single_chip_global_batch)`` for the benchable zoo
    (alexnet / googlenet / resnet50 / vgg16 / wrn)."""
    if name == "alexnet":
        from theanompi_tpu.models.alex_net import AlexNet

        return AlexNet, 1024
    if name == "googlenet":
        from theanompi_tpu.models.googlenet import GoogLeNet

        return GoogLeNet, 1024
    if name == "resnet50":
        from theanompi_tpu.models.model_zoo.resnet50 import ResNet50

        return ResNet50, 256
    if name == "vgg16":
        from theanompi_tpu.models.model_zoo.vgg import VGG16

        return VGG16, 128
    if name == "wrn":
        from theanompi_tpu.models.model_zoo.wrn import WRN

        return WRN, 1024
    if name == "transformer_lm":
        # beyond-parity LM row: ~136M params, T=1024, flash attention;
        # batch in SEQUENCES (bench reports tokens/sec alongside)
        from theanompi_tpu.models.lm import TransformerLM_136M

        return TransformerLM_136M, 8
    raise ValueError(f"unknown bench model {name!r}")
