"""Benchable model registry: name -> (model class, single-chip global
batch). Shared by the root ``bench.py`` harness and
``tools/op_profile.py`` so the batch policy lives in one place.

Batch policy: AlexNet runs the reference workload's GLOBAL batch
(BASELINE config #2: 8 workers x 128 = 1024 — same SGD trajectory, and
a v5e only reaches full MXU utilization ~batch 1024); GoogLeNet uses
512 — the round-5 batch sweep (experiments/results/
googlenet_layout.json: 5547/5630/5118 img/s at 256/512/1024, OOM at
2048) puts the single-chip knee at 512; its step is ~35% max-pool
sweeps that scale with batch, so past the knee extra batch only grows
the bandwidth-bound work. (Config #3's global 1024 is a 32-WORKER
batch — at pod scale each chip sees 32 rows; the single-chip row's
batch is a free throughput parameter, and the earlier 1024 reading
5134.9 img/s is retained in the committed sweep for comparison.)
ResNet-50 uses config #4's batch 256; VGG16/WRN use the largest
power-of-two that fits one chip's HBM comfortably."""

from __future__ import annotations


def zoo_entry(name: str):
    """``(model_cls, single_chip_global_batch)`` for the benchable zoo
    (alexnet / googlenet / resnet50 / vgg16 / wrn; ``mlp`` is the
    CPU-profileable smoke entry ``tmpi profile`` defaults exercise)."""
    if name == "mlp":
        from theanompi_tpu.models.mlp import MLP

        return MLP, 64
    if name == "alexnet":
        from theanompi_tpu.models.alex_net import AlexNet

        return AlexNet, 1024
    if name == "googlenet":
        from theanompi_tpu.models.googlenet import GoogLeNet

        return GoogLeNet, 512
    if name == "resnet50":
        from theanompi_tpu.models.model_zoo.resnet50 import ResNet50

        return ResNet50, 256
    if name == "vgg16":
        from theanompi_tpu.models.model_zoo.vgg import VGG16

        return VGG16, 128
    if name == "wrn":
        from theanompi_tpu.models.model_zoo.wrn import WRN

        return WRN, 1024
    if name == "transformer_lm":
        # beyond-parity LM row: ~136M params, T=1024, flash attention;
        # batch in SEQUENCES (bench reports tokens/sec alongside)
        from theanompi_tpu.models.lm import TransformerLM_136M

        return TransformerLM_136M, 8
    if name == "transformer_lm_350m":
        # GPT-2-medium scale (~370M params): needs the bench runner's
        # donate-and-thread timing path (two f32 states would OOM a v5e)
        from theanompi_tpu.models.lm import TransformerLM_350M

        return TransformerLM_350M, 8
    raise ValueError(f"unknown bench model {name!r}")


def infer_fn(entry):
    """The eval-mode apply closure — ``(params, model_state, x) ->
    logits`` with ``train=False``, no rng, fixed BatchNorm stats — the
    ONE definition of "run this model for inference", shared by the
    serving engine (serve/engine.py jits it per batch bucket) and the
    eval loops (train.py ``make_eval_step``), so the two paths cannot
    drift (e.g. one forgetting to freeze BN).

    ``entry`` is a constructed :class:`~theanompi_tpu.models.contract.
    Model` instance, or a bench-zoo short name (resolved through
    :func:`zoo_entry` under its default recipe)."""
    model = entry
    if isinstance(entry, str):
        model_cls, _ = zoo_entry(entry)
        model = model_cls()

    def fwd(params, model_state, x):
        logits, _ = model.apply(params, model_state, x, train=False)
        return logits

    return fwd
