"""The model contract: what every zoo model provides to the framework.

Reference contract (SURVEY.md §2.1, ``models/alex_net.py`` et al.):
``params``, ``data``, ``compile_iter_fns()``, ``train_iter()``,
``val_iter()``, ``adjust_hyperp(epoch)``, ``cleanup()``. That shape was
imperative — Theano shared variables mutated by compiled functions, LR
adjusted by host code between epochs.

The TPU-native contract is functional. A model is:

- a **Recipe** (declarative hyperparams the model owns — batch size,
  optimizer, LR schedule, epochs; the framework forwards, never
  interprets);
- pure ``init(key) -> (params, state)`` and
  ``apply(params, state, images, train, rng) -> (logits, state)``;
- ``loss(logits, labels) -> scalar`` and ``metrics(logits, labels)``.

``compile_iter_fns`` becomes "the framework jits a train step around
these", ``adjust_hyperp`` becomes the recipe's schedule evaluated inside
the step, and ``cleanup`` disappears (no process state to tear down).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

PyTree = Any


@dataclasses.dataclass
class Recipe:
    """Model-owned training recipe (reference: module-level hyperparam
    dicts in each model file; SURVEY.md §5.6 scope 2)."""

    batch_size: int = 128
    n_epochs: int = 10
    optimizer: str = "momentum"
    opt_kwargs: dict = dataclasses.field(default_factory=dict)
    schedule: str = "constant"
    sched_kwargs: dict = dataclasses.field(default_factory=lambda: {"lr": 0.01})
    lr_unit: str = "epoch"  # 'epoch' | 'step': unit of the schedule's input
    input_shape: tuple = (32, 32, 3)  # (H, W, C)
    num_classes: int = 10
    compute_dtype: Any = jnp.float32  # bfloat16 for the big ImageNet models
    # cross-replica BN over the data axis (None = per-replica stats)
    bn_axis_name: Optional[str] = None
    # dataset defaults; the launcher may override (e.g. synthetic for tests)
    dataset: str = "synthetic"
    val_batch_size: Optional[int] = None

    def replace(self, **kw) -> "Recipe":
        return dataclasses.replace(self, **kw)


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer labels, computed in fp32
    (logits may be bf16 on TPU)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def classification_metrics(logits: jax.Array, labels: jax.Array) -> dict:
    """top-1/top-5 error — the reference Recorder's val metrics
    (reference: ``lib/recorder.py`` val cost/error/top-5)."""
    logits = logits.astype(jnp.float32)
    top1 = jnp.argmax(logits, axis=-1)
    err1 = jnp.mean((top1 != labels).astype(jnp.float32))
    k = min(5, logits.shape[-1])
    topk = jax.lax.top_k(logits, k)[1]
    errk = 1.0 - jnp.mean(jnp.any(topk == labels[:, None], axis=-1).astype(jnp.float32))
    return {"error": err1, "top5_error": errk}


class Model:
    """Base model. Subclasses set ``recipe`` and build ``self.net`` (a
    ``nn.Layer``) in ``__init__``; everything else is inherited."""

    name = "model"
    recipe: Recipe

    def __init__(self, recipe: Optional[Recipe] = None):
        self.recipe = recipe or self.default_recipe()
        self.net = self.build()

    # -- subclass surface ---------------------------------------------------
    @classmethod
    def default_recipe(cls) -> Recipe:
        raise NotImplementedError

    def build(self):
        """Return the network as an ``nn.Layer`` (or override apply)."""
        raise NotImplementedError

    # -- framework surface --------------------------------------------------
    @property
    def input_shape(self) -> tuple:
        return (self.recipe.batch_size, *self.recipe.input_shape)

    def init(self, key) -> tuple[PyTree, PyTree]:
        return self.net.init(key, self.input_shape)

    def apply(self, params, state, images, *, train: bool = False, rng=None):
        images = images.astype(self.recipe.compute_dtype)
        return self.net.apply(params, state, images, train=train, rng=rng)

    def loss(self, logits, labels):
        return softmax_cross_entropy(logits, labels)

    def metrics(self, logits, labels) -> dict:
        return classification_metrics(logits, labels)

    def optimizer(self):
        from theanompi_tpu.ops import get_optimizer

        return get_optimizer(self.recipe.optimizer, **self.recipe.opt_kwargs)

    def schedule(self):
        from theanompi_tpu.ops import get_schedule

        return get_schedule(self.recipe.schedule, **self.recipe.sched_kwargs)
