"""Mixture-of-experts transformer LM — the expert-parallel demonstrator.

BEYOND-PARITY EXTENSION (SURVEY.md §2.3: EP absent from the 2016
reference; the named-mesh design note makes the axis additive). Same
decoder-only skeleton as :class:`theanompi_tpu.models.transformer.
TransformerLM`, with every block's dense FFN replaced by a Switch-style
top-1 MoE (:func:`theanompi_tpu.ops.moe.switch_moe`): experts sharded
over an ``expert`` mesh axis that doubles as the data axis (each device
routes its own tokens; dispatch rides two ``lax.all_to_all``s over ICI),
with the Switch load-balance auxiliary loss on global statistics.

``make_ep_train_step`` composes EP with data parallelism (``dp_axis``:
the batch dim shards over (data, expert) jointly — the standard MoE
layout, each dp group running its own all-to-all dispatch) and with
sequence parallelism (tokens additionally sharded over a ``seq`` axis,
ring or Ulysses attention) — one SPMD program over a (data, expert,
seq) mesh.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from theanompi_tpu.models.transformer import (
    _rms,
    attention_block,
    global_positions,
    build_spec_step,
    cast_block_params,
    next_token_loss,
    paged_decode_step,
    paged_prefill,
    pick_nll,
    sync_grads_by_spec,
    validate_tp_divisibility,
    validate_ulysses_heads,
)
from theanompi_tpu.ops.moe import switch_moe

PyTree = Any

EXPERT_AXIS = "expert"


class MoETransformerLM(NamedTuple):
    """Config. ``n_experts`` experts per block; with an ``expert`` axis
    of size n, each device owns ``n_experts/n`` of them. ``d_ff`` is the
    per-expert hidden width."""

    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_len: int = 1024
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    attn: str = "ring"
    # compute dtype (see transformer.py::cast_block_params): params
    # stored fp32, matmul weights cast at use; the router gate and all
    # softmax/norm statistics stay fp32
    dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> PyTree:
        ks = jax.random.split(key, 3 + 5 * self.n_layers)
        d, h, E = self.d_model, self.d_ff, self.n_experts
        nh, hd = self.n_heads, self.d_model // self.n_heads
        s = 0.02
        params = {
            "tok_emb": s * jax.random.normal(ks[0], (self.vocab, d)),
            "pos_emb": s * jax.random.normal(ks[1], (self.max_len, d)),
            "head": s * jax.random.normal(ks[2], (d, self.vocab)),
            "blocks": [],
        }
        for i in range(self.n_layers):
            k0, k1, k2, k3, k4 = ks[3 + 5 * i : 8 + 5 * i]
            params["blocks"].append(
                {
                    "qkv": s * jax.random.normal(k0, (d, 3, nh, hd)),
                    "proj": s * jax.random.normal(k1, (nh, hd, d)),
                    "gate": s * jax.random.normal(k2, (d, E)),
                    "expert_in": s * jax.random.normal(k3, (E, d, h)),
                    "expert_out": s * jax.random.normal(k4, (E, h, d)),
                    "ln1": jnp.ones((d,)),
                    "ln2": jnp.ones((d,)),
                }
            )
        return params

    def forward(
        self,
        params: PyTree,
        tokens: jax.Array,  # [B_local, T_local]
        *,
        sp_axis: Optional[str] = None,
        ep_axis: Optional[str] = None,
        dp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
    ) -> tuple[jax.Array, jax.Array, jax.Array]:
        """-> (logits, aux_loss_sum, dropped_frac_mean). Runs inside
        shard_map; with ``ep_axis`` the expert leaves arrive sharded per
        :meth:`ep_param_specs` and this device's tokens are its own
        batch shard (ep doubles as dp). ``dp_axis`` adds plain data
        parallelism OVER the expert groups — the batch dim shards over
        (dp, ep) jointly, each dp group runs its own all-to-all dispatch
        to its replica of the expert shards, and the load-balance
        statistics stay GLOBAL (averaged over dp x ep x sp). ``tp_axis``
        tensor-shards WITHIN each expert and attention block (Megatron:
        heads column/row-split, each expert's hidden dim column/row-
        split — gelu is elementwise in the split dim — vocab-sharded
        head): one psum after the attention projection and one after
        the expert combine per block. The router gate stays replicated
        (routing needs the full [d, E] logits; it is negligible next to
        the experts)."""
        B, T = tokens.shape
        pos = global_positions(sp_axis, T)
        x = (params["tok_emb"][tokens] + params["pos_emb"][pos][None]).astype(
            self.dtype
        )

        aux_total = jnp.zeros(())
        drop_total = jnp.zeros(())
        for blk in params["blocks"]:
            blk = cast_block_params(blk, self.dtype)
            delta = attention_block(blk, x, self.attn, sp_axis)
            if tp_axis is not None:
                delta = lax.psum(delta, tp_axis)  # row-parallel proj
            x = x + delta

            hin = _rms(x, blk["ln2"])
            y, stats = switch_moe(
                hin.reshape(B * T, self.d_model),
                blk["gate"],
                blk["expert_in"],
                blk["expert_out"],
                ep_axis,
                capacity_factor=self.capacity_factor,
                # global over every token shard (switch_moe drops Nones;
                # tp replicas compute identical stats — no axis needed)
                stats_axes=(dp_axis, ep_axis, sp_axis),
            )
            if tp_axis is not None:
                # each tp peer held h_local columns of every expert; the
                # combine is linear in the expert output, so one psum on
                # y completes the row-parallel expert_out (Megatron MLP
                # pattern, per expert)
                y = lax.psum(y, tp_axis)
            # the gate scale promotes y to f32; return the residual
            # stream to the compute dtype
            x = x + y.reshape(B, T, self.d_model).astype(self.dtype)
            aux_total = aux_total + stats.aux_loss
            drop_total = drop_total + stats.dropped_frac
        return (
            x @ params["head"].astype(self.dtype),
            aux_total,
            drop_total / self.n_layers,
        )

    def loss(
        self,
        params: PyTree,
        tokens: jax.Array,
        sp_axis: Optional[str] = None,
        *,
        ep_axis: Optional[str] = None,
        dp_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
    ) -> jax.Array:
        """Next-token CE (global over the sequence, local over this
        device's batch) + ``aux_weight`` x the Switch load-balance
        penalty. Same boundary-target/psum structure as TransformerLM;
        with ``tp_axis`` the logits arrive vocab-sharded and the CE runs
        distributed (Megatron parallel cross-entropy)."""
        logits, aux, _ = self.forward(
            params, tokens, sp_axis=sp_axis, ep_axis=ep_axis,
            dp_axis=dp_axis, tp_axis=tp_axis,
        )
        ce = next_token_loss(tokens, sp_axis, pick_nll(logits, tp_axis))
        return ce + self.aux_weight * aux

    def ep_param_specs(self, ep_axis: str = EXPERT_AXIS,
                       tp_axis: Optional[str] = None) -> PyTree:
        """Expert weights sharded on their leading (expert) dim;
        everything else replicated. With ``tp_axis``: attention heads
        column/row-split, each expert's hidden dim column/row-split,
        vocab head column-split (the router gate and norms stay
        replicated)."""
        blk = {
            "qkv": P(None, None, tp_axis, None) if tp_axis else P(),
            "proj": P(tp_axis, None, None) if tp_axis else P(),
            "gate": P(),
            "expert_in": P(ep_axis, None, tp_axis),
            "expert_out": P(ep_axis, tp_axis, None),
            "ln1": P(),
            "ln2": P(),
        }
        return {
            "tok_emb": P(),
            "pos_emb": P(),
            "head": P(None, tp_axis) if tp_axis else P(),
            "blocks": [blk] * self.n_layers,
        }

    # -- paged-KV incremental decode (serve/decode subsystem) ------------

    def prefill_cache(self, params, tokens, pages, k_pool, v_pool, *,
                      page_size: int):
        """:func:`~theanompi_tpu.models.transformer.paged_prefill` with
        the dense top-1 Switch FFN (:func:`moe_decode_ffn`)."""
        return paged_prefill(
            self, params, tokens, pages, k_pool, v_pool, page_size,
            ffn=moe_decode_ffn,
        )

    def decode_step(self, params, k_pool, v_pool, page_tables, seq_lens,
                    last_tokens, active, temperature, key, *,
                    page_size: int):
        """:func:`~theanompi_tpu.models.transformer.paged_decode_step`
        with the dense top-1 Switch FFN (:func:`moe_decode_ffn`)."""
        return paged_decode_step(
            self, params, k_pool, v_pool, page_tables, seq_lens,
            last_tokens, active, temperature, key, page_size,
            ffn=moe_decode_ffn,
        )


def moe_decode_ffn(blk, hin):
    """Dense top-1 Switch FFN for incremental decode: each token runs
    ONLY its argmax expert (weights gathered per token), scaled by the
    router probability — ``switch_moe``'s route-and-combine without the
    all-to-all dispatch or the capacity grid. At decode there is no
    capacity pressure (a handful of tokens per iteration), so this
    matches the training forward whenever the token would not have been
    capacity-dropped there; capacity drops are a TRAINING throughput
    knob, not a serving semantic. ``blk`` arrives via
    ``cast_block_params`` (the gate stays fp32). Accepts ``[..., d]``.
    """
    shape = hin.shape
    h2 = hin.reshape(-1, shape[-1])                 # [N, d]
    gl = h2.astype(jnp.float32) @ blk["gate"]       # router logits, fp32
    probs = jax.nn.softmax(gl, axis=-1)
    eidx = jnp.argmax(gl, axis=-1)                  # [N]
    w_in = blk["expert_in"][eidx]                   # [N, d, h]
    w_out = blk["expert_out"][eidx]                 # [N, h, d]
    h = jax.nn.gelu(jnp.einsum("nd,ndh->nh", h2, w_in))
    y = jnp.einsum("nh,nhd->nd", h, w_out)
    scale = jnp.take_along_axis(probs, eidx[:, None], axis=-1)
    return (y.astype(jnp.float32) * scale).astype(hin.dtype).reshape(shape)


def ep_spec_setup(
    model: MoETransformerLM,
    mesh: Mesh,
    ep_axis: str,
    sp_axis: Optional[str],
    dp_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
):
    """Shared mesh/shape validation + sharding-spec construction for the
    expert-parallel step builders (:func:`make_ep_train_step` and the
    launchable ``parallel.nd.NDEngine``). Returns ``(axes, n_total,
    param_specs)``."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    axes = [a for a in (dp_axis, ep_axis, sp_axis, tp_axis) if a is not None]
    for a in axes:
        if a not in sizes:
            raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
    nep = sizes[ep_axis]
    if model.n_experts % nep:
        raise ValueError(
            f"n_experts={model.n_experts} must divide the {ep_axis!r} "
            f"axis size {nep}"
        )
    ntp = sizes[tp_axis] if tp_axis else 1
    if tp_axis:
        validate_tp_divisibility(model, tp_axis, ntp)
    validate_ulysses_heads(model, sp_axis, sizes, model.n_heads // ntp)
    n_total = 1
    for a in axes:
        n_total *= sizes[a]
    return axes, n_total, model.ep_param_specs(ep_axis, tp_axis)


def make_ep_train_step(
    model: MoETransformerLM,
    mesh: Mesh,
    lr: float = 1e-2,
    *,
    ep_axis: str = EXPERT_AXIS,
    sp_axis: Optional[str] = None,
    dp_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
    optimizer=None,
):
    """Jitted expert-parallel train step: ``(params, tokens) ->
    (new_params, loss)`` (or over ``(params, opt_state)`` with
    ``optimizer``, as in make_nd_train_step). Tokens ``[B, T]`` are
    ``P(ep_axis, sp_axis)`` — the expert axis is also the batch axis;
    with ``dp_axis`` the batch dim shards over ``(dp, ep)`` jointly
    (dp-major, so multi-controller host slices stay contiguous) — the
    standard dp x ep MoE layout, each dp group dispatching to its own
    replica of the expert shards. Gradient sync follows the universal
    spec rule (transformer.py): expert shards carry their own full
    contribution, replicated leaves psum across every participating
    axis. ``tp_axis`` tensor-shards within each expert/attention block
    (see :meth:`MoETransformerLM.forward`)."""
    axes, n_total, param_specs = ep_spec_setup(
        model, mesh, ep_axis, sp_axis, dp_axis, tp_axis
    )

    def body(params, tokens):
        loss, grads = jax.value_and_grad(model.loss)(
            params, tokens, sp_axis, ep_axis=ep_axis, dp_axis=dp_axis,
            tp_axis=tp_axis,
        )
        grads = sync_grads_by_spec(grads, param_specs, axes, n_total)
        for a in (dp_axis, ep_axis):
            if a is not None:
                loss = lax.pmean(loss, a)  # report the global batch mean
        return loss, grads

    batch_spec = (dp_axis, ep_axis) if dp_axis else ep_axis
    return build_spec_step(
        body, mesh, param_specs, P(batch_spec, sp_axis), lr, optimizer,
        lambda: model.init(jax.random.PRNGKey(0)),
    )
