"""Small CIFAR-10 CNN — the README quick-start model.

Reference: ``models/cifar10.py`` — ``Cifar10_model`` (SURVEY.md §2.1),
an AlexNet-style small CNN: two conv+LRN+pool stages, two hidden FC
layers with dropout, softmax output; momentum SGD with step-decayed LR.
"""

from __future__ import annotations

from theanompi_tpu import nn
from theanompi_tpu.models.contract import Model, Recipe
from theanompi_tpu.nn import init as initializers


class Cifar10_model(Model):
    name = "cifar10"

    @classmethod
    def default_recipe(cls) -> Recipe:
        return Recipe(
            batch_size=128,
            n_epochs=70,
            optimizer="momentum",
            opt_kwargs={"momentum": 0.9, "weight_decay": 1e-4},
            schedule="step",
            sched_kwargs={"lr": 0.01, "boundaries": [40, 60], "factor": 0.1},
            lr_unit="epoch",
            input_shape=(32, 32, 3),
            num_classes=10,
            dataset="cifar10",
        )

    def build(self):
        # he/glorot init rather than the 2016 fixed-std gaussians: with
        # this depth the tiny gaussians stall (vanishing grads) — verified
        # empirically; the architecture and recipe otherwise match.
        he = initializers.he_normal()
        return nn.Sequential(
            [
                nn.Conv(64, 5, padding="SAME", w_init=he, name="conv1"),
                nn.Activation("relu"),
                nn.Pool(3, stride=2, mode="max"),
                nn.LRN(),
                nn.Conv(128, 5, padding="SAME", w_init=he, name="conv2"),
                nn.Activation("relu"),
                nn.Pool(3, stride=2, mode="max"),
                nn.LRN(),
                nn.Flatten(),
                nn.Dense(384, name="fc3"),
                nn.Activation("relu"),
                nn.Dropout(0.5),
                nn.Dense(192, name="fc4"),
                nn.Activation("relu"),
                nn.Dropout(0.5),
                nn.Dense(self.recipe.num_classes, name="softmax"),
            ],
            name="cifar10_cnn",
        )
