"""Data pipeline.

TPU-native replacement for the reference's data layer (SURVEY.md §1 L3):
``models/data/{imagenet.py,cifar10.py}`` dataset classes plus the
``lib/proc_load_mpi.py`` spawned-loader subsystem. Datasets expose epoch
iterators of host numpy batches; the prefetch loader overlaps host I/O +
preprocessing with device compute (reference hid loading behind GPU
compute via MPI-spawned child processes; here a thread + device prefetch
does the same without process gymnastics).
"""

from theanompi_tpu.data.datasets import Dataset, get_dataset  # noqa: F401
from theanompi_tpu.data import imagenet as _imagenet  # noqa: F401  (registers datasets)
from theanompi_tpu.data import lm as _lm  # noqa: F401  (registers LM datasets)
