"""Language-model token datasets.

BEYOND-PARITY EXTENSION: the reference is a CNN framework with image
pipelines only (SURVEY.md §5.7 — no sequence dimension anywhere). The
transformer stack (models/transformer.py) needs token streams; these
classes provide them through the SAME ``Dataset`` interface the image
pipelines use (``train_epoch``/``val_epoch``/``n_train_batches``), so
the training driver, prefetch loader, recorder, and checkpointing apply
unchanged.

Conventions: an "image" is a token window ``[T] int32``; ``image_shape``
is ``(T,)`` and ``n_classes`` is the vocabulary size. Labels ARE the
token window itself (the model computes shifted next-token targets
internally), so batches are ``(tokens, tokens)`` pairs sharing one
array.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from theanompi_tpu.data.datasets import Dataset, register_dataset


class LMSynthetic_data(Dataset):
    """Deterministic synthetic token stream with LEARNABLE structure: a
    seeded order-1 Markov chain where each symbol has ``branching``
    likely successors (uniform over them, with ``noise`` probability of
    a uniform-random symbol). A transformer reduces next-token loss well
    below the unigram entropy iff it actually learns the transition
    table — the LM analogue of ``Synthetic_data``'s class-means fixture
    (SURVEY.md §4(d): seeded fake data for CI/mesh tests)."""

    name = "lm_synthetic"

    def __init__(
        self,
        seq_len: int = 128,
        vocab: int = 64,
        n_train: int = 512,
        n_val: int = 64,
        branching: int = 4,
        noise: float = 0.05,
        seed: int = 1234,
    ):
        self.image_shape = (seq_len,)
        self.n_classes = vocab
        rng = np.random.RandomState(seed)
        # transition table: symbol -> `branching` successors
        succ = np.stack(
            [rng.choice(vocab, size=branching, replace=False) for _ in range(vocab)]
        )

        def chain(n_windows, salt):
            r = np.random.RandomState(seed + salt)
            n_tok = n_windows * seq_len
            out = np.empty(n_tok, np.int32)
            s = r.randint(vocab)
            for i in range(n_tok):
                out[i] = s
                if r.rand() < noise:
                    s = r.randint(vocab)
                else:
                    s = succ[s, r.randint(branching)]
            return out.reshape(n_windows, seq_len)

        self.x_train = chain(n_train, 1)
        self.x_val = chain(n_val, 2)
        self.y_train = self.x_train  # targets = the window itself (shifted in-model)
        self.y_val = self.x_val


class LMText_data(Dataset):
    """Byte-level LM windows over a real text file — zero-download real
    data (the repo's own docs by default), the LM counterpart of
    ``Digits_data``. Text bytes are concatenated and cut into
    non-overlapping ``seq_len`` windows; split train/val by a held-out
    TAIL fraction (time-ordered split, no leakage)."""

    name = "lm_text"

    DEFAULT_FILES = ("README.md", "SURVEY.md", "PARITY.md", "BASELINE.md")

    def __init__(
        self,
        path: Optional[str] = None,
        seq_len: int = 128,
        val_frac: float = 0.1,
    ):
        if path:
            paths = [path]
        else:
            root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            paths = [
                p for f in self.DEFAULT_FILES
                if os.path.exists(p := os.path.join(root, f))
            ]
            if not paths:
                raise FileNotFoundError(
                    "lm_text: no default corpus files found; pass "
                    "dataset_kwargs={'path': <textfile>}"
                )
        blob = b"".join(open(p, "rb").read() for p in paths)
        toks = np.frombuffer(blob, np.uint8).astype(np.int32)
        n_win = len(toks) // seq_len
        if n_win < 8:
            raise ValueError(
                f"corpus too small: {len(toks)} bytes < 8 windows of {seq_len}"
            )
        wins = toks[: n_win * seq_len].reshape(n_win, seq_len)
        n_val = max(1, int(n_win * val_frac))
        self.image_shape = (seq_len,)
        self.n_classes = 256
        self.x_train = wins[: n_win - n_val]
        self.x_val = wins[n_win - n_val :]
        self.y_train = self.x_train
        self.y_val = self.x_val


register_dataset("lm_synthetic", LMSynthetic_data)
register_dataset("lm_text", LMText_data)
