"""Asynchronous device-prefetch loader.

Rebuild of the reference's parallel-loading subsystem (reference:
``lib/proc_load_mpi.py`` — one MPI-spawned child process per worker
pulling ``.hkl`` batch files, preprocessing, and double-buffering so
I/O + preprocessing hide behind GPU compute; SURVEY.md §3.4). On TPU the
same overlap needs no process gymnastics: a background thread runs the
host-side pipeline (load + augment + ``device_put``) a configurable
depth ahead, while the device executes the current step. ``device_put``
is async in JAX, so the H2D copy itself overlaps device compute — the
double-buffer the reference built by hand.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax


class PrefetchLoader:
    """Wrap a host batch iterator; yield device-placed batches ``depth``
    ahead of consumption.

    ``place`` maps a host batch to device arrays (e.g. sharded
    ``device_put`` onto a mesh). Exceptions in the worker thread are
    re-raised at the consumer's next ``__next__``.
    """

    _SENTINEL = object()

    def __init__(
        self,
        batches: Iterable,
        place: Optional[Callable] = None,
        depth: int = 2,
    ):
        self._place = place or (lambda b: jax.device_put(b))
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(iter(batches),),
            name="tmpi-prefetch", daemon=True,
        )
        self._thread.start()

    def _run(self, it: Iterator) -> None:
        try:
            # hwloc equivalent (reference: lib/hwloc_utils.py): pin the
            # preprocessing thread to the configured cpuset so it stays
            # off the controller/XLA-runtime cores; no-op unless
            # TMPI_LOADER_CPUS is set. Inside the try: a malformed
            # cpuset must surface as an error at the consumer, not a
            # dead producer and a consumer blocked forever on the queue.
            from theanompi_tpu.utils.hostaffinity import pin_thread

            pin_thread()
            from theanompi_tpu.obs.spans import obs_span

            for batch in it:
                if self._stop.is_set():
                    return
                # h2d span (obs/spans.py): the host->device place runs on
                # THIS producer thread, overlapped with device compute —
                # recorded for the trace, excluded from the summary's
                # wall-time fractions (owner-thread accounting)
                with obs_span("h2d"):
                    placed = self._place(batch)
                while not self._stop.is_set():
                    try:
                        self._q.put(placed, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            # The sentinel MUST reach the consumer even when the queue is
            # full (the normal case when production outpaces the train
            # step): block-with-timeout and retry until close() stops us,
            # exactly like the batch path above — a dropped sentinel
            # deadlocks the consumer in q.get() at end of epoch.
            while not self._stop.is_set():
                try:
                    self._q.put(self._SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        """Stop the producer and drop prefetched batches — call when
        abandoning the iterator early (e.g. max_steps truncation), so
        device-placed batches are not pinned for the process lifetime.
        Idempotent, and safe mid-exception: the preferred form is the
        context manager, which guarantees the producer thread is torn
        down even when the consuming loop raises (a bare ``for`` over
        an abandoned loader leaks the thread for the process lifetime)."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=2.0)

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._SENTINEL:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
