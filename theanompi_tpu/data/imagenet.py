"""ImageNet pipeline: memory-mapped preprocessed shards.

Reference: ``models/data/imagenet.py`` — ``ImageNet_data`` over
preprocessed hickle ``.hkl`` file-batches (256x256 uint8) with
``img_mean`` subtraction and random 227-crop + mirror done in the
spawned loader (``lib/proc_load_mpi.py``; SURVEY.md §2.1, §3.4). The
TPU-native equivalent replaces HDF5 file-batches with plain ``.npy``
shards opened via ``np.load(mmap_mode='r')`` — zero-copy reads, no
codec dependency, trivially producible from any source:

    $IMAGENET_DIR/
      train_images_0000.npy   uint8 [N, S, S, 3]   (S >= crop size, e.g. 256)
      train_labels_0000.npy   int   [N]
      ...more shards...
      val_images_0000.npy / val_labels_0000.npy
      mean.npy                float [S, S, 3] or [3]   (optional)

Shuffling follows the reference's file-batch scheme: shard order and
intra-shard order are permuted per epoch (seeded, same on every host);
batches never span shards, keeping reads sequential per shard.

``Imagenet_synthetic`` generates shape-identical fake data in memory —
the benchmarking/CI stand-in when no ImageNet is on disk.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Iterator, Optional

import numpy as np

from theanompi_tpu.data.datasets import Dataset, register_dataset
from theanompi_tpu import native


def shard_path(directory: str, split: str, kind: str, i: int) -> str:
    """Canonical shard filename — the ONE place the naming convention
    lives (write_shards, tools/make_shards, and the index glob agree)."""
    return os.path.join(directory, f"{split}_{kind}_{i:04d}.npy")


def shard_glob(directory: str, split: str, kind: str) -> str:
    return os.path.join(directory, f"{split}_{kind}_*.npy")


def write_shards(
    directory: str,
    split: str,
    images: np.ndarray,
    labels: np.ndarray,
    shard_size: int = 1024,
) -> int:
    """Write uint8 images/labels as the shard format above (used by tests
    and by any user conversion script). Returns the number of shards."""
    os.makedirs(directory, exist_ok=True)
    n = len(images)
    n_shards = -(-n // shard_size)
    for i in range(n_shards):
        sl = slice(i * shard_size, (i + 1) * shard_size)
        np.save(shard_path(directory, split, "images", i), images[sl])
        np.save(shard_path(directory, split, "labels", i), labels[sl])
    return n_shards


class ImageNet_data(Dataset):
    """ImageNet-1k from preprocessed mmap shards."""

    name = "imagenet"
    n_classes = 1000

    SEARCH = ("/root/data/imagenet", "/data/imagenet")

    def __init__(
        self,
        root: Optional[str] = None,
        crop: int = 227,
        train_mirror: bool = True,
        device_normalize: bool = True,
        val_crops: int = 1,
    ):
        base = self._find(root)
        self.crop = crop
        self.train_mirror = train_mirror
        if val_crops not in (1, 10):
            raise ValueError("val_crops must be 1 (center) or 10 (10-crop)")
        # 1 = center crop; 10 = the AlexNet-era protocol (4 corners +
        # center, each mirrored), logits averaged per image by the eval
        # step (train.make_eval_step(views=10)) — the published top-1
        # numbers the recipes were validated with use this
        self.val_views = val_crops
        self.image_shape = (crop, crop, 3)
        self._train = self._index(base, "train")
        self._val = self._index(base, "val")
        if not self._train:
            raise FileNotFoundError(f"no train_images_*.npy shards under {base}")
        mean_path = os.path.join(base, "mean.npy")
        # reference: per-pixel img_mean subtracted in the loader
        self.mean = (
            np.load(mean_path).astype(np.float32)
            if os.path.exists(mean_path)
            else np.float32(127.5)
        )
        self.scale = np.float32(1.0 / 58.0)  # ~global pixel std
        # device_normalize: batches stay uint8 on the host (crop+mirror
        # only) and the driver applies (x - mean) * scale ON DEVICE —
        # 4x less H2D traffic. device_transform is the driver's contract
        # (launch/worker.py); False restores host-side float batches.
        self.device_transform = (
            {"mean": self._mean_for_crop(crop), "scale": float(self.scale)}
            if device_normalize
            else None
        )

    @classmethod
    def _find(cls, root: Optional[str]) -> str:
        env = os.environ.get("IMAGENET_DIR", "")
        for c in ([root] if root else [p for p in (env, *cls.SEARCH) if p]):
            if c and glob.glob(shard_glob(c, "train", "images")):
                return c
        raise FileNotFoundError(
            "ImageNet shards not found; set $IMAGENET_DIR to a directory of "
            "train/val_images_*.npy shards (see module docstring for the "
            "format; use dataset='imagenet_synthetic' for benchmarks without data)"
        )

    @staticmethod
    def _index(base: str, split: str) -> list[tuple[str, str, int]]:
        shards = []
        for img_path in sorted(glob.glob(shard_glob(base, split, "images"))):
            lbl_path = img_path.replace("_images_", "_labels_")
            n = len(np.load(lbl_path, mmap_mode="r"))
            shards.append((img_path, lbl_path, n))
        return shards

    # -- Dataset interface over shards --------------------------------------
    @property
    def n_train(self) -> int:
        return sum(n for _, _, n in self._train)

    @property
    def n_val(self) -> int:
        return sum(n for _, _, n in self._val)

    def n_train_batches(self, batch_size: int) -> int:
        return sum(n // batch_size for _, _, n in self._train)

    def n_val_batches(self, batch_size: int) -> int:
        return sum(n // batch_size for _, _, n in self._val)

    def train_epoch(
        self,
        epoch: int,
        batch_size: int,
        seed: int = 0,
        part: Optional[slice] = None,
    ) -> Iterator:
        """``part`` (multi-controller): this host's slice of each global
        batch — sliced from the UNSORTED permutation (a random subset),
        then sorted for sequential mmap reads."""
        rng = np.random.RandomState(seed * 100003 + epoch)
        order = rng.permutation(len(self._train))
        for si in order:
            img_path, lbl_path, n = self._train[si]
            images = np.load(img_path, mmap_mode="r")
            labels = np.load(lbl_path)
            perm = rng.permutation(n)
            for b in range(n // batch_size):
                idx = perm[b * batch_size : (b + 1) * batch_size]
                if part is not None:
                    idx = idx[part]
                idx = np.sort(idx)
                # mmap gather: multithreaded memcpy when the native lib
                # built (reference loader's hkl read), numpy otherwise
                x = native.gather_rows(images, idx)
                if x is None:
                    x = np.asarray(images[idx])
                y = labels[idx].astype(np.int32)
                yield self._preprocess(x, rng, train=True), y

    def val_epoch(self, batch_size: int, part: Optional[slice] = None) -> Iterator:
        for img_path, lbl_path, n in self._val:
            images = np.load(img_path, mmap_mode="r")
            labels = np.load(lbl_path)
            for b in range(n // batch_size):
                sl = slice(b * batch_size, (b + 1) * batch_size)
                x = np.asarray(images[sl])
                y = labels[sl].astype(np.int32)
                if part is not None:
                    x, y = x[part], y[part]
                if self.val_views == 10:
                    yield self._ten_crop(x), y
                else:
                    yield self._preprocess(x, None, train=False), y

    def _ten_crop(self, x: np.ndarray) -> np.ndarray:
        """4 corners + center, each mirrored — view-major rows per image
        ``[img0_v0..img0_v9, img1_v0, ...]``, so a batch-dim shard holds
        whole images (the eval step averages logits over the 10 views).
        uint8 when the device-normalize path is on, floats otherwise."""
        n, h, w, _ = x.shape
        c = self.crop
        oys = [0, 0, h - c, h - c, (h - c) // 2]
        oxs = [0, w - c, 0, w - c, (w - c) // 2]
        views = []
        for oy, ox in zip(oys, oxs):
            v = x[:, oy : oy + c, ox : ox + c]
            views.append(v)
            views.append(v[:, :, ::-1])
        out = np.stack(views, axis=1).reshape(n * 10, c, c, x.shape[-1])
        if self.device_transform is not None:
            return np.ascontiguousarray(out)
        return (out.astype(np.float32) - self._mean_for_crop(c)) * self.scale

    def _mean_for_crop(self, c: int) -> np.ndarray:
        """The mean as applied post-crop: scalar / per-channel pass
        through; a full-plane mean is CENTER-cropped to the crop size for
        every sample (the plane is smooth; identical to the numpy path)."""
        if np.ndim(self.mean) == 3 and self.mean.shape[0] != c:
            return self.mean[
                (self.mean.shape[0] - c) // 2 : (self.mean.shape[0] - c) // 2 + c,
                (self.mean.shape[1] - c) // 2 : (self.mean.shape[1] - c) // 2 + c,
            ]
        return np.asarray(self.mean, np.float32)

    @staticmethod
    def _numpy_crop_mirror(x, oy, ox, flips, c):
        """The fancy-index crop+mirror fallback — the single source for
        the indexing the native kernels replicate (tests compare)."""
        n = len(x)
        rows = oy[:, None] + np.arange(c)
        cols = ox[:, None] + np.arange(c)
        cols = np.where(flips[:, None], cols[:, ::-1], cols)
        return x[np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :]]

    def _preprocess(
        self, x: np.ndarray, rng: Optional[np.random.RandomState], train: bool
    ) -> np.ndarray:
        """Random crop + mirror + mean/scale (reference:
        ``proc_load_mpi`` crop/mirror funcs). Val: center crop. The hot
        loop runs in the native C++ kernel when built (same RNG draws,
        bit-identical output — tests/test_native.py), numpy otherwise."""
        n, h, w, _ = x.shape
        c = self.crop
        if train:
            offs = rng.randint(0, (h - c + 1) * (w - c + 1), size=n)
            oy, ox = offs // (w - c + 1), offs % (w - c + 1)
            # draw even when mirroring is off: the data order downstream
            # of the RNG must not depend on the train_mirror flag
            flips = rng.rand(n) < 0.5
            if not self.train_mirror:
                flips = np.zeros(n, bool)
        else:
            oy = np.full(n, (h - c) // 2)
            ox = np.full(n, (w - c) // 2)
            flips = np.zeros(n, bool)
        if self.device_transform is not None:
            # crop/mirror only, dtype preserved; normalization happens on
            # device (worker's input_transform) — ship 4x fewer bytes.
            # Native kernel is uint8-only: any other shard dtype takes
            # the numpy path (same guard as the host path below).
            out = (
                native.crop_mirror_u8(x, oy, ox, flips, c)
                if x.dtype == np.uint8
                else None
            )
            if out is None:
                out = self._numpy_crop_mirror(x, oy, ox, flips, c)
            return out
        m = self._mean_for_crop(c)
        if x.dtype == np.uint8:
            out = native.crop_mirror_normalize(
                x, oy, ox, flips, c, m, float(self.scale)
            )
            if out is not None:
                return out
        out = self._numpy_crop_mirror(x, oy, ox, flips, c)
        return (out.astype(np.float32) - m) * self.scale


class Imagenet_synthetic(Dataset):
    """Shape-correct fake ImageNet for benchmarks/CI (no disk, seeded)."""

    name = "imagenet_synthetic"

    def __init__(
        self,
        n_train: int = 2048,
        n_val: int = 256,
        crop: int = 227,
        n_classes: int = 1000,
        seed: int = 0,
        device_normalize: bool = True,
    ):
        self.image_shape = (crop, crop, 3)
        self.n_classes = n_classes
        rng = np.random.RandomState(seed)
        # the ONE definition of the normalization constants — the
        # device_transform dict and both host-path conversions use these
        self.mean = np.float32(127.5)
        self.scale = np.float32(1.0 / 58.0)
        self.device_transform = (
            {"mean": self.mean, "scale": float(self.scale)}
            if device_normalize
            else None
        )

        def make(n, salt):
            r = np.random.RandomState(seed + salt)
            y = r.randint(0, n_classes, size=n).astype(np.int32)
            x = r.randint(0, 256, size=(n, *self.image_shape)).astype(np.uint8)
            return x, y

        self.x_train, self.y_train = make(n_train, 1)
        self.x_val, self.y_val = make(n_val, 2)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        return (x.astype(np.float32) - self.mean) * self.scale

    def augment(self, x: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        if self.device_transform is not None:
            return x  # uint8; normalized on device
        return self._normalize(x)

    def val_epoch(self, batch_size: int, part: Optional[slice] = None):
        for x, y in super().val_epoch(batch_size, part=part):
            if self.device_transform is None:
                x = self._normalize(x)
            yield x, y


register_dataset("imagenet", ImageNet_data)
register_dataset("imagenet_synthetic", Imagenet_synthetic)
