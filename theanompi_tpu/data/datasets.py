"""Dataset classes: synthetic fixture + CIFAR-10.

Reference: ``models/data/cifar10.py`` — ``Cifar10_data`` with
``n_train_batches`` and batch iterators (SURVEY.md §2.1). The synthetic
dataset is the deterministic fake-data fixture SURVEY.md §4(d) requires
for seeded distributed tests; it is linearly separable-ish (class means +
noise) so overfit smoke tests can assert learning.
"""

from __future__ import annotations

import os
import pickle
from typing import Iterator, Optional

import numpy as np


class Dataset:
    """Host-side dataset of (images NHWC float32, labels int32).

    Epoch iterators yield fixed-size batches; the last partial batch is
    dropped (the reference trained on whole file-batches the same way).
    """

    name = "dataset"
    image_shape: tuple = (32, 32, 3)
    n_classes: int = 10

    # subclasses populate these
    x_train: np.ndarray
    y_train: np.ndarray
    x_val: np.ndarray
    y_val: np.ndarray

    @property
    def n_train(self) -> int:
        return len(self.x_train)

    @property
    def n_val(self) -> int:
        return len(self.x_val)

    def n_train_batches(self, batch_size: int) -> int:
        return self.n_train // batch_size

    def n_val_batches(self, batch_size: int) -> int:
        return self.n_val // batch_size

    def train_epoch(
        self,
        epoch: int,
        batch_size: int,
        seed: int = 0,
        part: Optional[slice] = None,
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Deterministically shuffled epoch (seed + epoch → permutation),
        so every data-parallel worker computes the same global order —
        the reference broadcast shuffled filename lists from rank 0 for
        the same reason (reference: ``models/data/imagenet.py``).

        ``part`` (multi-controller): this host's slice of each global
        batch (``host_local_batch_slice``) — the permutation is shared
        (seeded) across hosts, and each host gathers + augments ONLY its
        own rows, the analogue of the reference's per-rank loader feed.
        """
        rng = np.random.RandomState(seed * 100003 + epoch)
        perm = rng.permutation(self.n_train)
        for i in range(self.n_train_batches(batch_size)):
            idx = perm[i * batch_size : (i + 1) * batch_size]
            if part is not None:
                idx = idx[part]
            yield self.augment(self.x_train[idx], rng), self.y_train[idx]

    def val_epoch(
        self, batch_size: int, part: Optional[slice] = None
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        for i in range(self.n_val_batches(batch_size)):
            sl = slice(i * batch_size, (i + 1) * batch_size)
            x, y = self.x_val[sl], self.y_val[sl]
            if part is not None:
                x, y = x[part], y[part]
            yield x, y

    def augment(self, x: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        """Train-time augmentation hook; default identity."""
        return x


class Synthetic_data(Dataset):
    """Deterministic fake data: x = class_mean + noise. Learnable, seeded,
    zero I/O — the fixture for every CI/mesh test."""

    name = "synthetic"

    def __init__(
        self,
        n_train: int = 1024,
        n_val: int = 256,
        image_shape: tuple = (32, 32, 3),
        n_classes: int = 10,
        seed: int = 1234,
        noise: float = 0.3,
    ):
        self.image_shape = image_shape
        self.n_classes = n_classes
        rng = np.random.RandomState(seed)
        means = rng.randn(n_classes, *image_shape).astype(np.float32)

        def make(n, salt):
            r = np.random.RandomState(seed + salt)
            y = r.randint(0, n_classes, size=n).astype(np.int32)
            x = means[y] + noise * r.randn(n, *image_shape).astype(np.float32)
            return x.astype(np.float32), y

        self.x_train, self.y_train = make(n_train, 1)
        self.x_val, self.y_val = make(n_val, 2)


def crop_mirror_augment(
    x: np.ndarray, rng: np.random.RandomState, pad: int = 4
) -> np.ndarray:
    """Vectorized random crop from ``pad``-px reflect padding + mirror —
    the WRN/CIFAR recipe's train augmentation (reference:
    ``models/data/utils.py`` crop/mirror funcs)."""
    n, h, w, _ = x.shape
    padded = np.pad(x, [(0, 0), (pad, pad), (pad, pad), (0, 0)], mode="reflect")
    offs = rng.randint(0, 2 * pad + 1, size=(n, 2))
    flips = rng.rand(n) < 0.5
    rows = offs[:, 0, None] + np.arange(h)  # (n, h)
    cols = offs[:, 1, None] + np.arange(w)  # (n, w)
    cols = np.where(flips[:, None], cols[:, ::-1], cols)
    return padded[np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :]]


class Cifar10_data(Dataset):
    """Real CIFAR-10 from the standard python-pickle batches.

    Looks for ``cifar-10-batches-py`` under ``$CIFAR10_DIR`` or common
    data roots. No network access is assumed: if the files are absent,
    raises with instructions (the reference likewise expected
    pre-downloaded ``.hkl``/pickle files on disk).

    Preprocessing follows the reference recipe: per-channel mean/std
    normalization; train-time augment = random crop from 4-pixel pad +
    horizontal mirror (reference: ``models/data/utils.py`` crop/mirror).
    """

    name = "cifar10"

    SEARCH = (
        "/root/data",
        "/data",
        os.path.expanduser("~/.cache/theanompi_tpu"),
    )

    def __init__(self, root: Optional[str] = None):
        base = self._find(root)
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(base, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(np.asarray(d[b"labels"]))
        x_train = np.concatenate(xs)
        y_train = np.concatenate(ys)
        with open(os.path.join(base, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x_val, y_val = d[b"data"], np.asarray(d[b"labels"])

        def to_nhwc(x):
            return x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0

        x_train, x_val = to_nhwc(x_train), to_nhwc(x_val)
        self.mean = x_train.mean(axis=(0, 1, 2), keepdims=True)
        self.std = x_train.std(axis=(0, 1, 2), keepdims=True) + 1e-7
        self.x_train = (x_train - self.mean) / self.std
        self.x_val = (x_val - self.mean) / self.std
        self.y_train = y_train.astype(np.int32)
        self.y_val = y_val.astype(np.int32)

    @classmethod
    def _find(cls, root: Optional[str]) -> str:
        # $CIFAR10_DIR is read at call time, not import time
        env = os.environ.get("CIFAR10_DIR", "")
        candidates = [root] if root else [p for p in (env, *cls.SEARCH) if p]
        for c in candidates:
            for sub in ("", "cifar-10-batches-py"):
                base = os.path.join(c, sub) if sub else c
                if os.path.exists(os.path.join(base, "data_batch_1")):
                    return base
        raise FileNotFoundError(
            "CIFAR-10 not found. Place the extracted 'cifar-10-batches-py' "
            f"directory under one of {[c for c in candidates]} or set $CIFAR10_DIR. "
            "(No network access is assumed; use dataset='synthetic' for smoke runs.)"
        )

    def augment(self, x: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        return crop_mirror_augment(x, rng)


class Digits_data(Dataset):
    """Real image data with zero downloads: sklearn's bundled handwritten
    digits (1,797 8x8 grayscale images, 10 classes). The smallest REAL
    dataset available in a no-network environment — used by the
    committed convergence experiments (experiments/) as evidence the
    training stack learns actual data, standing in for BASELINE config
    #1 until CIFAR-10 files are present (see ``Cifar10_data``).

    Images are nearest-upsampled to ``size`` x ``size`` and replicated to
    3 channels so the CNN zoo applies unchanged; split 80/20
    deterministic; normalized to zero mean / unit std like the CIFAR
    recipe.
    """

    name = "digits"

    def __init__(
        self,
        size: int = 16,
        val_frac: float = 0.2,
        seed: int = 0,
        augment_crop: bool = False,
        ten_crop_val: bool = False,
    ):
        """``augment_crop``: the WRN/CIFAR recipe's train augmentation
        (random crop from 4-px reflect pad + mirror). ``ten_crop_val``:
        the AlexNet-era 10-crop val protocol (4 corners + center of a
        2-px reflect-padded image, each mirrored; the eval step averages
        logits over views) — together these exercise the FULL model-zoo
        recipe path on real data with zero downloads."""
        try:
            from sklearn.datasets import load_digits
        except ImportError as e:
            raise ImportError(
                "dataset 'digits' needs scikit-learn (bundled data); "
                "use dataset='synthetic' if unavailable"
            ) from e
        digits = load_digits()
        x = digits.images.astype(np.float32)  # [N, 8, 8], values 0..16
        y = digits.target.astype(np.int32)
        rep = size // 8
        if size % 8:
            raise ValueError(f"size must be a multiple of 8, got {size}")
        x = x.repeat(rep, axis=1).repeat(rep, axis=2)
        x = np.stack([x, x, x], axis=-1)  # [N, size, size, 3]
        self.image_shape = (size, size, 3)
        self.n_classes = 10
        rng = np.random.RandomState(seed)
        order = rng.permutation(len(x))
        n_val = int(len(x) * val_frac)
        val_idx, train_idx = order[:n_val], order[n_val:]
        self.x_train, self.y_train = x[train_idx], y[train_idx]
        self.x_val, self.y_val = x[val_idx], y[val_idx]
        # normalization stats from the TRAIN split only (same discipline
        # as Cifar10_data — no val leakage into the constants)
        mean = self.x_train.mean()
        std = self.x_train.std() + 1e-7
        self.x_train = (self.x_train - mean) / std
        self.x_val = (self.x_val - mean) / std
        self.augment_crop = augment_crop
        self.val_views = 10 if ten_crop_val else 1

    def augment(self, x: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        return crop_mirror_augment(x, rng) if self.augment_crop else x

    def val_epoch(
        self, batch_size: int, part: Optional[slice] = None
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.val_views == 1:
            yield from super().val_epoch(batch_size, part=part)
            return
        # 10-crop: view-major rows per image (driver ships views x batch
        # image rows against batch label rows; eval averages over views)
        s = self.image_shape[0]
        for i in range(self.n_val_batches(batch_size)):
            sl = slice(i * batch_size, (i + 1) * batch_size)
            x, y = self.x_val[sl], self.y_val[sl]
            if part is not None:
                x, y = x[part], y[part]
            padded = np.pad(x, [(0, 0), (2, 2), (2, 2), (0, 0)], mode="reflect")
            h = padded.shape[1]
            oys = [0, 0, h - s, h - s, (h - s) // 2]
            oxs = [0, h - s, 0, h - s, (h - s) // 2]
            views = []
            for oy, ox in zip(oys, oxs):
                v = padded[:, oy : oy + s, ox : ox + s]
                views.append(v)
                views.append(v[:, :, ::-1])
            out = np.stack(views, axis=1).reshape(-1, s, s, x.shape[-1])
            yield np.ascontiguousarray(out), y


_REGISTRY = {
    "synthetic": Synthetic_data,
    "cifar10": Cifar10_data,
    "digits": Digits_data,
}


def get_dataset(name: str, **kwargs) -> Dataset:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown dataset {name!r}; available: {sorted(_REGISTRY)}") from None
    return cls(**kwargs)


def register_dataset(name: str, cls) -> None:
    _REGISTRY[name] = cls
