"""Native (C++) input-pipeline kernels — build + ctypes binding.

The reference hid preprocessing cost in a spawned loader process
(reference: ``lib/proc_load_mpi.py`` — hkl load, img_mean subtract,
random crop, mirror in numpy; SURVEY.md §3.4), with hwloc pinning the
loader near its GPU (``lib/hwloc_utils.py``). The TPU rebuild keeps the
prefetch thread but makes the hot loop itself native: ``loader.cpp`` is
compiled ON DEMAND with the system g++ into ``_tmpi_native.so`` (cached
beside the source, rebuilt when the source is newer) and called through
ctypes — no build-system dependency, and any failure degrades to the
numpy path (``available()`` returns False).

Set ``TMPI_NATIVE=0`` to force the numpy fallback;
``TMPI_LOADER_THREADS`` overrides the preprocessing thread count
(default: this process's CPU affinity count, capped at 8).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "loader.cpp")
# -march=native makes the artifact host-specific; key the cache by
# hostname so a shared-filesystem install (NFS venv across pod hosts)
# never runs another host's AVX build (SIGILL), and each host builds its
# own (~1s, once)
import platform as _platform

_SO = os.path.join(_DIR, f"_tmpi_native-{_platform.node() or 'local'}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def default_threads() -> int:
    """Loader thread count: the hwloc-equivalent default is the CPUs
    this process is actually bound to (respects container/taskset
    limits), capped — preprocessing should not starve the controller."""
    env = os.environ.get("TMPI_LOADER_THREADS")
    if env:
        return max(1, int(env))
    try:
        n = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        n = os.cpu_count() or 1
    return max(1, min(8, n - 1))


def _build() -> bool:
    if not os.path.exists(_SRC):
        # source missing (e.g. wheel without package data): a cached .so
        # for this host is still trustworthy; otherwise degrade
        return os.path.exists(_SO)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    # pid-unique tmp: N controller processes on one host may race to
    # build on first use; each compiles privately, os.replace is atomic,
    # last writer wins with a valid artifact either way
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC, "-lpthread",
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            return False
        os.replace(tmp, _SO)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("TMPI_NATIVE", "1") == "0":
            return None
        if not _build():
            print(
                "theanompi_tpu.native: C++ loader kernels unavailable "
                "(g++/source missing?) — using the slower numpy path",
                flush=True,
            )
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except (OSError, AttributeError):
            # load failure OR a stale cached .so missing a newer symbol
            # (source absent so no rebuild possible): degrade, don't crash
            print(
                f"theanompi_tpu.native: failed to load/bind {_SO} — using "
                "the slower numpy path",
                flush=True,
            )
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
        lib.tmpi_crop_mirror_normalize.restype = ctypes.c_int
        lib.tmpi_crop_mirror_normalize.argtypes = [
            ctypes.c_void_p,  # in u8
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p,  # oy i32
            ctypes.c_void_p,  # ox i32
            ctypes.c_void_p,  # flip u8
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p,  # mean f32
            ctypes.c_int64,
            ctypes.c_float,
            ctypes.c_void_p,  # out f32
            ctypes.c_int,
        ]
        lib.tmpi_crop_mirror_u8.restype = ctypes.c_int
        lib.tmpi_crop_mirror_u8.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.tmpi_gather_rows.restype = ctypes.c_int
        lib.tmpi_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int,
        ]


def available() -> bool:
    return _load() is not None


def crop_mirror_normalize(
    images: np.ndarray,  # uint8 [n, h, w, c]
    oy: np.ndarray,
    ox: np.ndarray,
    flip: np.ndarray,
    crop: int,
    mean: np.ndarray,  # f32 scalar [1] / per-channel [c] / plane [crop,crop,c]
    scale: float,
    n_threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Fused (u8 - mean) * scale with per-image crop+mirror. Returns the
    float32 batch, or None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n, h, w, c = images.shape
    images = np.ascontiguousarray(images)
    oy32 = np.ascontiguousarray(oy, dtype=np.int32)
    ox32 = np.ascontiguousarray(ox, dtype=np.int32)
    flip8 = np.ascontiguousarray(flip, dtype=np.uint8)
    mean32 = np.ascontiguousarray(mean, dtype=np.float32).reshape(-1)
    out = np.empty((n, crop, crop, c), dtype=np.float32)
    rc = lib.tmpi_crop_mirror_normalize(
        images.ctypes.data, n, h, w, c,
        oy32.ctypes.data, ox32.ctypes.data, flip8.ctypes.data,
        crop, crop,
        mean32.ctypes.data, mean32.size,
        ctypes.c_float(scale),
        out.ctypes.data,
        int(n_threads if n_threads is not None else default_threads()),
    )
    if rc != 0:
        raise ValueError(f"tmpi_crop_mirror_normalize failed (rc={rc})")
    return out


def crop_mirror_u8(
    images: np.ndarray,  # uint8 [n, h, w, c]
    oy: np.ndarray,
    ox: np.ndarray,
    flip: np.ndarray,
    crop: int,
    n_threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Per-image crop+mirror staying in uint8 (device-normalize
    pipeline: the (x - mean) * scale runs on-TPU, the host ships 4x
    fewer bytes). None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n, h, w, c = images.shape
    images = np.ascontiguousarray(images)
    oy32 = np.ascontiguousarray(oy, dtype=np.int32)
    ox32 = np.ascontiguousarray(ox, dtype=np.int32)
    flip8 = np.ascontiguousarray(flip, dtype=np.uint8)
    out = np.empty((n, crop, crop, c), dtype=np.uint8)
    rc = lib.tmpi_crop_mirror_u8(
        images.ctypes.data, n, h, w, c,
        oy32.ctypes.data, ox32.ctypes.data, flip8.ctypes.data,
        crop, crop,
        out.ctypes.data,
        int(n_threads if n_threads is not None else default_threads()),
    )
    if rc != 0:
        raise ValueError(f"tmpi_crop_mirror_u8 failed (rc={rc})")
    return out


def gather_rows(
    source: np.ndarray,  # uint8-viewable [n_total, ...] (mmap ok)
    idx: np.ndarray,
    n_threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Contiguous ``source[idx]`` via multithreaded memcpy (mmap shard ->
    batch assembly). Returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    if source.dtype != np.uint8 or not source.flags.c_contiguous:
        return None
    row_bytes = int(np.prod(source.shape[1:]))
    idx64 = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx64), *source.shape[1:]), dtype=np.uint8)
    rc = lib.tmpi_gather_rows(
        source.ctypes.data, row_bytes,
        idx64.ctypes.data, len(idx64),
        out.ctypes.data,
        int(n_threads if n_threads is not None else default_threads()),
    )
    if rc != 0:
        raise ValueError(f"tmpi_gather_rows failed (rc={rc})")
    return out
