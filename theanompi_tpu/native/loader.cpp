// Native input-pipeline kernels.
//
// TPU-native equivalent of the reference's loader-process hot path
// (reference: lib/proc_load_mpi.py — per-batch hkl load, img_mean
// subtract, random crop, mirror, all in numpy inside a spawned MPI child;
// SURVEY.md §3.4). There the preprocessing ran in a separate OS process
// to hide its cost; here the hot loop itself is C++ (multithreaded,
// single-pass, cache-friendly) called from the prefetch thread via
// ctypes — at 256-chip ImageNet rates (~100k img/s cluster-wide, §7
// "Hard parts" #2) the numpy gather/cast path is the bottleneck, this
// path is ~an order of magnitude faster per core and scales with
// threads.
//
// Layout contract: images are uint8 NHWC, contiguous; output is float32
// NHWC, contiguous. Each image i is cropped at (oy[i], ox[i]), flipped
// horizontally iff flip[i], then out = (u8 - mean) * scale, where mean
// is either a scalar (mean_len == 1), a per-channel vector
// (mean_len == c), or a full crop-sized plane (mean_len == crop_h*crop_w*c).

#include <cstdint>
#include <thread>
#include <vector>

extern "C" {

// Crop + mirror + normalize a batch. Returns 0 on success.
int tmpi_crop_mirror_normalize(
    const uint8_t* in,      // [n, h, w, c]
    int64_t n, int64_t h, int64_t w, int64_t c,
    const int32_t* oy,      // [n] crop row offsets
    const int32_t* ox,      // [n] crop col offsets
    const uint8_t* flip,    // [n] 0/1 horizontal mirror
    int64_t crop_h, int64_t crop_w,
    const float* mean,      // see mean_len contract above
    int64_t mean_len,
    float scale,
    float* out,             // [n, crop_h, crop_w, c]
    int n_threads) {
  if (crop_h > h || crop_w > w) return 1;
  if (!(mean_len == 1 || mean_len == c || mean_len == crop_h * crop_w * c))
    return 2;

  const int64_t in_row = w * c;
  const int64_t in_img = h * in_row;
  const int64_t out_row = crop_w * c;
  const int64_t out_img = crop_h * out_row;

  auto work = [&](int64_t i0, int64_t i1) {
    for (int64_t i = i0; i < i1; ++i) {
      const uint8_t* src = in + i * in_img + oy[i] * in_row + ox[i] * c;
      float* dst = out + i * out_img;
      const bool f = flip[i] != 0;
      for (int64_t y = 0; y < crop_h; ++y) {
        const uint8_t* srow = src + y * in_row;
        float* drow = dst + y * out_row;
        const float* mrow =
            (mean_len == crop_h * crop_w * c) ? mean + y * out_row : mean;
        for (int64_t x = 0; x < crop_w; ++x) {
          // mirrored reads keep writes sequential (write locality wins)
          const uint8_t* spix = f ? srow + (crop_w - 1 - x) * c : srow + x * c;
          float* dpix = drow + x * c;
          const float* mpix = (mean_len == crop_h * crop_w * c)
                                  ? mrow + x * c
                                  : mean;
          for (int64_t ch = 0; ch < c; ++ch) {
            const float m = (mean_len == 1) ? mean[0] : mpix[ch];
            dpix[ch] = (static_cast<float>(spix[ch]) - m) * scale;
          }
        }
      }
    }
  };

  if (n_threads <= 1 || n < 2) {
    work(0, n);
    return 0;
  }
  const int t = static_cast<int>(
      std::min<int64_t>(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(t);
  const int64_t per = (n + t - 1) / t;
  for (int k = 0; k < t; ++k) {
    const int64_t i0 = k * per;
    const int64_t i1 = std::min<int64_t>(i0 + per, n);
    if (i0 >= i1) break;
    threads.emplace_back(work, i0, i1);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Crop + mirror only, uint8 -> uint8 (the device-normalize pipeline:
// normalization happens on-TPU, so the host ships 4x fewer bytes).
int tmpi_crop_mirror_u8(
    const uint8_t* in,      // [n, h, w, c]
    int64_t n, int64_t h, int64_t w, int64_t c,
    const int32_t* oy, const int32_t* ox, const uint8_t* flip,
    int64_t crop_h, int64_t crop_w,
    uint8_t* out,           // [n, crop_h, crop_w, c]
    int n_threads) {
  if (crop_h > h || crop_w > w) return 1;
  const int64_t in_row = w * c;
  const int64_t in_img = h * in_row;
  const int64_t out_row = crop_w * c;
  const int64_t out_img = crop_h * out_row;
  auto work = [&](int64_t i0, int64_t i1) {
    for (int64_t i = i0; i < i1; ++i) {
      const uint8_t* src = in + i * in_img + oy[i] * in_row + ox[i] * c;
      uint8_t* dst = out + i * out_img;
      const bool f = flip[i] != 0;
      for (int64_t y = 0; y < crop_h; ++y) {
        const uint8_t* srow = src + y * in_row;
        uint8_t* drow = dst + y * out_row;
        if (!f) {
          __builtin_memcpy(drow, srow, static_cast<size_t>(out_row));
        } else {
          for (int64_t x = 0; x < crop_w; ++x) {
            const uint8_t* spix = srow + (crop_w - 1 - x) * c;
            uint8_t* dpix = drow + x * c;
            for (int64_t ch = 0; ch < c; ++ch) dpix[ch] = spix[ch];
          }
        }
      }
    }
  };
  if (n_threads <= 1 || n < 2) {
    work(0, n);
    return 0;
  }
  const int t = static_cast<int>(std::min<int64_t>(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(t);
  const int64_t per = (n + t - 1) / t;
  for (int k = 0; k < t; ++k) {
    const int64_t i0 = k * per;
    const int64_t i1 = std::min<int64_t>(i0 + per, n);
    if (i0 >= i1) break;
    threads.emplace_back(work, i0, i1);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Gather rows of a uint8 [n_total, row_bytes] array into a contiguous
// batch (mmap shard -> batch assembly without numpy fancy-indexing).
int tmpi_gather_rows(
    const uint8_t* in, int64_t row_bytes,
    const int64_t* idx, int64_t n,
    uint8_t* out, int n_threads) {
  auto work = [&](int64_t i0, int64_t i1) {
    for (int64_t i = i0; i < i1; ++i) {
      const uint8_t* src = in + idx[i] * row_bytes;
      uint8_t* dst = out + i * row_bytes;
      __builtin_memcpy(dst, src, static_cast<size_t>(row_bytes));
    }
  };
  if (n_threads <= 1 || n < 2) {
    work(0, n);
    return 0;
  }
  const int t = static_cast<int>(std::min<int64_t>(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(t);
  const int64_t per = (n + t - 1) / t;
  for (int k = 0; k < t; ++k) {
    const int64_t i0 = k * per;
    const int64_t i1 = std::min<int64_t>(i0 + per, n);
    if (i0 >= i1) break;
    threads.emplace_back(work, i0, i1);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
