"""``tmpi`` — the command-line launcher.

Rebuild of the reference CLI (reference: ``tmpi`` — approx.
``tmpi <RULE> <n> <devices> <modelfile> <modelclass>``, which built an
``mpirun`` command line; SURVEY.md §3.1). No mpirun on TPU: the rule
runs one SPMD program over a device mesh in-process.

Usage::

    tmpi BSP 8 theanompi_tpu.models.model_zoo.wrn WRN
    tmpi EASGD 8 theanompi_tpu.models.model_zoo.resnet50 ResNet50 --avg-freq 8
    tmpi GOSGD 8 theanompi_tpu.models.model_zoo.vgg VGG16
    tmpi BSP 8 my_model.py MyModel --strategy asa16 --epochs 5

``tmpi serve`` is the inference subcommand (serve/cli.py): serve a
training run's checkpoints with dynamic micro-batching and hot-reload;
``--replicas N`` runs a replica-group fleet behind the same endpoint
(serve/router.py: health-checked least-loaded routing, bounded
failover, supervised restarts)::

    tmpi serve --ckpt-dir runs/ck --model cifar10 --watch --port 8300
    tmpi serve --ckpt-dir runs/ck --model cifar10 --replicas 3 --watch

``tmpi lint`` runs every repo lint plus the SPMD safety analyzer
(tools/lint.py): collective-signature verification against goldens,
traffic-model cross-checks, donation audit, rank-divergence lint::

    tmpi lint --json            # CI report with stable rule IDs
    tmpi lint --update-golden   # accept a reviewed signature change

``tmpi profile`` is the step-time attribution profiler
(tools/profile.py): warm steps of one engine+model, reconciled against
the XLA cost model, the declared traffic model and the traced jaxpr
into a compute/comm/host/residual split with a roofline verdict::

    tmpi profile --model mlp --steps 8            # CPU-runnable
    tmpi profile --model alexnet --steps 20 --trace

``tmpi preflight`` is the memory & precision pre-flight
(tools/preflight.py): static peak-HBM budgeting (lowered, never
executed) with a per-leaf byte table, donation-realization audit and
dtype-flow lint, gated on the device's HBM capacity or an explicit
budget::

    tmpi preflight --model mlp --engine bsp --budget-gb 16
    tmpi preflight --model transformer_lm --engine nd --mesh 2x4

``tmpi chaos`` is the chaos campaign runner (tools/chaos.py): fuzzed
fault schedules over the full matrix (process, data AND storage
faults), each run under the supervisor and checked against a recovery
invariant oracle; failing schedules are shrunk to a minimal
``--inject-fault`` repro::

    tmpi chaos --seeds 25               # full matrix, all configs
    tmpi chaos --smoke --seeds 5        # tier-1 CPU smoke
    tmpi chaos --schedule 'crash@5+bitrot@3'
    tmpi chaos --serve --seeds 10       # serving-path campaign: fuzzed
                                        # replica crash/stall/corrupt-
                                        # reload faults against a live
                                        # router fleet under load

``tmpi report`` is the unified post-mortem (tools/report.py): merge a
run's per-rank obs streams into one causally-grouped event timeline —
incidents cite their evidence records — plus the drift trajectory,
per-phase wall breakdown and a completed/halted/degraded verdict::

    tmpi report runs/obs                 # markdown to stdout
    tmpi report runs/obs --out report.md
    tmpi report runs/obs --json          # machine-readable (schema'd)
"""

from __future__ import annotations

import argparse
import ast
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmpi",
        description="TPU-native Theano-MPI: distributed training launcher",
        # no prefix abbreviation: an abbreviated --npro would survive
        # _strip_flags in the respawn path and fork forever
        allow_abbrev=False,
    )
    p.add_argument("rule", choices=["BSP", "EASGD", "GOSGD", "bsp", "easgd", "gosgd"])
    p.add_argument("n_devices", type=int, help="number of chips (0 = all)")
    p.add_argument("modelfile", help="module path or .py file with the model class")
    p.add_argument("modelclass", help="model class name (e.g. WRN)")
    p.add_argument("--strategy", default="psum",
                   help="gradient exchange strategy (psum|ring|ring_bf16|ring_int8|"
                        "psum_bf16|hier or reference names ar|asa32|asa16|nccl32|"
                        "nccl16). 'hier' is the topology-aware "
                        "hierarchical exchange for --slices N meshes: "
                        "in-slice reduce-scatter over ICI, cross-slice "
                        "allreduce over DCN on only the scattered shard "
                        "(--wire-codec applies to that DCN hop alone), "
                        "then in-slice all-gather; composes with "
                        "--allreduce-buckets")
    p.add_argument("--wire-codec", default="none", metavar="CODEC[:ef]",
                   help="compressed-collectives codec (parallel/codec.py) "
                        "for EVERY engine's exchange: none|bf16|int8, "
                        "optional ':ef' suffix for error-feedback "
                        "residual accumulators (e.g. int8:ef — the "
                        "convergence-safe default for int8). Applies to "
                        "the BSP grad psum/ring wire, ZeRO's reduce-"
                        "scatter + all-gather, EASGD's elastic psum, "
                        "GoSGD's gossip message, and the ND engine's "
                        "sharded-axis grad psums; traffic gauges report "
                        "effective vs raw bytes")
    p.add_argument("--fused-update", action="store_true",
                   help="fuse the optimizer epilogue (weight decay + "
                        "global-norm clip + momentum/Nesterov + param "
                        "write) into ONE Pallas pass over donated "
                        "buffers (ops/pallas_update.py) — one HBM "
                        "round-trip per leaf instead of ~4; every "
                        "engine opts in; SGD-family recipes only "
                        "(momentum/nesterov/sgd)")
    p.add_argument("--allreduce-buckets", type=float, default=0.0,
                   metavar="MB",
                   help="BSP rule: chunk the gradient allreduce into "
                        "~MB-sized buckets whose psums launch inside "
                        "backward, overlapping comm with the tail of "
                        "the backward pass (GC3-style scheduling; "
                        "parallel/strategies.py). Same numerics as the "
                        "single psum; composes with --wire-codec "
                        "(':ef' syncs post-backward, bucketed). 0 = "
                        "off; 4-32 MB is the useful range — biggest "
                        "win multi-chip/DCN, a no-op on one chip")
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="fuse this many steps into one compiled dispatch "
                        "(one H2D transfer + one host dispatch per group) — "
                        "works for every rule: EASGD embeds its avg_freq "
                        "exchange in the scan, GoSGD keeps its gossip "
                        "cadence per substep; amortizes dispatch latency on "
                        "directly-attached hosts — measured HARMFUL on "
                        "network-tunneled dev chips, whose large single "
                        "transfers stall")
    p.add_argument("--dispatch-depth", type=int, default=1,
                   help="async dispatch pipeline: keep up to K steps in "
                        "flight before the host blocks on a metrics "
                        "fetch (utils/dispatch.py). 1 = classic per-step "
                        "sync; recorder JSONL rows are bit-identical "
                        "either way, deeper pipelines just emit them "
                        "later. Costs K extra in-flight input batches "
                        "of HBM; see README 'Async dispatch pipeline'")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation-cache directory: "
                        "repeated runs (bench sweeps, requeued jobs) "
                        "skip recompiling identical programs")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient accumulation: split each (per-device) "
                        "batch into this many microbatches inside the step "
                        "— large-batch SGD trajectory at small-batch "
                        "activation memory")
    p.add_argument("--slices", type=int, default=None,
                   help="BSP over a 2-D (dcn, data) multi-slice mesh with this "
                        "many slices (pod-scale: allreduce rides ICI within a "
                        "slice, DCN across)")
    p.add_argument("--zero", type=int, default=0, choices=[0, 1],
                   help="BSP with ZeRO-1: optimizer state sharded over the "
                        "data axis (psum_scatter grads -> segment update -> "
                        "all_gather params; same wire volume as allreduce)")
    p.add_argument("--tp", type=int, default=1,
                   help="LM models: Megatron tensor-parallel axis size "
                        "(heads/FFN/vocab sharded; one psum per sub-block)")
    p.add_argument("--sp", type=int, default=1,
                   help="LM models: sequence-parallel axis size (ring or "
                        "Ulysses attention per the recipe's attn=)")
    p.add_argument("--pp", type=int, default=1,
                   help="LM models: GPipe pipeline stages (layers sharded; "
                        "microbatches stream via ppermute)")
    p.add_argument("--expert", type=int, default=1,
                   help="MoELMModel: expert-parallel axis size (Switch-MoE "
                        "all-to-all dispatch; doubles as the batch axis)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="with --pp: microbatch count per step (default = pp; "
                        "bubble fraction is (pp-1)/(M+pp-1))")
    p.add_argument("--pp-interleave", type=int, default=1,
                   help="with --pp: virtual stages per device (Megatron "
                        "interleaved schedule; bubble shrinks to "
                        "(pp-1)/(M*v+pp-1); layers must divide pp*v)")
    p.add_argument("--epochs", type=int, default=None, help="override recipe n_epochs")
    p.add_argument("--max-steps", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None, help="override recipe batch")
    p.add_argument("--dataset", default=None, help="override recipe dataset")
    p.add_argument("--synthetic", action="store_true",
                   help="shortcut: --dataset synthetic (smoke runs, no data on disk)")
    p.add_argument("--dataset-arg", action="append", default=[], metavar="K=V",
                   help="dataset constructor kwarg (repeatable), e.g. "
                        "--dataset-arg n_train=512 --dataset-arg root=/data")
    p.add_argument("--recipe-arg", action="append", default=[], metavar="K=V",
                   help="recipe override (repeatable, JSON values), e.g. "
                        "--recipe-arg 'input_shape=[16,16,3]' "
                        "--recipe-arg num_classes=1000 (the model owns its "
                        "recipe; this is the session's override hook)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--save-dir", default=None, help="recorder output dir (JSONL + pickle)")
    p.add_argument("--tensorboard", action="store_true",
                   help="also emit TensorBoard scalars under <save-dir>/tb "
                        "(soft dependency on tensorboardX)")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--sync-ckpt", action="store_true",
                   help="write epoch checkpoints synchronously instead "
                        "of on the background writer thread: the save "
                        "is durable before the next step dispatches "
                        "(deterministic durability for preemption-prone "
                        "runs, at the cost of stalling the loop for the "
                        "full gather+write)")
    p.add_argument("--ckpt-sharded", action="store_true",
                   help="per-host sharded checkpoints (each controller "
                        "writes only its shards — no cross-host gather or "
                        "rank-0 memory spike; restore works under any "
                        "process count)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--print-freq", type=int, default=40)
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler device trace of a few steps "
                        "into this dir (view with tensorboard; the in-step "
                        "comm/compute split the reference read from host "
                        "brackets)")
    p.add_argument("--profile-steps", type=int, default=4)
    p.add_argument("--obs-dir", default=None,
                   help="observability output dir (obs/ subsystem): metric "
                        "snapshots (JSONL + Prometheus text), per-rank span "
                        "trace, heartbeat files, stall-watchdog reports — "
                        "schemas in theanompi_tpu/tools/check_obs_schema.py")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="seconds without global-step progress before the "
                        "stall watchdog dumps all thread stacks and arms a "
                        "post-mortem device trace (0 = disabled; set it "
                        "above the worst expected compile/eval pause)")
    p.add_argument("--metrics-snapshot-freq", type=int, default=0,
                   help="write a metrics snapshot every N steps (0 = epoch "
                        "boundaries only); requires --obs-dir")
    p.add_argument("--fleet-exporter-port", type=int, default=0,
                   help="chief-only fleet telemetry exporter (obs/"
                        "exporter.py): serve /metrics, /fleet.json and "
                        "/healthz on this port, aggregated across ranks "
                        "by tailing the obs dir (0 = off; requires "
                        "--obs-dir). Under --max-retries the exporter "
                        "outlives retries. Watch interactively with "
                        "`tmpi top OBS_DIR`")
    p.add_argument("--numerics-freq", type=int, default=0,
                   help="numerics flight recorder: compute in-graph "
                        "sentinels (grad/update/param norms, fused "
                        "non-finite count, per-rule divergence gauge) "
                        "every N steps inside the compiled step — they "
                        "drain through the dispatch pipeline, zero new "
                        "host syncs; 0 = off. GoSGD's divergence gauge "
                        "costs a param-sized pmean per numerics step, so "
                        "raise N on that rule")
    p.add_argument("--flight-window", type=int, default=64,
                   help="flight recorder: keep the last N drained step "
                        "records in a ring; an anomaly or stall dumps "
                        "them as <obs-dir>/anomaly_rank{r}/ with thread "
                        "stacks, span summary, optional state checkpoint "
                        "and an armed device trace")
    p.add_argument("--drift-tolerance", type=float, default=0.25,
                   help="model-drift watchdog (obs/drift.py): EWMA "
                        "relative-error band the tmpi_model_err_"
                        "{cost,traffic,memory} gauges may wander inside "
                        "before a drift anomaly fires (flight bundle "
                        "anomaly_rank{r}-drift/, kind=drift records in "
                        "metrics.jsonl); compare predictions vs "
                        "measured with `tmpi report OBS_DIR`")
    p.add_argument("--on-anomaly",
                   choices=["record", "dump", "halt", "rollback"],
                   default="dump",
                   help="what a detected numerics anomaly (NaN/Inf, EWMA "
                        "spike) does: record = anomaly JSONL + gauges "
                        "only; dump = also write the flight-recorder "
                        "triage bundle (default); halt = dump, then stop "
                        "training with a NumericsAnomaly error; rollback "
                        "= dump, then restore the last VERIFIED "
                        "checkpoint and keep training (needs --ckpt-dir; "
                        "see --rollback-budget/--rollback-skip)")
    p.add_argument("--rollback-budget", type=int, default=2,
                   help="with --on-anomaly rollback: how many restores a "
                        "run may absorb before the anomaly escalates to "
                        "a halt (budget exhausted = stop)")
    p.add_argument("--rollback-skip", type=int, default=1,
                   help="with --on-anomaly rollback: skip this many data "
                        "batches at the anomalous step on replay, so a "
                        "persistently bad batch cannot re-poison every "
                        "attempt (0 = replay everything)")
    p.add_argument("--max-retries", type=int, default=0,
                   help="run under the fault-tolerant supervisor "
                        "(launch/supervisor.py): retry a crashed run up "
                        "to N times, auto-resuming each attempt from the "
                        "newest VERIFIED checkpoint with exponential "
                        "backoff (requires --ckpt-dir; 0 = no supervisor)")
    p.add_argument("--retry-backoff", type=float, default=1.0,
                   help="supervisor backoff base in seconds: retry k "
                        "sleeps base * 2**(k-1), capped at 60s")
    p.add_argument("--retry-jitter", action="store_true",
                   help="decorrelated-jitter retry backoff instead of "
                        "the plain exponential ladder (sleep_k = "
                        "uniform(base, 3*sleep_{k-1}), capped): the "
                        "ladder is identical across controllers, so a "
                        "pod-wide fault retries as a synchronized "
                        "stampede — jitter de-phases the fleet; "
                        "deterministic under --seed, and the value "
                        "actually slept is recorded in the retry "
                        "JSONL record")
    p.add_argument("--scrub-interval", type=float, default=0.0,
                   help="background checkpoint scrubber: re-verify the "
                        "keep-chain every N seconds and quarantine "
                        "corrupt members (bit-rot, torn writes) into "
                        "<ckpt-dir>/quarantine/ so resume discovery "
                        "never re-pays a walk past a known-bad file "
                        "(kind=scrub records + tmpi_scrub_* gauges; "
                        "0 = off — the supervisor still scrubs once "
                        "before each retry)")
    p.add_argument("--fault-ledger", default=None, metavar="PATH",
                   help="fired-fault ledger file for --inject-fault: "
                        "fired specs are appended (fsynced BEFORE the "
                        "fault's side effect) and specs already in the "
                        "ledger arm as fired — once-only fault "
                        "semantics ACROSS process relaunches (the "
                        "chaos runner's sandbox relies on it)")
    p.add_argument("--elastic", action="store_true",
                   help="elastic world size (launch/supervisor.py + "
                        "utils/checkpoint.load_resharded): with "
                        "--max-retries, every retry re-probes the live "
                        "device world and RESHARDS the newest verified "
                        "checkpoint onto the new mesh instead of dying "
                        "on a topology change (n_devices acts as a "
                        "cap); with --resume alone, one-shot: resume a "
                        "checkpoint saved under a different topology "
                        "onto the current mesh (e.g. train-on-pod -> "
                        "serve-on-one-chip handoff). Requires "
                        "--ckpt-dir; checkpoints are always stamped "
                        "with their topology manifest, elastic or not")
    p.add_argument("--elastic-lr-scale", choices=["none", "linear"],
                   default="none",
                   help="with --elastic: rescale the recipe's base LR "
                        "by n_new/n_old on a world change (linear "
                        "scaling rule — meant for the per-worker-batch "
                        "rules whose GLOBAL batch grows with the "
                        "world; BSP's global batch is mesh-invariant, "
                        "so 'none' keeps its trajectory comparable)")
    p.add_argument("--sigterm-grace", type=float, default=0.0,
                   help="preemption grace window in seconds: > 0 "
                        "installs a SIGTERM handler that checkpoints, "
                        "marks the run resumable (resumable.json in "
                        "--ckpt-dir), and exits cleanly instead of dying "
                        "mid-step (0 = default SIGTERM disposition)")
    p.add_argument("--inject-fault", action="append", default=[],
                   metavar="KIND@STEP",
                   help="deterministic fault injection (repeatable; "
                        "utils/faults.py): crash@K, sigterm@K, "
                        "sigkill@K, ckpt_truncate@K, nan_batch@K, "
                        "loader_stall@K:SECONDS — each fires once, "
                        "before dispatching step K; exercises the "
                        "supervisor/rollback/integrity recovery paths")
    p.add_argument("--avg-freq", type=int, default=None,
                   help="EASGD/GoSGD: steps between exchanges (reference avg_freq)")
    p.add_argument("--group-size", type=int, default=None,
                   help="EASGD/GoSGD: chips per worker — each async worker is "
                        "a data-parallel group (16 workers on 256 chips = "
                        "--group-size 16)")
    p.add_argument("--alpha", type=float, default=None, help="EASGD elastic rate")
    p.add_argument("--p-push", type=float, default=None, help="GoSGD push probability")
    p.add_argument("--nproc", type=int, default=None,
                   help="spawn N controller processes on THIS machine (multi-host "
                        "simulation over virtual CPU devices; the mpirun equivalent). "
                        "On a real pod, run one tmpi per host with TMPI_* env or "
                        "TMPI_AUTO_INIT=1 instead.")
    p.add_argument("--devices-per-proc", type=int, default=None,
                   help="with --nproc: virtual CPU devices per process (default: "
                        "n_devices / nproc)")
    return p


def _force_platform() -> None:
    """Honor TMPI_FORCE_PLATFORM before any backend use (the env var
    alone is not enough once a site hook pre-selected a platform) —
    shared by the training path and the serve subcommand."""
    import os

    if os.environ.get("TMPI_FORCE_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["TMPI_FORCE_PLATFORM"])


def _strip_flags(argv: list, flags: tuple) -> list:
    """Remove ``--flag value`` / ``--flag=value`` pairs from argv."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in flags:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in flags):
            continue
        out.append(a)
    return out


def main(argv=None) -> int:
    import os

    argv = list(argv) if argv is not None else sys.argv[1:]
    if argv[:1] == ["lint"]:
        # static analysis subcommand (tools/lint.py); it sets up its own
        # multi-device virtual CPU platform before tracing, so every
        # entry point (tmpi lint, python -m, the lint_all alias) works
        # on a bare environment
        from theanompi_tpu.tools.lint import main as lint_main

        return lint_main(argv[1:])
    if argv[:1] == ["profile"]:
        # step-time attribution profiler (tools/profile.py): its own
        # parser + driver, dispatched before the training parser
        _force_platform()
        from theanompi_tpu.tools.profile import profile_main

        return profile_main(argv[1:])
    if argv[:1] == ["preflight"]:
        # memory & precision pre-flight (tools/preflight.py): static
        # peak-HBM budgeting + dtype-flow lint of one engine x model x
        # mesh configuration — lowers, never executes; sets up its own
        # multi-device platform like `tmpi lint`
        from theanompi_tpu.tools.preflight import preflight_main

        return preflight_main(argv[1:])
    if argv[:1] == ["chaos"]:
        # chaos campaign runner (tools/chaos.py): fuzzed fault
        # schedules + invariant oracle + shrinker; sets up its own
        # multi-device virtual CPU platform like `tmpi lint`
        from theanompi_tpu.tools.chaos import chaos_main

        return chaos_main(argv[1:])
    if argv[:1] == ["top"]:
        # fleet console (tools/top.py): read-only viewer over an obs
        # dir (live or post-mortem) — no jax, no platform setup
        from theanompi_tpu.tools.top import top_main

        return top_main(argv[1:])
    if argv[:1] == ["report"]:
        # unified run report (tools/report.py): merge every per-rank
        # stream into one causally-grouped timeline + verdict —
        # read-only like `tmpi top`; no jax, no platform setup
        from theanompi_tpu.tools.report import report_main

        return report_main(argv[1:])
    if argv[:1] == ["serve"]:
        # inference subcommand: its own parser + driver (serve/cli.py);
        # dispatched before the training parser, whose first positional
        # is a sync rule
        _force_platform()
        from theanompi_tpu.serve.cli import serve_main

        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)

    if args.nproc and args.nproc > 1 and (
        "TMPI_PROCESS_ID" in os.environ or "TMPI_NUM_PROCESSES" in os.environ
    ):
        # already a spawned controller: never respawn (fork-bomb guard)
        print(
            "tmpi: ignoring --nproc inside an already-spawned controller "
            f"(TMPI_PROCESS_ID={os.environ.get('TMPI_PROCESS_ID')})",
            file=sys.stderr,
        )
        args.nproc = None

    if args.nproc and args.nproc > 1:
        # mpirun equivalent: re-invoke this CLI as nproc cooperating
        # controller processes over sliced virtual CPU devices
        import shlex

        from theanompi_tpu.launch.multihost import spawn_local

        child_argv = list(argv) if argv is not None else sys.argv[1:]
        child_argv = _strip_flags(child_argv, ("--nproc", "--devices-per-proc"))
        per_proc = args.devices_per_proc or max(1, (args.n_devices or args.nproc) // args.nproc)
        codes = spawn_local(
            args.nproc,
            ["-m", "theanompi_tpu.cli", *child_argv],
            devices_per_proc=per_proc,
        )
        if any(codes):
            print(f"controller exit codes: {codes} "
                  f"({shlex.join(child_argv)})", file=sys.stderr)
        # signal deaths have NEGATIVE returncodes — max() would report 0
        # when another rank exited cleanly; any non-zero code is failure
        return 1 if any(codes) else 0

    # join the multi-controller world BEFORE any backend use (no-op when
    # not configured; reference: MPI_GPU_Process init at worker start)
    _force_platform()

    from theanompi_tpu.parallel.distributed import initialize_distributed

    initialize_distributed()

    from theanompi_tpu.launch.session import resolve_model
    from theanompi_tpu.launch.worker import run_training
    from theanompi_tpu.utils.faults import Preempted as _Preempted

    model_cls = resolve_model(args.modelfile, args.modelclass)

    overrides = {}
    if args.batch_size:
        overrides["batch_size"] = args.batch_size
    if args.synthetic:
        args.dataset = "synthetic"

    def parse_kv(pairs, flag):
        out = {}
        for kv in pairs:
            k, sep, v = kv.partition("=")
            if not sep:
                raise SystemExit(f"{flag} expects K=V, got {kv!r}")
            try:
                out[k] = json.loads(v)
            except json.JSONDecodeError:
                try:
                    # accept Python literals too: input_shape=(16,16,3)
                    out[k] = ast.literal_eval(v)
                except (ValueError, SyntaxError):
                    out[k] = v
        return out

    dataset_kwargs = parse_kv(args.dataset_arg, "--dataset-arg")
    for k, v in parse_kv(args.recipe_arg, "--recipe-arg").items():
        # recipes store shapes as tuples; JSON gives lists
        overrides[k] = tuple(v) if isinstance(v, list) else v

    rule_kwargs = {}
    if args.avg_freq is not None:
        rule_kwargs["avg_freq"] = args.avg_freq
    if args.group_size is not None:
        rule_kwargs["group_size"] = args.group_size
    if args.alpha is not None:
        rule_kwargs["alpha"] = args.alpha
    if args.p_push is not None:
        rule_kwargs["p_push"] = args.p_push

    if args.tensorboard and not args.save_dir:
        print("WARNING: --tensorboard needs --save-dir; no TB output will "
              "be written", flush=True)
    if (args.stall_timeout or args.metrics_snapshot_freq) and not args.obs_dir:
        print("WARNING: --stall-timeout/--metrics-snapshot-freq need "
              "--obs-dir; observability is off", flush=True)
    if args.fleet_exporter_port and not args.obs_dir:
        print("WARNING: --fleet-exporter-port needs --obs-dir (the "
              "exporter tails the obs dir); the fleet exporter is off",
              flush=True)
    # (--numerics-freq without --obs-dir warns inside run_training,
    # which covers API callers too)
    if args.scrub_interval and not args.ckpt_dir:
        print("WARNING: --scrub-interval needs --ckpt-dir; the "
              "checkpoint scrubber is off", flush=True)
    if args.on_anomaly == "rollback" and not args.ckpt_dir:
        raise SystemExit("--on-anomaly rollback requires --ckpt-dir "
                         "(the rollback restores a checkpoint)")
    if args.max_retries and not args.ckpt_dir:
        raise SystemExit("--max-retries requires --ckpt-dir (retries "
                         "auto-resume from the newest verified checkpoint)")
    if args.elastic and not args.ckpt_dir:
        raise SystemExit("--elastic requires --ckpt-dir (an elastic "
                         "resume reshards a checkpoint; without one "
                         "there is nothing to carry across the "
                         "topology change)")
    if args.sigterm_grace and not args.ckpt_dir:
        # without a ckpt dir the grace path has nothing to save and no
        # marker to drop — exiting 75/"resumable" would promise a
        # scheduler an auto-resume that silently restarts from step 0
        raise SystemExit("--sigterm-grace requires --ckpt-dir (the grace "
                         "window checkpoints and marks the run resumable)")

    if args.max_retries > 0:
        # fault-tolerant supervisor: bounded retry + verified
        # auto-resume + preemption-marker handling around run_training
        from theanompi_tpu.launch.supervisor import supervise_training

        def _run(**kw):
            return supervise_training(
                max_retries=args.max_retries,
                backoff_base=args.retry_backoff,
                retry_jitter=args.retry_jitter,
                **kw,
            )
        # elastic binds to the SUPERVISOR's kwarg (it re-probes the
        # world per attempt and forwards elastic=True to run_training
        # itself); the unsupervised branch below hands it straight to
        # run_training for the one-shot reshard-resume case
    else:
        _run = run_training

    inject_faults = args.inject_fault or None
    if inject_faults is not None and args.fault_ledger:
        # ledger-armed injector: once-only semantics survive process
        # relaunches (utils/faults.py module docstring) — the chaos
        # sandbox's resume launches pass the same ledger
        from theanompi_tpu.utils.faults import FaultInjector

        inject_faults = FaultInjector(inject_faults,
                                      ledger=args.fault_ledger)

    try:
        summary = _run(
            rule=args.rule.lower(),
            model_cls=model_cls,
            devices=args.n_devices or None,
            strategy=args.strategy,
            wire_codec=args.wire_codec,
            fused_update=args.fused_update,
            allreduce_buckets=args.allreduce_buckets,
            n_slices=args.slices,
            steps_per_dispatch=args.steps_per_dispatch,
            dispatch_depth=args.dispatch_depth,
            compile_cache_dir=args.compile_cache_dir,
            accum_steps=args.accum_steps,
            tp=args.tp,
            sp=args.sp,
            pp=args.pp,
            expert=args.expert,
            microbatches=args.microbatches,
            pp_interleave=args.pp_interleave,
            zero=args.zero,
            n_epochs=args.epochs,
            max_steps=args.max_steps,
            dataset=args.dataset,
            dataset_kwargs=dataset_kwargs,
            recipe_overrides=overrides,
            seed=args.seed,
            save_dir=args.save_dir,
            ckpt_dir=args.ckpt_dir,
            async_checkpoint=not args.sync_ckpt,
            sharded_ckpt=args.ckpt_sharded,
            resume=args.resume,
            print_freq=args.print_freq,
            tensorboard=args.tensorboard,
            profile_dir=args.profile_dir,
            profile_steps=args.profile_steps,
            obs_dir=args.obs_dir,
            stall_timeout=args.stall_timeout,
            metrics_snapshot_freq=args.metrics_snapshot_freq,
            fleet_exporter_port=args.fleet_exporter_port,
            numerics_freq=args.numerics_freq,
            flight_window=args.flight_window,
            on_anomaly=args.on_anomaly,
            drift_tolerance=args.drift_tolerance,
            rollback_budget=args.rollback_budget,
            rollback_skip=args.rollback_skip,
            sigterm_grace=args.sigterm_grace,
            inject_faults=inject_faults,
            scrub_interval=args.scrub_interval,
            elastic=args.elastic,
            elastic_lr_scale=args.elastic_lr_scale,
            **rule_kwargs,
        )
    except _Preempted as e:
        # graceful preemption: checkpointed + marked resumable inside
        # the grace window. EX_TEMPFAIL tells the scheduler this exit
        # is retryable; the next invocation (supervisor or --resume)
        # picks the run back up from the marker.
        print(json.dumps({"preempted": True, "step": e.step,
                          "resumable": True}))
        return 75  # EX_TEMPFAIL
    print(json.dumps({k: v for k, v in summary.items() if k != "state"}, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
