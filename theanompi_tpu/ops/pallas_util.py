"""Shared gates for the Pallas kernel families (quantization,
attention): one switch for the pure-jnp/unfused fallback
(``TMPI_PALLAS=0``) and one for interpreter-vs-Mosaic lowering, so a
policy change reaches every kernel at once."""

from __future__ import annotations

import os

import jax


def use_pallas() -> bool:
    """False: modules route to their jnp/unfused fallbacks (same math)."""
    return os.environ.get("TMPI_PALLAS", "1") != "0"


def interpret_mode() -> bool:
    """Native Mosaic lowering on TPU; the Pallas interpreter elsewhere
    (CPU test meshes) — identical numerics either way."""
    return jax.default_backend() != "tpu"
