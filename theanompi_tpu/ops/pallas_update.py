"""Fused optimizer-update Pallas kernel: weight decay + global-norm
grad clip + momentum/Nesterov + param write in ONE pass over HBM.

The classic path (ops/optimizers.py) walks every parameter leaf ~4
times per step — ``_decayed`` (read g, read p, write g'), the velocity
tree_map (read v, write v'), and ``apply_updates`` (read p, read u,
write p') — each a full HBM round-trip XLA does not reliably fuse
across the tree_map boundaries. At AlexNet scale that is ~1 GB of
avoidable HBM traffic per step, a first-order term in the 0.38-MFU
plateau (ROADMAP item 2a; see ``tmpi profile``'s residual fraction).
This module fuses the whole epilogue into one Pallas kernel per leaf:

    g_eff = clip_coef * g + wd * p          (decay + clip folded)
    v'    = mu * v - lr * g_eff
    p'    = p + v'                          (classical)
    p'    = p + mu * v' - lr * g_eff        (Nesterov)

reading each of (p, v, g) once and writing (p', v') once, with
``input_output_aliases`` donating the param/velocity buffers so the
update happens in place. The global-norm clip coefficient is ONE scalar
reduction over the grads computed before the kernel launch (clipping is
inherently global; ``clip_norm=None`` skips it and the coefficient is
the constant 1). Arithmetic runs in fp32 regardless of the param dtype
(bf16 params keep fp32 velocity, exactly like the tree_map rules) and
the fused ``p + step`` rounds ONCE to the param dtype — one ulp-level
difference from ``apply_updates``'s round-then-add on bf16 params,
bit-identical on fp32 (tests/test_pallas_update.py).

Exposed as a drop-in :class:`~theanompi_tpu.ops.optimizers.Optimizer`
whose ``apply`` field carries the fused form — ``train.make_train_step``
(and the ZeRO-1 / ND steps) prefer ``apply`` when present, so every
engine opts in through one ``--fused-update`` knob. ``update`` remains
the reference tree_map math (the oracle the parity tests diff against).

Layout: leaves are flattened and zero-padded to (rows, 128) lanes (the
repo's Pallas idiom — ops/pallas_quant.py) and the kernel runs on a
row-block grid so arbitrarily large leaves stream through VMEM.
``TMPI_PALLAS=0`` routes to the pure-jnp fallback (same math); off-TPU
the kernel runs through the Pallas interpreter — identical numerics
everywhere.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.optimizers import Optimizer, _acc_like
from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_LANES = 128
# rows per grid step: 5 buffers x 512 rows x 128 lanes x 4 B ~= 1.3 MB
# of VMEM per iteration — comfortably under the ~16 MB budget while
# large enough that the grid overhead is noise
_BLOCK_ROWS = 512


def _block_rows(rows: int) -> int:
    """Grid block size: VMEM-bounded row blocks on real TPU; ONE block
    in interpreter mode (no VMEM to respect, and the interpreter pays
    per grid step — a 37M-element AlexNet fc leaf would otherwise trace
    ~1000 interpreted iterations)."""
    if _interpret():
        return rows
    return min(_BLOCK_ROWS, rows)


# --------------------------------------------------------------------------
# kernels (momentum variant carries velocity; plain SGD is stateless)
# --------------------------------------------------------------------------


def _momentum_kernel(p_ref, v_ref, g_ref, sc_ref, p_out, v_out, *,
                     momentum, weight_decay, nesterov):
    lr = sc_ref[0, 0]
    coef = sc_ref[0, 1]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * coef + weight_decay * p
    v = momentum * v_ref[:] - lr * g
    v_out[:] = v
    step = momentum * v - lr * g if nesterov else v
    p_out[:] = (p + step).astype(p_out.dtype)


def _sgd_kernel(p_ref, g_ref, sc_ref, p_out, *, weight_decay):
    lr = sc_ref[0, 0]
    coef = sc_ref[0, 1]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * coef + weight_decay * p
    p_out[:] = (p - lr * g).astype(p_out.dtype)


def _to_rows(flat: jax.Array, block_rows: int):
    """Zero-pad a flat vector to a (rows, 128) layout whose row count
    divides the grid's block size; returns (2-D view, rows)."""
    L = flat.shape[0]
    rows = -(-L // _LANES)
    rows = -(-rows // block_rows) * block_rows
    pad = rows * _LANES - L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES), rows


def _scalars(lr, clip_coef) -> jax.Array:
    return jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(clip_coef, jnp.float32)]).reshape(1, 2)


def fused_update_leaf(p, v, g, lr, clip_coef, *, momentum: float,
                      weight_decay: float, nesterov: bool):
    """One leaf through the fused momentum kernel -> ``(p', v')``.
    ``v`` is the fp32 velocity (same shape as ``p``); ``clip_coef`` is
    the precomputed global-norm clip scale (1.0 = no clip)."""
    if not _use_pallas():
        pf = p.astype(jnp.float32)
        gf = g.astype(jnp.float32) * clip_coef + weight_decay * pf
        v2 = momentum * v - lr * gf
        step = momentum * v2 - lr * gf if nesterov else v2
        return (pf + step).astype(p.dtype), v2
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = p.shape
    flat_p = p.reshape(-1)
    block = _block_rows(-(-flat_p.shape[0] // _LANES))
    p2, rows = _to_rows(flat_p, block)
    v2, _ = _to_rows(v.astype(jnp.float32).reshape(-1), block)
    g2, _ = _to_rows(g.astype(jnp.float32).reshape(-1), block)
    grid = (rows // block,)
    vspec = pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, 2), lambda i: (0, 0),
                         memory_space=pltpu.SMEM)
    new_p, new_v = pl.pallas_call(
        partial(_momentum_kernel, momentum=momentum,
                weight_decay=weight_decay, nesterov=nesterov),
        out_shape=(
            jax.ShapeDtypeStruct(p2.shape, p2.dtype),
            jax.ShapeDtypeStruct(v2.shape, jnp.float32),
        ),
        grid=grid,
        in_specs=[vspec, vspec, vspec, sspec],
        out_specs=(vspec, vspec),
        # in-place: the param and velocity buffers are rewritten, not
        # copied — the donation that makes this ONE HBM round-trip
        input_output_aliases={0: 0, 1: 1},
        interpret=_interpret(),
    )(p2, v2, g2, _scalars(lr, clip_coef))
    L = math.prod(shape) if shape else 1
    return (new_p.reshape(-1)[:L].reshape(shape),
            new_v.reshape(-1)[:L].reshape(shape))


def fused_sgd_leaf(p, g, lr, clip_coef, *, weight_decay: float):
    """Stateless fused SGD leaf -> ``p'`` (no velocity buffer)."""
    if not _use_pallas():
        pf = p.astype(jnp.float32)
        gf = g.astype(jnp.float32) * clip_coef + weight_decay * pf
        return (pf - lr * gf).astype(p.dtype)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = p.shape
    flat_p = p.reshape(-1)
    block = _block_rows(-(-flat_p.shape[0] // _LANES))
    p2, rows = _to_rows(flat_p, block)
    g2, _ = _to_rows(g.astype(jnp.float32).reshape(-1), block)
    vspec = pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, 2), lambda i: (0, 0),
                         memory_space=pltpu.SMEM)
    new_p = pl.pallas_call(
        partial(_sgd_kernel, weight_decay=weight_decay),
        out_shape=jax.ShapeDtypeStruct(p2.shape, p2.dtype),
        grid=(rows // block,),
        in_specs=[vspec, vspec, sspec],
        out_specs=vspec,
        input_output_aliases={0: 0},
        interpret=_interpret(),
    )(p2, g2, _scalars(lr, clip_coef))
    L = math.prod(shape) if shape else 1
    return new_p.reshape(-1)[:L].reshape(shape)


# --------------------------------------------------------------------------
# clip coefficient: ONE global scalar over the raw grads
# --------------------------------------------------------------------------


def clip_coefficient(grads, clip_norm: Optional[float]):
    """Global-norm clip scale ``min(1, clip_norm / ||g||)`` over ALL
    leaves' raw gradients (fp32). Safe at both edges: a zero-norm grad
    tree yields coefficient 1 (no 0/0 NaN), a norm beyond ``clip_norm``
    scales every leaf by the same factor. ``None`` -> the constant 1."""
    if clip_norm is None:
        return jnp.float32(1.0)
    gsq = sum(
        jnp.sum(jnp.square(g.astype(jnp.float32)))
        for g in jax.tree_util.tree_leaves(grads)
    )
    norm = jnp.sqrt(gsq)
    return jnp.minimum(jnp.float32(1.0),
                       jnp.float32(clip_norm) / jnp.maximum(norm, 1e-16))


# --------------------------------------------------------------------------
# drop-in Optimizer builders (``apply`` = fused; ``update`` = the
# reference tree_map math, kept as the parity oracle)
# --------------------------------------------------------------------------


def _ref_decayed_clipped(grads, params, weight_decay, coef):
    return jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.float32) * coef
        + weight_decay * p.astype(jnp.float32),
        grads, params,
    )


def fused_momentum_sgd(momentum: float = 0.9, weight_decay: float = 0.0,
                       clip_norm: Optional[float] = None,
                       nesterov: bool = False) -> Optimizer:
    """Fused classical/Nesterov momentum SGD. State layout is IDENTICAL
    to ``momentum_sgd``/``nesterov_sgd`` (``{"vel": fp32}``), so
    checkpoints resume across the fused/unfused boundary."""
    mu, wd = float(momentum), float(weight_decay)

    def init(params):
        return {"vel": _acc_like(params)}

    def apply(grads, state, params, lr):
        coef = clip_coefficient(grads, clip_norm)
        leaves_p, treedef = jax.tree_util.tree_flatten(params)
        leaves_v = jax.tree_util.tree_leaves(state["vel"])
        leaves_g = jax.tree_util.tree_leaves(grads)
        out_p, out_v = [], []
        for p, v, g in zip(leaves_p, leaves_v, leaves_g):
            np_, nv = fused_update_leaf(
                p, v, g, lr, coef, momentum=mu, weight_decay=wd,
                nesterov=nesterov,
            )
            out_p.append(np_)
            out_v.append(nv)
        return (
            jax.tree_util.tree_unflatten(treedef, out_p),
            {"vel": jax.tree_util.tree_unflatten(treedef, out_v)},
        )

    def update(grads, state, params, lr):
        coef = clip_coefficient(grads, clip_norm)
        g = _ref_decayed_clipped(grads, params, wd, coef)
        vel = jax.tree_util.tree_map(
            lambda v, gi: mu * v - lr * gi, state["vel"], g
        )
        if nesterov:
            updates = jax.tree_util.tree_map(
                lambda v, gi: mu * v - lr * gi, vel, g
            )
        else:
            updates = vel
        return updates, {"vel": vel}

    name = ("nesterov" if nesterov else "momentum") + "_fused"
    return Optimizer(name, init, update, apply)


def fused_nesterov_sgd(momentum: float = 0.9, weight_decay: float = 0.0,
                       clip_norm: Optional[float] = None) -> Optimizer:
    return fused_momentum_sgd(momentum, weight_decay, clip_norm,
                              nesterov=True)


def fused_sgd(weight_decay: float = 0.0,
              clip_norm: Optional[float] = None) -> Optimizer:
    """Fused vanilla SGD (stateless, like ``sgd``)."""
    wd = float(weight_decay)

    def init(params):
        return ()

    def apply(grads, state, params, lr):
        coef = clip_coefficient(grads, clip_norm)
        new_p = jax.tree_util.tree_map(
            lambda p, g: fused_sgd_leaf(p, g, lr, coef, weight_decay=wd),
            params, grads,
        )
        return new_p, state

    def update(grads, state, params, lr):
        coef = clip_coefficient(grads, clip_norm)
        g = _ref_decayed_clipped(grads, params, wd, coef)
        return jax.tree_util.tree_map(lambda gi: -lr * gi, g), state

    return Optimizer("sgd_fused", init, update, apply)


_FUSED_BUILDERS = {
    "sgd": fused_sgd,
    "momentum": fused_momentum_sgd,
    "nesterov": fused_nesterov_sgd,
}


def fuse_optimizer(name: str, **kwargs) -> Optimizer:
    """The ``--fused-update`` entry point: the fused equivalent of a
    registry optimizer name (recipes name their rule as a string). Only
    the AlexNet-era SGD family has a fused kernel; anything else is
    refused loudly rather than silently falling back to the slow path.
    ``clip_norm`` is accepted here but is a DIRECT-API feature of the
    fused builders: a recipe cannot carry it in ``opt_kwargs``, because
    state init walks the classic registry, which refuses the fused-only
    knob (and ZeRO-1/ND refuse it regardless — their steps see local
    shards, so the fused global norm would be per-rank partial)."""
    try:
        builder = _FUSED_BUILDERS[name]
    except KeyError:
        raise ValueError(
            f"--fused-update has no fused kernel for optimizer {name!r}; "
            f"fused rules: {sorted(_FUSED_BUILDERS)} "
            "(ops/pallas_update.py — drop the flag for other rules)"
        ) from None
    return builder(**kwargs)
