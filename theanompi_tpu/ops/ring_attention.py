"""Sequence/context-parallel attention over a named mesh axis: ring
attention (K/V rotation) and Ulysses (head<->sequence all-to-all).

BEYOND-PARITY EXTENSION. The reference is a 2016 CNN framework with no
attention anywhere (SURVEY.md §5.7: "absent — definitively; do not build
SP/CP for parity"), but the same section's design note requires the mesh
layer to admit a ``seq`` axis additively — this module is that promise
kept, and the long-context capability the TPU rebuild is expected to
carry (ring attention per Liu et al. 2023, blockwise parallel
transformers; PAPERS.md).

Design: the sequence is sharded over a mesh axis. Each device keeps its
local Q block and streams the K/V blocks around the ring with ONE
``lax.ppermute`` per step (n-1 hops total), accumulating attention with
the online-softmax (flash) recurrence — peak memory is O(T/n) per
device, compute overlaps the neighbor exchange, and the collective
rides ICI. Works on any axis of any mesh built by
:mod:`theanompi_tpu.parallel.mesh` (including a future ('data', 'seq')
2-D layout) and on the virtual CPU mesh for tests.

Numerically exact (not approximate) attention: matches the full
single-device softmax to float tolerance (tests/test_ring_attention.py).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # masked-logit sentinel (finite: keeps the recurrence NaN-free)


def ring_attention(
    q: jax.Array,  # [B, Tq_local, H, D] — this shard's queries
    k: jax.Array,  # [B, Tk_local, H, D] — this shard's keys
    v: jax.Array,  # [B, Tk_local, H, D] — this shard's values
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    precision=None,
) -> jax.Array:
    """Exact blockwise attention with K/V rotating around ``axis_name``.

    Must run inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``; global positions are derived from the axis index, so
    ``causal=True`` masks against the GLOBAL sequence order. Returns the
    local output block ``[B, Tq_local, H, D]``.

    ``precision``: forwarded to the two einsums — TPU's default bf16
    matmul passes give ~5e-3 absolute error vs fp32 (measured);
    ``jax.lax.Precision.HIGHEST`` restores fp32 exactness at ~2x matmul
    cost.
    """
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    sc = scale if scale is not None else 1.0 / math.sqrt(D)

    q_pos = rank * Tq + jnp.arange(Tq)  # global query positions

    # online-softmax accumulators, [B, H, Tq(, D)]
    o0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(o, m, l, kt, vt, src):
        """Fold one K/V block (originating on rank ``src``) into the
        online-softmax accumulators."""
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), kt.astype(jnp.float32),
            precision=precision,
        ) * sc
        if causal:
            k_pos = src * Tk + jnp.arange(Tk)
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # exp(_NEG - m_new) underflows to 0 whenever any real logit
            # exists; when ALL logits in the block are masked m_new==_NEG
            # and p would be exp(0)=1 — zero those explicitly
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vt.astype(jnp.float32), precision=precision
        )
        return o, m_new, l

    # local block first (no rotation), then exactly n-1 hops; K and V
    # travel as ONE stacked ppermute per hop
    o, m, l = attend(o0, m0, l0, k, v, rank)
    kv = jnp.stack([k, v])

    def body(carry, t):
        o, m, l, kv = carry
        kv = lax.ppermute(kv, axis_name, perm)
        src = jnp.mod(rank - t, n)
        o, m, l = attend(o, m, l, kv[0], kv[1], src)
        return (o, m, l, kv), None

    (o, m, l, _), _ = lax.scan(body, (o, m, l, kv), jnp.arange(1, n))
    # causal guarantees >= 1 valid key per query (its own position), so l > 0
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, Tq, H, D]


def ulysses_attention(
    q: jax.Array,  # [B, T_local, H, D] — this shard's queries
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    precision=None,
    local_fn=None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style; Jacobs
    et al. 2023, PAPERS.md) — the other canonical SP scheme next to
    :func:`ring_attention`, trading the ring's n-1 K/V hops for two
    ``lax.all_to_all`` head<->sequence transposes.

    Inside ``shard_map`` with the sequence sharded over ``axis_name``:
    the first all-to-all scatters heads and gathers sequence, so each
    device holds ``H/n`` full-sequence heads; attention is then plain
    local softmax attention (no cross-device mask bookkeeping); the
    second all-to-all restores the ``[B, T_local, H, D]`` layout.
    Requires ``H % n == 0``. Peak memory is O(T_global^2) scores for the
    local heads — choose ring attention when T^2 dominates, Ulysses when
    head count is plentiful and ICI all-to-all is cheap (both are exact).

    ``local_fn`` overrides the local per-head attention step — pass
    :func:`theanompi_tpu.ops.pallas_attention.flash_attention` to run
    the gathered-sequence step as the fused Pallas kernel (drops the
    O(T^2) score materialization, keeping only the all-to-alls as the
    SP cost).
    """
    n = lax.psum(1, axis_name)
    # scatter heads (axis 2) across the mesh, gather sequence (axis 1):
    # [B, T/n, H, D] -> [B, T, H/n, D], blocks concatenated in rank order
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if local_fn is not None:
        out = local_fn(qg, kg, vg, causal=causal, scale=scale,
                       precision=precision)
    else:
        out = full_attention_reference(
            qg, kg, vg, causal=causal, scale=scale, precision=precision
        )
    # gather heads back, re-scatter the sequence
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def full_attention_reference(q, k, v, causal=False, scale=None, precision=None):
    """Plain full-softmax attention — the single-device oracle for tests
    and the local per-head kernel inside :func:`ulysses_attention`."""
    B, T, H, D = q.shape
    Tk = k.shape[1]
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        precision=precision,
    ) * sc
    if causal:
        # position-aligned-at-start convention, valid for Tq != Tk too
        # (matches pallas_attention's global row >= col mask)
        mask = jnp.arange(T)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32), precision=precision
    )
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
