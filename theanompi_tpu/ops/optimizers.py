"""Optimizer update rules as pure pytree transforms.

TPU-native equivalent of the reference's ``lib/opt.py`` (grep anchors:
``MSGD``-style builders, ``vels``, ``updates_v``/``updates_w``; reference
mount empty at build time — see SURVEY.md §2.1).

The reference built Theano update dicts in a **two-phase** scheme: the
train function wrote raw gradients into persistent velocity shared vars
("separate" mode), the exchanger allreduced those buffers between Theano
calls, and a second compiled function applied them to the weights. That
split existed only because communication happened *between* compiled
functions. Under XLA the whole step — forward, backward, collective,
update — is one compiled program, so here an optimizer is simply a pair
of pure functions over parameter pytrees:

    opt = momentum_sgd(momentum=0.9, weight_decay=5e-4)
    state = opt.init(params)
    updates, state = opt.update(grads, state, params, lr)
    params = apply_updates(params, updates)

Gradient synchronization (the exchanger) transforms ``grads`` *before*
``opt.update`` — exactly the reference's ordering, where comm saw raw
gradients and the weight update ran post-exchange.

Semantics match the reference recipes (2016 AlexNet-era conventions):

- weight decay is folded into the gradient: ``g += wd * p``;
- classical momentum:  ``v = mu*v - lr*g``; ``p += v``;
- Nesterov momentum:   ``v = mu*v - lr*g``; ``p += mu*v - lr*g``.

All arithmetic runs in the dtype of the optimizer state (fp32 by
default even when params are bf16) so that long momentum accumulations
do not lose precision on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

PyTree = Any


class Optimizer(NamedTuple):
    """A pure optimizer: ``init(params) -> state``, ``update(grads, state, params, lr) -> (updates, state)``.

    ``apply`` (optional): the FUSED one-pass form ``(grads, state,
    params, lr) -> (new_params, new_state)`` — params are rewritten
    inside the rule instead of materializing a separate update tree
    (ops/pallas_update.py: one HBM round-trip per leaf instead of the
    ~4 the ``update`` → ``apply_updates`` tree_maps cost). ``None`` for
    the classic two-phase optimizers; ``train.make_train_step`` prefers
    ``apply`` when present."""

    name: str
    init: Callable[[PyTree], PyTree]
    update: Callable[[PyTree, PyTree, PyTree, jax.Array], tuple[PyTree, PyTree]]
    apply: Any = None  # fused one-pass form, or None


def apply_updates(params: PyTree, updates: PyTree) -> PyTree:
    """``p += u`` leafwise, preserving the parameter dtype."""
    return jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p, params, updates
    )


def update_delta(new_params: PyTree, params: PyTree) -> PyTree:
    """``new - old`` leafwise in fp32 — the reconstructed update tree
    the numerics gauges read on the FUSED path, where the one-pass
    kernel (``Optimizer.apply``) never materializes updates. Gauge-only:
    callers gate it behind the numerics flag so sentinel-off steps pay
    nothing."""
    return jax.tree_util.tree_map(
        lambda n, p: n.astype(jnp.float32) - p.astype(jnp.float32),
        new_params, params,
    )


def _acc_like(params: PyTree, dtype=jnp.float32) -> PyTree:
    """Zero accumulator pytree in the accumulation dtype."""
    return jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, dtype or p.dtype), params)


def _decayed(grads: PyTree, params: PyTree, weight_decay: float, dtype=jnp.float32) -> PyTree:
    """Fold L2 weight decay into the gradient (reference: ``lib/opt.py`` adds
    ``weight_decay * p`` to the cost gradient)."""
    if weight_decay:
        return jax.tree_util.tree_map(
            lambda g, p: g.astype(dtype) + weight_decay * p.astype(dtype), grads, params
        )
    return jax.tree_util.tree_map(lambda g: g.astype(dtype), grads)


def sgd(weight_decay: float = 0.0) -> Optimizer:
    """Vanilla SGD: ``p -= lr * (g + wd*p)``. Stateless."""

    def init(params):
        return ()

    def update(grads, state, params, lr):
        g = _decayed(grads, params, weight_decay)
        updates = jax.tree_util.tree_map(lambda gi: -lr * gi, g)
        return updates, state

    return Optimizer("sgd", init, update)


def momentum_sgd(momentum: float = 0.9, weight_decay: float = 0.0) -> Optimizer:
    """Classical momentum SGD, the reference's default training rule
    (reference: ``lib/opt.py`` — momentum variant).

    ``v = mu*v - lr*(g + wd*p)``; ``p += v``.
    """

    def init(params):
        return {"vel": _acc_like(params)}

    def update(grads, state, params, lr):
        g = _decayed(grads, params, weight_decay)
        vel = jax.tree_util.tree_map(
            lambda v, gi: momentum * v - lr * gi, state["vel"], g
        )
        return vel, {"vel": vel}

    return Optimizer("momentum", init, update)


def nesterov_sgd(momentum: float = 0.9, weight_decay: float = 0.0) -> Optimizer:
    """Nesterov momentum in the same formulation the reference used
    (reference: ``lib/opt.py`` — Nesterov variant):

    ``v = mu*v - lr*g``; ``p += mu*v - lr*g``.
    """

    def init(params):
        return {"vel": _acc_like(params)}

    def update(grads, state, params, lr):
        g = _decayed(grads, params, weight_decay)
        vel = jax.tree_util.tree_map(
            lambda v, gi: momentum * v - lr * gi, state["vel"], g
        )
        updates = jax.tree_util.tree_map(
            lambda v, gi: momentum * v - lr * gi, vel, g
        )
        return updates, {"vel": vel}

    return Optimizer("nesterov", init, update)


def rmsprop(decay: float = 0.9, eps: float = 1e-8, weight_decay: float = 0.0) -> Optimizer:
    """RMSProp: ``s = rho*s + (1-rho)*g^2``; ``p -= lr * g / (sqrt(s) + eps)``."""

    def init(params):
        return {"sq": _acc_like(params)}

    def update(grads, state, params, lr):
        g = _decayed(grads, params, weight_decay)
        sq = jax.tree_util.tree_map(
            lambda s, gi: decay * s + (1.0 - decay) * jnp.square(gi), state["sq"], g
        )
        updates = jax.tree_util.tree_map(
            lambda gi, s: -lr * gi / (jnp.sqrt(s) + eps), g, sq
        )
        return updates, {"sq": sq}

    return Optimizer("rmsprop", init, update)


def adam(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0
) -> Optimizer:
    """Adam (Kingma & Ba 2015) with bias correction; named in the north-star
    contract alongside SGD (reference: ``lib/opt.py`` — "SGD/Adam updates")."""

    def init(params):
        return {
            "m": _acc_like(params),
            "v": _acc_like(params),
            "t": jnp.zeros((), jnp.int32),
        }

    def update(grads, state, params, lr):
        g = _decayed(grads, params, weight_decay)
        t = state["t"] + 1
        m = jax.tree_util.tree_map(
            lambda mi, gi: b1 * mi + (1.0 - b1) * gi, state["m"], g
        )
        v = jax.tree_util.tree_map(
            lambda vi, gi: b2 * vi + (1.0 - b2) * jnp.square(gi), state["v"], g
        )
        tf = t.astype(jnp.float32)
        scale = lr * jnp.sqrt(1.0 - b2**tf) / (1.0 - b1**tf)
        updates = jax.tree_util.tree_map(
            lambda mi, vi: -scale * mi / (jnp.sqrt(vi) + eps), m, v
        )
        return updates, {"m": m, "v": v, "t": t}

    return Optimizer("adam", init, update)


_REGISTRY: dict[str, Callable[..., Optimizer]] = {
    "sgd": sgd,
    "momentum": momentum_sgd,
    "nesterov": nesterov_sgd,
    "rmsprop": rmsprop,
    "adam": adam,
}


def get_optimizer(name: str, **kwargs) -> Optimizer:
    """Look up an optimizer builder by name (model recipes name their rule
    as a string, mirroring the reference's model-owned hyperparams)."""
    try:
        builder = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    try:
        return builder(**kwargs)
    except TypeError as e:
        # a recipe carrying a bad kwarg must refuse loudly on the
        # classic path, not crash with a raw TypeError — e.g. a
        # fused-only clip_norm left in opt_kwargs when --fused-update
        # is dropped
        hint = (
            " (clip_norm is a --fused-update-only knob — "
            "ops/pallas_update.py)" if "clip_norm" in kwargs else ""
        )
        raise ValueError(
            f"optimizer {name!r} does not accept {sorted(kwargs)}: "
            f"{e}{hint}"
        ) from None
