"""Learning-rate schedules as pure ``step -> lr`` functions.

The reference had no schedule abstraction: each model's
``adjust_hyperp(epoch)`` mutated a shared LR variable (reference:
``models/alex_net.py`` — ``adjust_hyperp``; SURVEY.md §2.1). Here a
schedule is a jittable function of the global step (or epoch), so the LR
lives *inside* the compiled train step and per-model recipes stay
declarative. ``step`` may be a traced ``jax.Array`` — schedules use only
arithmetic/`jnp.where`, never Python control flow on it.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

Schedule = Callable[..., jnp.ndarray]  # (step) -> lr


def constant(lr: float) -> Schedule:
    def schedule(step):
        del step
        return jnp.asarray(lr, jnp.float32)

    return schedule


def step_decay(
    lr: float, boundaries: Sequence[int], factor: float = 0.1
) -> Schedule:
    """AlexNet/ResNet-style piecewise-constant decay: multiply by ``factor``
    at each boundary (in steps or epochs, caller's choice of unit).

    Reference models divided LR by 10 on a fixed epoch schedule via
    ``adjust_hyperp`` (reference: ``models/alex_net.py``).
    """
    bounds = jnp.asarray(sorted(boundaries), jnp.float32)

    def schedule(step):
        n = jnp.sum(jnp.asarray(step, jnp.float32)[..., None] >= bounds, axis=-1)
        return jnp.asarray(lr, jnp.float32) * jnp.power(factor, n.astype(jnp.float32))

    return schedule


def exponential_decay(lr: float, decay: float, every: int = 1) -> Schedule:
    """``lr * decay**(step // every)`` — WRN-style smooth decay."""

    def schedule(step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / float(every))
        return jnp.asarray(lr, jnp.float32) * jnp.power(decay, k)

    return schedule


def polynomial_decay(lr: float, total_steps: int, power: float = 1.0, end_lr: float = 0.0) -> Schedule:
    def schedule(step):
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / float(total_steps), 0.0, 1.0)
        return (lr - end_lr) * jnp.power(1.0 - frac, power) + end_lr

    return schedule


def linear_warmup_cosine(lr: float, warmup_steps: int, total_steps: int, end_lr: float = 0.0) -> Schedule:
    """Warmup + cosine — not in the 2016 reference, but required for large-batch
    data-parallel runs (256-chip target) to keep top-1 parity at scale."""

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        warm = lr * s / jnp.maximum(1.0, float(warmup_steps))
        frac = jnp.clip(
            (s - warmup_steps) / jnp.maximum(1.0, float(total_steps - warmup_steps)), 0.0, 1.0
        )
        cos = end_lr + 0.5 * (lr - end_lr) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(s < warmup_steps, warm, cos)

    return schedule


_REGISTRY = {
    "constant": constant,
    "step": step_decay,
    "exp": exponential_decay,
    "poly": polynomial_decay,
    "warmup_cosine": linear_warmup_cosine,
}


def get_schedule(name: str, **kwargs) -> Schedule:
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown schedule {name!r}; available: {sorted(_REGISTRY)}") from None
