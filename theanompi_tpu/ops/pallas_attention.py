"""Pallas fused flash attention — forward + custom-VJP backward TPU kernels.

BEYOND-PARITY EXTENSION. The 2016 reference has no attention op anywhere
(SURVEY.md §5.7); this module is the TPU-native fused kernel behind the
framework's long-context demonstrators. XLA's default lowering of
softmax attention materializes the [B, H, T, T] score matrix in HBM
twice (forward + transposed backward); the flash formulation (online
softmax over K/V blocks, Dao et al.) keeps scores in VMEM tiles and
streams K/V through them, making attention HBM-traffic-bound in O(T·D)
instead of O(T^2). Both passes are Pallas TPU kernels:

- forward: one kernel, grid over (batch·heads, query blocks); K/V loops
  run as ``fori_loop`` over VMEM slices; per-row logsumexp is saved as
  the softmax residual.
- backward: the classic two-kernel split — a dq kernel gridded over
  query blocks and a dk/dv kernel gridded over key blocks — each
  recomputing the probability tiles from (q, k, lse) so the O(T^2)
  matrix never exists in either pass.

Numerics: the q·k^T and p·v matmuls run in the INPUT dtype on the MXU
with fp32 accumulation (``preferred_element_type``); softmax statistics,
probability tiles, and all gradient accumulators are fp32. For fp32
inputs the result matches the unfused reference to float tolerance
(tests/test_pallas_attention.py).

Layout contract matches :func:`theanompi_tpu.ops.ring_attention.
full_attention_reference`: ``[B, T, H, D] -> [B, Tq, H, D]``, optional
causal masking in GLOBAL position order (query i attends keys <= i).
Off-TPU the kernels run through the Pallas interpreter — identical
numerics on the CPU test meshes. ``TMPI_PALLAS=0`` falls back to the
unfused reference implementation.

K/V (and in backward Q) blocks for one batch·head row must fit VMEM:
fine through T ~ 8-16k at D <= 128; beyond that use
:func:`~theanompi_tpu.ops.ring_attention.ring_attention`, whose local
block this kernel exactly is (each device's ring hop folds one K/V
shard — the same online-softmax recurrence, distributed).

Measured (one TPU v5e, B=4 H=8 D=64 bf16, causal, grad step fwd+bwd,
best-of-3 with the tunnel round-trip subtracted; authoritative clean
fresh-process rows in experiments/results/flash_attention.json):
T=2048 0.48 ms vs 2.29 ms unfused (**4.8x**); T=4096 2.23 ms vs
9.60 ms (**4.3x**; D=128: 4.4x); T=8192 the unfused path exhausts HBM
on the 16 GB chip while flash runs in 5.73 ms. An earlier same-protocol
sweep in a warm process read 512x512 at 1.55 ms for the T=4096 row
(~6x) — tunneled-chip run-to-run variance is ~40%, so treat the
speedup as 4-6x. The ``block_q=block_k=512`` defaults come from that
sweep: 128x128 blocks are only ~1.4x over unfused (accumulator-rescale
overhead dominates), 512-wide blocks are 3-4x faster than 128-wide;
the causal block skip (:func:`_k_blocks_for`) is worth ~2x at large T.

Long-context operation (measured round 5, v5e, 136M model): the
classic backward kernels keep the FULL opposite sequence VMEM-resident
per grid step, which overflows the 16 MB scoped VMEM stack at
T >= 8192 (17-20.5 MB allocations -> compile failure; raising
``xla_tpu_scoped_vmem_limit_kib`` to 28 MB bought T=8192 at 36.3k
tokens/s but 16k failed even at 48 MB). The fix is structural: at
T >= ``_BWD_2D_MIN_T`` the backward dispatches to 2-D-grid kernels
(``_dq_kernel_2d``/``_dkv_kernel_2d``) that stream BOTH sides in
blocks and accumulate outputs across sequential grid revisits —
residency is O(block x D) regardless of T, no compiler flags, and
512-wide blocks stay usable: **T=8192 trains end-to-end at 46.5k
tokens/s (+28% over the flag route) and T=16384 at 23.5k** on one
chip (experiments/results/long_context.json). The 1-D kernels keep
the short-T regime (their in-register fori_loop skips causal-dead
blocks entirely; the 2-D grid only masks them).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_NEG = -1e30  # masked-logit sentinel (finite: keeps exp/max NaN-free)


class _Cfg(NamedTuple):
    """Static kernel config (hashable: custom_vjp nondiff argument)."""

    causal: bool
    scale: float
    Tq: int  # real (unpadded) query length
    Tk: int  # real (unpadded) key length
    BQ: int
    BK: int
    interpret: bool


def _mask(cfg: _Cfg, i, j, q_off, k_off):
    """[BQ, BK] validity of (query block i, key block j): key PADDING is
    masked in local coordinates (padding is per-shard); the causal
    triangle compares GLOBAL positions ``q_off + local`` vs ``k_off +
    local`` — offsets are zero for single-shard use and ``rank * T``
    under the ring."""
    lrow = i * cfg.BQ + lax.broadcasted_iota(jnp.int32, (cfg.BQ, cfg.BK), 0)
    lcol = j * cfg.BK + lax.broadcasted_iota(jnp.int32, (cfg.BQ, cfg.BK), 1)
    valid = lcol < cfg.Tk
    if cfg.causal:
        valid = valid & ((q_off + lrow) >= (k_off + lcol))
    return valid


def _k_blocks_for(cfg: _Cfg, i, nk, q_off, k_off):
    """Last k-block index (exclusive) query block ``i`` touches: under
    causal masking blocks strictly above the (global) diagonal are
    all-masked and skipped entirely — ~2x less work at large T, and
    whole fully-future K/V shards cost ~nothing under the ring."""
    if not cfg.causal:
        return nk
    jmax = (q_off - k_off + i * cfg.BQ + cfg.BQ - 1) // cfg.BK + 1
    return jnp.clip(jmax, 0, nk)


def _q_block_start(cfg: _Cfg, j, q_off, k_off):
    """First q-block index whose rows can (causally) see key block
    ``j`` — the dkv-kernel mirror of :func:`_k_blocks_for`."""
    if not cfg.causal:
        return 0
    return jnp.maximum(0, (k_off + j * cfg.BK - q_off) // cfg.BQ)


def _fwd_kernel(cfg: _Cfg, qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref):
    i = pl.program_id(1)
    q_off, k_off = qo_ref[0, 0], ko_ref[0, 0]
    q = q_ref[0]  # [BQ, D], input dtype
    D = q.shape[-1]
    nk = k_ref.shape[1] // cfg.BK

    acc0 = jnp.zeros((cfg.BQ, D), jnp.float32)
    m0 = jnp.full((cfg.BQ, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((cfg.BQ, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        v = v_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        valid = _mask(cfg, i, j, q_off, k_off)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc, m, l = lax.fori_loop(
        0, _k_blocks_for(cfg, i, nk, q_off, k_off), body, (acc0, m0, l0)
    )
    # l == 0 only for rows with no visible key at all — impossible
    # single-shard (causal: the diagonal key is local), but routine for
    # a ring hop whose whole K/V shard is in the causal future; the safe
    # divisor yields o = 0 and an effectively -inf lse, which the ring
    # merge weights to zero
    l_safe = jnp.maximum(l, 1e-37)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # [BQ, 1]


def _dq_kernel(cfg: _Cfg, qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref,
               lse_ref, dsum_ref, dq_ref):
    i = pl.program_id(1)
    q_off, k_off = qo_ref[0, 0], ko_ref[0, 0]
    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # [BQ, 1]
    dsum = dsum_ref[0]
    nk = k_ref.shape[1] // cfg.BK

    def body(j, dq):
        k = k_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        v = v_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(_mask(cfg, i, j, q_off, k_off), jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(k.dtype)
        return dq + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = lax.fori_loop(
        0, _k_blocks_for(cfg, i, nk, q_off, k_off), body,
        jnp.zeros(q.shape, jnp.float32),
    )
    dq_ref[0] = dq  # f32: ring hops accumulate partials losslessly


def _dkv_kernel(cfg: _Cfg, qo_ref, ko_ref, q_ref, do_ref, lse_ref, dsum_ref,
                k_ref, v_ref, dk_ref, dv_ref):
    j = pl.program_id(1)
    q_off, k_off = qo_ref[0, 0], ko_ref[0, 0]
    k = k_ref[0]
    v = v_ref[0]
    nq = q_ref.shape[1] // cfg.BQ

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]
        do = do_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]   # [BQ, 1]
        dsum = dsum_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(
            _mask(cfg, i, j, q_off, k_off), jnp.exp(s - lse), 0.0
        )  # [BQ, BK]
        dv = dv + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(q.dtype)
        dk = dk + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    # causal: query blocks strictly below this key block's diagonal see
    # none of it — start at the first overlapping block
    dk, dv = lax.fori_loop(
        _q_block_start(cfg, j, q_off, k_off), nq, body, (dk0, dv0)
    )
    dk_ref[0] = dk  # f32: ring hops accumulate partials losslessly
    dv_ref[0] = dv


# Threshold (local sequence length) above which the backward runs on the
# 2-D-grid kernels below: the classic 1-D kernels keep the FULL opposite
# sequence VMEM-resident per grid step, which overflows the scoped VMEM
# stack at long T (module docstring); the 2-D variants stream both sides
# in blocks, so residency is O(BQ x D + BK x D) regardless of T. Kept at
# 8192 (not lower) because the 1-D kernels' in-register fori_loop avoids
# the 2-D grid's per-(i, j) output read-modify-write and its masked
# causal-skip steps in the short-T regime where they already fit.
# Tests monkeypatch this to exercise the 2-D path at small T.
_BWD_2D_MIN_T = 8192


def _dq_kernel_2d(cfg: _Cfg, qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref,
                  lse_ref, dsum_ref, dq_ref):
    """dq with BOTH sides blocked: grid (BH, q blocks, k blocks), the
    k dim innermost so ``dq_ref``'s block is revisited sequentially and
    accumulates in VMEM (written back when the q index advances)."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    q_off, k_off = qo_ref[0, 0], ko_ref[0, 0]
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    jmax = _k_blocks_for(cfg, i, nk, q_off, k_off)

    @pl.when(j < jmax)
    def _acc():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        dsum = dsum_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(_mask(cfg, i, j, q_off, k_off), jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(k.dtype)
        dq_ref[0] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )


def _dkv_kernel_2d(cfg: _Cfg, qo_ref, ko_ref, q_ref, do_ref, lse_ref,
                   dsum_ref, k_ref, v_ref, dk_ref, dv_ref):
    """(dk, dv) with both sides blocked: grid (BH, k blocks, q blocks),
    the q dim innermost so the per-key-block outputs accumulate in VMEM
    across the q sweep."""
    j = pl.program_id(1)
    i = pl.program_id(2)
    q_off, k_off = qo_ref[0, 0], ko_ref[0, 0]

    @pl.when(i == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    istart = _q_block_start(cfg, j, q_off, k_off)

    @pl.when(i >= istart)
    def _acc():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        dsum = dsum_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(_mask(cfg, i, j, q_off, k_off), jnp.exp(s - lse), 0.0)
        dv_ref[0] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(q.dtype)
        dk_ref[0] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )


def _zero_offs():
    z = jnp.zeros((1, 1), jnp.int32)
    return z, z


def _as_off(x) -> jax.Array:
    return jnp.reshape(jnp.asarray(x, jnp.int32), (1, 1))


def _smem_spec():
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((1, 1), lambda b, i: (0, 0), memory_space=pltpu.SMEM)


def _q_major(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(shape, lambda b, i: (b, i) + (0,) * (len(shape) - 2),
                        memory_space=pltpu.VMEM)


def _full(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(shape, lambda b, i: (b,) + (0,) * (len(shape) - 1),
                        memory_space=pltpu.VMEM)


# NOTE: _smem_spec3/_by mirror _smem_spec/_q_major/_full for the 3-dim
# (b, x, y) grids of the 2-D backward kernels — the index-map arity is
# part of pallas_call's contract, so the families cannot share a lambda;
# keep the two groups in sync when changing memory spaces or layouts.
def _smem_spec3():
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((1, 1), lambda b, x, y: (0, 0),
                        memory_space=pltpu.SMEM)


def _by(which: str, shape):
    """3-index-grid block spec selecting the grid dim that indexes this
    operand's second axis: 'x' = grid dim 1, 'y' = grid dim 2."""
    from jax.experimental.pallas import tpu as pltpu

    pick = (lambda b, x, y: (b, x) + (0,) * (len(shape) - 2)) if which == "x" \
        else (lambda b, x, y: (b, y) + (0,) * (len(shape) - 2))
    return pl.BlockSpec(shape, pick, memory_space=pltpu.VMEM)


def _fwd(cfg: _Cfg, q3, k3, v3, q_off, k_off):
    """Padded [BH, T_pad, D] flash forward -> (o, lse[BH, T_pad, 1])."""
    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg),
        grid=(BH, Tqp // cfg.BQ),
        in_specs=[
            _smem_spec(),                     # q_off
            _smem_spec(),                     # k_off
            _q_major((1, cfg.BQ, D)),         # q
            _full((1, Tkp, D)),               # k
            _full((1, Tkp, D)),               # v
        ],
        out_specs=(
            _q_major((1, cfg.BQ, D)),
            # [BH, Tqp, 1]: a trailing singleton lane keeps the block's
            # last-two dims Mosaic-legal ((BQ, 1) == (div 8, full dim))
            _q_major((1, cfg.BQ, 1)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tqp, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Tqp, 1), jnp.float32),
        ),
        interpret=cfg.interpret,
    )(q_off, k_off, q3, k3, v3)
    return o, lse


def _dq_call(cfg: _Cfg, q3, k3, v3, g, lse, dsum, q_off, k_off):
    """dq partial (f32) for one K/V shard, given the GLOBAL lse/dsum."""
    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    return pl.pallas_call(
        functools.partial(_dq_kernel, cfg),
        grid=(BH, Tqp // cfg.BQ),
        in_specs=[
            _smem_spec(), _smem_spec(),
            _q_major((1, cfg.BQ, D)),         # q
            _full((1, Tkp, D)),               # k
            _full((1, Tkp, D)),               # v
            _q_major((1, cfg.BQ, D)),         # dO
            _q_major((1, cfg.BQ, 1)),         # lse
            _q_major((1, cfg.BQ, 1)),         # dsum
        ],
        out_specs=_q_major((1, cfg.BQ, D)),
        out_shape=jax.ShapeDtypeStruct((BH, Tqp, D), jnp.float32),
        interpret=cfg.interpret,
    )(q_off, k_off, q3, k3, v3, g, lse, dsum)


def _dkv_call(cfg: _Cfg, q3, g, lse, dsum, k3, v3, q_off, k_off):
    """(dk, dv) partials (f32) for one K/V shard vs these queries."""
    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    return pl.pallas_call(
        functools.partial(_dkv_kernel, cfg),
        grid=(BH, Tkp // cfg.BK),
        in_specs=[
            _smem_spec(), _smem_spec(),
            _full((1, Tqp, D)),               # q
            _full((1, Tqp, D)),               # dO
            _full((1, Tqp, 1)),               # lse
            _full((1, Tqp, 1)),               # dsum
            _q_major((1, cfg.BK, D)),         # k block
            _q_major((1, cfg.BK, D)),         # v block
        ],
        out_specs=(_q_major((1, cfg.BK, D)), _q_major((1, cfg.BK, D))),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tkp, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tkp, D), jnp.float32),
        ),
        interpret=cfg.interpret,
    )(q_off, k_off, q3, g, lse, dsum, k3, v3)


def _dsum_of(g, o):
    """Per-row sum(dO * O) — the softmax-gradient correction term
    (padded rows of g are zero, so their dsum is zero); [BH, Tqp, 1]."""
    return jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )


def _dq_call_2d(cfg: _Cfg, q3, k3, v3, g, lse, dsum, q_off, k_off):
    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    return pl.pallas_call(
        functools.partial(_dq_kernel_2d, cfg),
        grid=(BH, Tqp // cfg.BQ, Tkp // cfg.BK),
        in_specs=[
            _smem_spec3(), _smem_spec3(),
            _by("x", (1, cfg.BQ, D)),         # q
            _by("y", (1, cfg.BK, D)),         # k
            _by("y", (1, cfg.BK, D)),         # v
            _by("x", (1, cfg.BQ, D)),         # dO
            _by("x", (1, cfg.BQ, 1)),         # lse
            _by("x", (1, cfg.BQ, 1)),         # dsum
        ],
        out_specs=_by("x", (1, cfg.BQ, D)),   # revisited over the k dim
        out_shape=jax.ShapeDtypeStruct((BH, Tqp, D), jnp.float32),
        interpret=cfg.interpret,
    )(q_off, k_off, q3, k3, v3, g, lse, dsum)


def _dkv_call_2d(cfg: _Cfg, q3, g, lse, dsum, k3, v3, q_off, k_off):
    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    return pl.pallas_call(
        functools.partial(_dkv_kernel_2d, cfg),
        grid=(BH, Tkp // cfg.BK, Tqp // cfg.BQ),
        in_specs=[
            _smem_spec3(), _smem_spec3(),
            _by("y", (1, cfg.BQ, D)),         # q
            _by("y", (1, cfg.BQ, D)),         # dO
            _by("y", (1, cfg.BQ, 1)),         # lse
            _by("y", (1, cfg.BQ, 1)),         # dsum
            _by("x", (1, cfg.BK, D)),         # k block
            _by("x", (1, cfg.BK, D)),         # v block
        ],
        out_specs=(_by("x", (1, cfg.BK, D)), _by("x", (1, cfg.BK, D))),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tkp, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tkp, D), jnp.float32),
        ),
        interpret=cfg.interpret,
    )(q_off, k_off, q3, g, lse, dsum, k3, v3)


def _bwd_dispatch(cfg: _Cfg, q3, k3, v3, g, lse, dsum, q_off, k_off):
    """(dq, dk, dv) partials via the 1-D kernels, or the block-streamed
    2-D kernels when either side's LOCAL length reaches _BWD_2D_MIN_T —
    the one dispatch shared by the local backward and every ring hop
    (a ring shard of 8k+ would otherwise rebuild the full-residency
    kernels the threshold exists to avoid)."""
    if max(q3.shape[1], k3.shape[1]) >= _BWD_2D_MIN_T:
        dq = _dq_call_2d(cfg, q3, k3, v3, g, lse, dsum, q_off, k_off)
        dk, dv = _dkv_call_2d(cfg, q3, g, lse, dsum, k3, v3, q_off, k_off)
    else:
        dq = _dq_call(cfg, q3, k3, v3, g, lse, dsum, q_off, k_off)
        dk, dv = _dkv_call(cfg, q3, g, lse, dsum, k3, v3, q_off, k_off)
    return dq, dk, dv


def _bwd(cfg: _Cfg, q3, k3, v3, o, lse, g):
    q_off, k_off = _zero_offs()
    dsum = _dsum_of(g, o)
    dq, dk, dv = _bwd_dispatch(cfg, q3, k3, v3, g, lse, dsum, q_off, k_off)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q3, k3, v3):
    o, _ = _fwd(cfg, q3, k3, v3, *_zero_offs())
    return o


def _flash_vjp_fwd(cfg, q3, k3, v3):
    o, lse = _fwd(cfg, q3, k3, v3, *_zero_offs())
    return o, (q3, k3, v3, o, lse)


def _flash_vjp_bwd(cfg, res, g):
    return _bwd(cfg, *res, g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _to_heads_major(x, B, T, H, D):
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, T, D)


def _prepare(q, k, v, causal, scale, precision, block_q, block_k):
    """Shared prologue of the public entry points: precision upcast,
    block sizing, heads-major reshape, padding. Returns
    ``(cfg, q3, k3, v3, shape_meta)`` where shape_meta =
    ``(B, Tq, H, D, out_dtype)`` for :func:`_finish`."""
    out_dtype = q.dtype
    if precision in (lax.Precision.HIGHEST, "highest", "float32"):
        q, k, v = (t.astype(jnp.float32) for t in (q, k, v))

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    BQ, BK = min(block_q, _ceil_to(Tq, 8)), min(block_k, _ceil_to(Tk, 8))
    Tqp, Tkp = _ceil_to(Tq, BQ), _ceil_to(Tk, BK)
    cfg = _Cfg(bool(causal), float(sc), Tq, Tk, BQ, BK, _interpret())

    q3 = _to_heads_major(q, B, Tq, H, D)
    k3 = _to_heads_major(k, B, Tk, H, D)
    v3 = _to_heads_major(v, B, Tk, H, D)
    if Tqp != Tq:
        q3 = jnp.pad(q3, ((0, 0), (0, Tqp - Tq), (0, 0)))
    if Tkp != Tk:
        k3 = jnp.pad(k3, ((0, 0), (0, Tkp - Tk), (0, 0)))
        v3 = jnp.pad(v3, ((0, 0), (0, Tkp - Tk), (0, 0)))
    return cfg, q3, k3, v3, (B, Tq, H, D, out_dtype)


def _finish(o_padded, shape_meta):
    """Shared epilogue: unpad, restore [B, Tq, H, D], original dtype."""
    B, Tq, H, D, out_dtype = shape_meta
    o = o_padded[:, :Tq]
    return jnp.transpose(o.reshape(B, H, Tq, D), (0, 2, 1, 3)).astype(out_dtype)


def flash_attention(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, H, D]
    v: jax.Array,  # [B, Tk, H, D]
    causal: bool = False,
    scale: Optional[float] = None,
    precision=None,
    *,
    block_q: int = 512,
    block_k: int = 512,
) -> jax.Array:
    """Fused blockwise attention, differentiable: drop-in for
    :func:`~theanompi_tpu.ops.ring_attention.full_attention_reference`.

    Sequence lengths are padded up to the block sizes internally
    (padded keys masked, padded query rows discarded); head dim is used
    as-is (Mosaic pads lanes — D a multiple of 128 is fastest).

    ``precision``: matmuls run in the INPUT dtype with fp32 accumulation
    (softmax statistics are always fp32); ``Precision.HIGHEST`` upcasts
    the q/k/v tiles to fp32 — same numerics knob as the unfused
    reference, at ~2x matmul cost for bf16 inputs.
    """
    if not _use_pallas():
        from theanompi_tpu.ops.ring_attention import full_attention_reference

        return full_attention_reference(
            q, k, v, causal=causal, scale=scale, precision=precision
        )

    cfg, q3, k3, v3, meta = _prepare(
        q, k, v, causal, scale, precision, block_q, block_k
    )
    return _finish(_flash(cfg, q3, k3, v3), meta)


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


# -- ring + flash: sequence-parallel attention with fused local folds -------


class _RingCfg(NamedTuple):
    cfg: _Cfg
    axis: str


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def _ring_fwd_parts(rcfg: _RingCfg, q3, k3, v3):
    """Distributed flash forward: each hop folds one K/V shard with the
    fused kernel, producing a per-hop (o_j, lse_j); hops merge by the
    logsumexp-rescale law. Exact (not approximate) global softmax."""
    cfg, ax = rcfg.cfg, rcfg.axis
    n = lax.psum(1, ax)
    rank = lax.axis_index(ax)
    BH, Tqp, D = q3.shape
    q_off = _as_off(rank * cfg.Tq)

    acc0 = jnp.zeros((BH, Tqp, D), jnp.float32)
    m0 = jnp.full((BH, Tqp, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((BH, Tqp, 1), jnp.float32)
    kv0 = jnp.stack([k3, v3])
    perm = _ring_perm(n)

    def hop(carry, t):
        acc, m, l, kv = carry
        src = jnp.mod(rank - t, n)
        o_j, lse_j = _fwd(cfg, q3, kv[0], kv[1], q_off, _as_off(src * cfg.Tk))
        # merge block j into the running (acc, m, l): a fully-masked hop
        # has lse_j ~ -1e30 and o_j = 0, weighting to zero
        m_new = jnp.maximum(m, lse_j)
        w_old = jnp.exp(m - m_new)
        w_new = jnp.exp(lse_j - m_new)
        acc = acc * w_old + o_j.astype(jnp.float32) * w_new
        l = l * w_old + w_new
        kv = lax.ppermute(kv, ax, perm)
        return (acc, m_new, l, kv), None

    (acc, m, l, _), _ = lax.scan(hop, (acc0, m0, l0, kv0), jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-37)
    o = (acc / l_safe).astype(q3.dtype)
    lse = m + jnp.log(l_safe)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_flash(rcfg: _RingCfg, q3, k3, v3):
    return _ring_fwd_parts(rcfg, q3, k3, v3)[0]


def _ring_flash_vjp_fwd(rcfg, q3, k3, v3):
    o, lse = _ring_fwd_parts(rcfg, q3, k3, v3)
    return o, (q3, k3, v3, o, lse)


def _ring_flash_vjp_bwd(rcfg, res, g):
    """Ring backward (Liu et al. blockwise formulation): dq accumulates
    locally across hops; (dk, dv) partials travel WITH their K/V shard
    (one extra ppermute pair per hop) and are home after the n-th
    rotation. The per-hop kernels take the GLOBAL lse/dsum, so each
    partial is exact — fp32 accumulation end to end."""
    cfg, ax = rcfg.cfg, rcfg.axis
    q3, k3, v3, o, lse = res
    n = lax.psum(1, ax)
    rank = lax.axis_index(ax)
    dsum = _dsum_of(g, o)
    q_off = _as_off(rank * cfg.Tq)
    perm = _ring_perm(n)

    dq0 = jnp.zeros(q3.shape, jnp.float32)
    kv0 = jnp.stack([k3, v3])
    dkv0 = jnp.zeros(kv0.shape, jnp.float32)

    def hop(carry, t):
        dq, kv, dkv = carry
        src = jnp.mod(rank - t, n)
        k_off = _as_off(src * cfg.Tk)
        dq_j, dk_j, dv_j = _bwd_dispatch(
            cfg, q3, kv[0], kv[1], g, lse, dsum, q_off, k_off
        )
        dq = dq + dq_j
        dkv = dkv + jnp.stack([dk_j, dv_j])
        kv = lax.ppermute(kv, ax, perm)
        dkv = lax.ppermute(dkv, ax, perm)
        return (dq, kv, dkv), None

    (dq, _, dkv), _ = lax.scan(hop, (dq0, kv0, dkv0), jnp.arange(n))
    return dq.astype(q3.dtype), dkv[0].astype(k3.dtype), dkv[1].astype(v3.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(
    q: jax.Array,  # [B, T_local, H, D] — this shard's queries
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    precision=None,
    *,
    block_q: int = 512,
    block_k: int = 512,
) -> jax.Array:
    """Sequence-parallel ring attention whose per-hop fold IS the fused
    flash kernel — the composition of
    :func:`~theanompi_tpu.ops.ring_attention.ring_attention` (K/V
    rotation over ``axis_name``, one ppermute per hop, O(T/n) memory)
    with this module's Pallas kernels (no [T_local, T_local] score
    materialization per hop either). Must run inside ``shard_map`` with
    the sequence dim sharded over ``axis_name``; causal masking is in
    GLOBAL position order via the kernels' offset scalars, and the
    causal block skip makes fully-future K/V shards cost ~nothing.
    Differentiable via a whole-ring custom VJP (backward rings the K/V
    shards again, dk/dv partials traveling with them).

    ``precision=HIGHEST`` upcasts tiles to fp32 as in
    :func:`flash_attention`. ``TMPI_PALLAS=0`` falls back to the
    unfused :func:`~theanompi_tpu.ops.ring_attention.ring_attention`.
    """
    if not _use_pallas():
        from theanompi_tpu.ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, axis_name, causal=causal, scale=scale, precision=precision
        )

    cfg, q3, k3, v3, meta = _prepare(
        q, k, v, causal, scale, precision, block_q, block_k
    )
    return _finish(_ring_flash(_RingCfg(cfg, axis_name), q3, k3, v3), meta)
