"""Pallas fused flash attention — forward + custom-VJP backward TPU kernels.

BEYOND-PARITY EXTENSION. The 2016 reference has no attention op anywhere
(SURVEY.md §5.7); this module is the TPU-native fused kernel behind the
framework's long-context demonstrators. XLA's default lowering of
softmax attention materializes the [B, H, T, T] score matrix in HBM
twice (forward + transposed backward); the flash formulation (online
softmax over K/V blocks, Dao et al.) keeps scores in VMEM tiles and
streams K/V through them, making attention HBM-traffic-bound in O(T·D)
instead of O(T^2). Both passes are Pallas TPU kernels:

- forward: one kernel, grid over (batch·heads, query blocks); K/V loops
  run as ``fori_loop`` over VMEM slices; per-row logsumexp is saved as
  the softmax residual.
- backward: the classic two-kernel split — a dq kernel gridded over
  query blocks and a dk/dv kernel gridded over key blocks — each
  recomputing the probability tiles from (q, k, lse) so the O(T^2)
  matrix never exists in either pass.

Numerics: the q·k^T and p·v matmuls run in the INPUT dtype on the MXU
with fp32 accumulation (``preferred_element_type``); softmax statistics,
probability tiles, and all gradient accumulators are fp32. For fp32
inputs the result matches the unfused reference to float tolerance
(tests/test_pallas_attention.py).

Layout contract matches :func:`theanompi_tpu.ops.ring_attention.
full_attention_reference`: ``[B, T, H, D] -> [B, Tq, H, D]``, optional
causal masking in GLOBAL position order (query i attends keys <= i).
Off-TPU the kernels run through the Pallas interpreter — identical
numerics on the CPU test meshes. ``TMPI_PALLAS=0`` falls back to the
unfused reference implementation.

K/V (and in backward Q) blocks for one batch·head row must fit VMEM:
fine through T ~ 8-16k at D <= 128; beyond that use
:func:`~theanompi_tpu.ops.ring_attention.ring_attention`, whose local
block this kernel exactly is (each device's ring hop folds one K/V
shard — the same online-softmax recurrence, distributed).

Measured (one TPU v5e, B=4 H=8 D=64 bf16, causal, grad step fwd+bwd,
best-of-3 with the tunnel round-trip subtracted; authoritative clean
fresh-process rows in experiments/results/flash_attention.json):
T=2048 0.48 ms vs 2.29 ms unfused (**4.8x**); T=4096 2.23 ms vs
9.60 ms (**4.3x**; D=128: 4.4x); T=8192 the unfused path exhausts HBM
on the 16 GB chip while flash runs in 5.73 ms. An earlier same-protocol
sweep in a warm process read 512x512 at 1.55 ms for the T=4096 row
(~6x) — tunneled-chip run-to-run variance is ~40%, so treat the
speedup as 4-6x. The ``block_q=block_k=512`` defaults come from that
sweep: 128x128 blocks are only ~1.4x over unfused (accumulator-rescale
overhead dominates), 512-wide blocks are 3-4x faster than 128-wide;
the causal block skip (:func:`_k_blocks_for`) is worth ~2x at large T.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_NEG = -1e30  # masked-logit sentinel (finite: keeps exp/max NaN-free)


class _Cfg(NamedTuple):
    """Static kernel config (hashable: custom_vjp nondiff argument)."""

    causal: bool
    scale: float
    Tq: int  # real (unpadded) query length
    Tk: int  # real (unpadded) key length
    BQ: int
    BK: int
    interpret: bool


def _mask(cfg: _Cfg, i, j):
    """[BQ, BK] validity of (query block i, key block j) in GLOBAL
    positions: key padding masked always, lower-triangle when causal."""
    row = i * cfg.BQ + lax.broadcasted_iota(jnp.int32, (cfg.BQ, cfg.BK), 0)
    col = j * cfg.BK + lax.broadcasted_iota(jnp.int32, (cfg.BQ, cfg.BK), 1)
    valid = col < cfg.Tk
    if cfg.causal:
        valid = valid & (row >= col)
    return valid


def _k_blocks_for(cfg: _Cfg, i, nk):
    """Last k-block index (exclusive) query block ``i`` touches: under
    causal masking blocks strictly above the diagonal are all-masked and
    skipped entirely — ~2x less work at large T."""
    if not cfg.causal:
        return nk
    return jnp.minimum(nk, (i * cfg.BQ + cfg.BQ - 1) // cfg.BK + 1)


def _fwd_kernel(cfg: _Cfg, q_ref, k_ref, v_ref, o_ref, lse_ref):
    i = pl.program_id(1)
    q = q_ref[0]  # [BQ, D], input dtype
    D = q.shape[-1]
    nk = k_ref.shape[1] // cfg.BK

    acc0 = jnp.zeros((cfg.BQ, D), jnp.float32)
    m0 = jnp.full((cfg.BQ, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((cfg.BQ, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        v = v_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        valid = _mask(cfg, i, j)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc, m, l = lax.fori_loop(0, _k_blocks_for(cfg, i, nk), body, (acc0, m0, l0))
    # causal guarantees key j=row is valid for every real row; padded
    # rows still see all real keys (causal: keys <= row, row >= Tq-1),
    # so l > 0 everywhere
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)  # [BQ, 1]


def _dq_kernel(cfg: _Cfg, q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, dq_ref):
    i = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # [BQ, 1]
    dsum = dsum_ref[0]
    nk = k_ref.shape[1] // cfg.BK

    def body(j, dq):
        k = k_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        v = v_ref[0, pl.ds(j * cfg.BK, cfg.BK), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(_mask(cfg, i, j), jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(k.dtype)
        return dq + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = lax.fori_loop(
        0, _k_blocks_for(cfg, i, nk), body, jnp.zeros(q.shape, jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(cfg: _Cfg, q_ref, do_ref, lse_ref, dsum_ref, k_ref, v_ref,
                dk_ref, dv_ref):
    j = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    nq = q_ref.shape[1] // cfg.BQ

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]
        do = do_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]   # [BQ, 1]
        dsum = dsum_ref[0, pl.ds(i * cfg.BQ, cfg.BQ), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        p = jnp.where(_mask(cfg, i, j), jnp.exp(s - lse), 0.0)  # [BQ, BK]
        dv = dv + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - dsum) * cfg.scale).astype(q.dtype)
        dk = dk + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    # causal: query blocks strictly below this key block's diagonal see
    # none of it — start at the first overlapping block
    i0 = (j * cfg.BK) // cfg.BQ if cfg.causal else 0
    dk, dv = lax.fori_loop(i0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fwd(cfg: _Cfg, q3, k3, v3):
    """Padded [BH, T_pad, D] flash forward -> (o, lse)."""
    from jax.experimental.pallas import tpu as pltpu

    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    grid = (BH, Tqp // cfg.BQ)
    kv_spec = pl.BlockSpec(
        (1, Tkp, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM
    )
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, cfg.BQ, D), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            kv_spec,
            kv_spec,
        ],
        out_specs=(
            pl.BlockSpec((1, cfg.BQ, D), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # [BH, Tqp, 1]: a trailing singleton lane keeps the block's
            # last-two dims Mosaic-legal ((BQ, 1) == (div 8, full dim))
            pl.BlockSpec((1, cfg.BQ, 1), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tqp, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Tqp, 1), jnp.float32),
        ),
        interpret=cfg.interpret,
    )(q3, k3, v3)
    return o, lse


def _bwd(cfg: _Cfg, q3, k3, v3, o, lse, g):
    from jax.experimental.pallas import tpu as pltpu

    BH, Tqp, D = q3.shape
    Tkp = k3.shape[1]
    # per-row sum(dO * O) — the softmax-gradient correction term
    # (padded rows of g are zero, so their dsum is zero); [BH, Tqp, 1]
    dsum = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    def q_major(shape):
        return pl.BlockSpec(shape, lambda b, i: (b, i) + (0,) * (len(shape) - 2),
                            memory_space=pltpu.VMEM)

    def full(shape):
        return pl.BlockSpec(shape, lambda b, i: (b,) + (0,) * (len(shape) - 1),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg),
        grid=(BH, Tqp // cfg.BQ),
        in_specs=[
            q_major((1, cfg.BQ, D)),          # q
            full((1, Tkp, D)),                # k
            full((1, Tkp, D)),                # v
            q_major((1, cfg.BQ, D)),          # dO
            q_major((1, cfg.BQ, 1)),          # lse
            q_major((1, cfg.BQ, 1)),          # dsum
        ],
        out_specs=q_major((1, cfg.BQ, D)),
        out_shape=jax.ShapeDtypeStruct((BH, Tqp, D), q3.dtype),
        interpret=cfg.interpret,
    )(q3, k3, v3, g, lse, dsum)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg),
        grid=(BH, Tkp // cfg.BK),
        in_specs=[
            full((1, Tqp, D)),                # q
            full((1, Tqp, D)),                # dO
            full((1, Tqp, 1)),                # lse
            full((1, Tqp, 1)),                # dsum
            q_major((1, cfg.BK, D)),          # k block
            q_major((1, cfg.BK, D)),          # v block
        ],
        out_specs=(q_major((1, cfg.BK, D)), q_major((1, cfg.BK, D))),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tkp, D), k3.dtype),
            jax.ShapeDtypeStruct((BH, Tkp, D), v3.dtype),
        ),
        interpret=cfg.interpret,
    )(q3, g, lse, dsum, k3, v3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q3, k3, v3):
    o, _ = _fwd(cfg, q3, k3, v3)
    return o


def _flash_vjp_fwd(cfg, q3, k3, v3):
    o, lse = _fwd(cfg, q3, k3, v3)
    return o, (q3, k3, v3, o, lse)


def _flash_vjp_bwd(cfg, res, g):
    return _bwd(cfg, *res, g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _to_heads_major(x, B, T, H, D):
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, T, D)


def flash_attention(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, H, D]
    v: jax.Array,  # [B, Tk, H, D]
    causal: bool = False,
    scale: Optional[float] = None,
    precision=None,
    *,
    block_q: int = 512,
    block_k: int = 512,
) -> jax.Array:
    """Fused blockwise attention, differentiable: drop-in for
    :func:`~theanompi_tpu.ops.ring_attention.full_attention_reference`.

    Sequence lengths are padded up to the block sizes internally
    (padded keys masked, padded query rows discarded); head dim is used
    as-is (Mosaic pads lanes — D a multiple of 128 is fastest).

    ``precision``: matmuls run in the INPUT dtype with fp32 accumulation
    (softmax statistics are always fp32); ``Precision.HIGHEST`` upcasts
    the q/k/v tiles to fp32 — same numerics knob as the unfused
    reference, at ~2x matmul cost for bf16 inputs.
    """
    if not _use_pallas():
        from theanompi_tpu.ops.ring_attention import full_attention_reference

        return full_attention_reference(
            q, k, v, causal=causal, scale=scale, precision=precision
        )

    out_dtype = q.dtype
    if precision in (lax.Precision.HIGHEST, "highest", "float32"):
        q, k, v = (t.astype(jnp.float32) for t in (q, k, v))

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    BQ, BK = min(block_q, _ceil_to(Tq, 8)), min(block_k, _ceil_to(Tk, 8))
    Tqp, Tkp = _ceil_to(Tq, BQ), _ceil_to(Tk, BK)
    cfg = _Cfg(bool(causal), float(sc), Tq, Tk, BQ, BK, _interpret())

    q3 = _to_heads_major(q, B, Tq, H, D)
    k3 = _to_heads_major(k, B, Tk, H, D)
    v3 = _to_heads_major(v, B, Tk, H, D)
    if Tqp != Tq:
        q3 = jnp.pad(q3, ((0, 0), (0, Tqp - Tq), (0, 0)))
    if Tkp != Tk:
        k3 = jnp.pad(k3, ((0, 0), (0, Tkp - Tk), (0, 0)))
        v3 = jnp.pad(v3, ((0, 0), (0, Tkp - Tk), (0, 0)))

    o = _flash(cfg, q3, k3, v3)[:, :Tq]
    return jnp.transpose(o.reshape(B, H, Tq, D), (0, 2, 1, 3)).astype(out_dtype)


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m
