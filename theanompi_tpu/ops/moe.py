"""Switch-style mixture-of-experts with expert parallelism (EP).

BEYOND-PARITY EXTENSION (the reference is a 2016 CNN framework with no
MoE; SURVEY.md §2.3 lists EP "absent — not required", and the named-mesh
design note makes the axis additive). This is the TPU-idiomatic GShard/
Switch formulation: top-1 routing realized as DENSE one-hot dispatch
einsums (no data-dependent shapes — everything jits), experts sharded
over an ``expert`` mesh axis, tokens exchanged with ``lax.all_to_all``
over ICI.

Data layout inside ``shard_map`` over the expert axis (size n):

- every device carries its own token batch (the expert axis doubles as
  the data axis — the classic dp==ep fusion);
- expert weights are sharded on their leading dim: device i owns experts
  ``[i*E/n, (i+1)*E/n)``;
- dispatch: route local tokens into per-expert capacity slots
  ``[E, C, d]``, all-to-all so each device holds its experts' slots from
  EVERY peer ``[E/n, n*C, d]``, apply the local experts, all-to-all
  back, combine scaled by the gate probability.

Tokens beyond an expert's capacity are dropped (the residual stream
carries them unchanged) — Switch semantics. With ``axis_name=None`` the
same code runs dense on one device (the test oracle and the small-scale
fallback).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class MoEStats(NamedTuple):
    aux_loss: jax.Array  # load-balance penalty (Switch: E * sum f_e * P_e)
    dropped_frac: jax.Array  # fraction of tokens beyond capacity


def switch_moe(
    x: jax.Array,  # [S, d] local tokens (flatten batch x seq first)
    gate_w: jax.Array,  # [d, E] replicated router
    expert_in: jax.Array,  # [E_local, d, h] this device's experts
    expert_out: jax.Array,  # [E_local, h, d]
    axis_name: Optional[str],
    capacity_factor: float = 1.25,
    stats_axes: Optional[tuple] = None,
) -> tuple[jax.Array, MoEStats]:
    """Top-1 (Switch) MoE layer. Returns ``(y [S, d], MoEStats)`` where
    ``y`` is zero for dropped tokens (caller adds the residual).

    ``E = n * E_local`` experts globally; capacity per expert per device
    ``C = ceil(S * capacity_factor / E)``. The load-balance ``aux_loss``
    uses GLOBAL token statistics — averaged over ``stats_axes`` (default:
    the expert axis; pass every axis the tokens are sharded over, e.g.
    ``(expert, seq)``) — so its value, and therefore the training
    objective, is identical to the dense single-device computation
    (tested in tests/test_moe.py).
    """
    if stats_axes is None:
        stats_axes = (axis_name,) if axis_name is not None else ()
    stats_axes = tuple(a for a in stats_axes if a is not None)
    S, d = x.shape
    E_local = expert_in.shape[0]
    n = lax.psum(1, axis_name) if axis_name is not None else 1
    E = n * E_local
    C = math.ceil(S * capacity_factor / E)

    logits = x @ gate_w  # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    p = jnp.max(probs, axis=-1)  # [S] gate scale of the chosen expert
    e = jnp.argmax(probs, axis=-1)  # [S]
    # routing bookkeeping in f32 regardless of x.dtype: a bf16 cumsum
    # cannot count past 256 (8 mantissa bits), which would collide
    # capacity-slot assignments for popular experts with no error
    onehot = jax.nn.one_hot(e, E, dtype=jnp.float32)  # [S, E]

    # slot position of each token within its expert's capacity buffer
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [S, E]
    kept = (pos < C) & (onehot > 0)
    dropped = 1.0 - kept.any(axis=-1).astype(jnp.float32)
    slot = jax.nn.one_hot(pos.sum(axis=-1).astype(jnp.int32), C, dtype=jnp.float32)
    dispatch = (kept.astype(jnp.float32)[:, :, None] * slot[:, None, :]).astype(
        x.dtype
    )  # [S, E, C]

    buf = jnp.einsum("sec,sd->ecd", dispatch, x)  # [E, C, d]
    if axis_name is not None:
        # scatter experts to their owners, gather every peer's slots
        buf = lax.all_to_all(
            buf, axis_name, split_axis=0, concat_axis=1, tiled=True
        )  # [E_local, n*C, d]
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", buf, expert_in))
    out = jnp.einsum("ech,ehd->ecd", h, expert_out)  # [E_local, n*C, d]
    if axis_name is not None:
        out = lax.all_to_all(
            out, axis_name, split_axis=1, concat_axis=0, tiled=True
        )  # [E, C, d]
    y = jnp.einsum("sec,ecd->sd", dispatch, out) * p[:, None]

    # Switch load balance on GLOBAL stats: f_e = fraction of tokens
    # routed to e, P_e = mean router prob of e
    f_e = jnp.mean(onehot, axis=0)
    P_e = jnp.mean(probs, axis=0)
    n_drop = jnp.sum(dropped)
    for a in stats_axes:
        f_e = lax.pmean(f_e, a)
        P_e = lax.pmean(P_e, a)
        n_drop = lax.pmean(n_drop, a)
    aux = E * jnp.sum(f_e * P_e)
    return y, MoEStats(aux_loss=aux, dropped_frac=n_drop / S)
