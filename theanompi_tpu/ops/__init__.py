"""Pure-function compute ops: optimizers, LR schedules, numerics.

TPU-native replacement for the reference's ``lib/opt.py`` update-rule
builders (reference mount empty at build time — anchors per SURVEY.md §2.1).
"""

from theanompi_tpu.ops.optimizers import (  # noqa: F401
    Optimizer,
    adam,
    get_optimizer,
    momentum_sgd,
    nesterov_sgd,
    rmsprop,
    sgd,
)
from theanompi_tpu.ops.lr_schedules import (  # noqa: F401
    constant,
    exponential_decay,
    get_schedule,
    linear_warmup_cosine,
    polynomial_decay,
    step_decay,
)
