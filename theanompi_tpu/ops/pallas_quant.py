"""Pallas int8 quantize/dequantize kernels — the compressed-wire
building block for gradient exchange.

The reference's ``Exch_asa16`` cast ring segments to fp16 on the wire
(reference: ``lib/exchanger_strategy.py``; SURVEY.md §2.3 "fp16-
compressed comm"); the TPU-native escalation is int8 with a per-chunk
scale (EQuARX-style, PAPERS.md): 4x wire compression vs fp32 with the
accumulation still fp32. The quantize/dequantize hot loops are Pallas
TPU kernels (VPU elementwise over VMEM tiles); off-TPU (CPU test
meshes) the same kernels run through the Pallas interpreter, so the
numerics are identical everywhere.

Layout: kernels take the flat buffer reshaped to (rows, 128) lanes —
the natural VPU shape; callers pad to a multiple of 128 (the ring
already pads segments).

``TMPI_PALLAS=0`` switches to the pure-jnp fallback (same math).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_LANES = 128


def _quant_kernel(x_ref, vals_ref, scale_ref):
    amax = jnp.max(jnp.abs(x_ref[:]))
    scale = jnp.maximum(amax, 1e-30) / 127.0
    scale_ref[0, 0] = scale
    scaled = x_ref[:] / scale
    # round-to-nearest-even, clamp to int8 range
    vals_ref[:] = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)


def _dequant_kernel(vals_ref, scale_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scale_ref[0, 0]


def _quantize_jnp(x2d):
    amax = jnp.max(jnp.abs(x2d))
    scale = jnp.maximum(amax, 1e-30) / 127.0
    vals = jnp.clip(jnp.round(x2d / scale), -127, 127).astype(jnp.int8)
    return vals, jnp.reshape(scale, (1, 1))


def quantize_int8(x2d: jax.Array):
    """``(rows, 128) f32 -> ((rows, 128) int8, (1, 1) f32 scale)`` with a
    single per-buffer absmax scale."""
    if not _use_pallas():
        return _quantize_jnp(x2d)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _quant_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        interpret=_interpret(),
    )(x2d)


def dequantize_int8(vals: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_int8`."""
    if not _use_pallas():
        return vals.astype(jnp.float32) * scale[0, 0]
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct(vals.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(vals, scale)


def wire_encode(chunk: jax.Array) -> jax.Array:
    """Flat f32 chunk -> ONE packed int8 message ``(rows + 1, 128)``:
    quantized lanes plus a final row carrying the f32 scale's 4 bytes —
    a single ppermute per ring hop instead of a values+scale pair (the
    hops are latency-bound, especially over DCN). Chunk length must be a
    multiple of 128 (ring segments are padded)."""
    rows = chunk.shape[0] // _LANES
    vals, scale = quantize_int8(chunk.reshape(rows, _LANES))
    scale_bytes = jax.lax.bitcast_convert_type(scale, jnp.int8).reshape(1, 4)
    tail = jnp.zeros((1, _LANES), jnp.int8).at[:, :4].set(scale_bytes)
    return jnp.concatenate([vals, tail], axis=0)


def wire_decode(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`wire_encode` -> flat f32."""
    vals = packed[:-1]
    scale = jax.lax.bitcast_convert_type(
        packed[-1, :4].reshape(1, 1, 4), jnp.float32
    ).reshape(1, 1)
    return dequantize_int8(vals, scale).reshape(-1)

