"""Pallas int8 quantize/dequantize kernels — the compressed-wire
building block for gradient exchange.

The reference's ``Exch_asa16`` cast ring segments to fp16 on the wire
(reference: ``lib/exchanger_strategy.py``; SURVEY.md §2.3 "fp16-
compressed comm"); the TPU-native escalation is int8 with a per-block
scale (EQuARX-style, PAPERS.md): ~4x wire compression vs fp32 with the
accumulation still fp32. The quantize/dequantize hot loops are Pallas
TPU kernels (VPU elementwise over VMEM tiles); off-TPU (CPU test
meshes) the same kernels run through the Pallas interpreter, so the
numerics are identical everywhere.

Two scale granularities:

- **per-buffer** (``quantize_int8``): one absmax scale for the whole
  chunk — the original ring-segment scheme;
- **per-block** (``quantize_int8_block``): one absmax scale per
  (1, 128) lane row — the block-scaled recipe the codec layer
  (``parallel/codec.py``) uses per leaf, so one huge outlier only
  costs its own 128-element block the dynamic range.

Layout: kernels take the flat buffer reshaped to (rows, 128) lanes —
the natural VPU shape. ``wire_encode``/``wire_decode`` accept ANY
length (internal zero-pad to a 128 multiple; 1-element leaves work)
and pack values + block scales into ONE int8 message.

``TMPI_PALLAS=0`` switches to the pure-jnp fallback (same math).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_LANES = 128
# f32 scale bytes per value row packed into the wire tail (one f32 per
# 128-lane block -> 32 block scales per 128-byte scale row)
_SCALES_PER_ROW = _LANES // 4


def _quant_kernel(x_ref, vals_ref, scale_ref):
    amax = jnp.max(jnp.abs(x_ref[:]))
    scale = jnp.maximum(amax, 1e-30) / 127.0
    scale_ref[0, 0] = scale
    scaled = x_ref[:] / scale
    # round-to-nearest-even, clamp to int8 range
    vals_ref[:] = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)


def _dequant_kernel(vals_ref, scale_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scale_ref[0, 0]


def _quantize_jnp(x2d):
    amax = jnp.max(jnp.abs(x2d))
    scale = jnp.maximum(amax, 1e-30) / 127.0
    vals = jnp.clip(jnp.round(x2d / scale), -127, 127).astype(jnp.int8)
    return vals, jnp.reshape(scale, (1, 1))


def quantize_int8(x2d: jax.Array):
    """``(rows, 128) f32 -> ((rows, 128) int8, (1, 1) f32 scale)`` with a
    single per-buffer absmax scale."""
    if not _use_pallas():
        return _quantize_jnp(x2d)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _quant_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        interpret=_interpret(),
    )(x2d)


def dequantize_int8(vals: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_int8`."""
    if not _use_pallas():
        return vals.astype(jnp.float32) * scale[0, 0]
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct(vals.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(vals, scale)


# --------------------------------------------------------------------------
# block-scaled variants: one absmax scale per (1, 128) lane row — the
# per-leaf block quantizer the codec layer builds on
# --------------------------------------------------------------------------


def _quant_block_kernel(x_ref, vals_ref, scale_ref):
    # per-row reduction stays in VMEM (vector data, not a scalar):
    # keepdims shapes line up with the (rows, 1) scale output
    amax = jnp.max(jnp.abs(x_ref[:]), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    scale_ref[:] = scale
    vals_ref[:] = jnp.clip(jnp.round(x_ref[:] / scale), -127, 127).astype(
        jnp.int8
    )


def _dequant_block_kernel(vals_ref, scale_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scale_ref[:]


def _quantize_block_jnp(x2d):
    amax = jnp.max(jnp.abs(x2d), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    vals = jnp.clip(jnp.round(x2d / scale), -127, 127).astype(jnp.int8)
    return vals, scale


def quantize_int8_block(x2d: jax.Array):
    """``(rows, 128) f32 -> ((rows, 128) int8, (rows, 1) f32 scales)``
    with one absmax scale PER ROW (128-element block)."""
    if not _use_pallas():
        return _quantize_block_jnp(x2d)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _quant_block_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
            jax.ShapeDtypeStruct((x2d.shape[0], 1), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(x2d)


def dequantize_int8_block(vals: jax.Array, scales: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_int8_block`."""
    if not _use_pallas():
        return vals.astype(jnp.float32) * scales
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _dequant_block_kernel,
        out_shape=jax.ShapeDtypeStruct(vals.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(vals, scales)


# --------------------------------------------------------------------------
# packed wire format: values + block scales in ONE int8 message
# --------------------------------------------------------------------------


def _pad_rows(flat: jax.Array) -> jax.Array:
    """Zero-pad a flat f32 vector to a (rows, 128) lane layout."""
    L = flat.shape[0]
    rows = -(-L // _LANES)
    pad = rows * _LANES - L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def wire_rows(length: int) -> tuple:
    """``(value_rows, scale_rows)`` of the packed message for a flat
    buffer of ``length`` elements — the static wire-geometry helper the
    traffic accounting shares with the encoder."""
    if length < 1:
        raise ValueError(f"cannot wire-encode a length-{length} buffer")
    rows = -(-length // _LANES)
    srows = -(-rows // _SCALES_PER_ROW)
    return rows, srows


def _rows_from_packed(n_rows: int) -> int:
    """Invert ``rows + ceil(rows/32) == n_rows`` (strictly increasing in
    ``rows``, so the solution is unique); static shapes only."""
    for rows in range(1, n_rows):
        if rows + -(-rows // _SCALES_PER_ROW) == n_rows:
            return rows
    raise ValueError(f"not a packed wire message: {n_rows} rows")


def wire_encode(chunk: jax.Array) -> jax.Array:
    """Flat f32 chunk of ANY length >= 1 -> ONE packed int8 message
    ``(rows + ceil(rows/32), 128)``: block-quantized lanes plus tail
    rows carrying the per-block f32 scales' bytes — a single ppermute
    per ring hop instead of a values+scales pair (the hops are
    latency-bound, especially over DCN). Non-128-multiple lengths are
    zero-padded internally (decode with ``length=`` to strip); a
    zero-filled buffer encodes to zeros and decodes to exact zeros (the
    scale floor keeps it finite — no NaN/Inf on decode)."""
    rows, srows = wire_rows(chunk.shape[0])
    vals, scales = quantize_int8_block(_pad_rows(chunk))
    scale_bytes = jax.lax.bitcast_convert_type(
        scales.reshape(rows), jnp.int8
    ).reshape(-1)
    tail = (
        jnp.zeros((srows * _LANES,), jnp.int8)
        .at[: rows * 4]
        .set(scale_bytes)
        .reshape(srows, _LANES)
    )
    return jnp.concatenate([vals, tail], axis=0)


def wire_decode(packed: jax.Array, length: Optional[int] = None) -> jax.Array:
    """Inverse of :func:`wire_encode` -> flat f32 of the padded length
    ``rows * 128`` (callers that encoded a non-128-multiple buffer pass
    their static ``length`` to strip the zero pad)."""
    if length is not None:
        rows, srows = wire_rows(length)
        if rows + srows != packed.shape[0]:
            raise ValueError(
                f"packed message has {packed.shape[0]} rows but length="
                f"{length} implies {rows + srows}"
            )
    else:
        rows = _rows_from_packed(packed.shape[0])
    vals = packed[:rows]
    scales = jax.lax.bitcast_convert_type(
        packed[rows:].reshape(-1)[: rows * 4].reshape(rows, 1, 4),
        jnp.float32,
    ).reshape(rows, 1)
    flat = dequantize_int8_block(vals, scales).reshape(-1)
    if length is not None:
        flat = flat[:length]
    return flat
