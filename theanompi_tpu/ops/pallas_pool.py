"""Pallas max-pool (3x3, stride 1, SAME) with a fused eq-mask backward
— MEASURED AND REJECTED as the default path; opt-in via
``TMPI_PALLAS_POOL=1``.

Why this kernel was built: GoogLeNet's nine inception pool branches are
3x3/stride-1 max pools, and XLA lowers the AD of ``reduce_window`` max
to ``select-and-scatter`` — ~36 ms of a 202 ms batch-1024 step on one
v5e (round-4 ``tools/op_profile`` table), ~18% of the step in pool
BACKWARD alone. The classic eq-mask backward
(``dx[p] = sum_over_window_offsets g[q] * [x[p] == y[q]]``) is
bandwidth-optimal on paper; the pure-jnp formulation loses because XLA
won't fuse the 9-way shifted accumulation (135 ms for ONE batch-1024
28x28x480 pool vs ~3 ms s-a-s), so this Pallas version keeps the whole
spatial map in one VMEM block (inception maps are <= 28x28) and runs
the accumulation register-resident.

**Measured result (round 4, v5e, batch 1024): end-to-end GoogLeNet
5094 -> 2472 img/s with this kernel routed in — a 2.1x LOSS.** Two
physics reasons, recorded for the next person who tries:

1. In NHWC the +-1 spatial shifts fall on W — the SUBLANE dim of the
   (8, 128) vector tile — so every shifted read is a misaligned
   sublane shuffle, not an addressed VMEM row. Cheap shifts need H/W
   ABOVE the tile, i.e. an HWNC layout, and the NHWC<->HWNC transposes
   around the kernel cost ~as much as select-and-scatter itself.
2. The custom call is a fusion barrier: the reduce_window forward
   otherwise fuses into its neighbors (the ``broadcast_maximum_fusion``
   ops in the profile), and the custom VJP's saved ``y`` residual adds
   a full activation copy of HBM traffic.

So select-and-scatter is close to the practical optimum for NHWC max
pool on this target, and the kernel stays opt-in only.

Tie semantics when enabled: the gradient goes to EVERY position equal
to the window max (a valid subgradient). This matches the reference
stack — Theano's ``DownsampleFactorMaxGrad`` computed exactly this
eq-mask — while XLA's select-and-scatter picks the first maximum.
Tests pin tie-free equivalence with select-and-scatter and the
all-maxima tie behavior; off-TPU the kernels run in the Pallas
interpreter, and ``TMPI_PALLAS=0`` selects a jnp fallback with the
same semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.ops.pallas_util import interpret_mode as _interpret
from theanompi_tpu.ops.pallas_util import use_pallas as _use_pallas

_LANES = 128
# VMEM budget per buffer copy (bytes) when picking the batch tile.
# Mosaic materializes each of the 9 shifted slices on the kernel's VMEM
# stack (~12 block-sized temporaries total incl. the framed buffers and
# the f32 accumulator), so the per-buffer budget must leave the 16 MB
# scoped-vmem limit room for all of them: 2 MB blocks OOM'd at
# 18.5 MB stack; 512 KB keeps the stack ~5 MB.
_BLOCK_BYTES = 512 * 1024
# whole-spatial blocking only: cap on H*W (inception maps are <= 28x28;
# a 64x64 map would force batch-tile 1 and ~4 buffers x 2MB, still fine,
# but beyond that halo tiling would be needed — route to XLA instead)
_MAX_HW = 64 * 64


def _ninf(dtype):
    return jnp.array(-jnp.finfo(dtype).max, dtype)


def _frame(x, fill):
    """Pad spatial axes (1, 2) of a 4-D block by 1 with ``fill``, via
    concatenate — Mosaic TPU has no dynamic_update_slice/pad lowering."""
    B, H, W, C = x.shape
    row = jnp.full((B, 1, W, C), fill, x.dtype)
    xp = jnp.concatenate([row, x, row], axis=1)
    col = jnp.full((B, H + 2, 1, C), fill, x.dtype)
    return jnp.concatenate([col, xp, col], axis=2)


def _shift_max(xp, H, W):
    """Max over the 9 shifted (H, W) views of the padded (H+2, W+2)
    spatial dims (axes 1, 2 of a 4-D block)."""
    y = None
    for di in range(3):
        for dj in range(3):
            s = lax.slice_in_dim(
                lax.slice_in_dim(xp, di, di + H, axis=1), dj, dj + W, axis=2
            )
            y = s if y is None else jnp.maximum(y, s)
    return y


def _fwd_kernel(x_ref, y_ref, *, H, W):
    x = x_ref[:]
    xp = _frame(x, _ninf(x.dtype))
    y_ref[:] = _shift_max(xp, H, W)


def _bwd_kernel(x_ref, y_ref, g_ref, dx_ref, *, H, W):
    # compare in f32: Mosaic's vector cmpf has no bf16 form on this
    # target, and bf16 embeds exactly in f32 so equality is unchanged
    x = x_ref[:].astype(jnp.float32)
    yp = _frame(y_ref[:].astype(jnp.float32), _ninf(jnp.float32))
    gp = _frame(g_ref[:].astype(jnp.float32), jnp.array(0.0, jnp.float32))
    dx = jnp.zeros(x.shape, jnp.float32)
    for di in range(3):
        for dj in range(3):
            ys = lax.slice_in_dim(
                lax.slice_in_dim(yp, di, di + H, axis=1), dj, dj + W, axis=2
            )
            gs = lax.slice_in_dim(
                lax.slice_in_dim(gp, di, di + H, axis=1), dj, dj + W, axis=2
            )
            dx = dx + jnp.where(x == ys, gs, 0.0)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _tiles(N, H, W, C, itemsize):
    """(batch_tile, channel_tile): whole spatial map per block, channel
    tile one lane group, batch tile sized to the VMEM budget."""
    bc = min(C, _LANES)
    per_row = (H + 2) * (W + 2) * bc * itemsize
    bb = max(1, min(N, _BLOCK_BYTES // per_row))
    return bb, bc


def _pallas_fwd(x):
    from jax.experimental import pallas as pl

    N, H, W, C = x.shape
    bb, bc = _tiles(N, H, W, C, x.dtype.itemsize)
    spec = pl.BlockSpec((bb, H, W, bc), lambda i, j: (i, 0, 0, j))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, H=H, W=W),
        grid=(pl.cdiv(N, bb), pl.cdiv(C, bc)),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x)


def _pallas_bwd(x, y, g):
    from jax.experimental import pallas as pl

    N, H, W, C = x.shape
    bb, bc = _tiles(N, H, W, C, x.dtype.itemsize)
    spec = pl.BlockSpec((bb, H, W, bc), lambda i, j: (i, 0, 0, j))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, H=H, W=W),
        grid=(pl.cdiv(N, bb), pl.cdiv(C, bc)),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x, y, g)


def _jnp_fwd(x):
    return lax.reduce_window(
        x, _ninf(x.dtype), lax.max, (1, 3, 3, 1), (1, 1, 1, 1), "SAME"
    )


def _jnp_bwd(x, y, g):
    pad = ((0, 0), (1, 1), (1, 1), (0, 0))
    yp = jnp.pad(y, pad, constant_values=_ninf(y.dtype))
    gp = jnp.pad(g.astype(jnp.float32), pad)
    H, W = x.shape[1], x.shape[2]
    dx = jnp.zeros(x.shape, jnp.float32)
    for di in range(3):
        for dj in range(3):
            ys = lax.slice_in_dim(
                lax.slice_in_dim(yp, di, di + H, axis=1), dj, dj + W, axis=2
            )
            gs = lax.slice_in_dim(
                lax.slice_in_dim(gp, di, di + H, axis=1), dj, dj + W, axis=2
            )
            dx = dx + jnp.where(x == ys, gs, 0.0)
    return dx.astype(x.dtype)


@jax.custom_vjp
def maxpool3x3_s1(x):
    """NHWC 3x3/stride-1/SAME max pool; backward is the fused eq-mask
    kernel (all-maxima subgradient — Theano semantics, see module
    docstring)."""
    return _pallas_fwd(x) if _use_pallas() else _jnp_fwd(x)


def _vjp_fwd(x):
    y = maxpool3x3_s1(x)
    return y, (x, y)


def _vjp_bwd(res, g):
    x, y = res
    dx = _pallas_bwd(x, y, g) if _use_pallas() else _jnp_bwd(x, y, g)
    return (dx,)


maxpool3x3_s1.defvjp(_vjp_fwd, _vjp_bwd)


def routable(window, stride, padding, x) -> bool:
    """Can ``nn.Pool`` route this max pool here? OPT-IN only
    (``TMPI_PALLAS_POOL=1`` — see module docstring for the measured
    rejection), 3x3/stride-1 with SAME-equivalent padding, 4-D input,
    spatial map small enough for whole-map VMEM blocks."""
    import os

    if os.environ.get("TMPI_PALLAS_POOL", "0") != "1":
        return False
    if window != (3, 3) or stride != (1, 1) or x.ndim != 4:
        return False
    if isinstance(padding, str):
        if padding != "SAME":
            return False
    else:
        p = (padding, padding) if isinstance(padding, int) else tuple(padding)
        if p != (1, 1):
            return False
    return x.shape[1] * x.shape[2] <= _MAX_HW
