"""``tmpi chaos`` — seeded chaos campaigns over the full fault matrix.

Every recovery path in this framework (supervisor retry/backoff,
verified resume, anomaly rollback, SIGTERM grace, elastic reshard,
storage-fault walk-back, the scrubber) was proven by HAND-PICKED single
faults — ``--inject-fault sigkill@3`` — which is exactly how recovery
code rots: the combinations nobody wrote a test for are the ones
production hits. This module fuzzes the combinations. A campaign:

1. **generates randomized fault schedules** from a seeded RNG — kind x
   step x composition over the full matrix (process faults, data
   faults, and the storage kinds this PR adds: ``enospc`` /
   ``slow_write`` / ``bitrot`` / ``partial_set``), including
   back-to-back same-step pairs and fault-during-recovery timings (a
   second fault whose step lands inside the first fault's replay
   window);
2. **runs each schedule under** ``supervise_training`` — in-process
   when the schedule stays inside the process, in a subprocess sandbox
   (with relaunch-on-kill and a fired-fault ledger,
   ``utils/faults.FaultInjector(ledger=...)``) when it contains
   ``sigkill``;
3. **checks the invariant oracle** after every run (:data:`INVARIANTS`):
   the run completed to its target step with host/device step
   agreement, the newest VERIFIED checkpoint is restorable and finite
   (never poisoned), the final state is at parity with an uninterrupted
   baseline — bit-identical where the matrix says exact — the saved
   RNG stream position matches the baseline (an independent no-re-fed/
   no-skipped-batch detector: every consumed batch advances the key
   split stream), rc/resumable-marker semantics are honored, and every
   obs JSONL line is schema-clean;
4. **shrinks** a failing schedule to a minimal reproducer (greedy
   delta-debugging over the fault list) and emits it as a
   ready-to-paste ``--inject-fault`` command-line fragment plus a
   ``kind=chaos`` record in ``<out>/chaos.jsonl``.

The payoff is leverage: the same oracle runs over every engine x codec
x checkpoint-format combination (BSP and ZeRO-1, ``none`` and
``int8:ef``, single-file and sharded sets), so crash-safety of a new
knob is inherited by re-running the campaign, not re-deriving a test
matrix by hand.

Usage::

    tmpi chaos --seeds 25                  # full matrix, 4 configs
    tmpi chaos --smoke --seeds 5           # tier-1 CPU smoke (<120 s)
    tmpi chaos --schedule 'crash@5+bitrot@3'   # one directed schedule
    tmpi chaos --schedule crash@5 --mutate refeed   # oracle self-test

``--mutate refeed`` arms a deliberately seeded recovery bug (the worker
re-feeds one already-consumed batch on mid-epoch resume,
``TMPI_CHAOS_MUTATE``) — the campaign MUST catch and shrink it; that is
the proof the oracle is alive, the same way ``--inject-fault`` is the
proof the recovery paths are.

``--serve`` points the same machinery at the SERVING path instead of
training: seeded schedules over :data:`SERVE_MATRIX`
(``replica_crash@t`` / ``replica_stall@t:s`` / ``reload_corrupt@t`` /
``slow_replica@t:s``, t in seconds into the load window) fire at an
N-replica group (serve/router.py) under closed-loop client load, always
composed with a mid-window checkpoint hot-reload. The serving oracle
(:data:`SERVE_INVARIANTS`): zero dropped/failed requests while the
surviving capacity suffices, per-client served step monotone across
failover and reload, deadline semantics honored, schema-clean obs. The
same greedy shrink applies, and ``--mutate drop_inflight`` arms the
seeded router bug (an in-flight request on a dying replica is dropped
instead of re-admitted) the campaign must catch and shrink::

    tmpi chaos --serve --seeds 10
    tmpi chaos --serve --schedule replica_crash@0.4 --mutate drop_inflight

``--serve --decode`` points the serving campaign at a fleet of
continuous-batching DECODE engines (serve/decode/) instead of
eval-forward engines: clients stream mixed-length token prompts, and
the schedule draws from :data:`DECODE_MATRIX` — the shared kinds plus
``kv_exhaust@t:s`` (grab nearly every free KV page from inside a
member's decode loop and hold it for s seconds: admission must queue
on the free-list, never corrupt or crash) and ``long_prompt_burst@t``
(a concurrent burst of worst-case prompts with maximum output budgets
slamming the largest prefill bucket and the page reservation path).
The oracle gains ``kv_conserved``: after drain every member's KV
free-list must hold pages_out == pages_in with zero outstanding — a
leaked page is a silent capacity loss that compounds across requests::

    tmpi chaos --serve --decode --seeds 10
    tmpi chaos --serve --decode --schedule kv_exhaust@0.4:0.5
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# ---------------------------------------------------------------------------
# fault matrix
# ---------------------------------------------------------------------------

# kind -> properties the scheduler/oracle need:
#   exact:      an injected fault of this kind must leave the final state
#               BIT-IDENTICAL to the uninterrupted baseline (the resume/
#               walk-back contract); inexact kinds (nan_batch's rollback
#               skips data batches by design) get the weaker oracle
#   arg:        spec arg appended as KIND@STEP:ARG (stall/slow seconds)
#   subprocess: the fault kills the process — needs the sandbox
#   sharded:    only meaningful for sharded checkpoint sets
#   rollback:   needs numerics sentinels + --on-anomaly rollback armed
#   elastic:    a topology fault — the run gets a 2-slice mesh and
#               elastic supervision (reshard-to-survivors); inexact by
#               nature (the survivor world re-partitions the batch, so
#               final state legitimately differs from the flat baseline)
MATRIX: dict[str, dict] = {
    "crash": {},
    "sigterm": {},
    "sigkill": {"subprocess": True},
    "ckpt_truncate": {},
    "loader_stall": {"arg": 0.2},
    "nan_batch": {"exact": False, "rollback": True},
    "enospc": {},
    "slow_write": {"arg": 0.2},
    "bitrot": {},
    "partial_set": {"sharded": True},
    "slice_down": {"exact": False, "elastic": True},
}

# the tier-1 smoke matrix: in-process, sleep-free, storage kinds included
# (slice_down rides tier-1 as a DIRECTED smoke schedule instead —
# tests/test_chaos.py — so the seeded fuzz draws stay stable)
SMOKE_KINDS = ("crash", "ckpt_truncate", "enospc", "bitrot")

INVARIANTS = (
    "completed",        # final summary reached the target step count
    "device_truth",     # host step ledger == device step counter
    "verified_chain",   # a VERIFIED checkpoint is restorable at the end
    "finite_state",     # ... and every array in it is finite
    "parity",           # exact schedules: bit-identical to the baseline
    "no_refeed",        # exact schedules: saved RNG stream position
                        # matches the baseline (re-fed/skipped batch
                        # detector independent of params)
    "rc_semantics",     # every launch exited 0 / rc-75 / injected kill;
                        # the final launch exited 0; marker consumed
    "schema",           # every obs JSONL line validates
)


@dataclass
class ChaosConfig:
    """One engine x codec x checkpoint-format cell of the campaign."""

    name: str
    zero: int = 0
    wire_codec: str = "none"
    sharded_ckpt: bool = False
    devices: int = 4
    batch: int = 32
    n_train: int = 96       # -> 3 steps/epoch: mid-epoch resumes happen
    n_epochs: int = 2

    @property
    def steps_per_epoch(self) -> int:
        return self.n_train // self.batch

    @property
    def total_steps(self) -> int:
        return self.steps_per_epoch * self.n_epochs


def default_configs(smoke: bool) -> list[ChaosConfig]:
    if smoke:
        return [ChaosConfig("bsp_none")]
    return [
        ChaosConfig("bsp_none"),
        ChaosConfig("bsp_int8ef", wire_codec="int8:ef"),
        ChaosConfig("zero1_none", zero=1, sharded_ckpt=True),
        ChaosConfig("zero1_int8ef", zero=1, wire_codec="int8:ef",
                    sharded_ckpt=True),
    ]


# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------


def spec_kind(spec: str) -> str:
    return spec.partition("@")[0]


def usable_kinds(cfg: ChaosConfig, kinds: list[str]) -> list[str]:
    """The subset of ``kinds`` this config can actually draw:
    sharded-only kinds need a sharded config, and rollback kinds need a
    run long enough to hold a checkpoint to roll back TO (before the
    first epoch-boundary save the policy correctly degrades to halt —
    working-as-designed, not a schedule worth fuzzing)."""
    out = [k for k in kinds
           if not MATRIX[k].get("sharded") or cfg.sharded_ckpt]
    out = [k for k in out
           if not MATRIX[k].get("rollback")
           or cfg.steps_per_epoch + 1 <= cfg.total_steps]
    # elastic (topology) kinds run on a 2-slice mesh and reshard to
    # survivors: needs an even device count with at least one whole
    # slice left, and the plain-BSP replicated state (ZeRO's sharded
    # optimizer reshard across worlds is its own campaign)
    return [k for k in out
            if not MATRIX[k].get("elastic")
            or (cfg.devices >= 4 and cfg.devices % 2 == 0
                and not cfg.zero and not cfg.sharded_ckpt)]


def generate_schedule(rng: random.Random, cfg: ChaosConfig,
                      kinds: list[str], max_faults: int) -> list[str]:
    """One fuzzed schedule: 1..max_faults specs over the run's step
    range. Composition pressure is deliberate: with probability ~0.4 a
    fault reuses (or lands adjacent to) the previous fault's step —
    back-to-back faults and fault-during-recovery timings (the second
    fault fires inside the first one's replay) are where hand-written
    tests are thinnest."""
    usable = usable_kinds(cfg, kinds)
    if not usable:
        raise ValueError(
            f"no usable fault kinds for config {cfg.name!r}: {kinds} "
            "all filtered out (sharded-only kinds on a non-sharded "
            "config?) — pick --configs/--kinds that compose"
        )
    n = rng.randint(1, max_faults)
    schedule: list[str] = []
    prev_step: Optional[int] = None
    for _ in range(n):
        kind = rng.choice(usable)
        lo = (cfg.steps_per_epoch + 1 if MATRIX[kind].get("rollback")
              else 1)
        if prev_step is not None and rng.random() < 0.4:
            step = min(cfg.total_steps,
                       max(lo, prev_step + rng.choice((0, 1))))
        else:
            step = rng.randint(lo, cfg.total_steps)
        prev_step = step
        arg = MATRIX[kind].get("arg")
        schedule.append(f"{kind}@{step}" + (f":{arg}" if arg else ""))
    # at most one process-killer per schedule keeps the relaunch budget
    # small without losing composition coverage (two sigkills mostly
    # test the same path twice)
    killers = [s for s in schedule if spec_kind(s) == "sigkill"]
    for extra in killers[1:]:
        schedule.remove(extra)
    return schedule


# ---------------------------------------------------------------------------
# running one schedule
# ---------------------------------------------------------------------------


@dataclass
class RunResult:
    """Everything the oracle needs from one schedule's execution."""

    launches: list[str] = field(default_factory=list)  # per-launch outcome
    final_summary: Optional[dict] = None
    error: Optional[str] = None
    ckpt_dir: str = ""
    obs_dir: str = ""


def _base_run_kwargs(cfg: ChaosConfig, ckpt_dir: str, obs_dir: Optional[str],
                     schedule: list[str]) -> dict:
    from theanompi_tpu.models.mlp import MLP

    kw = dict(
        rule="bsp",
        model_cls=MLP,
        devices=cfg.devices,
        zero=cfg.zero,
        wire_codec=cfg.wire_codec,
        sharded_ckpt=cfg.sharded_ckpt,
        ckpt_dir=ckpt_dir,
        obs_dir=obs_dir,
        dataset="synthetic",
        dataset_kwargs={"n_train": cfg.n_train, "n_val": cfg.batch},
        recipe_overrides={"batch_size": cfg.batch},
        n_epochs=cfg.n_epochs,
        print_freq=0,
        seed=0,
    )
    if any(MATRIX[spec_kind(s)].get("rollback") for s in schedule):
        kw.update(numerics_freq=1, on_anomaly="rollback",
                  rollback_budget=len(schedule) + 1)
    if any(spec_kind(s) == "sigterm" for s in schedule):
        kw["sigterm_grace"] = 10.0
    if any(MATRIX[spec_kind(s)].get("elastic") for s in schedule):
        # whole-slice loss needs a slice to lose and a supervisor
        # allowed to reshard onto the survivors
        kw.update(n_slices=2, elastic=True)
    return kw


class BaselineCache:
    """Uninterrupted reference runs for parity checks, built lazily and
    cached per (config, step).

    The full-run baseline's keep-chain covers the epoch-boundary steps;
    a chaos run's newest verified checkpoint can also land MID-epoch
    (the crash-path and SIGTERM-grace saves checkpoint at the step they
    interrupt) — those anchors are produced on demand by a clean
    ``max_steps=step`` run, whose truncation save writes ``ckpt_step``
    with the exact state/rng an uninterrupted run holds after ``step``
    batches."""

    def __init__(self, out_dir: str):
        self.out_dir = out_dir
        self.seconds = 0.0
        self._full: dict[str, str] = {}
        self._at_step: dict[tuple, Optional[str]] = {}

    def full_dir(self, cfg: ChaosConfig) -> str:
        if cfg.name not in self._full:
            from theanompi_tpu.launch.worker import run_training

            t0 = time.perf_counter()
            ckpt_dir = os.path.join(self.out_dir,
                                    f"baseline_{cfg.name}", "ckpt")
            summary = run_training(**_base_run_kwargs(cfg, ckpt_dir,
                                                      None, []))
            self.seconds += time.perf_counter() - t0
            if summary["steps"] != cfg.total_steps:
                raise RuntimeError(
                    f"baseline for {cfg.name} stopped at step "
                    f"{summary['steps']}, expected {cfg.total_steps}"
                )
            self._full[cfg.name] = ckpt_dir
        return self._full[cfg.name]

    def at_step(self, cfg: ChaosConfig, step: int) -> Optional[str]:
        """A verified clean checkpoint of ``cfg`` at exactly ``step``
        (None only for step 0, which has no save to anchor on)."""
        key = (cfg.name, int(step))
        if key in self._at_step:
            return self._at_step[key]
        path = _chain_at_step(self.full_dir(cfg), step)
        if path is None and 0 < step <= cfg.total_steps:
            from theanompi_tpu.launch.worker import run_training

            t0 = time.perf_counter()
            ckpt_dir = os.path.join(self.out_dir, f"baseline_{cfg.name}",
                                    f"step{step}", "ckpt")
            run_training(max_steps=step,
                         **_base_run_kwargs(cfg, ckpt_dir, None, []))
            self.seconds += time.perf_counter() - t0
            path = _chain_at_step(ckpt_dir, step)
        self._at_step[key] = path
        return path


def _run_inprocess(cfg: ChaosConfig, schedule: list[str],
                   workdir: str) -> RunResult:
    """Run one schedule under supervise_training in THIS process: one
    FaultInjector threads through every supervisor attempt AND every
    rc-75-equivalent relaunch (Preempted re-raise -> marker resume), so
    each fault fires exactly once per schedule."""
    from theanompi_tpu.launch.supervisor import supervise_training
    from theanompi_tpu.utils.faults import FaultInjector, Preempted

    res = RunResult(ckpt_dir=os.path.join(workdir, "ckpt"),
                    obs_dir=os.path.join(workdir, "obs"))
    injector = FaultInjector(schedule)
    kw = _base_run_kwargs(cfg, res.ckpt_dir, res.obs_dir, schedule)
    resume = False
    budget = len(schedule) + 3
    for _ in range(budget):
        try:
            summary = supervise_training(
                max_retries=len(schedule) + 2, backoff_base=0.0,
                inject_faults=injector, resume=resume, **kw,
            )
            res.launches.append("ok")
            res.final_summary = summary
            return res
        except Preempted:
            # the marker the grace path dropped drives the next
            # launch's auto-resume — exactly the scheduler-requeue
            # contract rc 75 promises
            res.launches.append("preempted")
            continue
        except Exception as e:  # noqa: BLE001 — the oracle's evidence
            res.launches.append(f"error:{type(e).__name__}")
            res.error = repr(e)
            return res
    res.error = f"relaunch budget ({budget}) exhausted"
    return res


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _subprocess_env(mutate: Optional[str]) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TMPI_FORCE_PLATFORM"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if mutate:
        env["TMPI_CHAOS_MUTATE"] = mutate
    else:
        env.pop("TMPI_CHAOS_MUTATE", None)
    return env


def _run_subprocess(cfg: ChaosConfig, schedule: list[str], workdir: str,
                    mutate: Optional[str], timeout: float) -> RunResult:
    """Run one schedule in a subprocess sandbox — required whenever the
    schedule kills the process (sigkill has no in-process recovery).
    The chaos runner is the outer scheduler: it relaunches a killed/
    preempted run with ``--resume``, and the fired-fault LEDGER
    (``--fault-ledger``) carries once-only semantics across the process
    boundary — without it every relaunch would replay the kill forever."""
    import signal as _signal

    res = RunResult(ckpt_dir=os.path.join(workdir, "ckpt"),
                    obs_dir=os.path.join(workdir, "obs"))
    ledger = os.path.join(workdir, "fault_ledger.txt")
    args = [
        "BSP", str(cfg.devices), "theanompi_tpu.models.mlp", "MLP",
        "--synthetic", "--epochs", str(cfg.n_epochs),
        "--batch-size", str(cfg.batch), "--print-freq", "0",
        "--dataset-arg", f"n_train={cfg.n_train}",
        "--dataset-arg", f"n_val={cfg.batch}",
        "--ckpt-dir", res.ckpt_dir, "--obs-dir", res.obs_dir,
        "--max-retries", str(len(schedule) + 2), "--retry-backoff", "0",
        "--fault-ledger", ledger,
        "--wire-codec", cfg.wire_codec,
    ]
    if cfg.zero:
        args += ["--zero", str(cfg.zero)]
    if cfg.sharded_ckpt:
        args += ["--ckpt-sharded"]
    if any(MATRIX[spec_kind(s)].get("rollback") for s in schedule):
        args += ["--numerics-freq", "1", "--on-anomaly", "rollback",
                 "--rollback-budget", str(len(schedule) + 1)]
    if any(spec_kind(s) == "sigterm" for s in schedule):
        args += ["--sigterm-grace", "10"]
    if any(MATRIX[spec_kind(s)].get("elastic") for s in schedule):
        args += ["--slices", "2", "--elastic"]
    for s in schedule:
        args += ["--inject-fault", s]
    env = _subprocess_env(mutate)
    budget = len(schedule) + 3
    resume: list[str] = []
    for _ in range(budget):
        try:
            p = subprocess.run(
                [sys.executable, "-m", "theanompi_tpu.cli", *args, *resume],
                env=env, capture_output=True, text=True, timeout=timeout,
                cwd=_repo_root(),
            )
        except subprocess.TimeoutExpired as e:
            # a hung launch is a FINDING for this schedule (exactly the
            # class of bug a chaos tool exists to surface), not a
            # campaign-aborting runner error — record it and let the
            # oracle fail/shrink the schedule like any other violation
            res.launches.append("timeout")
            res.error = (f"launch exceeded {timeout:.0f}s "
                         f"({e.cmd[-3:]}...)")
            return res
        if p.returncode == 0:
            res.launches.append("ok")
            for line in reversed(p.stdout.strip().splitlines()):
                try:
                    res.final_summary = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            return res
        if p.returncode == 75:
            res.launches.append("preempted")
            resume = ["--resume"]
            continue
        if p.returncode in (-_signal.SIGKILL, -_signal.SIGTERM):
            res.launches.append(f"killed:{p.returncode}")
            resume = ["--resume"]
            continue
        res.launches.append(f"rc:{p.returncode}")
        res.error = (f"rc {p.returncode}\n{p.stdout[-1500:]}\n"
                     f"{p.stderr[-1500:]}")
        return res
    res.error = f"relaunch budget ({budget}) exhausted"
    return res


def run_schedule(cfg: ChaosConfig, schedule: list[str], workdir: str, *,
                 mutate: Optional[str] = None,
                 timeout: float = 300.0) -> RunResult:
    os.makedirs(workdir, exist_ok=True)
    if any(MATRIX[spec_kind(s)].get("subprocess") for s in schedule):
        return _run_subprocess(cfg, schedule, workdir, mutate, timeout)
    if mutate:
        os.environ["TMPI_CHAOS_MUTATE"] = mutate
    try:
        return _run_inprocess(cfg, schedule, workdir)
    finally:
        if mutate:
            os.environ.pop("TMPI_CHAOS_MUTATE", None)


# ---------------------------------------------------------------------------
# the invariant oracle
# ---------------------------------------------------------------------------


def _ckpt_arrays(path: str) -> dict[str, np.ndarray]:
    """The comparable content of one checkpoint: every saved array,
    minus the JSON sidecars whose text may legitimately differ across
    recovery histories (__usermeta__ records rollback skips;
    __integrity__ re-derives from the arrays; __meta__/__topology__
    describe layout, which shape checks already pin)."""
    data = np.load(path)
    skip = ("__integrity__", "__usermeta__", "__meta__", "__topology__",
            "__rng_impl__")
    return {k: data[k] for k in data.files if k not in skip}


def _sharded_member_paths(path: str) -> list[str]:
    from theanompi_tpu.utils.checkpoint import _SHARD_RE, _sharded_sets

    m = _SHARD_RE.search(os.path.basename(path))
    if not m:
        return [path]
    return _sharded_sets(os.path.dirname(path) or ".")[int(m.group(1))]


def _final_verified(ckpt_dir: str):
    from theanompi_tpu.utils.checkpoint import (
        checkpoint_step, latest_checkpoint,
    )

    path = latest_checkpoint(ckpt_dir, verify=True)
    return path, (checkpoint_step(path) if path else -1)


# fault kinds that can destroy a COMMITTED or in-flight save: a
# schedule made of these may legitimately leave ZERO verified
# checkpoints (every save torn/rotted/dropped) — an empty chain is only
# a violation when nothing in the schedule could have caused it
_SAVE_DESTROYING = ("ckpt_truncate", "bitrot", "partial_set", "enospc")


def check_invariants(cfg: ChaosConfig, schedule: list[str], res: RunResult,
                     baseline: BaselineCache) -> list[str]:
    """The oracle: the names of every violated invariant (empty = the
    schedule was absorbed correctly). See :data:`INVARIANTS`."""
    from theanompi_tpu.utils.checkpoint import read_resumable_marker

    viol: list[str] = []
    exact = all(MATRIX[spec_kind(s)].get("exact", True) for s in schedule)

    # a schedule can compose a rollback-policy fault with enough
    # save-destroyers that NOTHING verified remains when the rollback
    # needs it — the policy then degrades to halt (a DELIBERATE stop,
    # the documented PR-4 semantics, and the supervisor rightly never
    # retries it). That terminal state is legitimate: the oracle keeps
    # enforcing the quarantine invariant (no poisoned verified
    # checkpoint) and schema/marker hygiene, but not completion.
    _halt_names = ("RollbackRequested", "NumericsAnomaly")
    anomaly_halt = (
        any(MATRIX[spec_kind(f)].get("rollback") for f in schedule)
        and any(spec_kind(f) in _SAVE_DESTROYING for f in schedule)
        and res.error is not None
        and any(n in res.error for n in _halt_names)
    )

    s = res.final_summary
    # batches-consumed accounting: an anomaly rollback SKIPS data
    # batches by design (each skip consumes a batch without a training
    # step), so completion is judged on steps + skipped_steps — the
    # same ledger the resume-positioning contract uses
    consumed = (int(s.get("steps", -1)) + int(s.get("skipped_steps", 0))
                if s else -1)
    if not anomaly_halt and (
            res.error is not None or s is None
            or consumed != cfg.total_steps):
        viol.append("completed")
    if s is not None and s.get("device_steps") is not None and (
            s.get("device_steps") != s.get("steps")):
        viol.append("device_truth")

    path, step = _final_verified(res.ckpt_dir)
    if path is None:
        if not any(spec_kind(f) in _SAVE_DESTROYING for f in schedule):
            viol.append("verified_chain")
    else:
        arrays = _ckpt_arrays(path)
        member_arrays = [
            _ckpt_arrays(p) for p in _sharded_member_paths(path)
        ]
        if not all(
            np.isfinite(a).all()
            for ma in member_arrays
            for a in ma.values()
            if np.issubdtype(a.dtype, np.floating)
        ):
            viol.append("finite_state")
        if exact and step > 0:
            # parity against a CLEAN run's checkpoint at the SAME step
            # (a tail-of-run storage fault legitimately walks the chain
            # back, so the anchor is whatever IS restorable; step 0 has
            # no save to anchor on and is skipped)
            bpath = baseline.at_step(cfg, step)
            if bpath is None:
                viol.append("parity")
            else:
                barrays = _ckpt_arrays(bpath)
                if set(arrays) != set(barrays) or any(
                    not np.array_equal(arrays[k], barrays[k])
                    for k in arrays if k != "__rng__"
                ):
                    viol.append("parity")
                if "__rng__" in arrays and not np.array_equal(
                        arrays.get("__rng__"), barrays.get("__rng__")):
                    viol.append("no_refeed")

    if anomaly_halt:
        # the halt must still be CLEAN: no stale resumable marker
        # promising a scheduler an auto-resume into a halted policy
        if read_resumable_marker(res.ckpt_dir) is not None:
            viol.append("rc_semantics")
    else:
        bad_launch = [
            l for l in res.launches
            if l not in ("ok", "preempted") and not l.startswith("killed:")
        ]
        if (not res.launches or res.launches[-1] != "ok" or bad_launch
                or read_resumable_marker(res.ckpt_dir) is not None):
            viol.append("rc_semantics")

    viol.extend(_schema_violations(res.obs_dir))
    return viol


def _chain_at_step(ckpt_dir: str, step: int) -> Optional[str]:
    from theanompi_tpu.utils.checkpoint import _keep_chain, verify_checkpoint

    for s, _, path in _keep_chain(ckpt_dir):
        if s == step and verify_checkpoint(path):
            return path
    return None


def _schema_violations(obs_dir: str) -> list[str]:
    from theanompi_tpu.tools.check_obs_schema import check_file, discover

    if not obs_dir or not os.path.isdir(obs_dir):
        return []
    try:
        files = discover([obs_dir])
    except FileNotFoundError:
        return []
    errs: list[str] = []
    for f in files:
        errs += check_file(f)
    return ["schema"] if errs else []


# ---------------------------------------------------------------------------
# shrinking
# ---------------------------------------------------------------------------


def shrink_schedule(cfg: ChaosConfig, schedule: list[str],
                    baseline: BaselineCache, workdir: str, *,
                    mutate: Optional[str] = None, timeout: float = 300.0,
                    max_runs: int = 24) -> tuple[list[str], int]:
    """Greedy delta-debugging: drop one fault at a time while the
    reduced schedule still violates ANY invariant; fixed point = the
    minimal reproducer. Returns (minimal schedule, shrink runs spent)."""
    current = list(schedule)
    runs = 0
    changed = True
    while changed and len(current) > 1 and runs < max_runs:
        changed = False
        for i in range(len(current)):
            cand = current[:i] + current[i + 1:]
            wd = os.path.join(workdir, f"shrink{runs}")
            runs += 1
            res = run_schedule(cfg, cand, wd, mutate=mutate, timeout=timeout)
            if check_invariants(cfg, cand, res, baseline):
                current = cand
                changed = True
                break
            if runs >= max_runs:
                break
    return current, runs


def repro_line(schedule: list[str]) -> str:
    return " ".join(f"--inject-fault {s}" for s in schedule)


# ---------------------------------------------------------------------------
# the serving campaign (`tmpi chaos --serve`)
# ---------------------------------------------------------------------------

# serving fault kinds: spec is KIND@T[:ARG] with T seconds into the
# load window (floats, unlike the training matrix's step numbers).
#   replica_crash   hard-kill one healthy member (router.kill_replica:
#                   queued AND in-flight requests must fail over)
#   replica_stall   freeze one member's batcher for ARG seconds, once —
#                   the router's least-loaded scoring must steer around
#                   the growing queue, not blackhole behind it
#   reload_corrupt  commit a NEWER checkpoint then bit-rot it: the
#                   central reloader's verified keep-chain walk must
#                   skip it and keep serving the previous step
#   slow_replica    ARG seconds of extra latency per batch for the rest
#                   of the run (a degraded-not-dead member: EWMA-based
#                   routing shifts load, health checks keep it green)
SERVE_MATRIX: dict[str, dict] = {
    "replica_crash": {},
    "replica_stall": {"arg": 0.3},
    "reload_corrupt": {},
    "slow_replica": {"arg": 0.05},
}

# the decode fleet's matrix (``--serve --decode``): the engine-agnostic
# kinds, plus
#   kv_exhaust       from inside one member's decode loop, alloc all
#                    but one free KV page and hold them ARG seconds —
#                    admission must back up on the free-list (FIFO
#                    queueing, typed KVExhausted internally) and
#                    resume when the pages return; never a crash, a
#                    drop, or a corrupted page table
#   long_prompt_burst  a concurrent burst of worst-case-length prompts
#                    with maximum output budgets — slams the largest
#                    prefill bucket, the worst-case page reservation,
#                    and slot contention all at once
# (slow_replica is omitted: per-batch latency injection wraps the
# eval engine's _serve_batch; the decode equivalent of a persistently
# slow member is kv_exhaust's page pressure)
DECODE_MATRIX: dict[str, dict] = {
    "replica_crash": {},
    "replica_stall": {"arg": 0.3},
    "reload_corrupt": {},
    "kv_exhaust": {"arg": 0.5},
    "long_prompt_burst": {},
}

SERVE_INVARIANTS = (
    "no_drops",        # zero dropped/failed requests while the
                       # surviving capacity sufficed (every request
                       # terminally served/expired/rejected-with-
                       # retry — never silently lost)
    "step_monotone",   # per-client served params_step never moves
                       # backward across failover or hot-reload
    "deadline",        # DeadlineExceeded only after the deadline
                       # actually passed; no zombie expiries
    "completed",       # clients all ran, traffic was served, the
                       # router drained cleanly
    "schema",          # every obs JSONL line validates (router.jsonl,
                       # serve_r<id>.jsonl included)
    "kv_conserved",    # decode fleets only: after drain, every
                       # member's KV free-list is whole (pages_out ==
                       # pages_in, zero outstanding) — a leaked page
                       # is silent capacity loss
)


def parse_serve_spec(spec: str, matrix: Optional[dict] = None) -> tuple:
    """``KIND@T[:ARG]`` -> (kind, t_seconds, arg)."""
    matrix = SERVE_MATRIX if matrix is None else matrix
    kind, sep, rest = spec.partition("@")
    if not sep or kind not in matrix:
        raise ValueError(
            f"serve fault spec {spec!r} must be KIND@T with kind in "
            f"{sorted(matrix)}"
        )
    t_s, sep2, arg_s = rest.partition(":")
    arg = float(arg_s) if sep2 else matrix[kind].get("arg")
    return kind, float(t_s), arg


def generate_serve_schedule(rng: random.Random, duration: float,
                            max_faults: int,
                            matrix: Optional[dict] = None) -> list[str]:
    """One fuzzed serving schedule: 1..max_faults specs inside the load
    window, with the training generator's composition pressure (~0.4
    probability a fault lands on/next to the previous one's time — a
    crash DURING a stall, a second crash inside the first restart's
    backoff window)."""
    matrix = SERVE_MATRIX if matrix is None else matrix
    n = rng.randint(1, max_faults)
    schedule: list[str] = []
    prev_t: Optional[float] = None
    for _ in range(n):
        kind = rng.choice(sorted(matrix))
        if prev_t is not None and rng.random() < 0.4:
            t = min(0.8 * duration, prev_t + rng.choice((0.0, 0.1)))
        else:
            t = rng.uniform(0.15 * duration, 0.7 * duration)
        t = round(t, 2)
        prev_t = t
        arg = matrix[kind].get("arg")
        schedule.append(f"{kind}@{t}" + (f":{arg}" if arg is not None
                                         else ""))
    return schedule


@dataclass
class ServeRunResult:
    """Everything the serving oracle needs from one schedule's run."""

    ledgers: list = field(default_factory=list)  # per-client entry dicts
    router_stats: dict = field(default_factory=dict)
    drained: bool = False
    error: Optional[str] = None
    obs_dir: str = ""
    # decode fleets only: every member's KV free-list whole after
    # drain (None = not a decode run, invariant not applicable)
    kv_conserved: Optional[bool] = None


def _serve_model():
    from theanompi_tpu.models.mlp import MLP

    return MLP(MLP.default_recipe().replace(
        input_shape=(8, 8, 3), batch_size=8))


def _decode_model():
    from theanompi_tpu.models.zoo import zoo_entry

    cls, _ = zoo_entry("transformer_lm")
    return cls(cls.default_recipe().replace(
        input_shape=(64,), num_classes=32, d_model=32, n_heads=2,
        n_layers=2, d_ff=64, attn="ring", batch_size=4))


def _degrade_engine(eng, seconds: float, once: bool) -> None:
    """Wrap one engine's batch path with injected latency — the
    chaos-side stand-in for a GC pause / noisy neighbor (`once`) or a
    persistently slow host (not `once`). The eval engine's unit of
    work is ``_serve_batch``; the decode engine's is ``_iteration``."""
    if hasattr(eng, "_serve_batch"):
        orig = eng._serve_batch

        def stalled(reqs):
            if once:
                eng._serve_batch = orig
            time.sleep(seconds)
            orig(reqs)

        eng._serve_batch = stalled
    else:
        orig = eng._iteration

        def stalled_iter():
            if once:
                eng._iteration = orig
            time.sleep(seconds)
            orig()

        eng._iteration = stalled_iter


def _exhaust_engine(eng, hold_s: float, held: list) -> None:
    """kv_exhaust: from INSIDE the decode loop (the free-list is
    single-owner — foreign-thread allocs would race admission), grab
    all but one free KV page on the next iteration and hold them for
    ``hold_s`` seconds. Admission must back up on the free-list and
    resume when the pages return. ``held`` collects the grab so the
    runner can return pages that are still out when the window closes
    (after drain, once the batcher thread is gone)."""
    orig = eng._iteration
    grab: dict = {"fl": eng._cache.free_list, "pages": None, "t0": None}
    held.append(grab)

    def exhausted_iter():
        fl = grab["fl"]
        now = time.perf_counter()
        if grab["pages"] is None:
            n = max(0, fl.n_free - 1)
            grab["pages"] = fl.alloc(n) if n else []
            grab["t0"] = now
        elif grab["pages"] and now - grab["t0"] >= hold_s:
            fl.free(grab["pages"])
            grab["pages"] = []
            eng._iteration = orig
        orig()

    eng._iteration = exhausted_iter


def run_serve_schedule(schedule: list[str], workdir: str, *,
                       replicas: int = 2, duration: float = 2.0,
                       clients: int = 4, mutate: Optional[str] = None,
                       seed: int = 0,
                       decode: bool = False) -> ServeRunResult:
    """Run one serving schedule in-process: an N-replica Router under
    closed-loop client load, the fault controller firing the schedule
    at its T marks, and ALWAYS a good checkpoint committed mid-window
    (hot-reload under load rides every schedule). ``decode=True``
    swaps the fleet members for continuous-batching decode engines
    (clients stream mixed-length token prompts; the Router is
    UNCHANGED — that composition is the point)."""
    import jax

    from theanompi_tpu.serve.engine import (
        DeadlineExceeded, Rejected, ServeEngine,
    )
    from theanompi_tpu.serve.reload import CheckpointReloader
    from theanompi_tpu.serve.router import RequestDropped, Router
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint
    from theanompi_tpu.utils.faults import FaultInjector

    os.makedirs(workdir, exist_ok=True)
    res = ServeRunResult(obs_dir=os.path.join(workdir, "obs"))
    ckpt_dir = os.path.join(workdir, "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)

    model = _decode_model() if decode else _serve_model()
    state = init_train_state(model, jax.random.PRNGKey(0))
    ckpt_step = [1]

    def _commit(corrupt: bool = False) -> None:
        # step-dependent params so every swap is visible in served steps
        ckpt_step[0] += 1
        step = ckpt_step[0]
        bumped = state._replace(params=jax.tree_util.tree_map(
            lambda p: p + 0.01 * step, state.params))
        save_checkpoint(ckpt_dir, bumped, step,
                        rng=jax.random.PRNGKey(step), keep=10)
        if corrupt:
            FaultInjector.bitrot_newest(ckpt_dir)

    save_checkpoint(ckpt_dir, state, 1, rng=jax.random.PRNGKey(1), keep=10)

    def _member(rid):
        if decode:
            from theanompi_tpu.serve.decode import DecodeEngine

            eng = DecodeEngine(
                model, prefill_buckets=(4, 8), page_size=4,
                kv_pages=48, max_seqs=4, max_new_tokens=6,
                max_queue=256, obs_dir=res.obs_dir,
                replica_id=rid, sink_name=f"decode_r{rid}.jsonl",
            )
        else:
            eng = ServeEngine(
                model, buckets=(1, 4), max_queue=256, obs_dir=res.obs_dir,
                replica_id=rid, sink_name=f"serve_r{rid}.jsonl",
            )
        eng.load_initial(ckpt_dir)
        eng.warmup()
        eng.start()
        return eng

    router = Router(
        _member, replicas, obs_dir=res.obs_dir, health_interval=0.05,
        restart_base_s=0.05, restart_cap_s=0.4, seed=seed, mutate=mutate,
    )
    router.start()
    reloader = CheckpointReloader(router, ckpt_dir, interval=0.1)

    stop = threading.Event()
    ledgers: list[list] = [[] for _ in range(clients)]

    vocab = int(getattr(model.recipe, "num_classes", 0) or 0)

    def _client(idx: int) -> None:
        r = np.random.RandomState(1000 + idx)
        if not decode:
            shape = tuple(model.recipe.input_shape)
            x = r.randn(*shape).astype(np.float32)
        i = 0
        while not stop.is_set():
            if decode:
                # mixed-length token prompts spanning every prefill
                # bucket plus the prefill-free single-token path
                x = r.randint(0, vocab, size=r.randint(1, 10),
                              dtype=np.int32)
            # every 4th request carries a (generous) deadline so the
            # deadline invariant exercises the expiry path under faults
            deadline = 2000.0 if i % 4 == 0 else None
            entry: dict = {"deadline_ms": deadline}
            t0 = time.perf_counter()
            try:
                out = router.infer(x, deadline_ms=deadline, timeout=30.0)
                entry.update(status="served", step=int(out.step))
            except DeadlineExceeded:
                entry["status"] = "expired"
            except RequestDropped as e:
                entry.update(status="dropped", error=repr(e))
            except Rejected as e:
                entry.update(status="rejected",
                             error=type(e).__name__)
            except Exception as e:  # noqa: BLE001 — oracle evidence
                entry.update(status="failed", error=repr(e))
            entry["ms"] = round(1000.0 * (time.perf_counter() - t0), 3)
            ledgers[idx].append(entry)
            i += 1
            if entry["status"] == "rejected":
                time.sleep(0.01)  # honor retry-after in spirit

    def _fire(kind: str, arg: Optional[float]) -> None:
        if kind == "replica_crash":
            # kill the BUSIEST healthy member (deepest queue, ties to
            # the lowest id): the harshest realistic crash — it is the
            # replica actually holding in-flight work, so the failover
            # re-admission path is exercised every time instead of by
            # scheduling luck
            healthy = [rep for rep in router._replicas
                       if rep.state == "healthy" and rep.engine is not None]
            if healthy:
                victim = max(healthy,
                             key=lambda rep: (rep.engine.queue_depth,
                                              -rep.replica_id))
                router.kill_replica(victim.replica_id)
        elif kind in ("replica_stall", "slow_replica"):
            rep = next((rep for rep in router._replicas
                        if rep.state == "healthy"
                        and rep.engine is not None), None)
            if rep is not None:
                _degrade_engine(rep.engine,
                                arg or SERVE_MATRIX[kind]["arg"],
                                once=(kind == "replica_stall"))
        elif kind == "kv_exhaust":
            rep = next((rep for rep in router._replicas
                        if rep.state == "healthy"
                        and rep.engine is not None), None)
            if rep is not None:
                _exhaust_engine(rep.engine,
                                arg or DECODE_MATRIX[kind]["arg"], held)
        elif kind == "long_prompt_burst":
            # worst-case prompts (largest bucket + 1) with maximum
            # output budgets, submitted concurrently through the
            # router; outcomes land in their own ledger so the oracle
            # scores them like any client's
            top = 9  # the decode members' largest prefill bucket + 1
            prompts = [burst_rng.randint(0, max(vocab, 2), size=top,
                                         dtype=np.int32)
                       for _ in range(2 * replicas + 2)]

            def _burst_wait(p):
                entry: dict = {"deadline_ms": None}
                t0 = time.perf_counter()
                try:
                    out = router.infer(p, timeout=30.0)
                    entry.update(status="served", step=int(out.step))
                except RequestDropped as e:
                    entry.update(status="dropped", error=repr(e))
                except Rejected as e:
                    entry.update(status="rejected", error=type(e).__name__)
                except Exception as e:  # noqa: BLE001 — oracle evidence
                    entry.update(status="failed", error=repr(e))
                entry["ms"] = round(1000.0 * (time.perf_counter() - t0), 3)
                burst_ledger.append(entry)

            for p in prompts:
                threading.Thread(target=_burst_wait, args=(p,),
                                 daemon=True).start()
        elif kind == "reload_corrupt":
            _commit(corrupt=True)
            reloader.poll_once()  # force the load attempt NOW (it is
            # absorbed — serving keeps the current params); waiting on
            # the background poller leaves the exercise to timing luck
        elif kind == "good_reload":
            _commit(corrupt=False)
            # land the swap at the event mark: this IS the
            # reload-under-load composition, deterministically timed —
            # the background poller still runs for extra churn, but on
            # a loaded box its first poll can start after the window
            reloader.poll_once()

    events = [parse_serve_spec(s, DECODE_MATRIX if decode else None)
              for s in schedule]
    # hot-reload-under-load rides EVERY schedule: a good checkpoint
    # lands mid-window, so faults compose with a live swap (for a
    # decode fleet this IS hot-reload mid-generation: in-flight
    # sequences keep generating across the fleet-wide param swap)
    events.append(("good_reload", round(duration * 0.5, 2), None))
    events.sort(key=lambda e: e[1])
    held: list = []            # kv_exhaust grabs (returned post-drain)
    burst_ledger: list = []    # long_prompt_burst outcomes
    burst_rng = np.random.RandomState(seed * 7 + 3)

    def _controller() -> None:
        t_start = time.perf_counter()
        for kind, t, arg in events:
            wait = t - (time.perf_counter() - t_start)
            if wait > 0 and stop.wait(wait):
                return
            try:
                _fire(kind, arg)
            except Exception as e:  # noqa: BLE001 — runner bug, not
                # a finding: surface it as a run error
                res.error = f"fault controller: {e!r}"
                return

    threads = [threading.Thread(target=_client, args=(i,), daemon=True)
               for i in range(clients)]
    ctrl = threading.Thread(target=_controller, daemon=True)
    threads.append(ctrl)
    try:
        reloader.start()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        # the window closes `duration` after start OR 0.3 s after the
        # LAST scheduled event fired, whichever is later: on a loaded
        # box the controller's event marks slip, and closing on wall
        # time alone can cut the window before the composed
        # reload-under-load ever gets a post-swap request
        ctrl.join(timeout=2.0 * duration + 30.0)
        time.sleep(max(duration - (time.perf_counter() - t0), 0.3))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60.0)
        if any(t.is_alive() for t in threads):
            res.error = res.error or "client/controller thread hung"
        reloader.stop()
        res.drained = router.drain(timeout=30.0)
    if decode:
        # return any kv_exhaust pages still out when the window closed
        # (safe now: drain stopped the batcher threads that own the
        # free-lists), then assert conservation over every member that
        # is still attached — crashed members were failed-over and
        # their replacement engines are the ones in rotation
        for grab in held:
            if grab["pages"]:
                grab["fl"].free(grab["pages"])
                grab["pages"] = []
        res.kv_conserved = all(
            rep.engine._cache.free_list.conserved()
            for rep in router._replicas if rep.engine is not None
        )
    res.router_stats = router.stats()
    res.ledgers = ledgers + ([burst_ledger] if burst_ledger else [])
    return res


def check_serve_invariants(schedule: list[str],
                           res: ServeRunResult) -> list[str]:
    """The serving oracle: names of every violated invariant (empty =
    the schedule was absorbed). See :data:`SERVE_INVARIANTS`."""
    viol: list[str] = []
    entries = [e for ledger in res.ledgers for e in ledger]
    served = [e for e in entries if e["status"] == "served"]

    if (res.error is not None or not res.drained or not served
            or any(not ledger for ledger in res.ledgers)):
        viol.append("completed")

    # zero silent loss while capacity sufficed: the schedules this
    # campaign generates always leave the supervisor able to restore
    # capacity (factory restarts succeed), so ANY dropped/failed
    # request is a violation — counted both from the client ledgers
    # and the router's own counter (they must agree in kind)
    dropped = res.router_stats.get("tmpi_router_dropped_total", 0.0)
    if dropped > 0 or any(e["status"] in ("dropped", "failed")
                          for e in entries):
        viol.append("no_drops")

    for ledger in res.ledgers:
        steps = [e["step"] for e in ledger if e["status"] == "served"]
        if any(b < a for a, b in zip(steps, steps[1:])):
            viol.append("step_monotone")
            break

    for e in entries:
        d = e.get("deadline_ms")
        if e["status"] == "expired" and (d is None or e["ms"] < d - 50.0):
            viol.append("deadline")  # expired before its deadline
            break
        if e["status"] == "served" and d is not None and e["ms"] > d + 1500.0:
            viol.append("deadline")  # served long past its deadline
            break

    if res.kv_conserved is False:  # decode fleets only (None = N/A)
        viol.append("kv_conserved")

    viol.extend(_schema_violations(res.obs_dir))
    return viol


def shrink_serve_schedule(schedule: list[str], workdir: str, *,
                          replicas: int, duration: float, clients: int,
                          mutate: Optional[str], seed: int,
                          max_runs: int = 16,
                          decode: bool = False) -> tuple[list[str], int]:
    """Greedy delta-debugging over a failing serving schedule — same
    fixed-point loop as the training shrink."""
    current = list(schedule)
    runs = 0
    changed = True
    while changed and len(current) > 1 and runs < max_runs:
        changed = False
        for i in range(len(current)):
            cand = current[:i] + current[i + 1:]
            wd = os.path.join(workdir, f"shrink{runs}")
            runs += 1
            r = run_serve_schedule(cand, wd, replicas=replicas,
                                   duration=duration, clients=clients,
                                   mutate=mutate, seed=seed,
                                   decode=decode)
            if check_serve_invariants(cand, r):
                current = cand
                changed = True
                break
            if runs >= max_runs:
                break
    return current, runs


def run_serve_campaign(args: argparse.Namespace) -> dict:
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    chaos_log = os.path.join(out_dir, "chaos.jsonl")
    decode = bool(getattr(args, "decode", False))
    matrix = DECODE_MATRIX if decode else SERVE_MATRIX
    kind_name = "decode" if decode else "serve"
    config_name = f"{kind_name}_{args.replicas}r"

    if args.schedule:
        for s in args.schedule.split("+"):
            parse_serve_spec(s, matrix)  # fail fast on a bad spec
        plans = [(args.seed, args.schedule.split("+"))]
    else:
        plans = []
        for i in range(args.seeds):
            seed = args.seed + i
            rng = random.Random(seed * 100003 + 29)
            plans.append((seed, generate_serve_schedule(
                rng, args.serve_duration, args.max_faults, matrix)))

    t_start = time.perf_counter()
    # no parity baseline on the serving path; the bucket stays for the
    # summary line's shared format
    timings = {"baseline": 0.0, "runs": 0.0, "shrink": 0.0}
    results = []
    n_bad = 0
    with open(chaos_log, "a") as log_f:
        for seed, schedule in plans:
            wd = os.path.join(out_dir, f"{kind_name}_seed{seed}")
            t0 = time.perf_counter()
            res = run_serve_schedule(
                schedule, wd, replicas=args.replicas,
                duration=args.serve_duration, clients=args.serve_clients,
                mutate=args.mutate, seed=seed, decode=decode)
            viol = check_serve_invariants(schedule, res)
            timings["runs"] += time.perf_counter() - t0
            rec = {
                "kind": "chaos", "t": time.time(), "seed": int(seed),
                "config": config_name, "schedule": "+".join(schedule),
                "ok": not viol, "violations": ",".join(viol),
                "runs": 1,
                "seconds": round(time.perf_counter() - t0, 3),
            }
            if viol:
                n_bad += 1
                t0 = time.perf_counter()
                minimal, shrink_runs = shrink_serve_schedule(
                    schedule, wd, replicas=args.replicas,
                    duration=args.serve_duration,
                    clients=args.serve_clients, mutate=args.mutate,
                    seed=seed, decode=decode)
                timings["shrink"] += time.perf_counter() - t0
                rec["shrunk_schedule"] = "+".join(minimal)
                rec["repro"] = (f"--serve {'--decode ' if decode else ''}"
                                f"--schedule {'+'.join(minimal)}")
                rec["runs"] = rec["runs"] + shrink_runs
                print(f"[chaos] {kind_name} seed {seed} VIOLATED {viol} "
                      f"by {'+'.join(schedule)}; minimal repro: "
                      f"{rec['repro']}", flush=True)
                if res.error:
                    print(f"[chaos]   run error: {res.error[:400]}",
                          flush=True)
            else:
                n_served = sum(
                    1 for ledger in res.ledgers for e in ledger
                    if e["status"] == "served")
                print(f"[chaos] {kind_name} seed {seed} ok: "
                      f"{'+'.join(schedule)} absorbed "
                      f"({n_served} served, "
                      f"{int(res.router_stats.get('tmpi_router_failovers_total', 0))}"
                      f" failovers)", flush=True)
            log_f.write(json.dumps(rec) + "\n")
            log_f.flush()
            results.append(rec)

    timings["total"] = time.perf_counter() - t_start
    report = {
        "schedules": len(results),
        "ok": len(results) - n_bad,
        "violated": n_bad,
        "kinds": sorted(matrix),
        "configs": [config_name],
        "mutate": args.mutate,
        "results": results,
        "timings_s": {k: round(v, 3) for k, v in timings.items()},
        "out": out_dir,
    }
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    return report


# ---------------------------------------------------------------------------
# campaign driver
# ---------------------------------------------------------------------------


def run_campaign(args: argparse.Namespace) -> dict:
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    chaos_log = os.path.join(out_dir, "chaos.jsonl")
    kinds = list(SMOKE_KINDS if args.smoke else
                 (args.kinds.split(",") if args.kinds else MATRIX))
    for k in kinds:
        if k not in MATRIX:
            raise SystemExit(f"unknown fault kind {k!r}; matrix: "
                             f"{sorted(MATRIX)}")
    configs = default_configs(args.smoke)
    if args.configs:
        want = args.configs.split(",")
        configs = [c for c in default_configs(False) if c.name in want]
        if not configs:
            raise SystemExit(f"no config matches {args.configs!r}")

    t_start = time.perf_counter()
    timings = {"baseline": 0.0, "runs": 0.0, "shrink": 0.0}
    baseline = BaselineCache(out_dir)

    # directed mode: one explicit schedule instead of fuzzing
    if args.schedule:
        plans = [(0, configs[0], args.schedule.split("+"))]
    else:
        for cfg in configs:
            # refuse up front with an actionable message rather than an
            # IndexError mid-campaign
            if not usable_kinds(cfg, kinds):
                raise SystemExit(
                    f"config {cfg.name!r} has no usable fault kinds in "
                    f"{kinds} (sharded-only kinds on a non-sharded "
                    "config?) — adjust --kinds/--configs"
                )
        plans = []
        for i in range(args.seeds):
            seed = args.seed + i
            cfg = configs[i % len(configs)]
            rng = random.Random(seed * 100003 + 17)
            plans.append((seed, cfg,
                          generate_schedule(rng, cfg, kinds,
                                            args.max_faults)))

    results = []
    n_bad = 0
    with open(chaos_log, "a") as log_f:
        for seed, cfg, schedule in plans:
            baseline.full_dir(cfg)  # build the reference run up front
            wd = os.path.join(out_dir, f"seed{seed}_{cfg.name}")
            t0 = time.perf_counter()
            res = run_schedule(cfg, schedule, wd, mutate=args.mutate,
                               timeout=args.run_timeout)
            viol = check_invariants(cfg, schedule, res, baseline)
            timings["runs"] += time.perf_counter() - t0
            rec = {
                "kind": "chaos", "t": time.time(), "seed": int(seed),
                "config": cfg.name, "schedule": "+".join(schedule),
                "ok": not viol, "violations": ",".join(viol),
                "runs": len(res.launches),
                "seconds": round(time.perf_counter() - t0, 3),
            }
            if viol:
                n_bad += 1
                t0 = time.perf_counter()
                minimal, shrink_runs = shrink_schedule(
                    cfg, schedule, baseline, wd, mutate=args.mutate,
                    timeout=args.run_timeout)
                timings["shrink"] += time.perf_counter() - t0
                rec["shrunk_schedule"] = "+".join(minimal)
                rec["repro"] = repro_line(minimal)
                rec["runs"] = rec["runs"] + shrink_runs
                print(f"[chaos] seed {seed} ({cfg.name}) VIOLATED "
                      f"{viol} by {'+'.join(schedule)}; minimal repro: "
                      f"{rec['repro']}", flush=True)
                if res.error:
                    print(f"[chaos]   run error: {res.error[:400]}",
                          flush=True)
            else:
                print(f"[chaos] seed {seed} ({cfg.name}) ok: "
                      f"{'+'.join(schedule)} absorbed "
                      f"({len(res.launches)} launch(es))", flush=True)
            log_f.write(json.dumps(rec) + "\n")
            log_f.flush()
            results.append(rec)

    # baseline wall time is attributed wherever it was lazily paid
    # (up-front full runs + on-demand mid-epoch anchors inside the
    # oracle); the dedicated bucket reports the true total
    timings["baseline"] = baseline.seconds
    timings["total"] = time.perf_counter() - t_start
    report = {
        "schedules": len(results),
        "ok": len(results) - n_bad,
        "violated": n_bad,
        "kinds": kinds,
        "configs": [c.name for c in configs],
        "mutate": args.mutate,
        "results": results,
        "timings_s": {k: round(v, 3) for k, v in timings.items()},
        "out": out_dir,
    }
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    return report


def chaos_main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi chaos", description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=25,
                    help="fuzzed schedules to run (one seed each)")
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed: schedule i uses seed+i")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CPU smoke: bsp/none config, in-process "
                         "sleep-free kinds only (crash/ckpt_truncate/"
                         "enospc/bitrot) — the <120 s CI mode")
    ap.add_argument("--schedule", default=None, metavar="K@S[+K@S...]",
                    help="run ONE directed schedule instead of fuzzing "
                         "(e.g. 'crash@5+bitrot@3')")
    ap.add_argument("--kinds", default=None,
                    help="comma-joined fault-kind subset of the matrix")
    ap.add_argument("--configs", default=None,
                    help="comma-joined config subset "
                         "(bsp_none,bsp_int8ef,zero1_none,zero1_int8ef)")
    ap.add_argument("--max-faults", type=int, default=3,
                    help="max faults per fuzzed schedule")
    ap.add_argument("--mutate", choices=["refeed", "drop_inflight"],
                    default=None,
                    help="arm a deliberately seeded recovery bug "
                         "(oracle self-test): 'refeed' re-feeds one "
                         "consumed batch on mid-epoch resume; "
                         "'drop_inflight' (--serve only) drops an "
                         "in-flight request on replica death instead "
                         "of re-admitting it — the campaign must "
                         "catch and shrink it")
    ap.add_argument("--serve", action="store_true",
                    help="chaos the SERVING path instead of training: "
                         "fuzzed SERVE_MATRIX schedules against an "
                         "N-replica router under client load")
    ap.add_argument("--decode", action="store_true",
                    help="with --serve: fleet of continuous-batching "
                         "decode engines; schedules draw from "
                         "DECODE_MATRIX (adds kv_exhaust/"
                         "long_prompt_burst) and the oracle adds "
                         "kv_conserved")
    ap.add_argument("--replicas", type=int, default=2, metavar="N",
                    help="--serve: replica-group size")
    ap.add_argument("--serve-duration", type=float, default=2.0,
                    help="--serve: load-window seconds per schedule")
    ap.add_argument("--serve-clients", type=int, default=4,
                    help="--serve: closed-loop client threads")
    ap.add_argument("--out", default="chaos_out",
                    help="campaign output dir (chaos.jsonl, report.json, "
                         "per-seed work dirs)")
    ap.add_argument("--run-timeout", type=float, default=300.0,
                    help="per-subprocess-launch timeout seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON report to stdout")
    args = ap.parse_args(argv)

    if args.decode and not args.serve:
        raise SystemExit("--decode modifies the serving campaign; "
                         "pass --serve --decode")
    if args.mutate == "drop_inflight" and not args.serve:
        raise SystemExit("--mutate drop_inflight needs --serve (it is "
                         "a router bug, not a training one)")
    if args.mutate == "refeed" and args.serve:
        raise SystemExit("--mutate refeed is a training-resume bug; "
                         "--serve wants drop_inflight")

    from theanompi_tpu.tools.lint import _ensure_virtual_devices

    _ensure_virtual_devices()

    try:
        report = run_serve_campaign(args) if args.serve \
            else run_campaign(args)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — rc 2 = runner bug, not a finding
        print(f"tmpi chaos: internal error: {e!r}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 2
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        t = report["timings_s"]
        print(
            f"chaos: {report['ok']}/{report['schedules']} schedules "
            f"absorbed ({report['violated']} violated) over configs "
            f"{report['configs']} | timings_s baseline={t['baseline']} "
            f"runs={t['runs']} shrink={t['shrink']} total={t['total']}"
        )
        for r in report["results"]:
            if not r["ok"]:
                print(f"  seed {r['seed']} {r['config']}: "
                      f"{r['violations']} <- {r['schedule']} | repro: "
                      f"{r.get('repro', '')}")
    return 1 if report["violated"] else 0


if __name__ == "__main__":
    sys.exit(chaos_main())
