"""Perf-regression gate: diff two bench/profile snapshots on ratio
invariants (attribution-profiler PR).

The BENCH_r* trajectory was archival — numbers landed in the repo and
nothing failed when they regressed. This gate makes it enforceable:
give it a committed baseline and a fresh reading (a ``tmpi profile``
``report.json``, a raw ``bench.py`` result object, or a bench
``kind=metrics`` snapshot line) and it fails when a RATIO invariant
moved beyond its tolerance band:

- ``mfu`` — model FLOPs utilization (symmetric band: an unexplained
  2x jump is drift just like a drop — ratio invariants are supposed to
  be stable, not merely high);
- ``host_blocked_frac`` — the dispatch pipeline's host tax;
- ``compression_ratio`` — the codec layer's claimed wire win;
- ``hbm_gbps`` — achieved HBM bandwidth;
- ``preflight_peak_bytes`` — the memory pre-flight's predicted peak
  HBM (a ``tmpi preflight`` ``kind=preflight`` record, the
  ``tmpi_preflight_peak_bytes`` gauge, or a profile report's
  ``memory`` block) — the memory trajectory gated like MFU;
- ``ici_bytes_per_step`` / ``dcn_bytes_per_step`` — the per-link-class
  wire split (hierarchical-collectives PR): a change that silently
  moves traffic onto the slow cross-slice DCN link — or grows it —
  fails exactly like an MFU drop. A 0.0 DCN baseline (single-slice
  runs) is carried and compared absolutely, so DCN bytes APPEARING
  where there were none also fails;
- ``model_err_cost`` / ``model_err_traffic`` / ``model_err_memory`` —
  the drift watchdog's EWMA relative error per truth source (model-
  drift PR; a profile report's ``drift`` block or the
  ``tmpi_model_err_*`` gauges). The models' HONESTY is a gated ratio
  invariant like MFU: a change that doubles how wrong ``cost_model()``
  is about the step wall fails CI even when the step got faster;
- ``serve_p99_ms`` / ``serve_goodput_rps`` — the replica-fleet serving
  invariants (``bench.py --serve-bench --replicas N`` against the
  committed ``experiments/serve_bench/baseline.json``);
- ``decode_tokens_per_sec`` / ``decode_p99_ttft_ms`` — the LM
  continuous-batching decode invariants (``bench.py --decode-bench``
  against ``experiments/decode_bench/baseline.json``): a decode-path
  change that halves token throughput or triples submit->first-token
  latency fails exactly like an MFU drop;
- per-file: a profile report's attribution fractions must sum to
  1.0 +/- the fraction tolerance (the decomposition's own invariant).

A baseline metric valued EXACTLY 0.0 is still a carried metric —
presence is decided by key, never truthiness — and is compared
absolutely within :data:`ZERO_BASELINE_ABS_TOL` (no ratio exists).

Only metrics present in BOTH files are diffed (a bench result and a
profile report share mfu/host_blocked_frac; schema drift that removes
a previously-compared metric fails loudly rather than silently
shrinking coverage).

Usage::

    python -m theanompi_tpu.tools.perf_gate baseline.json current.json
    python -m theanompi_tpu.tools.perf_gate a.json b.json --rel-tol 0.15
    tools/perf_gate.py old_report.json new_report.json   # repo-root shim

Exit codes: 0 = within bands, 1 = regression/drift, 2 = unusable input.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Optional

# symmetric relative band per ratio metric (overridable via --rel-tol):
# wide enough for CPU test-mesh noise, tight enough that a 2x drift
# (the mutation the acceptance path injects) can never pass
DEFAULT_REL_TOL = 0.25
# |sum(fractions) - 1| bound per profile report (absolute)
FRACTION_SUM_TOL = 0.02
# a baseline metric whose value is EXACTLY 0.0 (a fast host rounds
# host_blocked_frac to zero) has no ratio to diff — the current value
# is compared absolutely against this band instead. Presence in the
# baseline is decided by KEY, never by truthiness: a 0.0 baseline is a
# carried metric, not a vanished one.
ZERO_BASELINE_ABS_TOL = 0.02

# the ratio invariants the gate understands, in report order
GATE_METRICS = ("mfu", "host_blocked_frac", "compression_ratio",
                "hbm_gbps", "preflight_peak_bytes",
                "ici_bytes_per_step", "dcn_bytes_per_step",
                "model_err_cost", "model_err_traffic",
                "model_err_memory",
                # serving-fleet invariants (bench.py --serve-bench
                # --replicas N; committed baseline under experiments/)
                "serve_p99_ms", "serve_goodput_rps",
                # LM decode invariants (bench.py --decode-bench;
                # committed baseline under experiments/decode_bench/)
                "decode_tokens_per_sec", "decode_p99_ttft_ms")


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, (int, float)) and math.isfinite(float(v)):
        return float(v)
    return None


def extract_invariants(obj: dict) -> dict:
    """``{metric: value}`` for every gate metric the snapshot carries.
    Accepts the three snapshot shapes the repo emits:

    - ``tmpi profile`` report.json (``kind=profile_report``);
    - a raw bench.py result object (flat keys);
    - a bench/obs ``kind=metrics`` snapshot (``metrics`` map with
      ``<source>_``-prefixed gauge names)."""
    out: dict = {}
    if not isinstance(obj, dict):
        return out
    if obj.get("kind") == "metrics":
        flat = obj.get("metrics", {})
        for key in GATE_METRICS:
            best = None
            for name, v in flat.items():
                if name != key and not name.endswith(f"_{key}"):
                    continue
                n = _num(v)
                if n is None:
                    continue
                # rank candidates: a static cost/peak constant (e.g.
                # tmpi_cost_peak_hbm_gbps next to the measured
                # tmpi_hbm_gbps) must never shadow the achieved gauge,
                # and the shortest (most direct) name wins ties
                rank = (("cost" in name) or ("peak" in name), len(name))
                if best is None or rank < best[0]:
                    best = (rank, n)
            if best is not None:
                out[key] = best[1]
        return out
    if obj.get("kind") == "preflight":
        n = _num(obj.get("peak_bytes"))
        if n is not None:
            out["preflight_peak_bytes"] = n
        return out
    # profile report / raw bench result: flat keys first, then the
    # report's nested homes
    for key in GATE_METRICS:
        n = _num(obj.get(key))
        if n is None and key == "compression_ratio":
            n = _num(obj.get("traffic", {}).get("compression_ratio")
                     if isinstance(obj.get("traffic"), dict) else None)
        if n is None and key == "hbm_gbps":
            n = _num(obj.get("throughput", {}).get("hbm_gbps")
                     if isinstance(obj.get("throughput"), dict) else None)
        if n is None and key == "preflight_peak_bytes":
            n = _num(obj.get("memory", {}).get("peak_bytes")
                     if isinstance(obj.get("memory"), dict) else None)
        if n is None and key in ("ici_bytes_per_step", "dcn_bytes_per_step"):
            n = _num(obj.get("traffic", {}).get(key)
                     if isinstance(obj.get("traffic"), dict) else None)
        if n is None and key.startswith("model_err_"):
            n = _num(obj.get("drift", {}).get(key)
                     if isinstance(obj.get("drift"), dict) else None)
        if n is not None:
            out[key] = n
    return out


def fraction_sum(obj: dict) -> Optional[float]:
    """Sum of a profile report's attribution fractions (None when the
    snapshot carries none — bench results don't)."""
    attr = obj.get("attribution")
    if isinstance(attr, dict) and isinstance(attr.get("fractions"), dict):
        vals = [_num(v) for v in attr["fractions"].values()]
        if all(v is not None for v in vals):
            return float(sum(vals))
    if isinstance(obj.get("fractions"), dict):  # kind=profile record
        vals = [_num(v) for v in obj["fractions"].values()]
        if all(v is not None for v in vals):
            return float(sum(vals))
    return None


def gate(baseline: dict, current: dict,
         rel_tol: float = DEFAULT_REL_TOL,
         frac_tol: float = FRACTION_SUM_TOL) -> dict:
    """Compare two parsed snapshots; returns ``{ok, checks, errors}``
    (``checks``: one row per diffed invariant)."""
    checks = []
    errors = []
    base_inv = extract_invariants(baseline)
    cur_inv = extract_invariants(current)
    common = [k for k in GATE_METRICS if k in base_inv and k in cur_inv]
    if not common:
        errors.append(
            "no common ratio invariants between the two snapshots "
            f"(baseline has {sorted(base_inv)}, current has "
            f"{sorted(cur_inv)}) — nothing to gate on"
        )
    for key in common:
        b, c = base_inv[key], cur_inv[key]
        if b == 0:
            # exactly-0.0 baseline: a CARRIED metric (key presence
            # decided above, never value truthiness) with no ratio to
            # form — compare absolutely within ZERO_BASELINE_ABS_TOL
            # instead of demanding exact equality
            delta = abs(c)
            tol = ZERO_BASELINE_ABS_TOL
            ok = delta <= tol
        else:
            delta = abs(c - b) / abs(b)
            tol = rel_tol
            ok = delta <= tol
        checks.append({
            "metric": key, "baseline": b, "current": c,
            "rel_delta": round(delta, 6), "tolerance": tol, "ok": ok,
        })
    # schema-drift guard: a metric the baseline carried must not vanish.
    # Membership is KEY presence in the extracted map — a 0.0-valued
    # baseline metric is carried, not vanished (regression-tested with
    # a 0.0 host_blocked_frac baseline)
    for key in base_inv:
        if key not in cur_inv:
            errors.append(
                f"baseline carries {key!r} but the current snapshot "
                "does not — coverage silently shrank"
            )
    for label, obj in (("baseline", baseline), ("current", current)):
        s = fraction_sum(obj)
        if s is not None:
            ok = abs(s - 1.0) <= frac_tol
            checks.append({
                "metric": f"{label}_fractions_sum", "baseline": 1.0,
                "current": round(s, 6), "rel_delta": round(abs(s - 1.0), 6),
                "tolerance": frac_tol, "ok": ok,
            })
    ok = not errors and all(c["ok"] for c in checks) and bool(checks)
    return {"ok": ok, "checks": checks, "errors": errors}


def _load(path: str) -> dict:
    """Parse one snapshot file; JSONL inputs use their LAST parseable
    object line (a metrics.jsonl tail is a valid baseline)."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            return obj
    except json.JSONDecodeError:
        pass
    last = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            o = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(o, dict):
            last = o
    if last is None:
        raise ValueError(f"{path!r}: no JSON object found")
    return last


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("baseline", help="committed snapshot "
                    "(profile report.json / bench result / metrics "
                    "snapshot JSONL)")
    ap.add_argument("current", help="fresh snapshot to gate")
    ap.add_argument("--rel-tol", type=float, default=DEFAULT_REL_TOL,
                    help="symmetric relative band per ratio metric "
                         f"(default {DEFAULT_REL_TOL})")
    ap.add_argument("--frac-tol", type=float, default=FRACTION_SUM_TOL,
                    help="absolute |fraction sum - 1| bound "
                         f"(default {FRACTION_SUM_TOL})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    args = ap.parse_args(argv)
    try:
        baseline = _load(args.baseline)
        current = _load(args.current)
    except (OSError, ValueError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    result = gate(baseline, current, rel_tol=args.rel_tol,
                  frac_tol=args.frac_tol)
    if args.json:
        print(json.dumps(result, indent=1))
    else:
        for c in result["checks"]:
            print(
                f"{'OK  ' if c['ok'] else 'FAIL'} {c['metric']:>24}: "
                f"{c['baseline']:.6g} -> {c['current']:.6g} "
                f"(delta {c['rel_delta']:.3f}, tol {c['tolerance']})"
            )
        for e in result["errors"]:
            print(f"ERROR {e}")
        print("perf gate: " + ("PASS" if result["ok"] else "FAIL"))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
