"""Validate telemetry JSONL against the documented schemas.

Every machine-readable line this framework emits — Recorder history
(``<run>.jsonl``), span traces (``obs/spans_rank*.jsonl``), metric
snapshots (``obs/metrics.jsonl``, bench.py's snapshot line), heartbeat
and stall reports, the serving engine's ``serve``/``reload`` records
(``obs/serve.jsonl``), the continuous-batching decode engine's
``decode`` records (``obs/decode.jsonl``, ``tmpi_decode_*`` metric
family) — must match ONE of the record kinds below, keyed
by the ``kind`` field. Downstream parsing (bench.py drivers, BENCH_*.json
diffing, tools/plot_history.py) reads these streams; without an
enforced schema they drift silently and the first symptom is a broken
plot three PRs later. The schema table here is the single source of
truth (README "Observability" documents it for humans) and a test
validates every line the live system emits against it.

Usage::

    python -m theanompi_tpu.tools.check_obs_schema RUN_DIR [...]
    python -m theanompi_tpu.tools.check_obs_schema path/to/run.jsonl

Directories are walked for ``*.jsonl`` (including ``obs/``
subdirectories). Exit code 1 on any invalid line.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Any, Optional

_NUM = (int, float)

# kind -> {field: (types, required)}; fields absent from a spec are
# allowed if numeric/str (the Recorder forwards model-defined metrics:
# loss/error/top5_error/lr/... — an open union by design)
SCHEMAS: dict[str, dict[str, tuple[tuple, bool]]] = {
    "train": {
        "step": ((int,), True),
    },
    "val": {
        "epoch": ((int,), True),
    },
    "epoch": {
        "epoch": ((int,), True),
        "seconds": (_NUM, True),
    },
    "span": {
        "name": ((str,), True),
        "rank": ((int,), True),
        "t0": (_NUM, True),
        "dur": (_NUM, True),
        "depth": ((int,), True),
        # amortized spans (utils/dispatch.py spaced syncs): duration is
        # ATTRIBUTED window time, not a begin/finish bracket — flagged
        # so trace readers can tell the two apart
        "amortized": ((bool,), False),
    },
    "span_summary": {
        "rank": ((int,), True),
        "t0": (_NUM, True),
        "wall_s": (_NUM, True),
        "fractions": ((dict,), True),
        "totals_s": ((dict,), True),
        "counts": ((dict,), True),
    },
    "metrics": {
        "t": (_NUM, True),
        "metrics": ((dict,), True),
        "step": ((int,), False),
        "source": ((str,), False),
        "labels": ((dict,), False),
    },
    # compressed-collectives wire declaration (obs/comm.py, written by
    # Observability.set_traffic_model when the engine declares its
    # traffic model): the sustained per-step bytes a codec run moves
    # (`wire_bytes`) next to the fp32 equivalent (`raw_bytes`) and the
    # codec that did it — the per-run compression proof line bench.py
    # --codec-sweep reads back.
    "comm": {
        "t": (_NUM, True),
        "rule": ((str,), True),
        "codec": ((str,), True),
        "n_workers": ((int,), True),
        "raw_bytes": (_NUM, True),
        "wire_bytes": (_NUM, True),
        "compression_ratio": (_NUM, True),
        # per-link-class split (amortized, per device): the cross-slice
        # DCN share of the effective and raw wire next to the in-slice
        # ICI remainder — ici+dcn == wire_bytes and raw_ici+raw_dcn ==
        # raw_bytes by construction (obs/comm.py TrafficModel). 0 on
        # single-slice meshes; optional so pre-multislice records stay
        # valid. Live companions: the tmpi_comm_{ici,dcn}_bytes_per_step
        # (+ raw_*) gauges and the achieved tmpi_comm_{ici,dcn}_gbps
        # pair (analytic per-link bytes / measured step seconds).
        "ici_bytes": (_NUM, False),
        "dcn_bytes": (_NUM, False),
        "raw_ici_bytes": (_NUM, False),
        "raw_dcn_bytes": (_NUM, False),
    },
    "heartbeat": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "pid": ((int,), True),
        # dispatch-pipeline liveness split (utils/dispatch.py): step
        # advancing while last_drained_step froze at in_flight=depth is
        # a wedged DEVICE program; both frozen is a stalled HOST driver
        "dispatch_in_flight": ((int,), False),
        "last_drained_step": ((int,), False),
    },
    # numerics flight recorder (obs/numerics.py, obs/flight.py): one
    # sentinel row per drained numerics step (also the flight ring's
    # entry format). Non-finite values cannot ride a JSON numeric map —
    # they are dropped from `metrics` and named in `nonfinite_keys`
    # (comma-joined); the fused non-finite COUNT stays numeric.
    "numerics": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "metrics": ((dict,), True),
        "nonfinite_keys": ((str,), False),
    },
    # one record per detected anomaly (NaN/Inf trigger or EWMA spike),
    # written at dispatch-drain time into numerics_rank{r}.jsonl
    "anomaly": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "metric": ((str,), True),
        "reason": ((str,), True),
        "policy": ((str,), False),
        "value": (_NUM, False),
        "value_repr": ((str,), False),  # non-finite values ride as text
        "ewma": (_NUM, False),
        "factor": (_NUM, False),
        "epoch": ((int,), False),
    },
    "stall": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "stall_s": (_NUM, True),
        "timeout_s": (_NUM, True),
        "stacks": ((dict,), True),
        "postmortem_trace": ((str,), False),
    },
    # fault-tolerant run supervisor (launch/supervisor.py): one record
    # per failed or preempted attempt, appended to supervisor.jsonl.
    # `step` is the VERIFIED checkpoint step the next attempt resumes
    # from (-1 = none: the retry restarts from scratch); `resumable`
    # marks a SIGTERM-grace exit that checkpointed cleanly.
    "retry": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "attempt": ((int,), True),
        "step": ((int,), True),
        "error": ((str,), True),
        # the backoff ACTUALLY slept — under --retry-jitter this is the
        # seeded decorrelated-jitter draw, the de-phasing proof line
        "backoff_s": (_NUM, True),
        "resumable": ((bool,), False),
        # instability attribution (chaos PR): which layer killed the
        # attempt — crash/preempt/topology/storage/anomaly
        # (launch/supervisor.classify_retry_cause)
        "cause": ((str,), False),
        # the attempt's device world size (elastic PR): present on
        # every elastic-supervised record so supervisor.jsonl alone
        # shows the topology trajectory across retries
        "world": ((int,), False),
    },
    # checkpoint scrubber (utils/checkpoint.scrub_checkpoint_dir /
    # CheckpointScrubber): one record per scrub pass — keep-chain
    # members re-verified, how many failed, the quarantined filenames
    # (comma-joined; empty string = clean pass), and the pass's wall
    # seconds. Written by the worker's background scrubber
    # (--scrub-interval) and by the supervisor's retry-time pass.
    "scrub": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "checked": ((int,), True),
        "corrupt": ((int,), True),
        "quarantined": ((str,), True),
        "seconds": (_NUM, True),
    },
    # thread-stress harness (tools/analyze/stress.py): one record per
    # StressHarness.run — the scenario name, the seed that reproduces
    # the schedule, rounds actually executed, the verdict (`ok` with
    # `violations` comma-joined; empty string = clean), the run's wall
    # seconds, and the finest switch interval applied. Written to
    # <obs_dir>/stress.jsonl by the tier-1 stress tests and ad-hoc
    # stress runs.
    "stress": {
        "t": (_NUM, True),
        "scenario": ((str,), True),
        "seed": ((int,), True),
        "rounds": ((int,), True),
        "ok": ((bool,), True),
        "violations": ((str,), False),
        "seconds": (_NUM, False),
        "switch_interval_min": (_NUM, False),
    },
    # chaos campaign runner (tools/chaos.py, `tmpi chaos`): one record
    # per fuzzed fault schedule — the seed that generated it, the
    # engine/codec config label, the schedule itself ('+'-joined
    # KIND@STEP specs), the invariant oracle's verdict (`ok` with
    # `violations` naming the failed invariants, comma-joined), how
    # many training runs the schedule cost (incl. process relaunches),
    # and — for a failing schedule — the shrunken minimal repro as a
    # ready-to-paste --inject-fault command-line fragment.
    "chaos": {
        "t": (_NUM, True),
        "seed": ((int,), True),
        "config": ((str,), True),
        "schedule": ((str,), True),
        "ok": ((bool,), True),
        "violations": ((str,), False),
        "runs": ((int,), False),
        "seconds": (_NUM, False),
        "repro": ((str,), False),
        "shrunk_schedule": ((str,), False),
    },
    # elastic supervision (launch/supervisor.py): one record per
    # attempt — the device world size the attempt was launched in,
    # probed from the live (sorted) device enumeration; prev_world
    # appears from the second attempt on, so a topology change reads
    # directly off the pair
    "topology": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "attempt": ((int,), True),
        "world": ((int,), True),
        "prev_world": ((int,), False),
    },
    # elastic resume (launch/worker.py + utils/checkpoint.py
    # load_resharded): one record per checkpoint actually resharded
    # onto a changed mesh — saved vs live world size, the reshard's
    # wall seconds, how many state leaves moved, and the implied
    # per-replica batch after the move
    "reshard": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "from_world": ((int,), True),
        "to_world": ((int,), True),
        "seconds": (_NUM, True),
        "leaves": ((int,), False),
        "per_replica_batch": ((int,), False),
    },
    # anomaly rollback (--on-anomaly rollback, launch/worker.py): one
    # record per restore, written to numerics_rank{r}.jsonl next to the
    # anomaly records that triggered it. `step` is the anomalous step,
    # `restore_step` the verified checkpoint step restored, `skipped`
    # the data batches the replay will skip at the anomalous step.
    "rollback": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "restore_step": ((int,), True),
        "budget_left": ((int,), True),
        "skipped": ((int,), False),
    },
    # step-time attribution (obs/attribution.py, written by
    # Observability.snapshot into metrics.jsonl when the engine
    # declared a cost model): one record per snapshot — the measured
    # step wall, the compute/comm/host/residual fractions (validated
    # below: they must sum to 1.0 +/- 0.02, the decomposition's own
    # invariant), the roofline classification, and the utilization
    # readings (mfu vs spec peak, or mfu_calibrated on devices without
    # one; achieved hbm_gbps). tools/perf_gate.py diffs these.
    "profile": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "step_seconds": (_NUM, True),
        "fractions": ((dict,), True),
        "classification": ((str,), True),
        "peak_source": ((str,), False),
        "rule": ((str,), False),
        "mfu": (_NUM, False),
        "mfu_calibrated": (_NUM, False),
        "hbm_gbps": (_NUM, False),
    },
    # memory & precision pre-flight (`tmpi preflight`,
    # tools/preflight.py): one record per pre-flight run appended to
    # metrics.jsonl next to a metrics snapshot carrying the
    # tmpi_preflight_peak_bytes / tmpi_preflight_fit /
    # tmpi_preflight_state_bytes gauges — the memory trajectory line
    # tools/perf_gate.py diffs (gate metric `preflight_peak_bytes`).
    # `peak_bytes` is the PREDICTED per-device peak (XLA memory
    # analysis of the lowered step + the declared donation audit);
    # `fit`/`budget_bytes` appear when a budget exists (--budget-gb or
    # the device table's HBM capacity).
    "preflight": {
        "t": (_NUM, True),
        "model": ((str,), True),
        "engine": ((str,), True),
        "codec": ((str,), True),
        "n_devices": ((int,), True),
        "peak_bytes": (_NUM, True),
        "fused": ((bool,), False),
        "state_bytes": (_NUM, False),
        "budget_bytes": (_NUM, False),
        "budget_source": ((str,), False),
        "fit": ((bool,), False),
        "device_kind": ((str,), False),
        "findings": ((int,), False),
    },
    # sharding & layout analyzer (tools/analyze/sharding.py, `tmpi
    # lint --obs-dir`): one record per analyzed engine x codec x
    # --fused-update config. `leaves` is the declared spec-table size,
    # `mismatched` the leaves whose compiled input sharding disagrees
    # with the recipe, `hidden_bytes` the GSPMD-inserted collective
    # wire (per-device, amortized) absent from the traced program —
    # the SHARD002 hidden-wire total, next to the compiled/traced/
    # declared byte figures it was reconciled against.
    "shard": {
        "t": (_NUM, True),
        "engine": ((str,), True),
        "codec": ((str,), True),
        "n_devices": ((int,), True),
        "leaves": ((int,), True),
        "mismatched": ((int,), True),
        "hidden_bytes": (_NUM, True),
        "fused": ((bool,), False),
        "compiled_wire_bytes": (_NUM, False),
        "traced_wire_bytes": (_NUM, False),
        "declared_raw_bytes": (_NUM, False),
        "findings": ((int,), False),
    },
    # fleet telemetry plane (obs/fleet.py): one record per CHANGED
    # fleet view (step advance or a flag set changing), appended to
    # fleet.jsonl by a record-writing FleetTailer (the chief exporter;
    # `tmpi top` is read-only). `step` is the fleet max step, `ranks`
    # how many ranks reported telemetry; rank-id lists (stragglers /
    # frozen / missed / skewed) ride comma-joined like scrub's
    # `quarantined` (empty string = none). `step_seconds_*` is the
    # step-time distribution over ranks' smoothed step times;
    # `link_class` tags comm_gbps with the interconnect the bytes ride
    # (dcn when the __topology__ mesh is multislice, else ici).
    "fleet": {
        "t": (_NUM, True),
        "step": ((int,), True),
        "ranks": ((int,), True),
        "step_spread": ((int,), False),
        "step_seconds_min": (_NUM, False),
        "step_seconds_p50": (_NUM, False),
        "step_seconds_p99": (_NUM, False),
        "step_seconds_max": (_NUM, False),
        "slowest_rank": ((int,), False),
        "straggler_count": ((int,), False),
        "stragglers": ((str,), False),
        "frozen": ((str,), False),
        "missed": ((str,), False),
        "skewed": ((str,), False),
        "mfu_min": (_NUM, False),
        "mfu_median": (_NUM, False),
        "comm_gbps": (_NUM, False),
        "link_class": ((str,), False),
        "slices": ((int,), False),
        "retries": ((int,), False),
    },
    # serving engine (serve/engine.py): periodic + drain-time stats
    # records in <obs_dir>/serve.jsonl. `params_step` is the checkpoint
    # step being served (-1 before the first load); `metrics` is a flat
    # numeric map whose keys all carry the tmpi_serve_ prefix (latency
    # p50/p99 ms, queue depth, batch-fill, request/batch/reload totals)
    # — the prefix is ENFORCED below so serve telemetry stays greppable
    # under one name family.
    "serve": {
        "t": (_NUM, True),
        "params_step": ((int,), True),
        "metrics": ((dict,), True),
        # replica-group members (`tmpi serve --replicas N`) stamp which
        # member wrote the record (serve_r<id>.jsonl); absent on the
        # classic single-engine path (byte-compatible)
        "replica_id": ((int,), False),
    },
    # continuous-batching decode engine (serve/decode/engine.py):
    # periodic + drain-time stats records in <obs_dir>/decode.jsonl
    # (decode_r<id>.jsonl for replica-fleet members). Same shape as
    # kind=serve — `params_step` is the served checkpoint step, and
    # `metrics` is a flat numeric map — but the keys carry the
    # tmpi_decode_ prefix (TTFT p50/p99 ms, TPOT ms, tokens/sec, KV
    # page occupancy and free-list conservation totals, per-status
    # request totals) — ENFORCED below so token-serving telemetry
    # stays greppable under its own name family, distinct from the
    # eval-forward engine's.
    "decode": {
        "t": (_NUM, True),
        "params_step": ((int,), True),
        "metrics": ((dict,), True),
        "replica_id": ((int,), False),
    },
    # replica-group router (serve/router.py): one record per routing
    # event in <obs_dir>/router.jsonl. `event` says which: "health"
    # (replica state transition, from_state/to_state), "failover" (an
    # in-flight request re-admitted off a dying replica, to_replica),
    # "restart" (supervisor revived a member, backoff_s is the
    # decorrelated-jitter delay it waited), "drop" (failover budget or
    # capacity exhausted — the oracle's zero-drop invariant greps
    # these), "reload"/"reload_failed" (central hot-reload fan-out),
    # and "snapshot" (drain-time stats; `metrics` keys carry the
    # tmpi_router_ prefix, ENFORCED below like serve's).
    "router": {
        "t": (_NUM, True),
        "event": ((str,), True),
        "replica_id": ((int,), False),
        "from_state": ((str,), False),
        "to_state": ((str,), False),
        "to_replica": ((int,), False),
        "backoff_s": (_NUM, False),
        "from_step": ((int,), False),
        "to_step": ((int,), False),
        "ms": (_NUM, False),
        "ok": ((bool,), False),
        "error": ((str,), False),
        "metrics": ((dict,), False),
    },
    # one record per checkpoint hot-reload applied by the serving
    # engine (serve/reload.py): the step served before, the verified
    # step swapped in, and the off-hot-path load+swap latency. A
    # reload that verified but failed to LOAD (keep-chain pruned the
    # file between discovery and open — the TOCTOU race) writes
    # ok=false with to_step=-1 and the error; serving never blinked,
    # the next poll retries.
    "reload": {
        "t": (_NUM, True),
        "from_step": ((int,), True),
        "to_step": ((int,), True),
        "ms": (_NUM, False),
        "ok": ((bool,), False),
        "error": ((str,), False),
    },
    # model-drift watchdog (obs/drift.py, written by the obs facade's
    # drain path into metrics.jsonl): one change-gated record per EWMA
    # movement — per-model relative error of predicted vs measured
    # (model_err_cost: roofline wall vs measured step; model_err_traffic:
    # priced comm seconds vs the measured remainder; model_err_memory:
    # declared state bytes vs device.memory_stats() high-water), the
    # worst-offending component per model (per-link for traffic,
    # per-leaf-family for memory), the tolerance band in force, and the
    # sources currently past it comma-joined (empty string = none).
    # `peak_source` says whether errors are vs spec peaks or the
    # first-drain calibration (CPU test meshes, like kind=profile).
    "drift": {
        "rank": ((int,), True),
        "t": (_NUM, True),
        "step": ((int,), True),
        "tolerance": (_NUM, True),
        "breached": ((str,), True),
        "step_seconds": (_NUM, False),
        "peak_source": ((str,), False),
        "model_err_cost": (_NUM, False),
        "model_err_traffic": (_NUM, False),
        "model_err_memory": (_NUM, False),
        "worst_cost": ((str,), False),
        "worst_traffic": ((str,), False),
        "worst_memory": ((str,), False),
    },
    # unified run report (tools/report.py, `tmpi report --json`): ONE
    # self-contained object per invocation — the run verdict
    # (completed/halted/degraded) with its evidence, the causally-
    # grouped incident list (each citing the file:line evidence records
    # it adopted), the merged monotonic event timeline, the per-phase
    # wall breakdown (span_summary rollup) and the drift trajectory.
    # Nested structures are DECLARED typed fields (like profile's
    # `fractions`), so the open-union scalar rule still holds for
    # extras. Deliberately byte-deterministic for a finished dir: no
    # wall-clock stamps ride the body (tests diff two invocations).
    "report": {
        "verdict": ((str,), True),
        "ranks": ((int,), True),
        "n_events": ((int,), True),
        "n_incidents": ((int,), True),
        "steps": ((int,), False),
        "evidence": ((list,), False),
        "timeline": ((list,), False),
        "incidents": ((list,), False),
        "phases": ((dict,), False),
        "drift": ((dict,), False),
        "fleet": ((dict,), False),
    },
}

# the serving metric name family (serve records may only carry these-
# prefixed keys; the engine's registry families are documented here so
# dashboards and the schema stay in one place):
#   tmpi_serve_latency_seconds   histogram  request submit->result
#   tmpi_serve_queue_depth       gauge      requests waiting
#   tmpi_serve_batch_fill        gauge      real/bucket rows, last batch
#   tmpi_serve_params_step       gauge      checkpoint step served
#   tmpi_serve_requests_total    counter    by status=served|expired|rejected
#   tmpi_serve_batches_total     counter    by bucket=N
#   tmpi_serve_reloads_total     counter    hot-reloads applied
SERVE_METRIC_PREFIX = "tmpi_serve_"

# the decode metric name family (kind=decode records may only carry
# these-prefixed keys — enforced below, same deal as serve's):
#   tmpi_decode_ttft_seconds    histogram  submit -> first token
#   tmpi_decode_tpot_seconds    histogram  per-token decode interval
#   tmpi_decode_queue_depth     gauge      prompts waiting for a slot
#   tmpi_decode_batch_occupancy gauge      running seqs / max_seqs
#   tmpi_decode_kv_pages_used   gauge      KV pool pages outstanding
#   tmpi_decode_kv_pages_free   gauge      KV pool pages in free list
#   tmpi_decode_requests_total  counter    by status=served|expired|
#                                          evicted|rejected|failed
#   tmpi_decode_tokens_total    counter    tokens sampled and returned
#   tmpi_decode_prefills_total  counter    by bucket=N
#   tmpi_decode_reloads_total   counter    hot-reloads applied
DECODE_METRIC_PREFIX = "tmpi_decode_"

# the router metric name family (serve/router.py; kind=router snapshot
# records may only carry these-prefixed keys — enforced below, same
# deal as SERVE_METRIC_PREFIX). Counters are fleet totals; gauges are
# refreshed by the supervisor's health pass:
#   tmpi_router_requests_total  counter  by status=served|dropped|
#                                        rejected|expired|stale_retry|
#                                        stale_served
#   tmpi_router_failovers_total counter  in-flight re-admissions that
#                                        landed on a healthy replica
#   tmpi_router_restarts_total  counter  supervisor revivals (+ by
#                                        status=failed for factory
#                                        errors, retried with backoff)
#   tmpi_router_reloads_total   counter  central hot-reload fan-outs
#   tmpi_router_healthy         gauge    replicas in rotation
#   tmpi_router_replicas        gauge    configured group size
#   tmpi_router_queue_depth     gauge    fleet backlog (sum of members)
#   tmpi_router_capacity_rps    gauge    surviving-capacity EWMA (the
#                                        503 Retry-After denominator)
#   tmpi_router_step_floor      gauge    served-step monotone floor
ROUTER_METRIC_PREFIX = "tmpi_router_"

# the step-attribution gauge family (obs/attribution.py; set live at
# every dispatcher drain sync, documented here next to its record kind —
# snapshot metric maps are an open union by design, so unlike
# SERVE_METRIC_PREFIX these names are documentation, not enforcement):
#   tmpi_mfu                  gauge  achieved/peak FLOP/s (spec peak)
#   tmpi_mfu_calibrated       gauge  compute fraction vs calibrated peak
#   tmpi_hbm_gbps             gauge  achieved HBM GB/s (any backend)
#   tmpi_step_compute_frac    gauge  model compute share of the step
#   tmpi_step_comm_frac       gauge  model collective share
#   tmpi_step_host_frac       gauge  measured host-blocked share
#   tmpi_step_residual_frac   gauge  unattributed remainder
#   tmpi_cost_flops_per_step  gauge  XLA cost-analysis FLOPs/step
#   tmpi_cost_hbm_bytes_per_step  gauge  XLA bytes-accessed/step
# per-link-class comm gauges (obs/comm.py TrafficModel.as_metrics +
# the obs facade's step cadence; 0 / absent on single-slice meshes):
#   tmpi_comm_ici_bytes_per_step      gauge  in-slice effective B/step
#   tmpi_comm_dcn_bytes_per_step      gauge  cross-slice effective B/step
#   tmpi_comm_raw_ici_bytes_per_step  gauge  in-slice fp32 B/step
#   tmpi_comm_raw_dcn_bytes_per_step  gauge  cross-slice fp32 B/step
#   tmpi_comm_ici_gbps        gauge  achieved in-slice GB/s
#   tmpi_comm_dcn_gbps        gauge  achieved cross-slice GB/s
# the model-drift gauge family (obs/drift.py via the obs facade's drain
# cadence; documentation like the tmpi_mfu block — kind=drift records
# are the enforced surface). Values are EWMA relative errors, so 0.0 is
# a perfect model and 0.25 is the default anomaly tolerance:
#   tmpi_model_err_cost      gauge  |roofline wall - step wall| / step
#   tmpi_model_err_traffic   gauge  |priced comm - measured comm| / comm
#   tmpi_model_err_memory    gauge  |declared state - HBM high-water| / HW
#   tmpi_drift_breaches_total counter  drift anomalies raised this run
# kind=profile fractions must sum to 1 within this absolute tolerance
PROFILE_FRACTION_SUM_TOL = 0.02

# the fleet-aggregation gauge family (obs/fleet.py; refreshed on every
# tailer pass, served by obs/exporter.py `/metrics`; documentation like
# the tmpi_mfu block — kind=fleet records are the enforced surface):
#   tmpi_fleet_ranks             gauge  ranks reporting telemetry
#   tmpi_fleet_step              gauge  fleet max step
#   tmpi_fleet_step_spread       gauge  max-min step over ranks
#   tmpi_fleet_step_seconds      gauge  by q=min|p50|p99|max over ranks
#   tmpi_fleet_slowest_rank      gauge  highest smoothed step time
#   tmpi_fleet_stragglers        gauge  persistent-straggler count
#   tmpi_fleet_frozen            gauge  silent ranks behind the fleet
#   tmpi_fleet_missed_heartbeats gauge  ranks with stale heartbeats
#   tmpi_fleet_skewed            gauge  numerics-skewed ranks
#   tmpi_fleet_healthy           gauge  1 healthy / 0 unhealthy
#   tmpi_fleet_mfu_min           gauge  min MFU over ranks
#   tmpi_fleet_mfu_median        gauge  median MFU over ranks
#   tmpi_fleet_comm_gbps         gauge  by link=ici|dcn
#   tmpi_fleet_rank_step         gauge  by rank=R, per-rank progress
#   tmpi_fleet_slice_step        gauge  by slice=S (multislice only)
#   tmpi_fleet_retries           gauge  supervisor retries observed
#   tmpi_fleet_refresh_errors    gauge  suppressed tailer exceptions
FLEET_METRIC_PREFIX = "tmpi_fleet_"


def _check_numeric_map(d: dict, what: str) -> list[str]:
    errs = []
    for k, v in d.items():
        if not isinstance(k, str):
            errs.append(f"{what} key {k!r} is not a string")
        if not isinstance(v, _NUM) or isinstance(v, bool):
            errs.append(f"{what}[{k!r}] = {v!r} is not numeric")
        elif not math.isfinite(float(v)):
            errs.append(f"{what}[{k!r}] = {v!r} is not finite")
    return errs


def validate_record(obj: Any) -> list[str]:
    """Error strings for one parsed JSONL record (empty = valid)."""
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not an object"]
    kind = obj.get("kind")
    if kind not in SCHEMAS:
        return [f"unknown kind {kind!r} (known: {sorted(SCHEMAS)})"]
    spec = SCHEMAS[kind]
    errs = []
    for field, (types, required) in spec.items():
        if field not in obj:
            if required:
                errs.append(f"{kind}: missing required field {field!r}")
            continue
        v = obj[field]
        # bool is an int subclass; an int-typed field must reject True
        if isinstance(v, bool) and bool not in types:
            errs.append(f"{kind}.{field} = {v!r} is bool, want "
                        f"{'/'.join(t.__name__ for t in types)}")
        elif not isinstance(v, types):
            errs.append(f"{kind}.{field} = {v!r} is "
                        f"{type(v).__name__}, want "
                        f"{'/'.join(t.__name__ for t in types)}")
    for field, v in obj.items():
        if field == "kind" or field in spec:
            continue
        # open-union extras must stay scalar (nested structures belong
        # in a typed field, or downstream flattening breaks)
        if not isinstance(v, (str, int, float, bool)) and v is not None:
            errs.append(f"{kind}: extra field {field!r} has non-scalar "
                        f"type {type(v).__name__}")
    if not errs:
        if kind in ("metrics", "numerics"):
            errs += _check_numeric_map(obj["metrics"], "metrics")
        elif kind == "serve":
            errs += _check_numeric_map(obj["metrics"], "metrics")
            for k in obj["metrics"]:
                if isinstance(k, str) and not k.startswith(SERVE_METRIC_PREFIX):
                    errs.append(
                        f"serve.metrics key {k!r} lacks the "
                        f"{SERVE_METRIC_PREFIX!r} prefix"
                    )
        elif kind == "decode":
            errs += _check_numeric_map(obj["metrics"], "metrics")
            for k in obj["metrics"]:
                if isinstance(k, str) and not k.startswith(DECODE_METRIC_PREFIX):
                    errs.append(
                        f"decode.metrics key {k!r} lacks the "
                        f"{DECODE_METRIC_PREFIX!r} prefix"
                    )
        elif kind == "router" and isinstance(obj.get("metrics"), dict):
            errs += _check_numeric_map(obj["metrics"], "metrics")
            for k in obj["metrics"]:
                if isinstance(k, str) and not k.startswith(ROUTER_METRIC_PREFIX):
                    errs.append(
                        f"router.metrics key {k!r} lacks the "
                        f"{ROUTER_METRIC_PREFIX!r} prefix"
                    )
        elif kind == "profile":
            errs += _check_numeric_map(obj["fractions"], "fractions")
            if not errs:
                total = sum(obj["fractions"].values())
                if abs(total - 1.0) > PROFILE_FRACTION_SUM_TOL:
                    errs.append(
                        f"profile fractions sum to {total:.6f}, not "
                        f"1.0 +/- {PROFILE_FRACTION_SUM_TOL} — the "
                        "attribution lost a component"
                    )
        elif kind == "span_summary":
            errs += _check_numeric_map(obj["fractions"], "fractions")
            errs += _check_numeric_map(obj["totals_s"], "totals_s")
            # the acceptance invariant: owner-thread top-level fractions
            # cover disjoint stretches of the run wall clock
            total = sum(obj["fractions"].values())
            if total > 1.0 + 1e-6:
                errs.append(
                    f"span_summary fractions sum to {total:.6f} > 1.0"
                )
        elif kind == "stall":
            for name, frames in obj["stacks"].items():
                if not isinstance(frames, list) or not all(
                    isinstance(f, str) for f in frames
                ):
                    errs.append(f"stall.stacks[{name!r}] is not a list "
                                "of frame strings")
    return errs


def check_file(path: str) -> list[str]:
    """``'path:line: error'`` strings for every invalid line."""
    errs = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"{path}:{i}: unparseable JSON ({e})")
                continue
            for e in validate_record(obj):
                errs.append(f"{path}:{i}: {e}")
    return errs


def discover(paths: list[str]) -> list[str]:
    files = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(
                glob.glob(os.path.join(p, "**", "*.jsonl"), recursive=True)
            ) + sorted(
                glob.glob(os.path.join(p, "**", "heartbeat_rank*.json"),
                          recursive=True)
            ) + sorted(
                glob.glob(os.path.join(p, "**", "stall_rank*.json"),
                          recursive=True)
            )
            if not found:
                raise FileNotFoundError(f"no telemetry files under {p!r}")
            files += found
        else:
            files.append(p)
    return files


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="telemetry .jsonl/.json files, or directories to "
                         "walk (run save-dirs, obs dirs)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only the summary line")
    args = ap.parse_args(argv)
    files = discover(args.paths)
    all_errs = []
    n_lines = 0
    for f in files:
        with open(f) as fh:
            n_lines += sum(1 for line in fh if line.strip())
        all_errs += check_file(f)
    if not args.quiet:
        for e in all_errs:
            print(e)
    print(
        f"checked {n_lines} records in {len(files)} files: "
        + ("OK" if not all_errs else f"{len(all_errs)} schema errors")
    )
    return 1 if all_errs else 0


if __name__ == "__main__":
    sys.exit(main())
