"""``tmpi top``: live (or post-mortem) fleet console over an obs dir.

Renders the merged FleetView (obs/fleet.py) as a terminal table — one
row per rank: step progress, smoothed step seconds, MFU, anomaly
count, and flags (STRAGGLER / FROZEN / STALE / SKEW) — plus a fleet
summary line (step spread, step-time p50/p99/max, slowest rank, comm
GB/s by link class, supervisor retries, health verdict).

Two modes::

    tmpi top OBS_DIR            # live: redraws every --interval s
    tmpi top OBS_DIR --once     # one snapshot, then exit — works on
                                # any FINISHED obs dir (post-mortem:
                                # staleness is judged against the
                                # newest timestamp in the dir, not
                                # wall clock)

Read-only by construction: the tailer runs with ``write_records=False``
(a viewer must never grow the dir it watches) and everything happens on
the main thread — no ``tmpi-`` thread to leak into the run's thread
model. ANSI color/clearing only when stdout is a tty (pipes get plain
text, so tests and ``| head`` stay clean).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Optional

from theanompi_tpu.obs.fleet import FleetTailer, FleetView, fleet_topology

_CLEAR = "\x1b[2J\x1b[H"
_RED = "\x1b[31m"
_YELLOW = "\x1b[33m"
_GREEN = "\x1b[32m"
_RESET = "\x1b[0m"


def _fmt(v, spec: str = "", none: str = "-") -> str:
    if v is None:
        return none
    try:
        return format(v, spec)
    except (TypeError, ValueError):
        return str(v)


def render(view: Optional[FleetView], *, color: bool = False) -> str:
    """The fleet table as one string (no trailing clear codes)."""
    if view is None or not view.rows:
        return "fleet: no telemetry yet\n"

    def paint(text: str, code: str) -> str:
        return f"{code}{text}{_RESET}" if color else text

    lines = []
    lines.append(
        f"fleet step {view.step}  spread {view.step_spread}  "
        f"step_s p50/p99/max {view.step_s_p50:.3f}/{view.step_s_p99:.3f}"
        f"/{view.step_s_max:.3f}  slowest rank {view.slowest_rank}  "
        f"comm {_fmt(view.comm_gbps, '.1f')} GB/s ({view.link_class})  "
        f"retries {view.retries}"
    )
    verdict = ("HEALTHY" if view.healthy
               else "UNHEALTHY: " + "; ".join(view.unhealthy_reasons()))
    lines.append(paint(verdict, _GREEN if view.healthy else _RED))
    if len(view.slices) > 1:
        for s in view.slices:
            lines.append(
                f"  slice {s['slice']}: ranks {s['ranks']} step {s['step']}"
                + (f"  stragglers {s['stragglers']}" if s["stragglers"]
                   else "")
                + (f"  frozen {s['frozen']}" if s["frozen"] else "")
            )
    header = (f"{'rank':>4} {'step':>8} {'step s':>8} {'mfu':>6} "
              f"{'comm GB/s':>10} {'anom':>5} {'hb age':>7}  flags")
    lines.append(header)
    lines.append("-" * len(header))
    for row in view.rows:
        flags = []
        if row["frozen"]:
            flags.append(paint("FROZEN", _RED))
        elif row["missed"]:
            flags.append(paint("STALE", _YELLOW))
        if row["straggler"]:
            flags.append(paint("STRAGGLER", _RED))
        elif row["straggling"]:
            flags.append(paint("SLOW", _YELLOW))
        if row["skewed"]:
            flags.append(paint("SKEW", _YELLOW))
        lines.append(
            f"{row['rank']:>4} {row['step']:>8} "
            f"{_fmt(row['step_seconds'], '.3f'):>8} "
            f"{_fmt(row['mfu'], '.2f'):>6} "
            f"{_fmt(view.comm_gbps, '.1f'):>10} "
            f"{row['anomalies']:>5} "
            f"{_fmt(row['heartbeat_age_s'], '.0f'):>7}  "
            + (" ".join(flags) if flags else "ok")
        )
    return "\n".join(lines) + "\n"


def top_main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi top", description=__doc__.splitlines()[0]
    )
    ap.add_argument("obs_dir", help="obs directory to watch (live run or "
                                    "finished post-mortem)")
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit (post-mortem mode: "
                         "staleness vs the dir's newest timestamp)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live refresh period in seconds (default 2)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint dir whose __topology__ manifest "
                         "drives per-slice rollups")
    args = ap.parse_args(argv)

    tailer = FleetTailer(
        args.obs_dir,
        topology=fleet_topology(args.ckpt_dir),
        live=not args.once,
        write_records=False,  # a viewer never grows the dir it reads
    )
    tty = sys.stdout.isatty()
    if args.once:
        view = tailer.refresh()
        sys.stdout.write(render(view, color=tty))
        return 0
    try:
        while True:
            view = tailer.refresh()
            if tty:
                sys.stdout.write(_CLEAR)
            sys.stdout.write(render(view, color=tty))
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(top_main())
