"""``tmpi profile`` — one authoritative answer to "where does the step
go?" (attribution-profiler PR; ROADMAP item 2's required input).

Runs N warm steps of a zoo model under one engine on the visible mesh,
then reconciles the measured step wall against every analytic model the
repo already owns — XLA cost analysis of the SAME compiled step
(utils/flops.py), the engine's declared ``traffic_model()`` wire bytes
(obs/comm.py), the SPMD analyzer's traced-jaxpr collective pricing
(tools/analyze/signature.py) — into a compute / comm / host / residual
decomposition with a roofline classification (obs/attribution.py).
Optionally captures a ``jax.profiler`` trace and joins the
``tools/op_profile.py`` per-op table against the model, naming the top
ops the model does NOT explain: the fusion-work candidates.

Writes ``report.json`` (+ ``trace/`` under ``--trace``) into ``--out``
and prints the human table. The report is the unit
``tools/perf_gate.py`` diffs — run it in CI against a committed
baseline to make the BENCH_r* trajectory enforceable.

Usage::

    tmpi profile --model mlp --steps 8                 # CPU-runnable
    tmpi profile --model alexnet --engine bsp --steps 20 --trace
    tmpi profile --model transformer_lm --engine nd --steps 10

The traffic cross-check re-traces the engine's step jaxpr and compares
its collective bytes against the declared ``traffic_model()`` under the
SPMD101 tolerance (tools/analyze/rules.py) — the same contract ``tmpi
lint`` enforces statically, verified here on the live configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

ENGINES = ("bsp", "zero1", "easgd", "gosgd", "nd")
WARMUP_STEPS = 2


def _build_engine(engine_name: str, model, mesh, codec: Optional[str],
                  avg_freq: int, fused_update: bool = False,
                  allreduce_buckets: float = 0.0, strategy: str = "psum"):
    """The worker driver's engine selection, minimal (no datasets)."""
    if allreduce_buckets and engine_name != "bsp":
        raise ValueError(
            "--allreduce-buckets buckets the BSP in-step allreduce only"
        )
    if strategy != "psum" and engine_name != "bsp":
        raise ValueError("--strategy applies to the BSP engine only")
    if engine_name == "bsp":
        from theanompi_tpu.parallel.bsp import BSPEngine

        return BSPEngine(model, mesh, strategy=strategy, wire_codec=codec,
                         fused_update=fused_update,
                         allreduce_buckets=allreduce_buckets)
    if engine_name == "zero1":
        from theanompi_tpu.parallel.zero import ZeroEngine

        return ZeroEngine(model, mesh, wire_codec=codec,
                          fused_update=fused_update)
    if engine_name == "easgd":
        from theanompi_tpu.parallel.easgd import EASGDEngine

        return EASGDEngine(model, mesh, avg_freq=avg_freq,
                           wire_codec=codec, fused_update=fused_update)
    if engine_name == "gosgd":
        from theanompi_tpu.parallel.gosgd import GOSGDEngine

        return GOSGDEngine(model, mesh, wire_codec=codec,
                           fused_update=fused_update)
    if engine_name == "nd":
        from theanompi_tpu.parallel.nd import NDEngine

        if not getattr(model, "is_lm", False):
            raise ValueError(
                "--engine nd profiles LM models only (try "
                "--model transformer_lm)"
            )
        from theanompi_tpu.parallel.mesh import DATA_AXIS

        return NDEngine(model, mesh, dp_axis=DATA_AXIS, wire_codec=codec,
                        fused_update=fused_update)
    raise ValueError(f"unknown engine {engine_name!r}; known: {ENGINES}")


def resolve_model_and_batch(model_cls, engine_name: str, n_dev: int,
                            batch: Optional[int]):
    """``(model, global_batch)`` under the worker driver's batch
    semantics: per-worker rules (easgd/gosgd) train ``batch`` PER
    device (global = n x batch), everything else shards one global
    batch rounded up to the mesh. Shared with ``tmpi preflight`` so
    the two tools always configure the SAME program for the same
    flags (the perf gate compares their outputs)."""
    recipe = model_cls.default_recipe()
    base = int(batch or recipe.batch_size)
    if engine_name in ("easgd", "gosgd"):
        global_batch = base * n_dev
    else:
        base = -(-base // n_dev) * n_dev  # shard evenly on any mesh
        global_batch = base
    return model_cls(recipe.replace(batch_size=base)), global_batch


def _trace_parts(engine, engine_name: str, state, model,
                 global_batch: int) -> list:
    """``(fn, abstract_args, weight)`` per traced program — the inputs
    :func:`~theanompi_tpu.obs.attribution.traced_wire_bytes` prices for
    the traffic cross-check (EASGD's exchange amortized by avg_freq,
    GoSGD's gossip/no-gossip variants by the gossip cadence)."""
    import jax

    from theanompi_tpu.utils.flops import abstract_batch

    x, y = abstract_batch(model, global_batch)
    astate = jax.eval_shape(lambda s: s, state)
    rng = jax.random.PRNGKey(0)
    if engine_name == "nd":
        return [(engine._steps[False], (astate, x, rng), 1.0)]
    if engine_name == "gosgd":
        every = max(1, int(engine.gossip_every))
        parts = [(engine._steps[(True, False)], (astate, x, y, rng),
                  1.0 / every)]
        if every > 1:
            parts.append((engine._steps[(False, False)],
                          (astate, x, y, rng), 1.0 - 1.0 / every))
        return parts
    parts = [(engine._steps[False], (astate, x, y, rng), 1.0)]
    if engine_name == "easgd":
        parts.append((engine._exchange, (astate,),
                      1.0 / max(1, int(engine.avg_freq))))
    return parts


def run_profile(
    model_name: str = "mlp",
    engine_name: str = "bsp",
    steps: int = 8,
    batch: Optional[int] = None,
    devices: Optional[int] = None,
    codec: str = "none",
    avg_freq: int = 4,
    out_dir: str = "tmpi_profile",
    trace: bool = False,
    seed: int = 0,
    fused_update: bool = False,
    allreduce_buckets: float = 0.0,
    strategy: str = "psum",
    slices: int = 0,
) -> dict:
    """Run the warm-step measurement + attribution; returns (and
    writes) the report dict. See the module docstring."""
    import numpy as np

    import jax

    from theanompi_tpu.models.zoo import zoo_entry
    from theanompi_tpu.obs.attribution import (
        attribute_step,
        crosscheck_traffic,
        join_op_table,
        traced_wire_bytes,
    )
    from theanompi_tpu.parallel import make_mesh
    from theanompi_tpu.parallel.codec import get_codec
    from theanompi_tpu.parallel.mesh import put_global_batch

    if steps < 1:
        raise ValueError("--steps must be >= 1")
    if engine_name not in ENGINES:
        raise ValueError(f"unknown engine {engine_name!r}; known: {ENGINES}")
    codec_obj = get_codec(codec if codec != "none" else None)
    slices = int(slices or 0)
    if slices > 1:
        # the flat-vs-hierarchical comparison mesh: DCN-outermost 2-D
        # shape, same device set — flat 'psum' over both axes and
        # 'hier' over the split run on identical hardware
        from theanompi_tpu.parallel.mesh import make_multislice_mesh

        if engine_name != "bsp":
            raise ValueError("--slices profiles the BSP engine only")
        mesh = make_multislice_mesh(devices or None, n_slices=slices)
    else:
        mesh = make_mesh(devices or None)
    n_dev = mesh.devices.size
    model_cls, _ = zoo_entry(model_name)
    model, global_batch = resolve_model_and_batch(
        model_cls, engine_name, n_dev, batch)
    engine = _build_engine(engine_name, model, mesh,
                           codec if codec_obj.active else None, avg_freq,
                           fused_update=fused_update,
                           allreduce_buckets=allreduce_buckets,
                           strategy=strategy)

    state = engine.init_state(jax.random.PRNGKey(seed))
    r = np.random.RandomState(seed)
    is_lm = bool(getattr(model, "is_lm", False))
    ishape = tuple(model.recipe.input_shape)
    if is_lm:
        toks = r.randint(0, model.recipe.num_classes,
                         (global_batch, *ishape)).astype(np.int32)
        if hasattr(engine, "place_batch"):
            x, y = engine.place_batch(toks, toks)
        else:
            import jax.numpy as jnp

            x = put_global_batch(mesh, jnp.asarray(toks))
            y = x
    else:
        import jax.numpy as jnp

        x = put_global_batch(
            mesh, jnp.asarray(r.randn(global_batch, *ishape), jnp.float32)
        )
        y = put_global_batch(
            mesh,
            jnp.asarray(r.randint(0, model.recipe.num_classes,
                                  global_batch), jnp.int32),
        )

    rng = jax.random.PRNGKey(seed + 1)
    every = int(getattr(engine, "exchange_every", 0) or 0)

    def one_step(state, rng, i):
        """One step (+ the engine's periodic exchange at its cadence),
        each phase blocked — a profiler measures, it may sync freely
        (the training hot loop's lint does not apply here)."""
        rng, sub = jax.random.split(rng)
        t0 = time.perf_counter()
        state, m = engine.train_step(state, x, y, sub)
        t_disp = time.perf_counter() - t0
        jax.block_until_ready(m["loss"])
        t_step = time.perf_counter() - t0
        t_exch = 0.0
        if every and (i + 1) % every == 0:
            t0 = time.perf_counter()
            state = engine.exchange(state)
            jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
            t_exch = time.perf_counter() - t0
        return state, rng, t_step, t_disp, t_exch

    for i in range(WARMUP_STEPS):  # compile + warm outside the window
        state, rng, *_ = one_step(state, rng, i)
    step_times, disp_times, exch_s = [], [], 0.0
    for i in range(steps):
        state, rng, t_step, t_disp, t_exch = one_step(
            state, rng, WARMUP_STEPS + i
        )
        step_times.append(t_step)
        disp_times.append(t_disp)
        exch_s += t_exch
    got = engine.get_step(state)
    want = WARMUP_STEPS + steps
    if got != want:
        raise RuntimeError(
            f"tmpi profile: step counter advanced {got} != {want} — the "
            "backend did not execute the measured program"
        )

    med = float(np.median(step_times))
    step_seconds = med + exch_s / steps  # exchange amortized like comm
    host_frac = min(1.0, float(np.median(disp_times)) / step_seconds)

    cost = None
    try:
        cost = engine.cost_model(state, global_batch)
    except Exception as e:  # noqa: BLE001 — report degrades, not dies
        print(f"[profile] cost model unavailable: {e!r}", file=sys.stderr)
    traffic = engine.traffic_model(state)

    # one abstract trace of the engine's programs serves BOTH the
    # memory block and the traffic cross-check below — the two
    # analyses must see the same programs
    try:
        parts = _trace_parts(engine, engine_name, state, model,
                             global_batch)
    except Exception as e:  # noqa: BLE001
        parts = None
        parts_error = f"{type(e).__name__}: {e}"

    # static memory block (memory pre-flight, ISSUE 12): XLA
    # memory_analysis of the SAME step lowered over abstract operands +
    # the engine's declared per-leaf residency — `tmpi profile` reports
    # where the bytes live next to where the time goes
    mem_block = None
    try:
        if parts is None:
            raise RuntimeError(parts_error)
        from theanompi_tpu.tools.analyze.memory import analyze_step_memory
        from theanompi_tpu.utils.flops import hbm_capacity_bytes

        mfn, margs, _ = parts[0]
        cap = hbm_capacity_bytes()
        mrep = analyze_step_memory(
            mfn, margs, engine.memory_model(margs[0]),
            bool(getattr(engine, "donates_state", False)),
            engine=engine_name, codec=traffic.codec,
            fused=fused_update, budget_bytes=cap,
            budget_source="device-table" if cap else "",
        )
        mem_block = {
            "peak_bytes": mrep.peak_bytes,
            "state_bytes_per_device": mrep.donated_expected_bytes,
            "donation_shortfall": mrep.donation_shortfall,
            "xla": mrep.xla.as_json(),
            "budget_bytes": mrep.budget_bytes,
            "fit": mrep.fit,
        }
    except Exception as e:  # noqa: BLE001 — report degrades, not dies
        print(f"[profile] memory analysis unavailable: {e!r}",
              file=sys.stderr)

    # traffic cross-check: traced jaxpr collective bytes vs the
    # declared model, under the SPMD101 tolerance (live configuration)
    try:
        if parts is None:
            raise RuntimeError(parts_error)
        if codec_obj.active:
            traced = traced_wire_bytes(
                parts, codec_bytes=codec_obj.wire_bytes_per_element
            )
            declared = float(traffic.bytes_per_step_amortized)
        else:
            traced = traced_wire_bytes(parts)
            declared = float(traffic.raw_bytes_per_step_amortized)
        crosscheck = crosscheck_traffic(traced, declared)
    except Exception as e:  # noqa: BLE001
        crosscheck = {"ok": False, "error": f"{type(e).__name__}: {e}"}

    attr = attribute_step(step_seconds, cost=cost, traffic=traffic,
                          host_frac=host_frac)

    ops = None
    if trace:
        trace_dir = os.path.join(out_dir, "trace")
        os.makedirs(trace_dir, exist_ok=True)
        k = min(4, steps)
        jax.profiler.start_trace(trace_dir)
        for i in range(k):
            state, rng, *_ = one_step(state, rng, want + i)
        jax.profiler.stop_trace()
        from theanompi_tpu.tools.op_profile import op_table

        ops = join_op_table(op_table(trace_dir, steps=k), attr)

    img_s = global_batch / step_seconds
    flops_s = cost.flops / step_seconds if cost is not None else None
    report = {
        "kind": "profile_report",
        "model": model_name,
        "engine": engine_name,
        "codec": traffic.codec,
        "n_devices": n_dev,
        "device_kind": jax.devices()[0].device_kind,
        "steps": steps,
        "global_batch": global_batch,
        # the MFU-push knobs this reading was taken under — the
        # committed before/after pair (experiments/profile/) is
        # meaningless without them
        "knobs": {"fused_update": bool(fused_update),
                  "allreduce_buckets": float(allreduce_buckets or 0.0),
                  "strategy": strategy,
                  "slices": slices},
        "step_seconds": {
            "median_s": round(med, 6),
            "exchange_s_amortized": round(exch_s / steps, 6),
            "attributed_s": round(step_seconds, 6),
            "spread_frac": round(
                (max(step_times) - min(step_times)) / med, 4
            ) if med else None,
            "k": steps,
        },
        # top-level mfu: the one number the perf gate diffs — spec MFU
        # where the device has a peak, the calibrated stand-in elsewhere
        "mfu": attr.mfu if attr.mfu is not None else attr.mfu_calibrated,
        "mfu_source": attr.peak_source,
        "host_blocked_frac": round(host_frac, 6),
        "throughput": {
            "images_per_sec": round(img_s, 2),
            "tflops_per_sec": round(flops_s / 1e12, 4)
            if flops_s is not None else None,
            "hbm_gbps": round(attr.hbm_gbps, 3)
            if attr.hbm_gbps is not None else None,
        },
        "cost": {
            "flops_per_step": cost.flops if cost is not None else None,
            "hbm_bytes_per_step": cost.hbm_bytes
            if cost is not None else None,
            "peak_tflops": round(cost.peak_flops_per_sec / 1e12, 2)
            if cost is not None and cost.peak_flops_per_sec else None,
            "peak_hbm_gbps": round(cost.peak_hbm_bytes_per_sec / 1e9, 1)
            if cost is not None and cost.peak_hbm_bytes_per_sec else None,
            "peak_source": attr.peak_source,
        },
        "traffic": {
            "rule": traffic.rule,
            "codec": traffic.codec,
            "raw_bytes_per_step": traffic.raw_bytes_per_step_amortized,
            "wire_bytes_per_step": traffic.bytes_per_step_amortized,
            "compression_ratio": traffic.compression_ratio,
            # per-link-class split (0 on single-slice meshes): the
            # perf-gate's DCN-byte invariant diffs these like MFU
            "ici_bytes_per_step": traffic.ici_bytes_per_step,
            "dcn_bytes_per_step": traffic.dcn_bytes_per_step,
            "raw_ici_bytes_per_step": traffic.raw_ici_bytes_per_step,
            "raw_dcn_bytes_per_step": traffic.raw_dcn_bytes_per_step,
            "crosscheck": crosscheck,
        },
        "attribution": {
            "fractions": {k: round(v, 6)
                          for k, v in attr.fractions.items()},
            "seconds": {k: round(v, 6) for k, v in attr.seconds.items()},
            "fractions_sum": round(attr.fractions_sum, 6),
            "classification": attr.classification,
            "detail": attr.detail,
        },
    }
    if mem_block is not None:
        report["memory"] = mem_block
    if ops is not None:
        report["ops"] = ops
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=1)
    return report


def format_report(report: dict) -> str:
    """The human table (``tmpi profile`` stdout)."""
    a = report["attribution"]
    t = report["traffic"]
    lines = [
        f"tmpi profile — {report['model']} / {report['engine']} "
        f"(codec {report['codec']}) on {report['n_devices']}x "
        f"{report['device_kind']}",
        f"  step: {report['step_seconds']['attributed_s'] * 1e3:.3f} ms "
        f"({report['throughput']['images_per_sec']:.1f} items/s, "
        f"{report['steps']} timed steps)",
        f"  mfu: {report['mfu']:.4f} ({report['mfu_source']})"
        + (f"  |  {report['throughput']['tflops_per_sec']:.2f} TFLOP/s"
           if report["throughput"]["tflops_per_sec"] is not None else "")
        + (f"  |  HBM {report['throughput']['hbm_gbps']:.1f} GB/s"
           if report["throughput"]["hbm_gbps"] is not None else ""),
        "  step-time attribution "
        f"({a['classification']}, fractions sum "
        f"{a['fractions_sum']:.3f}):",
    ]
    for k in ("compute", "comm", "host", "residual"):
        lines.append(
            f"    {k:>8}: {a['fractions'][k] * 100:6.2f}%  "
            f"({a['seconds'][k] * 1e3:8.3f} ms)"
        )
    if report.get("memory"):
        m = report["memory"]
        fit = ("" if m["fit"] is None else
               ("  ->  FITS" if m["fit"] else "  ->  OVER BUDGET"))
        lines.append(
            f"  memory: predicted peak {m['peak_bytes'] / 1e6:.1f} MB"
            f"/device (state {m['state_bytes_per_device'] / 1e6:.1f} MB, "
            f"temp {m['xla']['temp_bytes'] / 1e6:.1f} MB)" + fit
        )
    if t.get("dcn_bytes_per_step"):
        lines.append(
            f"  per-link wire: ici {t['ici_bytes_per_step']:.0f} B + "
            f"dcn {t['dcn_bytes_per_step']:.0f} B/step (raw dcn "
            f"{t['raw_dcn_bytes_per_step']:.0f} B — the codec'd hop)"
        )
    cc = t["crosscheck"]
    if "error" in cc:
        lines.append(f"  traffic cross-check: ERROR {cc['error']}")
    else:
        lines.append(
            f"  traffic cross-check: traced {cc['traced_bytes']:.0f} B "
            f"vs declared {cc['declared_bytes']:.0f} B/step "
            f"(tol {cc['tolerance_bytes']:.0f} B) — "
            + ("OK" if cc["ok"] else "DRIFT")
        )
    if "ops" in report:
        from theanompi_tpu.obs.attribution import format_join

        lines.append(format_join(report["ops"]))
    return "\n".join(lines)


def profile_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi profile", description=__doc__.split("\n\n")[0]
    )
    ap.add_argument("--model", default="mlp",
                    help="zoo model (models/zoo.py; 'mlp' is the "
                         "CPU-runnable default)")
    ap.add_argument("--engine", default="bsp", choices=ENGINES)
    ap.add_argument("--steps", type=int, default=8,
                    help="timed warm steps (compile excluded)")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the recipe batch (per-worker batch "
                         "for easgd/gosgd)")
    ap.add_argument("--devices", type=int, default=None,
                    help="cap the mesh to N visible devices (default "
                         "all)")
    ap.add_argument("--codec", default="none",
                    help="wire codec for the profiled exchange "
                         "(parallel/codec.py: none|bf16|int8[:ef])")
    ap.add_argument("--avg-freq", type=int, default=4,
                    help="easgd: steps between elastic exchanges")
    ap.add_argument("--out", default="tmpi_profile",
                    help="output dir (report.json [+ trace/])")
    ap.add_argument("--trace", action="store_true",
                    help="also capture a jax.profiler trace and join "
                         "the per-op table against the analytic model "
                         "(tools/op_profile.py; needs a device op "
                         "track — TPU)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fused-update", action="store_true",
                    help="profile with the one-pass fused optimizer "
                         "epilogue (ops/pallas_update.py)")
    ap.add_argument("--allreduce-buckets", type=float, default=0.0,
                    metavar="MB",
                    help="BSP engine: profile with the bucketed "
                         "overlap-with-backward allreduce "
                         "(parallel/strategies.py; 0 = off)")
    ap.add_argument("--strategy", default="psum",
                    help="BSP engine: gradient exchange strategy "
                         "(psum|hier|...; 'hier' needs --slices N)")
    ap.add_argument("--slices", type=int, default=0,
                    help="profile on a multislice (dcn, data) mesh with "
                         "N slices — the flat-vs-hier comparison shape "
                         "(BSP only; 0 = single-slice mesh)")
    args = ap.parse_args(argv)
    report = run_profile(
        model_name=args.model, engine_name=args.engine, steps=args.steps,
        batch=args.batch, devices=args.devices, codec=args.codec,
        avg_freq=args.avg_freq, out_dir=args.out, trace=args.trace,
        seed=args.seed, fused_update=args.fused_update,
        allreduce_buckets=args.allreduce_buckets,
        strategy=args.strategy, slices=args.slices,
    )
    print(format_report(report))
    print(f"wrote {os.path.join(args.out, 'report.json')}")
    cc = report["traffic"]["crosscheck"]
    if not cc.get("ok"):
        print("traffic cross-check FAILED: the declared traffic_model() "
              "and the traced program disagree (see tmpi lint SPMD101)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(profile_main())
