"""Where does the step time go? — per-op TPU time table from a profiler
trace.

The reference's whole observability story was the Recorder's wall-clock
calc/comm/wait split (reference: ``lib/recorder.py``, SURVEY.md §5.1);
its "TPU equivalent" clause promises the comm/compute split from the XLA
profile instead. The Recorder captures those traces
(``run_training(profile_dir=...)`` / ``tmpi --profile-dir``); this tool
READS them: it aggregates the device's "XLA Ops" track from the trace
viewer JSON into a per-op table (time, count, share), the same numbers
the TensorBoard op_profile tab shows — without needing TensorBoard (the
bundled plugin's converter is incompatible with the installed TF), and
greppable/committable for regression hunting.

Round-3 case study (this tool's output, one v5e): ResNet-50 batch-256
step = 101 ms, of which ~51 ms is ``convert_reduce_fusion`` ops — the
forward convolutions fused with the BatchNorm two-moment statistic
reduces — and ~42 ms general ``fusion`` ops (backward convs +
elementwise); i.e. the step is conv-emitter- and BN-sweep-bound in XLA
with no single hot Python-visible op, which is why LRN-style manual
kernel surgery (the AlexNet 14k->18k win) has no ResNet equivalent.

Usage:
  python -m theanompi_tpu.tools.op_profile --model resnet50 --steps 5
  python -m theanompi_tpu.tools.op_profile --trace /path/to/profile_dir
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
from typing import Optional


def _load_trace_events(trace_dir: str) -> list:
    """Events of the NEWEST trace-viewer dump under ``trace_dir``."""
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — capture one with "
            "jax.profiler.trace / run_training(profile_dir=...)"
        )
    with gzip.open(paths[-1]) as f:
        return json.load(f)["traceEvents"]


def generalize(name: str) -> str:
    """Collapse instruction numbering so instances aggregate:
    ``convert_reduce_fusion.307`` -> ``convert_reduce_fusion.#``."""
    return re.sub(r"[0-9]+", "#", name)


def op_table(trace_dir: str, steps: int = 1) -> list:
    """Aggregate the device "XLA Ops" track into rows sorted by time.

    Returns ``[{"op", "ms_per_step", "count_per_step", "share"}, ...]``
    (empty on traces with no device op track, e.g. CPU-only captures).
    ``steps``: how many identical steps the capture window contained —
    times are divided by it. Top-level wrapper ops that CONTAIN the
    others (a multi-step ``while.#`` whose duration ~= the whole window)
    are dropped to avoid double counting.
    """
    events = _load_trace_events(trace_dir)
    pids = {
        e["pid"]: e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    tids = {
        (e["pid"], e["tid"]): e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    # one table = ONE device: on multi-chip traces every '/device:TPU:n'
    # process carries (SPMD) copies of the same ops — summing them would
    # inflate ms_per_step by the device count. Use the first device pid.
    dev_pids = sorted(
        p for p, name in pids.items() if name.startswith("/device:")
    )
    the_pid = dev_pids[0] if dev_pids else None
    agg: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    longest: collections.Counter = collections.Counter()
    t0, t1 = float("inf"), 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        if e["pid"] != the_pid:
            continue
        if tids.get((e["pid"], e["tid"])) != "XLA Ops":
            continue
        name = generalize(e["name"])
        dur = e.get("dur", 0)
        agg[name] += dur
        cnt[name] += 1
        longest[name] = max(longest[name], dur)
        t0 = min(t0, e.get("ts", 0))
        t1 = max(t1, e.get("ts", 0) + dur)
    wall = max(t1 - t0, 0.0)
    # drop container ops — a while/scan wrapper is one event spanning
    # (nearly) the whole device window, with all its children ALSO on
    # the track; keeping both would double count. A wrapper is only a
    # wrapper if the REST of the ops fill the window too (its children);
    # a legitimately dominant megakernel leaves the rest of the window
    # empty and must be kept.
    grand = sum(agg.values())
    total = 0.0
    rows = []
    for name, dur in agg.items():
        if wall and longest[name] >= 0.85 * wall and (grand - dur) >= 0.7 * wall:
            continue
        total += dur
        rows.append((name, dur, cnt[name]))
    rows.sort(key=lambda r: -r[1])
    return [
        {
            "op": name,
            "ms_per_step": dur / steps / 1e3,
            "count_per_step": c / steps,
            "share": dur / total if total else 0.0,
        }
        for name, dur, c in rows
    ]


def format_table(rows: list, top: int = 20) -> str:
    if not rows:
        return (
            "no device 'XLA Ops' track in trace (CPU-only capture? "
            "per-op tables need a TPU trace)"
        )
    lines = [f"{'ms/step':>10}  {'count':>7}  {'share':>6}  op"]
    for r in rows[:top]:
        lines.append(
            f"{r['ms_per_step']:10.3f}  {r['count_per_step']:7.1f}  "
            f"{r['share']*100:5.1f}%  {r['op'][:80]}"
        )
    shown = sum(r["share"] for r in rows[:top])
    if len(rows) > top:
        lines.append(f"(+{len(rows) - top} more ops, {100*(1-shown):.1f}% of time)")
    return "\n".join(lines)


def capture_model_step(model_name: str, batch: Optional[int], steps: int,
                       trace_dir: str) -> None:
    """Run ``steps`` fused train steps of a zoo model under the profiler
    (real device; compile excluded from the capture window)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.zoo import zoo_entry
    from theanompi_tpu.train import init_train_state, make_multi_step, make_train_step

    model_cls, base_batch = zoo_entry(model_name)
    model = model_cls(
        model_cls.default_recipe().replace(batch_size=batch or base_batch)
    )
    b = model.recipe.batch_size
    state = init_train_state(model, jax.random.PRNGKey(0))
    r = np.random.RandomState(0)
    if getattr(model, "is_lm", False):
        # token windows: x IS the label stream (next-token objective)
        x = jnp.asarray(
            r.randint(0, model.recipe.num_classes,
                      (b, *model.recipe.input_shape)), jnp.int32
        )
        y = x
    else:
        x = jnp.asarray(r.randn(b, *model.recipe.input_shape), jnp.float32)
        y = jnp.asarray(r.randint(0, model.recipe.num_classes, b), jnp.int32)
    runner = jax.jit(make_multi_step(make_train_step(model), steps))
    out = runner(state, x, y, jax.random.PRNGKey(1))
    np.asarray(out[1]["loss"])  # compile + warm outside the window
    jax.profiler.start_trace(trace_dir)
    out = runner(state, x, y, jax.random.PRNGKey(1))
    np.asarray(out[1]["loss"])
    jax.profiler.stop_trace()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--trace", help="analyze an existing profile dir "
                    "(e.g. from tmpi --profile-dir)")
    ap.add_argument("--model", default="resnet50",
                    help="zoo model to capture+analyze (no --trace)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None,
                    help="fused steps in the capture window (default 5) "
                    "/ per-step divisor for --trace (default 1 — pass "
                    "the real step count of the capture to get ms/step)")
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_args(argv)

    if args.trace:
        trace_dir = args.trace
        steps = args.steps or 1
    else:
        steps = args.steps or 5
        trace_dir = os.path.join("/tmp", f"tmpi_opprof_{args.model}")
        capture_model_step(args.model, args.batch, steps, trace_dir)
    rows = op_table(trace_dir, steps=steps)
    print(format_table(rows, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
