"""``tmpi preflight`` — will this engine x model x mesh x codec fit in
HBM, and where does every byte and every precision boundary live?

Answers the question STATICALLY, before a single step runs: the
engine's numerics-off train step is lowered over abstract
``ShapeDtypeStruct`` operands (compiles, never executes — the PR-9
``compiled_cost()`` discipline), XLA's ``memory_analysis()`` is read
off the executable, per-leaf HBM residency comes from the engine's
declared ``memory_model()`` (sharded leaves divided by their mesh
extent), the donation audit verifies the declared ``donates_state``
actually REALIZED its bytes (MEM002), and the dtype-flow lint
(tools/analyze/precision.py) walks the same trace for fp32 islands /
bf16 accumulation hazards. The verdict gates on ``--budget-gb`` or the
device table's HBM capacity column (utils/flops.py
``hbm_capacity_bytes``); on refusal the top-10 largest live buffers
are named so the fix is actionable.

Usage::

    tmpi preflight --model mlp --engine bsp --budget-gb 16
    tmpi preflight --model alexnet --engine zero1 --codec int8:ef
    tmpi preflight --model transformer_lm --engine nd --mesh 2x4
    tmpi preflight --model mlp --engine bsp --fused-update --json

Exit codes: 0 = fits and no findings, 1 = over budget or findings,
2 = the pre-flight itself failed.

With ``--obs-dir`` a ``kind=preflight`` JSONL record plus a metrics
snapshot carrying ``tmpi_preflight_peak_bytes`` / ``tmpi_preflight_fit``
land in ``<obs-dir>/metrics.jsonl`` — the same trajectory hooks
``tools/perf_gate.py`` diffs (``preflight_peak_bytes`` is a gate
metric), so the memory trajectory is enforceable like MFU.

The SAME rule families run over the committed tiny-model matrix inside
``tmpi lint`` (tools/analyze/memory.py / precision.py) with golden
residency/dtype-flow snapshots; this command is the one-config,
real-model, real-budget entry point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

ENGINES = ("bsp", "zero1", "easgd", "gosgd", "nd")


def _parse_mesh(spec: Optional[str]) -> Optional[tuple]:
    if not spec:
        return None
    try:
        dims = tuple(int(d) for d in spec.lower().split("x"))
    except ValueError:
        raise ValueError(f"--mesh wants N or AxB, got {spec!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"--mesh dimensions must be >= 1, got {spec!r}")
    return dims


def _build(model_name: str, engine_name: str, mesh_dims: Optional[tuple],
           codec: str, fused_update: bool, avg_freq: int,
           batch: Optional[int]):
    """(engine, model, mesh, global_batch) — the worker driver's engine
    selection over the requested mesh (profile.py's builder for 1-D
    meshes; the 2-D ``AxB`` form is the ND engine's data x model
    split)."""
    from theanompi_tpu.models.zoo import zoo_entry
    from theanompi_tpu.parallel import make_mesh
    from theanompi_tpu.parallel.codec import get_codec
    from theanompi_tpu.tools.profile import (
        _build_engine,
        resolve_model_and_batch,
    )

    codec_obj = get_codec(codec if codec != "none" else None)
    wire = codec if codec_obj.active else None
    model_cls, _ = zoo_entry(model_name)
    if mesh_dims is not None and len(mesh_dims) > 1:
        if engine_name != "nd":
            raise ValueError(
                f"--mesh {'x'.join(map(str, mesh_dims))}: multi-axis "
                "meshes are the nd engine's (data x model); "
                f"{engine_name} runs a 1-D data mesh"
            )
        n = 1
        for d in mesh_dims:
            n *= d
        mesh = make_mesh(n, axis_names=("data", "model"),
                         shape=mesh_dims)
    else:
        mesh = make_mesh(mesh_dims[0] if mesh_dims else None)
    # batch semantics shared with `tmpi profile` — same flags, same
    # configured program (the perf gate compares their outputs)
    model, global_batch = resolve_model_and_batch(
        model_cls, engine_name, mesh.devices.size, batch)
    if engine_name == "nd" and len(mesh.axis_names) > 1:
        from theanompi_tpu.parallel.nd import NDEngine

        if not getattr(model, "is_lm", False):
            raise ValueError("--engine nd pre-flights LM models only")
        engine = NDEngine(model, mesh, dp_axis="data", tp_axis="model",
                          wire_codec=wire, fused_update=fused_update)
    else:
        engine = _build_engine(engine_name, model, mesh, wire, avg_freq,
                               fused_update=fused_update)
    return engine, model, mesh, global_batch


def run_preflight(
    model_name: str = "mlp",
    engine_name: str = "bsp",
    mesh: Optional[str] = None,
    codec: str = "none",
    fused_update: bool = False,
    budget_gb: Optional[float] = None,
    batch: Optional[int] = None,
    avg_freq: int = 4,
    obs_dir: Optional[str] = None,
    seed: int = 0,
) -> dict:
    """Run the static pre-flight; returns the report dict (see the
    module docstring). Raises on configuration errors — the CLI maps
    those to rc 2."""
    import jax

    from theanompi_tpu.tools.analyze.memory import (
        analyze_step_memory,
        memory_findings,
    )
    from theanompi_tpu.tools.analyze.precision import (
        accumulation_findings,
        fp32_island_findings,
        fused_update_invariant_findings,
    )
    from theanompi_tpu.utils.flops import hbm_capacity_bytes

    engine, model, mesh_obj, global_batch = _build(
        model_name, engine_name, _parse_mesh(mesh), codec, fused_update,
        avg_freq, batch,
    )
    rng = jax.random.PRNGKey(seed)
    state = jax.eval_shape(engine.init_state, rng)
    # per-engine step variant + abstract operands come from the SAME
    # dispatch `tmpi profile` traces (profile._trace_parts), so the two
    # tools can never lower different program variants for one config
    from theanompi_tpu.tools.profile import _trace_parts

    step_fn, step_args, _ = _trace_parts(
        engine, engine_name, state, model, global_batch)[0]

    device = jax.devices()[0]
    budget = None
    budget_source = ""
    if budget_gb is not None:
        budget = float(budget_gb) * 1e9
        budget_source = "--budget-gb"
    else:
        cap = hbm_capacity_bytes(device)
        if cap is not None:
            budget = float(cap)
            budget_source = "device-table"

    report = analyze_step_memory(
        step_fn, step_args, engine.memory_model(state),
        bool(getattr(engine, "donates_state", False)),
        engine=engine_name, codec=codec, fused=fused_update,
        budget_bytes=budget, budget_source=budget_source,
    )
    findings = memory_findings(report)

    tag = f"[{engine_name}/{codec}{'/fused' if fused_update else ''}]"
    jaxpr = jax.make_jaxpr(step_fn)(*step_args)
    findings.extend(fp32_island_findings(jaxpr, engine=engine_name,
                                         tag=tag))
    findings.extend(accumulation_findings(jaxpr, engine=engine_name,
                                          tag=tag))
    if fused_update:
        findings.extend(fused_update_invariant_findings())

    out = report.as_json()
    out["kind"] = "preflight_report"
    out["model"] = model_name
    out["device_kind"] = getattr(device, "device_kind", "")
    out["mesh"] = "x".join(str(d) for d in mesh_obj.devices.shape)
    out["global_batch"] = int(global_batch)
    out["findings"] = [f.as_json() for f in findings]
    if obs_dir:
        _write_obs(obs_dir, out)
    return out


def _write_obs(obs_dir: str, report: dict) -> None:
    """The ``kind=preflight`` record + a metrics snapshot with the
    ``tmpi_preflight_*`` gauges, appended to ``<obs_dir>/metrics.jsonl``
    (schema: tools/check_obs_schema.py) — the memory-trajectory line
    ``tools/perf_gate.py`` diffs."""
    os.makedirs(obs_dir, exist_ok=True)
    t = time.time()
    rec = {
        "kind": "preflight", "t": t,
        "model": report["model"], "engine": report["engine"],
        "codec": report["codec"], "fused": bool(report["fused"]),
        "n_devices": int(report["n_devices"]),
        "peak_bytes": float(report["peak_bytes"]),
        "state_bytes": float(report["state_bytes_per_device"]),
        "device_kind": report.get("device_kind", ""),
        "findings": len(report["findings"]),
    }
    if report.get("budget_bytes") is not None:
        rec["budget_bytes"] = float(report["budget_bytes"])
        rec["budget_source"] = report.get("budget_source", "")
    if report.get("fit") is not None:
        rec["fit"] = bool(report["fit"])
    metrics = {
        "tmpi_preflight_peak_bytes": float(report["peak_bytes"]),
        "tmpi_preflight_state_bytes": float(
            report["state_bytes_per_device"]),
    }
    if report.get("fit") is not None:
        metrics["tmpi_preflight_fit"] = 1.0 if report["fit"] else 0.0
    if report.get("budget_bytes") is not None:
        metrics["tmpi_preflight_budget_bytes"] = float(
            report["budget_bytes"])
    snap = {"kind": "metrics", "t": t, "source": "preflight",
            "metrics": metrics}
    with open(os.path.join(obs_dir, "metrics.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(snap) + "\n")


def _fmt(n: Optional[float]) -> str:
    from theanompi_tpu.tools.analyze.memory import _fmt_bytes

    return "-" if n is None else _fmt_bytes(n)


def format_report(report: dict, top: int = 12) -> str:
    """The human verdict + per-leaf byte table (``tmpi preflight``
    stdout)."""
    x = report["xla"]
    lines = [
        f"tmpi preflight — {report['model']} / {report['engine']} "
        f"(codec {report['codec']}, "
        f"{'fused' if report['fused'] else 'unfused'} update) on "
        f"{report['mesh']} {report['device_kind']}",
        f"  state: {_fmt(report['state_bytes_per_device'])}/device "
        f"({len(report['buffers'])} buffers); donation "
        + ("declared+realized" if report["declared_donates"]
           and not report["donation_shortfall"]
           else "NOT realized" if report["declared_donates"]
           else "not declared"),
        f"  xla: argument {_fmt(x['argument_bytes'])}, output "
        f"{_fmt(x['output_bytes'])}, temp {_fmt(x['temp_bytes'])}, "
        f"aliased {_fmt(x['alias_bytes'])}",
        f"  predicted peak: {_fmt(report['peak_bytes'])}/device",
    ]
    if report["budget_bytes"] is not None:
        verdict = "FITS" if report["fit"] else "DOES NOT FIT"
        lines.append(
            f"  budget: {_fmt(report['budget_bytes'])} "
            f"({report['budget_source']}) -> {verdict}"
        )
    else:
        lines.append("  budget: unknown (no device HBM entry; pass "
                     "--budget-gb) -> verdict withheld")
    lines.append(f"  per-leaf residency (top {top}):")
    for r in report["buffers"][:top]:
        shape = "x".join(str(d) for d in r["shape"]) if r["shape"] else ""
        # the sharding column is the engine recipe's DECLARED spec
        # (parallel/recipe.py leaf_factors -> MemoryLeaf.spec), not a
        # re-derivation: [] = replicated, [['data']] = dim 0 on 'data'
        spec = r.get("spec")
        sharded = (f"  P{spec} 1/{r['shard_factor']}"
                   if spec and r.get("shard_factor", 1) > 1 else "")
        lines.append(
            f"    {_fmt(r['bytes']):>12}  {r['name']}"
            + (f"  [{r['dtype']} {shape}]" if r["dtype"] else "")
            + sharded
        )
    for f in report["findings"]:
        lines.append(f"  {f['rule']}: {f['message']}")
    ok = (report["fit"] is not False) and not report["findings"]
    lines.append("tmpi preflight: " + ("OK" if ok else "REFUSED"))
    return "\n".join(lines)


def preflight_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi preflight", description=__doc__.split("\n\n")[0])
    ap.add_argument("--model", default="mlp",
                    help="zoo model (models/zoo.py)")
    ap.add_argument("--engine", default="bsp", choices=ENGINES)
    ap.add_argument("--mesh", default=None, metavar="AxB",
                    help="mesh shape: N (1-D data mesh over N devices) "
                         "or AxB (nd: data x model); default all "
                         "visible devices, 1-D")
    ap.add_argument("--codec", default="none",
                    help="wire codec (parallel/codec.py: "
                         "none|bf16|int8[:ef])")
    ap.add_argument("--fused-update", action="store_true",
                    help="pre-flight the fused one-pass optimizer "
                         "epilogue (also pins its fp32-math invariant, "
                         "PREC003)")
    ap.add_argument("--budget-gb", type=float, default=None,
                    help="HBM budget per device in GB (default: the "
                         "device table's capacity; CPU has none)")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the recipe batch (per-worker for "
                         "easgd/gosgd)")
    ap.add_argument("--avg-freq", type=int, default=4,
                    help="easgd: steps between elastic exchanges")
    ap.add_argument("--obs-dir", default=None,
                    help="append the kind=preflight record + "
                         "tmpi_preflight_* gauges to "
                         "<dir>/metrics.jsonl")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="machine-readable report on stdout")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    from theanompi_tpu.tools.lint import _ensure_virtual_devices

    _ensure_virtual_devices()
    try:
        report = run_preflight(
            model_name=args.model, engine_name=args.engine,
            mesh=args.mesh, codec=args.codec,
            fused_update=args.fused_update, budget_gb=args.budget_gb,
            batch=args.batch, avg_freq=args.avg_freq,
            obs_dir=args.obs_dir, seed=args.seed,
        )
    except Exception as e:  # noqa: BLE001 — rc 2 = pre-flight broke
        print(f"tmpi preflight: failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.json_out:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
    return 0 if (report["fit"] is not False
                 and not report["findings"]) else 1


if __name__ == "__main__":
    sys.exit(preflight_main())
