"""Hot-loop lint: no host<->device syncs in the worker train loops.

ISSUE 2 removed the per-step host sync from ``launch/worker.py``'s
train loops — metric D2H fetches live ONLY in the dispatch pipeline's
drain (``utils/dispatch.py``), so the host can keep ``--dispatch-depth``
steps in flight. This lint keeps it that way: it fails if a host-
materializing call (``float(...)``, ``.item(...)``, ``np.asarray(...)``,
``jax.device_get(...)``, ``block_until_ready(...)``) reappears inside a
train loop — the kind of one-line "just print the loss" patch that
silently reinstates a full round trip per step.

Scope: every ``for ... in loader`` loop inside ``run_training`` (the
per-step and fused dispatch loops). The epoch-level code around them —
eval's single end-of-epoch ``float(v)`` drain, checkpoint enqueue,
``Recorder.end(..., sync=...)`` comm brackets after a pipeline flush —
is deliberately out of scope: those are per-epoch / per-exchange syncs,
not per-step ones.

Usage::

    python -m theanompi_tpu.tools.check_hot_loop            # lint worker.py
    python -m theanompi_tpu.tools.check_hot_loop path.py    # lint that file

Exit code 1 on any violation (CI gate; tests/test_check_hot_loop.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Optional

# host-materializing call patterns forbidden inside the train loops
FORBIDDEN = (
    "float(",
    ".item(",
    "np.asarray(",
    "jax.device_get(",
    "block_until_ready(",
)

WORKER_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "launch", "worker.py",
)


def train_loop_segments(source: str, func: str = "run_training"):
    """``(first_lineno, segment_source)`` for every ``for ... in
    <something mentioning 'loader'>`` loop inside ``func`` — the worker
    train loops. Raises if the function or the loops are missing, so a
    refactor that moves them cannot turn this lint into a silent pass."""
    tree = ast.parse(source)
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func!r} found to lint")
    segs = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.For) and "loader" in ast.unparse(sub.iter):
            segs.append((sub.lineno, ast.get_source_segment(source, sub)))
    if not segs:
        raise ValueError(
            f"no 'for ... in loader' train loops found in {func!r} — "
            "the lint's anchor moved; update tools/check_hot_loop.py"
        )
    return segs


def check_source(source: str, func: str = "run_training") -> list[str]:
    """Violation strings (empty = clean)."""
    errs = []
    for lineno, seg in train_loop_segments(source, func=func):
        for off, line in enumerate(seg.splitlines()):
            code = line.split("#", 1)[0]
            for tok in FORBIDDEN:
                if tok in code:
                    errs.append(
                        f"line {lineno + off}: forbidden host sync "
                        f"{tok!r} inside the train loop: {line.strip()} "
                        "(metric fetches belong in utils/dispatch.py's "
                        "drain)"
                    )
    return errs


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else WORKER_PATH
    with open(path) as f:
        source = f.read()
    errs = check_source(source)
    for e in errs:
        print(f"{path}:{e}")
    print(
        f"hot-loop lint on {os.path.relpath(path)}: "
        + ("OK" if not errs else f"{len(errs)} violations")
    )
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
