"""Hot-loop lint: no host<->device syncs in the worker train loops.

ISSUE 2 removed the per-step host sync from ``launch/worker.py``'s
train loops — metric D2H fetches live ONLY in the dispatch pipeline's
drain (``utils/dispatch.py``), so the host can keep ``--dispatch-depth``
steps in flight. This lint keeps it that way: it fails if a host-
materializing call (``float(...)``, ``.item(...)``, ``np.asarray(...)``,
``jax.device_get(...)``, ``block_until_ready(...)``) reappears inside a
train loop — the kind of one-line "just print the loss" patch that
silently reinstates a full round trip per step.

Scope: every ``for ... in loader`` loop inside ``run_training`` (the
per-step and fused dispatch loops). The epoch-level code around them —
eval's single end-of-epoch ``float(v)`` drain, checkpoint enqueue,
``Recorder.end(..., sync=...)`` comm brackets after a pipeline flush —
is deliberately out of scope: those are per-epoch / per-exchange syncs,
not per-step ones.

Usage::

    python -m theanompi_tpu.tools.check_hot_loop            # lint worker.py
    python -m theanompi_tpu.tools.check_hot_loop path.py    # lint that file

Exit code 1 on any violation (CI gate; tests/test_check_hot_loop.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Optional

# host-materializing calls forbidden inside the train loops; matched on
# the AST (ast.Call func shapes), NOT by substring — a '#' inside a
# string literal or a benign "float(" in a log message can never
# truncate code or false-positive
# bare calls: float(x), plus the from-import forms of the module-
# qualified syncs below (`from jax import device_get`, ...)
FORBIDDEN_NAMES = {"float", "block_until_ready", "device_get", "asarray"}
FORBIDDEN_ATTRS = {"item", "block_until_ready"}  # any .item() / .block_until_ready()
FORBIDDEN_MODULE_ATTRS = {  # module-qualified calls: np.asarray(x), ...
    "asarray": {"np", "numpy"},
    "device_get": {"jax"},
}

WORKER_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "launch", "worker.py",
)


def _forbidden_call(node: ast.Call) -> Optional[str]:
    """The violated pattern (display token) if ``node`` is a forbidden
    host-materializing call, else None."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in FORBIDDEN_NAMES:
        return f"{f.id}("
    if isinstance(f, ast.Attribute):
        if f.attr in FORBIDDEN_ATTRS:
            return f".{f.attr}("
        mods = FORBIDDEN_MODULE_ATTRS.get(f.attr)
        if mods and isinstance(f.value, ast.Name) and f.value.id in mods:
            return f"{f.value.id}.{f.attr}("
    return None


def _train_loops(source: str, func: str = "run_training") -> list[ast.For]:
    """Every ``for ... in <something mentioning 'loader'>`` loop inside
    ``func`` — the worker train loops. Raises if the function or the
    loops are missing, so a refactor that moves them cannot turn this
    lint into a silent pass."""
    tree = ast.parse(source)
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func!r} found to lint")
    loops = [
        sub for sub in ast.walk(fn)
        if isinstance(sub, ast.For) and "loader" in ast.unparse(sub.iter)
    ]
    if not loops:
        raise ValueError(
            f"no 'for ... in loader' train loops found in {func!r} — "
            "the lint's anchor moved; update tools/check_hot_loop.py"
        )
    return loops


def train_loop_segments(source: str, func: str = "run_training"):
    """``(first_lineno, segment_source)`` per train loop (anchor guard
    helper; the lint itself walks the loop nodes directly)."""
    return [(loop.lineno, ast.get_source_segment(source, loop))
            for loop in _train_loops(source, func=func)]


def check_source(source: str, func: str = "run_training") -> list[str]:
    """Violation strings (empty = clean)."""
    errs = []
    for loop in _train_loops(source, func=func):
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            tok = _forbidden_call(node)
            if tok is not None:
                errs.append(
                    f"line {node.lineno}: forbidden host sync "
                    f"{tok!r} inside the train loop: "
                    f"{ast.unparse(node)} "
                    "(metric fetches belong in utils/dispatch.py's "
                    "drain)"
                )
    return errs


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else WORKER_PATH
    with open(path) as f:
        source = f.read()
    errs = check_source(source)
    for e in errs:
        print(f"{path}:{e}")
    print(
        f"hot-loop lint on {os.path.relpath(path)}: "
        + ("OK" if not errs else f"{len(errs)} violations")
    )
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
