"""Hot-loop lint: no host<->device syncs in the worker train loops.

ISSUE 2 removed the per-step host sync from ``launch/worker.py``'s
train loops — metric D2H fetches live ONLY in the dispatch pipeline's
drain (``utils/dispatch.py``), so the host can keep ``--dispatch-depth``
steps in flight. This lint keeps it that way: it fails if a host-
materializing call (``float(...)``, ``.item(...)``, ``np.asarray(...)``,
``jax.device_get(...)``, ``block_until_ready(...)``) reappears inside a
train loop — the kind of one-line "just print the loss" patch that
silently reinstates a full round trip per step.

Scope: every ``for ... in loader`` loop inside ``run_training`` (the
per-step and fused dispatch loops). The epoch-level code around them —
eval's single end-of-epoch ``float(v)`` drain, checkpoint enqueue,
``Recorder.end(..., sync=...)`` comm brackets after a pipeline flush —
is deliberately out of scope: those are per-epoch / per-exchange syncs,
not per-step ones.

**Serve hot path** (ISSUE 7 satellite): the same guard now covers the
serving engine's micro-batch loop (``serve/engine.py`` —
``ServeEngine._loop`` / ``_serve_batch``). The contract there is ONE
host materialization per micro-batch: the batched logits fetch at
``_serve_batch``'s top level is the sanctioned sync point, so
``check_serve_source`` flags host-materializing calls anywhere in the
dequeue loop (``_loop``) and inside any per-request ``for`` loop of
``_serve_batch`` — the "fetch each request's logits separately" patch
that would turn one device round trip per batch into one per request.

**Decode hot loop** (ISSUE 20 satellite, rule HOT004): the continuous-
batching decode engine (``serve/decode/engine.py``) has a stricter
contract than the eval engine — exactly ONE host drain per iteration,
the top-level ``np.asarray`` on the fused next-token vector in
``DecodeEngine._iteration``. ``check_decode_source`` flags host-
materializing calls anywhere in the batcher's dispatch loop (``_loop``)
and inside any per-sequence ``for`` loop of ``_iteration`` — the
"fetch each sequence's token separately" patch that would turn one
device round trip per iteration into one per RUNNING SEQUENCE (and
with it the whole point of batching the decode step).

**Profiler warm-step path** (ISSUE 12 satellite): ``tmpi profile``
(tools/profile.py) measures by blocking, but only at its sanctioned
points — the ``one_step`` closure's ``block_until_ready`` reads. Rule
HOT003 (``check_profile_source``) fails on any other host-
materializing call inside ``one_step`` or inside the warm/measure
loops that drive it: an extra sync would silently change what the
profiler times.

Usage::

    python -m theanompi_tpu.tools.check_hot_loop            # worker + serve
                                                            # + decode + profile
    python -m theanompi_tpu.tools.check_hot_loop path.py    # train-loop lint
                                                            # on that file

Exit code 1 on any violation (CI gate; tests/test_check_hot_loop.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Optional

# host-materializing calls forbidden inside the train loops; matched on
# the AST (ast.Call func shapes), NOT by substring — a '#' inside a
# string literal or a benign "float(" in a log message can never
# truncate code or false-positive
# bare calls: float(x), plus the from-import forms of the module-
# qualified syncs below (`from jax import device_get`, ...)
FORBIDDEN_NAMES = {"float", "block_until_ready", "device_get", "asarray"}
FORBIDDEN_ATTRS = {"item", "block_until_ready"}  # any .item() / .block_until_ready()
FORBIDDEN_MODULE_ATTRS = {  # module-qualified calls: np.asarray(x), ...
    "asarray": {"np", "numpy"},
    "device_get": {"jax"},
}

WORKER_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "launch", "worker.py",
)
SERVE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "serve", "engine.py",
)
DECODE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "serve", "decode", "engine.py",
)
PROFILE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "profile.py",
)
# the serve micro-batch hot path: the dequeue loop and the batch server
_SERVE_FUNCS = ("_loop", "_serve_batch")
# the decode hot path (HOT004): the batcher's dispatch loop and the
# continuous-batching iteration it drives
_DECODE_FUNCS = ("_loop", "_iteration")
# `tmpi profile` hot path anchors (tools/profile.py): the per-step
# closure holding the SANCTIONED blocked reads, and the warm/measure
# loops that drive it
_PROFILE_FUNC = "run_profile"
_PROFILE_STEP = "one_step"


def _forbidden_call(node: ast.Call) -> Optional[str]:
    """The violated pattern (display token) if ``node`` is a forbidden
    host-materializing call, else None."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in FORBIDDEN_NAMES:
        return f"{f.id}("
    if isinstance(f, ast.Attribute):
        if f.attr in FORBIDDEN_ATTRS:
            return f".{f.attr}("
        mods = FORBIDDEN_MODULE_ATTRS.get(f.attr)
        if mods and isinstance(f.value, ast.Name) and f.value.id in mods:
            return f"{f.value.id}.{f.attr}("
    return None


def _train_loops(source: str, func: str = "run_training") -> list[ast.For]:
    """Every ``for ... in <something mentioning 'loader'>`` loop inside
    ``func`` — the worker train loops. Raises if the function or the
    loops are missing, so a refactor that moves them cannot turn this
    lint into a silent pass."""
    tree = ast.parse(source)
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func!r} found to lint")
    loops = [
        sub for sub in ast.walk(fn)
        if isinstance(sub, ast.For) and "loader" in ast.unparse(sub.iter)
    ]
    if not loops:
        raise ValueError(
            f"no 'for ... in loader' train loops found in {func!r} — "
            "the lint's anchor moved; update tools/check_hot_loop.py"
        )
    return loops


def train_loop_segments(source: str, func: str = "run_training"):
    """``(first_lineno, segment_source)`` per train loop (anchor guard
    helper; the lint itself walks the loop nodes directly)."""
    return [(loop.lineno, ast.get_source_segment(source, loop))
            for loop in _train_loops(source, func=func)]


def check_source(source: str, func: str = "run_training") -> list[str]:
    """Violation strings (empty = clean)."""
    errs = []
    for loop in _train_loops(source, func=func):
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            tok = _forbidden_call(node)
            if tok is not None:
                errs.append(
                    f"line {node.lineno}: forbidden host sync "
                    f"{tok!r} inside the train loop: "
                    f"{ast.unparse(node)} "
                    "(metric fetches belong in utils/dispatch.py's "
                    "drain)"
                )
    return errs


def _serve_funcs(tree: ast.Module) -> list:
    fns = [node for node in ast.walk(tree)
           if isinstance(node, ast.FunctionDef)
           and node.name in _SERVE_FUNCS]
    if len(fns) < len(_SERVE_FUNCS):
        found = {f.name for f in fns}
        raise ValueError(
            f"serve hot-path anchors {sorted(set(_SERVE_FUNCS) - found)} "
            "not found — the micro-batch loop moved; update "
            "tools/check_hot_loop.py"
        )
    return fns


def _outermost_for_nodes(fn: ast.FunctionDef):
    """AST nodes inside ``fn``'s outermost ``for`` loops only — a
    nested loop's subtree is already covered by its ancestor's walk
    (double-reporting would inflate the violation count), and calls at
    the function's top level are the sanctioned once-per-batch /
    once-per-iteration sync points."""
    fors = [n for n in ast.walk(fn) if isinstance(n, ast.For)]
    inner = {id(sub) for loop in fors
             for sub in ast.walk(loop) if sub is not loop
             and isinstance(sub, ast.For)}
    return (n for loop in fors if id(loop) not in inner
            for n in ast.walk(loop))


def check_serve_source(source: str) -> list:
    """Violation strings for the serve micro-batch hot path (empty =
    clean). ``_loop`` must never materialize host values (it holds the
    queue lock and gates every request's latency); ``_serve_batch`` may
    materialize ONCE per batch at its top level (the batched logits
    fetch) but never inside a per-request ``for`` loop."""
    errs = []
    for fn in _serve_funcs(ast.parse(source)):
        nodes = (ast.walk(fn) if fn.name == "_loop"
                 else _outermost_for_nodes(fn))
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            tok = _forbidden_call(node)
            if tok is not None:
                where = ("the serve dequeue loop" if fn.name == "_loop"
                         else "a per-request loop of _serve_batch")
                errs.append(
                    f"line {node.lineno}: forbidden host sync {tok!r} "
                    f"inside {where}: {ast.unparse(node)} "
                    "(one materialization per micro-batch, at "
                    "_serve_batch top level, is the sanctioned sync "
                    "point)"
                )
    return errs


def check_decode_source(source: str) -> list:
    """Violation strings for the continuous-batching decode hot path
    (``serve/decode/engine.py``; empty = clean) — rule HOT004. The
    contract: exactly ONE host drain per decode iteration, the
    top-level ``np.asarray`` on the fused next-token vector in
    ``_iteration``. ``_loop`` (the batcher thread: it holds the engine
    condvar and gates every sequence's next token) must never
    materialize host values; inside ``_iteration`` no per-sequence
    ``for`` loop may — per-sequence fetches multiply the round trip by
    the running-batch size. Anchor-guarded: renaming ``_loop`` /
    ``_iteration`` fails loudly instead of silently passing."""
    tree = ast.parse(source)
    fns = [node for node in ast.walk(tree)
           if isinstance(node, ast.FunctionDef)
           and node.name in _DECODE_FUNCS]
    if len(fns) < len(_DECODE_FUNCS):
        found = {f.name for f in fns}
        raise ValueError(
            f"decode hot-path anchors "
            f"{sorted(set(_DECODE_FUNCS) - found)} not found — the "
            "decode iteration moved; update tools/check_hot_loop.py"
        )
    errs = []
    for fn in fns:
        nodes = (ast.walk(fn) if fn.name == "_loop"
                 else _outermost_for_nodes(fn))
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            tok = _forbidden_call(node)
            if tok is not None:
                where = ("the decode dispatch loop"
                         if fn.name == "_loop"
                         else "a per-sequence loop of _iteration")
                errs.append(
                    f"line {node.lineno}: forbidden host sync {tok!r} "
                    f"inside {where}: {ast.unparse(node)} (the ONE "
                    "sanctioned drain is _iteration's top-level "
                    "np.asarray on the fused next-token vector)"
                )
    return errs


def check_profile_source(source: str) -> list:
    """Violation strings for ``tmpi profile``'s warm-step path
    (tools/profile.py; empty = clean). The profiler measures by
    BLOCKING — but only where the measurement contract says so: the
    ``one_step`` closure's ``block_until_ready`` reads are the
    sanctioned syncs (the blocked warmup/measure bracket). Anything
    else is drift that silently changes what ``tmpi profile`` times:

    - inside ``one_step``: any OTHER host-materializing call
      (``float``/``.item``/``asarray``/``device_get``) — a per-step
      metric fetch would fold host-transfer time into the step reading;
    - inside the warm/measure loops that drive ``one_step`` (every
      ``for`` loop in ``run_profile`` whose body calls it): ANY
      host-materializing call, ``block_until_ready`` included — a
      second sync point would double-count device time.

    Anchor-guarded like the other hot paths: a refactor that renames
    ``run_profile``/``one_step`` fails loudly instead of silently
    passing."""
    tree = ast.parse(source)
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == _PROFILE_FUNC:
            fn = node
            break
    if fn is None:
        raise ValueError(
            f"profile hot-path anchor {_PROFILE_FUNC!r} not found — the "
            "warm-step loop moved; update tools/check_hot_loop.py"
        )
    step_fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(fn):
        if isinstance(node, ast.FunctionDef) and node.name == _PROFILE_STEP:
            step_fn = node
            break
    if step_fn is None:
        raise ValueError(
            f"profile step anchor {_PROFILE_STEP!r} not found inside "
            f"{_PROFILE_FUNC!r}; update tools/check_hot_loop.py"
        )
    errs = []
    for node in ast.walk(step_fn):
        if not isinstance(node, ast.Call):
            continue
        tok = _forbidden_call(node)
        if tok is not None and "block_until_ready" not in tok:
            errs.append(
                f"line {node.lineno}: forbidden host sync {tok!r} "
                f"inside {_PROFILE_STEP}: {ast.unparse(node)} "
                "(only the sanctioned block_until_ready measurement "
                "reads belong in the profiled step)"
            )
    step_ids = {id(n) for n in ast.walk(step_fn)}
    loops = [
        node for node in ast.walk(fn)
        if isinstance(node, ast.For) and id(node) not in step_ids
        and any(isinstance(sub, ast.Name) and sub.id == _PROFILE_STEP
                for sub in ast.walk(node))
    ]
    if not loops:
        raise ValueError(
            f"no warm-step loops driving {_PROFILE_STEP!r} found in "
            f"{_PROFILE_FUNC!r}; update tools/check_hot_loop.py"
        )
    for loop in loops:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            tok = _forbidden_call(node)
            if tok is not None:
                errs.append(
                    f"line {node.lineno}: forbidden host sync {tok!r} "
                    f"inside a warm-step measurement loop: "
                    f"{ast.unparse(node)} (all syncs live inside "
                    f"{_PROFILE_STEP}'s blocked reads — a second sync "
                    "point double-counts device time)"
                )
    return errs


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        path = argv[0]
        with open(path) as f:
            errs = check_source(f.read())
        for e in errs:
            print(f"{path}:{e}")
        print(
            f"hot-loop lint on {os.path.relpath(path)}: "
            + ("OK" if not errs else f"{len(errs)} violations")
        )
        return 1 if errs else 0
    rc = 0
    for path, checker in ((WORKER_PATH, check_source),
                          (SERVE_PATH, check_serve_source),
                          (DECODE_PATH, check_decode_source),
                          (PROFILE_PATH, check_profile_source)):
        with open(path) as f:
            errs = checker(f.read())
        for e in errs:
            print(f"{path}:{e}")
        print(
            f"hot-loop lint on {os.path.relpath(path)}: "
            + ("OK" if not errs else f"{len(errs)} violations")
        )
        rc |= 1 if errs else 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
