"""JPEG tree -> mmap shards + mean.npy (one-command ImageNet ingestion).

The reference trained from preprocessed 256x256 uint8 hickle batch files
produced by an offline pipeline, with a precomputed ``img_mean``
(reference: ``models/data/imagenet.py`` + the hkl batch layout consumed
by ``lib/proc_load_mpi.py``; SURVEY.md §3.4, §7 hard-part 3 — "crop
details gate top-1 parity"). This tool is the TPU build's equivalent
converter: a class-per-directory JPEG tree (the standard ImageNet
layout) becomes the ``.npy`` shard format of
:mod:`theanompi_tpu.data.imagenet`, streaming (constant memory),
multi-process (decode/resize dominate), with the per-pixel train mean.

Resize convention (the reference era's): shorter side -> ``size`` with
bilinear interpolation, then center crop to ``size x size``, RGB. Labels
are the sorted class-directory names, written to ``class_index.json``.

Usage::

    python -m theanompi_tpu.tools.make_shards IN_DIR OUT_DIR \
        [--size 256] [--shard-size 1024] [--workers N] [--splits train,val]

IN_DIR must contain ``train/<class>/*.JPEG`` (and optionally
``val/<class>/...``); any PIL-readable extension works.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from multiprocessing import Pool
from typing import Iterator, Optional

import numpy as np

from theanompi_tpu.data.imagenet import shard_path

_EXTS = (".jpeg", ".jpg", ".png", ".bmp")


def _list_split(
    split_dir: str, class_to_label: Optional[dict] = None
) -> tuple[dict, list[tuple[str, int]]]:
    """Class->label mapping + (path, label) pairs for one split.

    With ``class_to_label`` given (the TRAIN mapping), this split's
    class dirs are looked up in it — an unknown class is an error, and a
    split missing some classes keeps the train indices (labels must mean
    the same thing in every split)."""
    dirs = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    if class_to_label is None:
        class_to_label = {c: i for i, c in enumerate(dirs)}
    else:
        unknown = [d for d in dirs if d not in class_to_label]
        if unknown:
            raise ValueError(
                f"{split_dir} has classes absent from the train split: "
                f"{unknown[:5]}{'...' if len(unknown) > 5 else ''} — labels "
                "are defined by the train class index"
            )
    samples = []
    for cls in dirs:
        cdir = os.path.join(split_dir, cls)
        label = class_to_label[cls]
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith(_EXTS):
                samples.append((os.path.join(cdir, f), label))
    return class_to_label, samples


def _decode_one(args: tuple[str, int, int]) -> Optional[tuple[np.ndarray, int]]:
    """Decode + shorter-side resize + center crop; None on a corrupt file
    (logged, skipped — ImageNet has a handful)."""
    path, label, size = args
    try:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB")
            w, h = im.size
            if w <= h:
                nw, nh = size, max(size, round(h * size / w))
            else:
                nh, nw = size, max(size, round(w * size / h))
            im = im.resize((nw, nh), Image.BILINEAR)
            left = (nw - size) // 2
            top = (nh - size) // 2
            im = im.crop((left, top, left + size, top + size))
            return np.asarray(im, dtype=np.uint8), label
    except Exception as e:  # corrupt/truncated file
        print(f"skipping {path}: {e}", file=sys.stderr)
        return None


def _decoded_stream(
    samples: list[tuple[str, int]], size: int, workers: int
) -> Iterator[tuple[np.ndarray, int]]:
    jobs = ((p, l, size) for p, l in samples)
    if workers <= 1:
        for j in jobs:
            out = _decode_one(j)
            if out is not None:
                yield out
        return
    with Pool(workers) as pool:
        for out in pool.imap(_decode_one, jobs, chunksize=16):
            if out is not None:
                yield out


def convert_split(
    in_dir: str,
    out_dir: str,
    split: str,
    size: int = 256,
    shard_size: int = 1024,
    workers: int = 1,
    shuffle_seed: Optional[int] = 0,
    compute_mean: bool = False,
    class_index: Optional[dict] = None,
) -> dict:
    """Convert one split; returns {n_images, n_shards, class_index}.

    ``shuffle_seed`` shuffles the (path,label) list once before
    sharding so each shard is class-mixed (the epoch pipeline shuffles
    shard order + intra-shard order, but batches never span shards —
    a class-sorted shard would bias every batch). None disables.

    ``class_index`` (class -> label) pins labels across splits: pass the
    train mapping when converting val so a class missing from one split
    cannot shift every later label. Without it the mapping is derived
    from this split's sorted dirs and written to ``class_index.json``.
    """
    split_dir = os.path.join(in_dir, split)
    writes_index = class_index is None
    class_index, samples = _list_split(split_dir, class_index)
    if not samples:
        raise FileNotFoundError(f"no images under {split_dir}")
    if shuffle_seed is not None:
        rng = np.random.RandomState(shuffle_seed)
        order = rng.permutation(len(samples))
        samples = [samples[i] for i in order]
    os.makedirs(out_dir, exist_ok=True)

    mean_acc = np.zeros((size, size, 3), np.float64) if compute_mean else None
    buf_x = np.empty((shard_size, size, size, 3), np.uint8)
    buf_y = np.empty((shard_size,), np.int64)
    fill = 0
    shard_i = 0
    total = 0

    def flush(n: int):
        nonlocal shard_i
        np.save(shard_path(out_dir, split, "images", shard_i), buf_x[:n])
        np.save(shard_path(out_dir, split, "labels", shard_i), buf_y[:n])
        shard_i += 1

    for img, label in _decoded_stream(samples, size, workers):
        buf_x[fill] = img
        buf_y[fill] = label
        if mean_acc is not None:
            mean_acc += img
        fill += 1
        total += 1
        if fill == shard_size:
            flush(fill)
            fill = 0
    if fill:
        flush(fill)

    if mean_acc is not None and total:
        np.save(
            os.path.join(out_dir, "mean.npy"),
            (mean_acc / total).astype(np.float32),
        )
    if writes_index:
        with open(os.path.join(out_dir, "class_index.json"), "w") as f:
            json.dump(class_index, f, indent=0)
    return {"n_images": total, "n_shards": shard_i, "class_index": class_index}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("in_dir", help="JPEG tree: <in_dir>/<split>/<class>/*.jpeg")
    ap.add_argument("out_dir", help="shard output dir ($IMAGENET_DIR target)")
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--shard-size", type=int, default=1024)
    ap.add_argument("--workers", type=int, default=os.cpu_count() or 1)
    ap.add_argument("--splits", default="train,val")
    ap.add_argument("--no-shuffle", action="store_true",
                    help="keep class-sorted order (debugging only: batches "
                         "never span shards, so unshuffled shards bias them)")
    args = ap.parse_args(argv)

    splits = [s.strip() for s in args.splits.split(",") if s.strip()]
    # train defines the class index; every other split reuses it
    splits.sort(key=lambda s: s != "train")
    class_index = None
    for split in splits:
        info = convert_split(
            args.in_dir, args.out_dir, split,
            size=args.size, shard_size=args.shard_size, workers=args.workers,
            shuffle_seed=None if args.no_shuffle else 0,
            compute_mean=(split == "train"),
            class_index=class_index,
        )
        class_index = info["class_index"]
        print(
            json.dumps(
                {"split": split, "n_images": info["n_images"],
                 "n_shards": info["n_shards"],
                 "n_classes": len(info["class_index"])}
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
