"""Plot Recorder histories — the reference's repo-root plot helper,
TPU-native edition.

Reference: the ``show.py``-style script next to ``lib/recorder.py``
(SURVEY.md §1 L8 / §5.1): it loaded the recorder's pickled history and
plotted cost/error curves for one or more runs. Same contract here,
over the Recorder's JSONL stream (``<save_dir>/<run>.jsonl``,
`utils/recorder.py`): train loss + LR per step, val loss/error per
epoch, and images/sec — for any number of runs on shared axes, so
sync-rule comparisons (the reference's main use: BSP vs EASGD curves)
are one command:

    python -m theanompi_tpu.tools.plot_history experiments/results/bsp \\
        experiments/results/easgd -o rules.png

Accepts run directories (every ``*.jsonl`` inside) or ``.jsonl`` files.
Headless-safe (Agg backend); ``--show`` opens a window where a display
exists.

When a run was recorded with ``--obs-dir`` pointing INSIDE its save
dir (an ``obs/`` directory next to the run JSONL), a third panel row
appears: achieved interconnect GB/s per step (obs/metrics.jsonl
snapshots) and per-kind span time fractions (the ``span_summary`` line
of obs/spans_rank*.jsonl). Runs recorded with ``--numerics-freq`` add
a FOURTH row from ``obs/numerics_rank0.jsonl``: grad/update norms
(left, log scale) and the per-rule divergence gauge (right), with
detected anomaly steps marked as vertical lines on both. Runs whose
engine declared a cost model add an ATTRIBUTION row from the
``kind=profile`` records (obs/attribution.py): stacked step-time
fractions (compute/comm/host/residual — where the step goes) on the
left, the MFU trend (spec MFU, or the calibrated stand-in dashed) on
the right. Runs watched by a record-writing FleetTailer (obs/fleet.py:
the chief exporter) add a FLEET row from ``obs/fleet.jsonl``: the
per-rank step-time spread band (min..max over ranks, median line) with
red vlines where the persistent-straggler detector fired (left), and
the frozen/silent-rank count (right) — append-mode rerun safe like the
comm panel. Runs whose drift watchdog wrote ``kind=drift`` records
(obs/drift.py) add a DRIFT row: the EWMA relative error per truth
source (cost/traffic/memory, log scale) with red vlines where the
watchdog breached tolerance (left) and the cumulative breach count
(right) — append-mode rerun safe like every other obs panel. Runs
without obs/numerics/profile/fleet/drift data plot exactly as before —
extra rows only render when at least one run has them.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_jsonl(path: str) -> dict:
    """Split one Recorder JSONL into train/val series."""
    train: dict[str, list] = {"step": [], "loss": [], "error": [],
                              "lr": [], "images_per_sec": [],
                              "ips_step": []}
    val: dict[str, list] = {"epoch": [], "loss": [], "error": []}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("kind") == "train":
                for k in train:
                    if k in row:
                        train[k].append(row[k])
                # throughput is SPARSE under fused dispatch (one reading
                # per dispatch, on the group's final substep row): pair
                # it with its own step axis, never the full step list
                if "images_per_sec" in row and "step" in row:
                    train["ips_step"].append(row["step"])
            elif row.get("kind") == "val":
                for k in val:
                    if k in row:
                        val[k].append(row[k])
    return {"train": train, "val": val}


def load_obs(jsonl_path: str) -> dict:
    """Obs-subsystem series for the run at ``jsonl_path``: looks for an
    ``obs/`` directory next to the run JSONL (the ``--obs-dir`` inside
    the save dir convention). Returns ``{"comm_step": [...],
    "comm_gbps": [...], "fractions": {kind: frac}}`` — empty lists/dict
    when the run has no (or unreadable) obs data, so callers degrade
    gracefully."""
    out: dict = {"comm_step": [], "comm_gbps": [], "comm_gbps_raw": [],
                 # per-link-class series (multislice runs): the ICI and
                 # DCN shares of the achieved rate, paired with
                 # comm_step like the raw series (None when absent)
                 "comm_gbps_ici": [], "comm_gbps_dcn": [],
                 "codec": None, "fractions": {},
                 # step-time attribution (kind=profile records,
                 # obs/attribution.py): stacked fractions + MFU trend
                 "prof_step": [], "prof_fracs": [], "prof_mfu": [],
                 "prof_mfu_calibrated": [],
                 # model-drift watchdog (kind=drift records,
                 # obs/drift.py): EWMA relative error per truth source,
                 # None-paired with drift_step like the comm series
                 "drift_step": [], "drift_cost": [], "drift_traffic": [],
                 "drift_memory": [], "drift_breach_steps": []}
    obs_dir = os.path.join(os.path.dirname(os.path.abspath(jsonl_path)), "obs")
    metrics = os.path.join(obs_dir, "metrics.jsonl")
    if os.path.exists(metrics):
        try:
            with open(metrics) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if row.get("kind") == "comm":
                        # the run's wire declaration (last wins, like
                        # the span summary): names the codec for the
                        # legend of the raw-vs-effective pair
                        out["codec"] = row.get("codec")
                        continue
                    if row.get("kind") == "profile" and "step" in row:
                        if out["prof_step"] and (
                            row["step"] < out["prof_step"][-1]
                        ):
                            # append-mode rerun: newest run's series
                            # wins (mirrors the comm-series rule)
                            for k in ("prof_step", "prof_fracs",
                                      "prof_mfu", "prof_mfu_calibrated"):
                                out[k] = []
                        if out["prof_step"] and (
                            row["step"] == out["prof_step"][-1]
                        ):
                            out["prof_step"].pop()
                            out["prof_fracs"].pop()
                            out["prof_mfu"].pop()
                            out["prof_mfu_calibrated"].pop()
                        out["prof_step"].append(row["step"])
                        out["prof_fracs"].append(row.get("fractions", {}))
                        out["prof_mfu"].append(row.get("mfu"))
                        out["prof_mfu_calibrated"].append(
                            row.get("mfu_calibrated")
                        )
                        continue
                    if row.get("kind") == "drift" and "step" in row:
                        if out["drift_step"] and (
                            row["step"] < out["drift_step"][-1]
                        ):
                            # append-mode rerun: newest run's series
                            # wins (mirrors the comm-series rule)
                            for k in ("drift_step", "drift_cost",
                                      "drift_traffic", "drift_memory",
                                      "drift_breach_steps"):
                                out[k] = []
                        if out["drift_step"] and (
                            row["step"] == out["drift_step"][-1]
                        ):
                            # change-gated re-emit at an unchanged step
                            # (EWMA moved between drains): newest wins
                            for k in ("drift_step", "drift_cost",
                                      "drift_traffic", "drift_memory"):
                                out[k].pop()
                        out["drift_step"].append(row["step"])
                        out["drift_cost"].append(row.get("model_err_cost"))
                        out["drift_traffic"].append(
                            row.get("model_err_traffic"))
                        out["drift_memory"].append(
                            row.get("model_err_memory"))
                        if row.get("breached"):
                            out["drift_breach_steps"].append(row["step"])
                        continue
                    if row.get("kind") != "metrics" or "step" not in row:
                        continue
                    gbps = row.get("metrics", {}).get("tmpi_comm_gbps")
                    raw = row.get("metrics", {}).get("tmpi_comm_gbps_raw")
                    ici = row.get("metrics", {}).get("tmpi_comm_ici_gbps")
                    dcn = row.get("metrics", {}).get("tmpi_comm_dcn_gbps")
                    if gbps is not None:
                        if out["comm_step"] and row["step"] < out["comm_step"][-1]:
                            # append-mode rerun into the same obs dir:
                            # the step counter restarted — keep only the
                            # newest run's series (mirrors the
                            # last-summary-wins rule below)
                            out["comm_step"], out["comm_gbps"] = [], []
                            out["comm_gbps_raw"] = []
                            out["comm_gbps_ici"] = []
                            out["comm_gbps_dcn"] = []
                        if out["comm_step"] and row["step"] == out["comm_step"][-1]:
                            # epoch-end snapshot repeats the step of the
                            # last per-step snapshot: newest value wins
                            out["comm_gbps"][-1] = gbps
                            out["comm_gbps_raw"][-1] = raw
                            out["comm_gbps_ici"][-1] = ici
                            out["comm_gbps_dcn"][-1] = dcn
                        else:
                            out["comm_step"].append(row["step"])
                            out["comm_gbps"].append(gbps)
                            # paired with comm_step even when absent
                            # (codec-off runs): None rows drop at plot
                            out["comm_gbps_raw"].append(raw)
                            out["comm_gbps_ici"].append(ici)
                            out["comm_gbps_dcn"].append(dcn)
        except (OSError, ValueError):
            pass  # partial/corrupt telemetry: plot what parses
    # rank 0's trace is the driver view; one bar set per run
    span_files = sorted(glob.glob(os.path.join(obs_dir, "spans_rank*.jsonl")))
    if span_files:
        try:
            with open(span_files[0]) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if row.get("kind") == "span_summary":
                        # last summary wins (append-mode reruns into the
                        # same dir stack summaries; newest describes the
                        # most recent run)
                        out["fractions"] = row.get("fractions", {})
        except (OSError, ValueError):
            pass
    # numerics flight-recorder telemetry (obs/numerics.py): sentinel
    # rows -> norm/divergence curves, anomaly records -> step markers
    out.update({"nm_step": [], "grad_norm": [], "update_norm": [],
                "div_step": [], "divergence": [], "anomaly_steps": []})
    numerics = os.path.join(obs_dir, "numerics_rank0.jsonl")
    if os.path.exists(numerics):
        try:
            with open(numerics) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if row.get("kind") == "numerics":
                        m = row.get("metrics", {})
                        if "nm_grad_norm" in m:
                            out["nm_step"].append(row["step"])
                            out["grad_norm"].append(m["nm_grad_norm"])
                            out["update_norm"].append(
                                m.get("nm_update_norm", float("nan"))
                            )
                        if "nm_divergence" in m:
                            out["div_step"].append(row["step"])
                            out["divergence"].append(m["nm_divergence"])
                    elif row.get("kind") == "anomaly":
                        out["anomaly_steps"].append(row["step"])
        except (OSError, ValueError):
            pass  # partial/corrupt telemetry: plot what parses
    # fleet telemetry (obs/fleet.py kind=fleet records): per-rank
    # step-time spread band (min/median/max over ranks) + the steps
    # where the persistent-straggler detector fired
    out.update({"fleet_step": [], "fleet_min": [], "fleet_p50": [],
                "fleet_max": [], "fleet_frozen": [],
                "straggler_steps": []})
    fleet = os.path.join(obs_dir, "fleet.jsonl")
    if os.path.exists(fleet):
        try:
            with open(fleet) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if row.get("kind") != "fleet" or "step" not in row:
                        continue
                    if out["fleet_step"] and (
                        row["step"] < out["fleet_step"][-1]
                    ):
                        # append-mode rerun into the same obs dir: the
                        # step counter restarted — newest run's series
                        # wins (mirrors the comm-series rule)
                        for k in ("fleet_step", "fleet_min", "fleet_p50",
                                  "fleet_max", "fleet_frozen",
                                  "straggler_steps"):
                            out[k] = []
                    if out["fleet_step"] and (
                        row["step"] == out["fleet_step"][-1]
                    ):
                        # flag-change record at an unchanged step:
                        # newest values win
                        for k in ("fleet_step", "fleet_min", "fleet_p50",
                                  "fleet_max", "fleet_frozen"):
                            out[k].pop()
                    out["fleet_step"].append(row["step"])
                    out["fleet_min"].append(
                        row.get("step_seconds_min", 0.0))
                    out["fleet_p50"].append(
                        row.get("step_seconds_p50", 0.0))
                    out["fleet_max"].append(
                        row.get("step_seconds_max", 0.0))
                    out["fleet_frozen"].append(
                        len([r for r in (row.get("frozen") or "").split(",")
                             if r]))
                    if row.get("straggler_count", 0) or row.get("stragglers"):
                        out["straggler_steps"].append(row["step"])
        except (OSError, ValueError):
            pass  # partial/corrupt telemetry: plot what parses
    return out


def discover(paths: list[str]) -> dict[str, str]:
    """``{label: jsonl_path}`` from a mix of dirs and files. Labels that
    collide (two ``run.jsonl`` inputs, or identically-named dirs) are
    ALL relabeled with the shortest path suffix that tells them apart —
    every requested run appears, unambiguously."""
    pairs: list[tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(f"no *.jsonl under {p!r}")
            for f in found:
                label = os.path.basename(os.path.dirname(f)) or \
                    os.path.splitext(os.path.basename(f))[0]
                if len(found) > 1:
                    label = os.path.splitext(os.path.basename(f))[0]
                pairs.append((label, f))
        else:
            pairs.append((os.path.splitext(os.path.basename(p))[0], p))

    def suffix(f: str, k: int) -> str:
        parts = os.path.normpath(os.path.abspath(f)).split(os.sep)
        return "/".join(parts[-k:])

    from collections import Counter

    # identity is the resolved path: the same file listed under two
    # spellings ('expA/x.jsonl' and './expA/x.jsonl') is ONE run, and
    # only genuinely different files count as peers to disambiguate
    seen: set = set()
    uniq = []
    for lbl, f in pairs:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            uniq.append((lbl, f))
    counts = Counter(lbl for lbl, _ in uniq)
    runs: dict[str, str] = {}
    for lbl, f in uniq:
        if counts[lbl] > 1:
            peers = [
                g for l2, g in uniq
                if l2 == lbl and os.path.abspath(g) != os.path.abspath(f)
            ]
            k = 2
            while any(suffix(g, k) == suffix(f, k) for g in peers):
                k += 1
            lbl = suffix(f, k)
        if lbl in runs:  # safety net: never drop a requested run
            base, i = lbl, 2
            while lbl in runs:
                lbl, i = f"{base}#{i}", i + 1
        runs[lbl] = f
    return runs


def plot(runs: dict[str, str], out: str, show: bool = False,
         smooth: int = 1) -> str:
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    def smoothed(xs, ys, k):
        if k <= 1 or len(ys) < k:
            return xs, ys
        acc, out_x, out_y = 0.0, [], []
        for i, y in enumerate(ys):
            acc += y
            if i >= k:
                acc -= ys[i - k]
            if i >= k - 1:
                out_x.append(xs[i])
                out_y.append(acc / k)
        return out_x, out_y

    obs = {label: load_obs(path) for label, path in runs.items()}
    has_obs = any(
        o["comm_gbps"] or o["fractions"] for o in obs.values()
    )
    has_nm = any(
        o["nm_step"] or o["div_step"] or o["anomaly_steps"]
        for o in obs.values()
    )
    has_prof = any(o["prof_step"] for o in obs.values())
    has_fleet = any(o["fleet_step"] for o in obs.values())
    has_drift = any(o["drift_step"] for o in obs.values())
    n_rows = (2 + int(has_obs) + int(has_nm) + int(has_prof)
              + int(has_fleet) + int(has_drift))
    fig, axes = plt.subplots(n_rows, 2, figsize=(11, 3.5 * n_rows))
    (ax_loss, ax_val), (ax_ips, ax_lr) = axes[0], axes[1]
    ax_comm = ax_frac = ax_nm = ax_div = ax_attr = ax_mfu = None
    ax_fleet = ax_frozen = ax_drift = ax_breach = None
    row = 2
    if has_obs:
        ax_comm, ax_frac = axes[row]
        row += 1
    if has_nm:
        ax_nm, ax_div = axes[row]
        row += 1
    if has_prof:
        ax_attr, ax_mfu = axes[row]
        row += 1
    if has_fleet:
        ax_fleet, ax_frozen = axes[row]
        row += 1
    if has_drift:
        ax_drift, ax_breach = axes[row]
    frac_kinds: list[str] = []
    for o in obs.values():
        frac_kinds += [k for k in o["fractions"] if k not in frac_kinds]
    for run_i, (label, path) in enumerate(runs.items()):
        h = load_jsonl(path)
        t, v = h["train"], h["val"]
        o = obs[label]
        if ax_comm is not None and o["comm_gbps"]:
            eff_label = (
                f"{label} ({o['codec']} wire)"
                if o.get("codec") and o["codec"] != "none" else label
            )
            line, = ax_comm.plot(
                *smoothed(o["comm_step"], o["comm_gbps"], smooth),
                label=eff_label,
            )
            raw_pairs = [
                (s, v) for s, v in zip(o["comm_step"], o["comm_gbps_raw"])
                if v is not None
            ]
            if raw_pairs:
                # effective vs raw: the vertical gap IS the codec win —
                # dashed raw in the same color so runs stay grouped
                rs, rv = zip(*raw_pairs)
                ax_comm.plot(*smoothed(list(rs), list(rv), smooth),
                             linestyle="--", color=line.get_color(),
                             alpha=0.6, label=f"{label} raw fp32")
            # per-link-class split (multislice runs): ICI dotted, DCN
            # dash-dot in the run's color — the DCN series is the one
            # a wire codec visibly pulls down on the hierarchical rule
            for key, style, cls in (("comm_gbps_ici", ":", "ici"),
                                    ("comm_gbps_dcn", "-.", "dcn")):
                pairs = [(s, v) for s, v in zip(o["comm_step"], o[key])
                         if v is not None]
                if pairs:
                    ls, lv = zip(*pairs)
                    ax_comm.plot(*smoothed(list(ls), list(lv), smooth),
                                 linestyle=style, color=line.get_color(),
                                 alpha=0.8, label=f"{label} {cls}")
        if ax_frac is not None and o["fractions"]:
            # grouped bars: one cluster per span kind, one bar per run
            width = 0.8 / max(1, len(runs))
            xs = [frac_kinds.index(k) + run_i * width
                  for k in o["fractions"]]
            ax_frac.bar(xs, list(o["fractions"].values()), width=width,
                        label=label)
        if ax_nm is not None and o["nm_step"]:
            ax_nm.plot(*smoothed(o["nm_step"], o["grad_norm"], smooth),
                       label=f"{label} grad")
            ax_nm.plot(*smoothed(o["nm_step"], o["update_norm"], smooth),
                       label=f"{label} update", linestyle="--")
        if ax_div is not None and o["div_step"]:
            ax_div.plot(*smoothed(o["div_step"], o["divergence"], smooth),
                        label=label)
        if ax_attr is not None and o["prof_step"]:
            # stacked step-time fractions (kind=profile records): the
            # stack IS the step — where each step's wall went; residual
            # clamps at 0 for display (a negative residual means the
            # models over-explain, already flagged in the record)
            kinds = ("compute", "comm", "host", "residual")
            series = [
                [max(0.0, f.get(k, 0.0)) for f in o["prof_fracs"]]
                for k in kinds
            ]
            ax_attr.stackplot(
                o["prof_step"], series, alpha=0.7,
                labels=[f"{label} {k}" for k in kinds]
                if len(runs) > 1 else list(kinds),
            )
        if ax_mfu is not None and o["prof_step"]:
            spec = [(s, v) for s, v in zip(o["prof_step"], o["prof_mfu"])
                    if v is not None]
            cal = [(s, v) for s, v in
                   zip(o["prof_step"], o["prof_mfu_calibrated"])
                   if v is not None]
            if spec:
                ax_mfu.plot(*zip(*spec), label=f"{label} mfu")
            if cal:
                # the calibrated stand-in (no spec peak): dashed so it
                # cannot be misread as a real utilization number
                ax_mfu.plot(*zip(*cal), linestyle="--",
                            label=f"{label} mfu (calibrated)")
        if ax_fleet is not None and o["fleet_step"]:
            # spread band: min..max step time over ranks, median on top —
            # a widening band IS the straggler story at a glance
            ax_fleet.fill_between(o["fleet_step"], o["fleet_min"],
                                  o["fleet_max"], alpha=0.25,
                                  label=f"{label} min..max")
            ax_fleet.plot(o["fleet_step"], o["fleet_p50"],
                          label=f"{label} median")
            for j, s in enumerate(sorted(set(o["straggler_steps"]))):
                ax_fleet.axvline(
                    s, color="red", alpha=0.5, linestyle="-",
                    label=f"{label} straggler" if j == 0 else None)
        if ax_frozen is not None and o["fleet_step"]:
            ax_frozen.step(o["fleet_step"], o["fleet_frozen"],
                           where="post", label=f"{label} frozen ranks")
        if ax_drift is not None and o["drift_step"]:
            # one curve per truth source; zeros (a momentarily perfect
            # model) drop rather than fight the log axis
            for key, name, style in (("drift_cost", "cost", "-"),
                                     ("drift_traffic", "traffic", "--"),
                                     ("drift_memory", "memory", ":")):
                pairs = [(s, v) for s, v in zip(o["drift_step"], o[key])
                         if v is not None and v > 0]
                if pairs:
                    ax_drift.plot(*zip(*pairs), linestyle=style,
                                  label=f"{label} {name}")
            for j, s in enumerate(sorted(set(o["drift_breach_steps"]))):
                ax_drift.axvline(
                    s, color="red", alpha=0.5,
                    label=f"{label} breach" if j == 0 else None)
        if ax_breach is not None and o["drift_step"]:
            bset = set(o["drift_breach_steps"])
            cum, n = [], 0
            for s in o["drift_step"]:
                n += int(s in bset)
                cum.append(n)
            ax_breach.step(o["drift_step"], cum, where="post",
                           label=f"{label} breaches")
        if o["anomaly_steps"]:
            # anomaly markers on both numerics panels: first marker per
            # run carries the legend entry, the rest stay unlabeled
            for ax in (ax_nm, ax_div):
                if ax is None:
                    continue
                for j, s in enumerate(sorted(set(o["anomaly_steps"]))):
                    ax.axvline(s, color="red", alpha=0.4, linestyle=":",
                               label=f"{label} anomaly" if j == 0 else None)
        if t["step"] and t["loss"]:
            ax_loss.plot(*smoothed(t["step"], t["loss"], smooth), label=label)
        if v["epoch"]:
            # presence, not truthiness: an all-zero error series (a run
            # that reached 0% val error) is still the error curve
            key = "error" if len(v["error"]) == len(v["epoch"]) else "loss"
            ax_val.plot(v["epoch"], v[key], marker="o", label=f"{label} ({key})")
        if t["ips_step"] and t["images_per_sec"]:
            ax_ips.plot(*smoothed(t["ips_step"], t["images_per_sec"], smooth),
                        label=label)
        if t["step"] and t["lr"]:
            ax_lr.plot(t["step"][: len(t["lr"])], t["lr"], label=label)
    ax_loss.set(title="train loss", xlabel="step")
    ax_val.set(title="validation", xlabel="epoch")
    ax_ips.set(title="throughput (images/sec)", xlabel="step")
    ax_lr.set(title="learning rate", xlabel="step")
    all_axes = [ax_loss, ax_val, ax_ips, ax_lr]
    if ax_comm is not None:
        ax_comm.set(title="interconnect GB/s (effective solid, raw fp32 "
                          "dashed — gap = codec win; ici dotted / dcn "
                          "dash-dot on multislice runs)",
                    xlabel="step")
        ax_frac.set(title="span time fractions (of run wall clock)")
        if frac_kinds:
            ax_frac.set_xticks(range(len(frac_kinds)))
            ax_frac.set_xticklabels(frac_kinds, rotation=30, ha="right",
                                    fontsize=8)
        all_axes += [ax_comm, ax_frac]
    if ax_nm is not None:
        ax_nm.set(title="grad/update norm (numerics sentinels)",
                  xlabel="step")
        if ax_nm.lines:
            ax_nm.set_yscale("log")  # norms span orders of magnitude
        ax_div.set(title="divergence gauge (anomaly steps dotted red)",
                   xlabel="step")
        all_axes += [ax_nm, ax_div]
    if ax_attr is not None:
        ax_attr.set(title="step-time attribution "
                          "(compute/comm/host/residual fractions)",
                    xlabel="step", ylim=(0, 1.05))
        ax_mfu.set(title="MFU trend (dashed = calibrated peak)",
                   xlabel="step")
        all_axes += [ax_attr, ax_mfu]
    if ax_fleet is not None:
        ax_fleet.set(title="fleet step-time spread (band min..max over "
                           "ranks; red = persistent straggler)",
                     xlabel="step")
        ax_frozen.set(title="frozen (silent) ranks", xlabel="step")
        all_axes += [ax_fleet, ax_frozen]
    if ax_drift is not None:
        ax_drift.set(title="model drift: EWMA relative error per truth "
                           "source (red = tolerance breach)",
                     xlabel="step")
        if ax_drift.lines:
            ax_drift.set_yscale("log")  # errors span orders of magnitude
        ax_breach.set(title="cumulative drift breaches", xlabel="step")
        all_axes += [ax_drift, ax_breach]
    for ax in all_axes:
        ax.grid(True, alpha=0.3)
        if ax.lines or ax.patches or ax.collections:
            ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    if show:
        plt.show()
    plt.close(fig)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help="run directories or .jsonl files to plot together")
    p.add_argument("-o", "--out", default="history.png")
    p.add_argument("--smooth", type=int, default=1,
                   help="moving-average window over train-series points")
    p.add_argument("--show", action="store_true")
    args = p.parse_args(argv)
    runs = discover(args.paths)
    out = plot(runs, args.out, show=args.show, smooth=args.smooth)
    print(f"wrote {out} ({len(runs)} run{'s' if len(runs) != 1 else ''})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
